"""Multi-process decode service: the shared-memory data plane (ISSUE 6
tentpole).

BENCH_r05 measured the north-star ResNet-50 at 2261 im/s/chip synthetic
but 134 im/s input-fed — `resnet50_e2e_fraction_of_synthetic` = 0.059 —
with ALL decode on a single host core.  PR 2's DeviceFeed overlapped
the H2D transfer; the decode side stayed a single-threaded Python
pipeline (the GIL serializes the PIL threadpool, and the native C++
reader is an optional build).  This module is the production decode
plane underneath `ImageRecordIter(workers=N)` and `DeviceFeed`:

1. **True processes.**  A `DecodeService` pool of N worker PROCESSES —
   GIL-free parallel decode even without the native reader.  Workers
   are STRICTLY jax-free (numpy + PIL + the recordio framing only;
   `_resize_linear` exists because the gluon `_resize_np` goes through
   jax.image): a forked child that calls into the parent's initialized
   XLA runtime deadlocks in backend_compile — measured, not
   hypothetical.  A startup handshake backstops the residual
   fork-with-threads risk: a pool whose workers never report ready is
   declared unavailable and the caller degrades, it does not hang.
2. **Sharded readers.**  Each worker owns a disjoint, deterministic
   shard of the record keyspace per epoch (`shard_records`): every
   worker computes the SAME seeded permutation for (seed, epoch) and
   takes a strided slice of its batch-sized BLOCKS — exact-once
   coverage per epoch with zero coordination, and at most one partial
   batch per epoch pool-wide (steps-per-epoch do not depend on the
   worker count).  Indexed (.idx) and plain .rec files partition the
   same way: the parent resolves a byte offset per record
   (`recordio.list_record_offsets` for plain files) and workers seek
   independently on their own file handles.
3. **Shared-memory slab ring.**  Batches land in pre-allocated
   uint8/float32 slabs inside ONE `multiprocessing.shared_memory`
   segment.  The queues carry slot numbers, never pixels: the hot
   path does zero per-batch pickling and zero copies — the consumer
   hands the slab view straight to `DeviceFeed`'s `device_put`
   (uint8 stays the wire format end-to-end; mean/std + cast run on
   device via `set_input_transform` / `make_normalizer`).

Slab lifetime: `DecodeService.__next__` recycles the PREVIOUS batch's
slot when it is called — by which point every consumer in this repo
(the feed worker places batch N before pulling N+1; the sync path
copies into an NDArray immediately) is done with the view.  Holders
that need a slab longer call `SlabBatch.release()` explicitly when
done (idempotent) and copy what they keep.

Worker death is survivable: a worker that dies mid-epoch is
AUTO-RESPAWNED (up to ``MXNET_IO_WORKER_RESTARTS`` pool-wide, counted
on ``io.decode.worker_restarts``).  The replacement resumes the SAME
(wid, epoch) shard slice at the first undelivered batch — augmentation
RNG derives per (seed, epoch, wid, seq, record), so the resumed stream
is bit-identical to an uninterrupted one and every record is still
decoded exactly once.  Slots the dead worker held are reclaimed
through a shared slot-owner table, so the ring never shrinks.  Past
the respawn budget a dead worker is a hard mid-epoch error, as before.

Corrupt records are QUARANTINED, not fatal (ISSUE 9): with a
``<rec>.crc`` sidecar present (`recordio.write_crc_sidecar`) every
payload is CRC-verified before decode, and a mismatching OR
undecodable record is skipped — the batch ships short, the parent
books ``io.decode.records_corrupt`` + a ring event + a quarantine
JSONL entry naming file/offset — under the pool-wide per-epoch
``MXNET_IO_CORRUPT_BUDGET`` (exceeded → typed
`CorruptRecordBudgetExceeded`).  Per-RECORD RNG derivation is what
keeps the surviving records bit-identical to an uninjected run.

Observability (`monitor.events` + the flight-recorder ring):

    io.decode.batches / records / bytes    volume
    io.decode.wait_us                      consumer wait on the ring
    io.decode.queue_depth                  ready-batch gauge (observe)
    io.decode.epochs                       epochs announced
    io.decode.worker_restarts              dead workers auto-respawned
    io.decode.records_corrupt              records quarantined

A consumer wait above 1 ms lands a `("io", "stall")` event with the
queue depth in the black-box ring, so a dump attributes starvation to
decode (depth 0 here) vs wire/H2D (`feed.stall` with depth 0 there).

Cross-process tracing (ISSUE 11): workers are jax- and telemetry-free
by design, so they cannot emit spans — instead every batch message
carries the decode interval's wall-clock timing (`time.time()` start +
duration), and the CONSUMER re-parents it on delivery: an `io.decode`
span is emitted on the worker's behalf (`telemetry.emit_foreign`) with
the WORKER's pid, parented under the consumer's innermost open span
(the feed span on the e2e path) and stamped with the current global
step.  The delivered `SlabBatch` carries the resulting `TraceContext`
in `.trace`, so downstream stages (DeviceFeed's transfer span) can
join the same trace.  On a merged chrome timeline one training step
therefore shows the worker's decode slice in the worker's own process
row, correlated with the consumer's step.

Degradation: hosts where shared memory or process spawn is unavailable
(sandboxes) raise `DecodeServiceUnavailable` from the constructor;
`ImageRecordIter` catches it, warns ONCE, and continues on the legacy
threaded pipeline — an existing call site never crashes.
"""
from __future__ import annotations

import os
import queue as _queue
import threading
import time
import warnings

import numpy as _np

from .. import config as _cfg
from .. import fault as _fault
from ..integrity import (CorruptRecordBudgetExceeded, RecordCorrupt,
                         checksum_fn)
from ..monitor import events
from .recordio import (idx_sidecar_path, list_record_offsets,
                       read_crc_sidecar, read_record, unpack_img)

__all__ = ["DecodeService", "DecodeServiceUnavailable", "SlabBatch",
           "shard_records", "decode_record", "service_available"]

#: consumer waits above this land in the flight-recorder ring (same
#: threshold as DeviceFeed's feed.stall events)
_STALL_RECORD_US = 1000

#: parent-side timeout ceiling for a wedged pool (a worker that dies
#: without a sentinel must surface as an error, not a hang)
_DRAIN_TIMEOUT_S = 30.0

#: steady-state pull deadline: seconds without ANY worker message
#: before the consumer declares the pool wedged — a child that posted
#: "ready" but then deadlocked (inherited-lock fork hazard, module
#: docstring) is alive, so the dead-worker check never fires; generous
#: because one slot may legitimately take seconds (cold page cache,
#: network filesystems)
_PULL_TIMEOUT_S = 120.0

#: seconds each worker gets to post its startup-handshake "ready" —
#: past this the pool is declared unavailable (→ threaded fallback)
_READY_TIMEOUT_S = 20.0


class DecodeServiceUnavailable(RuntimeError):
    """Shared memory / process spawn unavailable on this host; callers
    fall back to the threaded pipeline."""


# ---------------------------------------------------------------------------
# shard partitioning — pure, deterministic, coordination-free
# ---------------------------------------------------------------------------

def shard_records(n, num_shards, shard_id, epoch=0, shuffle=False,
                  seed=0, batch_size=None):
    """Indices (into the canonical record order) owned by `shard_id`
    for `epoch`.

    Every shard computes the SAME global permutation for
    (seed, epoch) — `RandomState` shuffle is bit-deterministic across
    platforms — then takes its slice with no inter-worker
    communication: the shards are disjoint and their union is exactly
    `range(n)`.  `shuffle=False` keeps the identity order.

    `batch_size=None` slices record-strided (`order[shard_id::N]`).
    With `batch_size=B` the permutation is cut into contiguous
    B-sized blocks and the BLOCKS are strided across shards, so every
    worker emits whole batches and only the worker owning the final
    (short) block emits a partial one — at most ONE ragged batch per
    epoch pool-wide, matching the single-reader pipelines, instead of
    one per worker.  Steps-per-epoch therefore do not change with the
    worker count."""
    if not 0 <= shard_id < num_shards:
        raise ValueError("shard_id %d not in [0, %d)"
                         % (shard_id, num_shards))
    order = _np.arange(n, dtype=_np.int64)
    if shuffle:
        rs = _np.random.RandomState(
            (int(seed) * 1000003 + int(epoch)) % (2 ** 31 - 1))
        rs.shuffle(order)
    if batch_size is None:
        return order[shard_id::num_shards]
    b = int(batch_size)
    if b <= 0:
        raise ValueError("batch_size must be positive")
    blocks = [order[s:s + b]
              for s in range(shard_id * b, n, num_shards * b)]
    return _np.concatenate(blocks) if blocks else order[:0]


# ---------------------------------------------------------------------------
# decode + augment — shared by the worker processes and the threaded
# ImageRecordIter path (one decode semantics, two execution engines)
# ---------------------------------------------------------------------------

def _axis_resize(a, n_out, axis):
    """Triangle-filter resample of one axis (the jax.image.resize
    'linear' semantics: half-pixel centers, antialiased when
    downscaling, edge weights renormalized) as a banded gather —
    a few vectorized adds, NO BLAS: a tensordot here fans out into
    the multithreaded BLAS pool, and one worker quietly eating every
    host core defeats the whole point of worker scaling."""
    n_in = a.shape[axis]
    scale = n_out / n_in
    k = min(scale, 1.0)             # widen the kernel on downscale
    taps = int(_np.ceil(2.0 / k)) + 1
    centers = (_np.arange(n_out) + 0.5) / scale - 0.5
    idx = _np.floor(centers - (taps - 1) / 2.0).astype(_np.int64)
    idx = idx[:, None] + _np.arange(taps)[None, :]      # (n_out, taps)
    w = _np.clip(1.0 - _np.abs((idx - centers[:, None]) * k),
                 0.0, None)
    w *= (idx >= 0) & (idx < n_in)  # out-of-range taps drop, then the
    w /= w.sum(axis=1, keepdims=True)   # row renormalizes (edge rule)
    w = w.astype(_np.float32)
    idx = _np.clip(idx, 0, n_in - 1)
    a = _np.moveaxis(_np.asarray(a, _np.float32), axis, 0)
    bshape = (-1,) + (1,) * (a.ndim - 1)
    out = _np.zeros((n_out,) + a.shape[1:], _np.float32)
    for t in range(taps):
        out += a[idx[:, t]] * w[:, t].reshape(bshape)
    return _np.moveaxis(out, 0, axis)


def _resize_linear(img, size):
    """Bilinear (w, h) resize of an HWC image in pure numpy — NO jax:
    decode-service workers must stay jax-free (a forked child that
    touches the parent's initialized XLA runtime deadlocks in
    backend_compile; module docstring)."""
    w_out, h_out = size
    a = _np.asarray(img, _np.float32)
    if a.ndim == 2:
        a = a[:, :, None]
    return _axis_resize(_axis_resize(a, h_out, 0), w_out, 1)


def decode_record(raw, data_shape, resize, rand_crop, rand_mirror, rng,
                  mean=None, std=None, dtype="uint8", out=None):
    """Decode one packed image record to CHW and return (pixels, label).

    Mirrors the reference augment order (resize short side → crop →
    mirror).  `dtype="uint8"` ships raw pixels (normalize on device);
    `"float32"` applies `mean`/`std` host-side (shape (3,1,1) or None).
    `out` is an optional preallocated CHW array (a shared-memory slab
    row) the pixels are written into."""
    header, img = unpack_img(raw)               # HWC uint8
    c, h, w = data_shape
    if resize > 0:
        short = min(img.shape[:2])
        scale = resize / short
        img = _resize_linear(img, (int(round(img.shape[1] * scale)),
                                   int(round(img.shape[0] * scale))))
    H, W = img.shape[:2]
    if rand_crop and H > h and W > w:
        y0 = rng.randint(0, H - h + 1)
        x0 = rng.randint(0, W - w + 1)
    else:
        y0, x0 = max(0, (H - h) // 2), max(0, (W - w) // 2)
    if H < h or W < w:
        img = _resize_linear(img, (w, h))
        y0 = x0 = 0
    img = img[y0:y0 + h, x0:x0 + w]
    if rand_mirror and rng.rand() < 0.5:
        img = img[:, ::-1]
    label = header.label if hasattr(header.label, "__len__") else \
        _np.float32(header.label)
    chw = img.transpose(2, 0, 1)
    if dtype == "uint8":            # raw pixels on the wire
        if chw.dtype != _np.uint8:  # resize goes through float32
            chw = chw.astype(_np.uint8)
        if out is not None:
            out[:] = chw
            return out, label
        return _np.ascontiguousarray(chw), label
    chw = chw.astype(_np.float32)
    if mean is not None:
        chw = chw - mean
    if std is not None:
        chw = chw / std
    chw = chw.astype(dtype, copy=False)
    if out is not None:
        out[:] = chw
        return out, label
    return _np.ascontiguousarray(chw), label


def _batch_rng(seed, epoch, wid, seq):
    """Augment RNG for ONE batch, derived from (seed, epoch, wid, seq).
    Kept for callers that want a whole-batch stream; the workers now
    derive per RECORD (`_record_rng`) — see its docstring for why."""
    return _np.random.RandomState(
        (int(seed) * 2654435761 + int(epoch) * 1000003 +
         int(wid) * 8191 + int(seq) * 7919 + 1) % (2 ** 31 - 1))


def _record_rng(seed, epoch, wid, seq, j):
    """Augment RNG for ONE record, derived from
    (seed, epoch, wid, seq, record-position-in-batch).

    Deriving per RECORD (not per batch with sequential draws) gives
    two independence properties the integrity layer needs on top of
    the respawn bit-identity the per-batch scheme already had:
    a QUARANTINED record consumes no draws, so the clean records
    around it keep exactly the pixels an uninjected run produces (the
    bit-identical-clean-stream contract) — and a respawned worker
    resuming at batch `seq` still reproduces every record of it."""
    return _np.random.RandomState(
        (int(seed) * 2654435761 + int(epoch) * 1000003 +
         int(wid) * 8191 + int(seq) * 7919 + int(j) * 104729 + 1)
        % (2 ** 31 - 1))


def _write_label(row, label):
    """Scalar or vector label into a float32 (label_width,) slab row."""
    row[:] = 0.0
    if hasattr(label, "__len__"):
        k = min(len(label), row.shape[0])
        row[:k] = _np.asarray(label, _np.float32)[:k]
    else:
        row[0] = float(label)


# ---------------------------------------------------------------------------
# availability probe
# ---------------------------------------------------------------------------

_AVAILABLE = None


def service_available():
    """Whether this host can run the multi-process service: shared
    memory allocates and the configured start method exists.  Probed
    once (tiny segment, immediately unlinked)."""
    global _AVAILABLE
    if _AVAILABLE is None:
        try:
            import multiprocessing as mp
            from multiprocessing import shared_memory
            method = _start_method()
            if method not in mp.get_all_start_methods():
                raise RuntimeError("start method %r unavailable" % method)
            seg = shared_memory.SharedMemory(create=True, size=16)
            seg.close()
            seg.unlink()
            _AVAILABLE = True
        except Exception:           # noqa: BLE001 — any failure means
            _AVAILABLE = False      # "use the threaded pipeline"
    return _AVAILABLE


def _start_method():
    return _cfg.get("MXNET_IO_MP_START", "fork")


# ---------------------------------------------------------------------------
# worker process
# ---------------------------------------------------------------------------

def _attach_shm(name):
    """Attach the parent's segment.  Workers share the parent's
    resource-tracker process (fork and spawn both inherit its fd), and
    its cache is a per-name set — the attach-side register dedupes and
    the parent's single unlink unregisters, so no child-side tracker
    bookkeeping is needed (an explicit child unregister would race the
    siblings' and spam KeyError tracebacks from the tracker)."""
    from multiprocessing import shared_memory
    return shared_memory.SharedMemory(name=name)


def _slot_views(buf, spec):
    """Per-slot (data, label) numpy views over the shared segment."""
    batch = spec["batch"]
    shape = (batch,) + tuple(spec["data_shape"])
    ddt = _np.dtype(spec["dtype"])
    dbytes = int(_np.prod(shape)) * ddt.itemsize
    lbytes = batch * spec["label_width"] * 4
    stride = dbytes + lbytes
    views = []
    for s in range(spec["slots"]):
        off = s * stride
        data = _np.ndarray(shape, dtype=ddt, buffer=buf,
                           offset=off)
        label = _np.ndarray((batch, spec["label_width"]),
                            dtype=_np.float32, buffer=buf,
                            offset=off + dbytes)
        views.append((data, label))
    return views, stride


def _worker_main(wid, spec, ctrl_q, free_q, out_q, cur_epoch,
                 owners=None, corrupt_n=None):
    """Worker process entry: decode this worker's shard of each
    announced epoch into free slab slots.  jax-free by design — only
    numpy/PIL/recordio run here.  `owners` is the shared slot-owner
    table: a worker writes its wid when it acquires a slot, the PARENT
    clears it on message receipt — so a slot held by a worker that died
    is identifiable and reclaimable (auto-respawn).

    `corrupt_n` is the pool-wide per-epoch quarantine counter (a
    lock-free shared int — racy increments can only UNDER-count,
    which errs on the tolerant side of the budget): a record whose
    payload fails its sidecar CRC or whose decode raises is
    QUARANTINED — reported to the parent as a ``("corrupt", ...)``
    message naming file offset and reason, skipped, the batch shipped
    short — until ``MXNET_IO_CORRUPT_BUDGET`` is exceeded, at which
    point the worker fails the epoch loudly (the parent re-raises a
    typed `CorruptRecordBudgetExceeded`)."""
    seg = None
    fh = None
    if os.environ.get("MXNET_IO_WORKER_DEBUG"):
        import faulthandler
        faulthandler.dump_traceback_later(
            20, exit=True,
            file=open("/tmp/decode_worker_%d.trace" % os.getpid(), "w"))
    try:
        seg = _attach_shm(spec["shm"])
        views, _ = _slot_views(seg.buf, spec)
        fh = open(spec["path"], "rb")
        # startup handshake: the parent refuses to trust a pool until
        # every worker proves it came up (a wedged fork must degrade
        # to the threaded pipeline, never hang the consumer)
        out_q.put(("ready", -1, wid))
        offsets = spec["offsets"]
        n = len(offsets)
        workers = spec["workers"]
        batch = spec["batch"]
        mean = spec["mean"]
        std = spec["std"]
        crcs = spec.get("crcs")
        crc_of = checksum_fn(spec["crc_algo"]) \
            if crcs is not None else None
        budget = int(spec.get("corrupt_budget", -1))
        while True:
            cmd = ctrl_q.get()
            if cmd[0] == "stop":
                return
            epoch = cmd[1]
            # a respawned replacement resumes its predecessor's slice
            # at the first UNDELIVERED batch; a fresh epoch starts at 0
            skip = int(cmd[2]) if len(cmd) > 2 else 0
            # batch-block-aligned shard: every worker's slice is a
            # whole number of batches except the one owning the final
            # short block — at most ONE partial batch per epoch
            order = shard_records(n, workers, wid, epoch=epoch,
                                  shuffle=spec["shuffle"],
                                  seed=spec["seed"], batch_size=batch)
            seq = skip
            aborted = False
            slot = None
            try:
                for start in range(skip * batch, len(order), batch):
                    idxs = order[start:start + batch]
                    slot = _acquire_slot(free_q, cur_epoch, epoch)
                    if slot is None:        # epoch aborted (reset)
                        aborted = True
                        break
                    # decode-interval wall clock (time.time(): epoch
                    # time IS comparable across processes, unlike
                    # perf_counter) — rides the batch message so the
                    # consumer can emit this interval as an io.decode
                    # span in THIS worker's process row
                    bt0 = time.time()
                    if owners is not None:
                        owners[slot] = wid
                    dview, lview = views[slot]
                    k = 0           # clean records land compacted
                    for j, ri in enumerate(idxs):
                        try:
                            fh.seek(offsets[ri])
                            # in-flight payload corruption injector
                            # (io.corrupt, fault.py): caught below by
                            # the CRC sidecar or the decoder — the
                            # production quarantine path, not a mock
                            raw = read_record(fh)
                            if raw is None:
                                raise RecordCorrupt(
                                    spec["path"], int(offsets[ri]),
                                    "EOF mid-shard (truncated file)")
                            if _fault.should_fire("io.corrupt"):
                                raw = _fault.flip_bits(raw)
                            if crc_of is not None and \
                                    int(crcs[ri]) >= 0 and \
                                    crc_of(raw) != int(crcs[ri]):
                                raise RecordCorrupt(
                                    spec["path"], int(offsets[ri]),
                                    "payload CRC mismatch")
                            # per-RECORD augment RNG (seed, epoch,
                            # wid, seq, j): bit-identical whether this
                            # record is decoded by the original
                            # worker, a post-crash replacement, or in
                            # a run where its NEIGHBOR was quarantined
                            rng = _record_rng(spec["seed"], epoch,
                                              wid, seq, j)
                            _, label = decode_record(
                                raw, spec["data_shape"],
                                spec["resize"], spec["rand_crop"],
                                spec["rand_mirror"], rng, mean=mean,
                                std=std, dtype=spec["dtype"],
                                out=dview[k])
                        except Exception as e:  # noqa: BLE001 —
                            # quarantine: ONE bad record must not kill
                            # the worker or perturb its clean stream
                            out_q.put((
                                "corrupt", epoch, wid,
                                int(offsets[ri]),
                                ("%s: %s" % (type(e).__name__,
                                             e))[:200]))
                            cn = 1
                            if corrupt_n is not None:
                                cn = corrupt_n.value + 1
                                corrupt_n.value = cn
                            if 0 <= budget < cn:
                                raise CorruptRecordBudgetExceeded(
                                    spec["path"], cn, budget)
                            continue
                        _write_label(lview[k], label)
                        k += 1
                    out_q.put(("batch", epoch, slot, k, wid, seq,
                               bt0, int((time.time() - bt0) * 1e6)))
                    slot = None             # ownership passed on (the
                    seq += 1                # parent clears owners[])
                    if cur_epoch.value != epoch:
                        aborted = True
                        break
            except Exception as e:          # noqa: BLE001 — surfaced
                if slot is not None:        # half-filled slot: return
                    if owners is not None:  # it, don't shrink the ring
                        owners[slot] = -1
                    free_q.put(slot)
                out_q.put(("error", epoch, wid,                # to the
                           "%s: %s" % (type(e).__name__, e)))  # parent
                continue
            out_q.put(("eoe", epoch, wid, seq if not aborted else -1))
    except (KeyboardInterrupt, BrokenPipeError, EOFError):
        pass                        # parent went away; exit quietly
    finally:
        try:
            if fh is not None:
                fh.close()
            if seg is not None:
                seg.close()
        except Exception:           # noqa: BLE001
            pass


def _acquire_slot(free_q, cur_epoch, epoch):
    """Blocking free-slot take that notices an epoch abort (reset):
    returns a slot id, or None when the epoch moved on."""
    while True:
        if cur_epoch.value != epoch:
            return None
        try:
            return free_q.get(timeout=0.05)
        except _queue.Empty:
            continue


# ---------------------------------------------------------------------------
# consumer side
# ---------------------------------------------------------------------------

class SlabBatch:
    """One decoded batch living in a shared-memory slot.

    `data` is the (count, C, H, W) slab view (uint8 or float32),
    `label` the (count, label_width) float32 view.  The views stay
    valid until the slot is recycled — which happens at the NEXT
    `DecodeService.__next__` (or an explicit `release()`).  `wid`/`seq`
    identify the producing worker and its batch ordinal, so a batch
    stream is attributable (and bit-reproducibility testable).
    `trace` (ISSUE 11) is the `telemetry.TraceContext` of the
    `io.decode` span the consumer emitted on the worker's behalf —
    None when telemetry is off — so downstream stages can join the
    same trace."""

    __slots__ = ("data", "label", "count", "wid", "seq", "trace",
                 "_svc", "_slot")

    def __init__(self, data, label, count, wid, seq, svc, slot,
                 trace=None):
        self.data = data
        self.label = label
        self.count = count
        self.wid = wid
        self.seq = seq
        self.trace = trace
        self._svc = svc
        self._slot = slot

    def release(self):
        """Return the slot to the ring (idempotent).  After this the
        `data`/`label` views may be overwritten by a worker."""
        svc, self._svc = self._svc, None
        if svc is not None:
            svc._recycle(self._slot, self)


class DecodeService:
    """Worker-process pool decoding a RecordIO file into a
    shared-memory slab ring (module docstring has the architecture).

    Iteration yields one epoch of `SlabBatch`es; `reset()` advances to
    a fresh epoch (discarding any in-flight batches); re-entering
    `iter()` after exhaustion re-arms the next epoch automatically.
    Batches arrive in worker-completion order — per-epoch record
    coverage (exactly once, disjoint shards) is deterministic, the
    interleaving across workers is not.

    Raises `DecodeServiceUnavailable` when the host cannot run it
    (no shared memory / process spawn) — callers degrade to the
    threaded pipeline."""

    def __init__(self, path_imgrec, batch_size, data_shape, workers=None,
                 label_width=1, shuffle=False, seed=0, resize=-1,
                 rand_crop=False, rand_mirror=False, dtype="uint8",
                 mean=None, std=None, ring_slots=None):
        if dtype not in ("uint8", "float32"):
            raise ValueError("dtype must be 'uint8' or 'float32'")
        if len(data_shape) != 3 or data_shape[0] != 3:
            raise ValueError("data_shape must be (3, H, W)")
        if not service_available():
            raise DecodeServiceUnavailable(
                "shared memory / process spawn unavailable on this host")
        workers = int(workers if workers is not None
                      else _cfg.get("MXNET_IO_WORKERS"))
        if workers < 1:
            raise ValueError("workers must be >= 1")
        self._path = path_imgrec
        self._batch = int(batch_size)
        self._data_shape = tuple(data_shape)
        self._workers_n = workers
        self._label_width = int(label_width)
        self._shuffle = bool(shuffle)
        self._seed = int(seed)
        self._dtype = dtype
        self._offsets = self._resolve_offsets(path_imgrec)
        slots = int(ring_slots if ring_slots is not None
                    else _cfg.get("MXNET_IO_RING_SLOTS"))
        if slots <= 0:
            slots = 2 * workers + 2
        self._slots_n = max(slots, workers + 1)
        # optional integrity sidecar (<rec>.crc): per-record payload
        # CRCs the workers verify before decoding — a mismatch is
        # QUARANTINED (skipped + reported), not decoded into garbage
        crc_algo, crc_arr = None, None
        sidecar = read_crc_sidecar(path_imgrec)
        if sidecar is not None:
            crc_algo, crc_map = sidecar
            checksum_fn(crc_algo)   # unknown algo fails HERE, loudly
            crc_arr = _np.full(len(self._offsets), -1, _np.int64)
            for i, off in enumerate(self._offsets):
                crc_arr[i] = crc_map.get(int(off), -1)
        self._corrupt_budget = int(_cfg.get("MXNET_IO_CORRUPT_BUDGET"))
        self._spec = {
            "path": path_imgrec, "offsets": self._offsets,
            "batch": self._batch, "data_shape": self._data_shape,
            "label_width": self._label_width, "workers": workers,
            "shuffle": self._shuffle, "seed": self._seed,
            "resize": int(resize), "rand_crop": bool(rand_crop),
            "rand_mirror": bool(rand_mirror), "dtype": dtype,
            "mean": None if mean is None else
            _np.asarray(mean, _np.float32).reshape(3, 1, 1),
            "std": None if std is None else
            _np.asarray(std, _np.float32).reshape(3, 1, 1),
            "slots": self._slots_n, "shm": None,
            "crcs": crc_arr, "crc_algo": crc_algo,
            "corrupt_budget": self._corrupt_budget,
        }
        dbytes = int(_np.prod((self._batch,) + self._data_shape)) * \
            _np.dtype(dtype).itemsize
        self._slot_stride = dbytes + self._batch * self._label_width * 4
        self._started = False
        self._closed = False
        self._exhausted = False
        self._consumed = False      # anything pulled from this epoch?
        self._epoch = -1
        self._eoe_wids = set()      # workers done with this epoch
        self._current = None        # SlabBatch the consumer holds
        self._shm = None
        self._procs = []
        self._ctrl = []
        self._free_q = None
        self._out_q = None
        self._cur_epoch = None      # mp.Value workers poll for aborts
        self._owners = None         # shared slot-owner table (respawn)
        self._corrupt_n = None      # pool-wide per-epoch quarantines
        self._delivered = {}        # wid -> batches received this epoch
        self._restarts_left = int(_cfg.get("MXNET_IO_WORKER_RESTARTS"))
        self._lock = threading.Lock()   # slot recycle is cross-thread

    @property
    def num_records(self):
        return len(self._offsets)

    @property
    def workers(self):
        return self._workers_n

    @staticmethod
    def _resolve_offsets(path):
        """One byte offset per record, in canonical order: the .idx
        sidecar's key order when present, else a sequential header
        scan (`list_record_offsets`)."""
        idx_path = idx_sidecar_path(path)
        if os.path.exists(idx_path):
            offsets = []
            with open(idx_path) as fin:
                for line in fin:
                    parts = line.strip().split("\t")
                    if len(parts) >= 2:
                        offsets.append(int(parts[1]))
            if offsets:
                return _np.asarray(offsets, _np.int64)
        # compact int64 array: under spawn the spec is pickled per
        # worker, and a million-record list would ship as python ints
        return _np.asarray(list_record_offsets(path), _np.int64)

    # -- lifecycle -----------------------------------------------------
    def _start(self):
        import multiprocessing as mp
        from multiprocessing import shared_memory
        ctx = mp.get_context(_start_method())
        try:
            self._shm = shared_memory.SharedMemory(
                create=True, size=self._slots_n * self._slot_stride)
        except Exception as e:
            raise DecodeServiceUnavailable(
                "cannot allocate %d-byte shared ring: %s"
                % (self._slots_n * self._slot_stride, e)) from e
        self._spec["shm"] = self._shm.name
        self._views, _ = _slot_views(self._shm.buf, self._spec)
        self._free_q = ctx.Queue()
        self._out_q = ctx.Queue()
        self._cur_epoch = ctx.Value("l", -1, lock=False)
        # slot-owner table: worker writes its wid on slot acquire, the
        # parent clears on delivery — slots a dead worker held are
        # identifiable and reclaimed on respawn (ring never shrinks)
        self._owners = ctx.Array("l", [-1] * self._slots_n, lock=False)
        # pool-wide quarantine counter, lock-free on purpose: a racy
        # lost increment only under-counts toward the budget, and a
        # SIGKILLed worker can never wedge siblings on a Value lock
        self._corrupt_n = ctx.Value("l", 0, lock=False)
        for s in range(self._slots_n):
            self._free_q.put(s)
        try:
            with warnings.catch_warnings():
                # workers are jax-free by design (module docstring);
                # jax's blanket fork warning does not apply to them
                warnings.filterwarnings(
                    "ignore", message=".*os.fork.*",
                    category=RuntimeWarning)
                warnings.filterwarnings(
                    "ignore", message=".*fork.*",
                    category=DeprecationWarning)
                for wid in range(self._workers_n):
                    self._ctrl.append(None)
                    self._procs.append(None)
                    self._spawn_worker(ctx, wid)
        except Exception as e:
            self.close()
            raise DecodeServiceUnavailable(
                "cannot start decode workers: %s" % e) from e
        # startup handshake: every worker must post "ready" before the
        # pool is trusted — a fork that wedged (inherited lock, broken
        # sandbox) degrades to the threaded pipeline instead of
        # hanging the first next()
        ready = set()
        deadline = time.monotonic() + _READY_TIMEOUT_S
        while len(ready) < self._workers_n:
            try:
                msg = self._out_q.get(
                    timeout=min(0.5, max(0.01,
                                         deadline - time.monotonic())))
                if msg[0] == "ready":
                    ready.add(msg[2])
                continue
            except _queue.Empty:
                pass
            dead = [p.name for p in self._procs
                    if p is not None and not p.is_alive()]
            if dead or time.monotonic() > deadline:
                self.close()
                raise DecodeServiceUnavailable(
                    "decode workers failed to start (%d/%d ready; "
                    "dead: %s)" % (len(ready), self._workers_n,
                                   dead or "none, timed out"))
        self._started = True

    def _spawn_worker(self, ctx, wid):
        """Start (or re-start) worker `wid` on a FRESH control queue —
        a respawn must not consume the corpse's stale epoch announce
        (it carries no resume offset)."""
        old = self._ctrl[wid]
        if old is not None:
            try:
                old.cancel_join_thread()
                old.close()
            except Exception:       # noqa: BLE001
                pass
        cq = ctx.Queue()
        p = ctx.Process(
            target=_worker_main,
            args=(wid, self._spec, cq, self._free_q,
                  self._out_q, self._cur_epoch, self._owners,
                  self._corrupt_n),
            daemon=True, name="DecodeWorker-%d" % wid)
        p.start()
        self._ctrl[wid] = cq
        self._procs[wid] = p

    def _respawn(self, dead_wids, resume=True):
        """Worker-death recovery: rebuild the WHOLE pool — every
        worker, on FRESH queues — within the pool-wide restart budget
        (MXNET_IO_WORKER_RESTARTS).  Returns False when the budget
        cannot cover the dead set — the caller then hard-errors, the
        pre-elastic behaviour.

        The rebuild is total because surgical replacement cannot be
        made kill-safe: a hard-killed worker (segfault, OOM kill) can
        die HOLDING an mp.Queue lock — free_q's reader lock (a blocked
        worker spends its life inside ``free_q.get`` holding it) or
        out_q's writer lock — and every survivor sharing that queue
        then wedges forever.  Fresh queues sidestep any poisoned lock;
        the slab ring itself is raw shared memory (lock-free) and
        carries over, as does the slot the consumer currently holds a
        view into.

        Determinism: called only once the out queue is drained (the
        callers detect death from the empty-queue branch), so every
        batch that reached the parent is counted in `self._delivered`.
        Each worker — replacement and survivor alike — resumes its
        (wid, epoch) shard slice at the first undelivered batch;
        per-record RNG derivation (seed, epoch, wid, seq, j) makes the
        resumed streams bit-identical to an uninterrupted run, with
        every record still decoded exactly once."""
        import multiprocessing as mp
        dead_wids = sorted(dead_wids)
        if self._restarts_left < len(dead_wids):
            return False
        self._restarts_left -= len(dead_wids)
        ctx = mp.get_context(_start_method())
        # total teardown: a survivor may be blocked on a lock the
        # corpse died holding — terminate, then kill the stubborn
        for p in self._procs:
            if p is not None and p.is_alive():
                p.terminate()
        for p in self._procs:
            if p is None:
                continue
            p.join(timeout=2.0)
            if p.is_alive():
                p.kill()
                p.join(timeout=1.0)
        for q in (self._free_q, self._out_q):
            try:
                q.cancel_join_thread()
                q.close()
            except Exception:       # noqa: BLE001
                pass
        # fresh data plane: new queues, every slot free again except
        # the one the consumer is holding a view into right now.  The
        # held-slot read and the queue swap are ONE critical section
        # with _recycle (a cross-thread SlabBatch.release racing this
        # rebuild): a release that lands before the swap clears
        # _current — its slot joins the rebuilt queue below; one that
        # lands after targets the NEW queue, whose rebuild excluded
        # the held slot.  Either way the slot survives exactly once.
        with self._lock:
            cur = self._current
            held = cur._slot if cur is not None else -1
            self._free_q = ctx.Queue()
            self._out_q = ctx.Queue()
            self._cur_epoch = ctx.Value("l", self._epoch, lock=False)
            reclaimed = 0
            for s in range(self._slots_n):
                if self._owners[s] >= 0:
                    reclaimed += 1
                self._owners[s] = -1
                if s != held:
                    self._free_q.put(s)
        for wid in range(self._workers_n):
            self._spawn_worker(ctx, wid)
            if resume and self._epoch >= 0 \
                    and wid not in self._eoe_wids:
                self._ctrl[wid].put(
                    ("epoch", self._epoch,
                     int(self._delivered.get(wid, 0))))
        for wid in dead_wids:
            events.incr("io.decode.worker_restarts")
            try:
                from ..telemetry import flightrec as _bb
                _bb.record("io", "worker_restart", wid=int(wid),
                           epoch=int(self._epoch),
                           skip=int(self._delivered.get(wid, 0)),
                           slots_reclaimed=reclaimed,
                           restarts_left=int(self._restarts_left))
            except Exception:       # noqa: BLE001 — forensics only
                pass
        warnings.warn(
            "decode worker(s) %s died; pool rebuilt on fresh queues "
            "(epoch %d resumes at each worker's first undelivered "
            "batch; %d slot(s) reclaimed, %d restart(s) left)"
            % (dead_wids, self._epoch, reclaimed, self._restarts_left),
            RuntimeWarning, stacklevel=3)
        return True

    def close(self):
        """Stop the pool and free the shared ring.  Idempotent; the
        service cannot be restarted after close."""
        if self._closed:
            return
        self._closed = True
        self._exhausted = True
        if self._cur_epoch is not None:
            self._cur_epoch.value = -2      # abort any in-flight epoch
        for cq in self._ctrl:
            try:
                cq.put(("stop",))
            except Exception:       # noqa: BLE001
                pass
        for p in self._procs:
            if p is None:
                continue
            p.join(timeout=2.0)
            if p.is_alive():
                p.terminate()
                p.join(timeout=1.0)
        for q in [self._free_q, self._out_q] + self._ctrl:
            if q is None:
                continue
            try:
                q.cancel_join_thread()
                q.close()
            except Exception:       # noqa: BLE001
                pass
        self._procs = []
        self._ctrl = []
        self._views = None
        self._current = None
        if self._shm is not None:
            try:                    # unlink FIRST: a consumer still
                self._shm.unlink()  # holding a slab view makes close()
            except Exception:       # raise BufferError, and the name
                pass                # must not leak in /dev/shm
            try:
                self._shm.close()
            except Exception:       # noqa: BLE001
                pass
            self._shm = None

    def __del__(self):
        try:
            self.close()
        except Exception:           # noqa: BLE001
            pass

    # -- slot recycling ------------------------------------------------
    def _recycle(self, slot, sb):
        # capture the queue ref INSIDE the lock: _respawn swaps
        # self._free_q under the same lock, so we either target the
        # old queue (discarded — the rebuild re-frees our slot) or the
        # new one (which the rebuild withheld our slot from); putting
        # outside the critical section on a stale ref would leak the
        # slot and shrink the ring
        with self._lock:
            if self._current is sb:
                self._current = None
            q = None if self._closed else self._free_q
        if q is not None:
            try:
                q.put(slot)
            except Exception:       # noqa: BLE001
                pass

    def _release_current(self):
        cur = self._current
        if cur is not None:
            cur.release()

    # -- epoch control -------------------------------------------------
    def reset(self):
        """Advance to a fresh epoch.  In-flight batches of the old one
        are drained and their slots recycled; a no-op when the current
        epoch is freshly announced and nothing was consumed yet (so
        `reset()` followed by `iter()` advances exactly once)."""
        if self._closed:
            raise RuntimeError("DecodeService is closed")
        if not self._started:
            self._start()
        elif not self._consumed and not self._exhausted \
                and self._epoch >= 0:
            return                  # current epoch is still untouched
        self._release_current()
        if self._epoch >= 0 and self._outstanding_alive():
            self._drain_epoch()
        # a worker that died in a previous epoch must be back before
        # the announce, or its shard of the new epoch silently stalls
        dead = [wid for wid in range(self._workers_n)
                if not self._procs[wid].is_alive()]
        if dead and not self._respawn(dead, resume=False):
            self._exhausted = True
            raise RuntimeError(
                "decode worker(s) %s died and the restart budget "
                "(MXNET_IO_WORKER_RESTARTS) is exhausted" % dead)
        self._epoch += 1
        self._eoe_wids = set()
        self._exhausted = False
        self._consumed = False
        self._delivered = {}
        if self._corrupt_n is not None:
            self._corrupt_n.value = 0   # quarantine budget is per-epoch
        self._cur_epoch.value = self._epoch
        for cq in self._ctrl:
            cq.put(("epoch", self._epoch))
        events.incr("io.decode.epochs")

    def _outstanding_alive(self):
        """Live workers that have not posted this epoch's sentinel."""
        return [wid for wid in range(self._workers_n)
                if wid not in self._eoe_wids
                and self._procs[wid].is_alive()]

    def _drain_epoch(self):
        """After aborting an epoch (reset mid-epoch), absorb every
        straggler message and recycle its slot until each live worker
        posted its end-of-epoch sentinel — so the next epoch starts
        with a clean ring and an empty queue."""
        self._cur_epoch.value = -2          # != any announced epoch
        deadline = time.monotonic() + _DRAIN_TIMEOUT_S
        while self._outstanding_alive():
            if time.monotonic() > deadline:
                raise RuntimeError(
                    "decode service: drain timed out (%d/%d workers "
                    "reported)"
                    % (len(self._eoe_wids), self._workers_n))
            try:
                msg = self._out_q.get(timeout=0.2)
            except _queue.Empty:
                continue
            if msg[0] == "batch":
                self._owners[msg[2]] = -1
                self._free_q.put(msg[2])
            elif msg[0] == "corrupt":
                continue            # aborted epoch: not booked
            elif msg[0] in ("eoe", "error") and msg[1] == self._epoch:
                self._eoe_wids.add(msg[2])

    # -- iteration -----------------------------------------------------
    def __iter__(self):
        if not self._started or self._exhausted or self._consumed:
            self.reset()
        return self

    def __next__(self):
        if self._closed:
            raise StopIteration
        if not self._started or self._epoch < 0:
            self.reset()
        if self._exhausted:
            raise StopIteration
        from .. import fault
        fault.maybe_slow("io.slow")
        fault.maybe_raise("io.read", exc_type=fault.InjectedIOError)
        self._consumed = True
        self._release_current()
        t0 = time.perf_counter()
        while True:
            try:
                depth = self._out_q.qsize()
            except (NotImplementedError, OSError):
                depth = -1
            try:
                msg = self._out_q.get(timeout=0.5)
            except _queue.Empty:
                outstanding = [wid for wid in range(self._workers_n)
                               if wid not in self._eoe_wids]
                dead = [wid for wid in outstanding
                        if not self._procs[wid].is_alive()]
                if dead:
                    # a worker owing batches is dead and the queue is
                    # drained (this branch): respawn it resuming its
                    # (wid, epoch) slice at the first undelivered
                    # batch — bit-identical stream, exactly-once
                    # records — unless the budget ran dry, which is
                    # the pre-elastic hard mid-epoch error
                    if self._respawn(dead):
                        t0 = time.perf_counter()    # fresh deadline
                        continue
                    self._exhausted = True
                    raise RuntimeError(
                        "decode worker(s) %s died mid-epoch and the "
                        "restart budget (MXNET_IO_WORKER_RESTARTS) "
                        "is exhausted" % dead)
                if not outstanding:         # all sentinels seen (can
                    self._exhausted = True  # only happen via races)
                    raise StopIteration
                if time.perf_counter() - t0 > _PULL_TIMEOUT_S:
                    # alive-but-wedged pool (a child deadlocked after
                    # its handshake): surface, don't hang the step loop
                    self._exhausted = True
                    raise RuntimeError(
                        "decode service: no batch from worker(s) %s "
                        "for %.0fs — pool wedged (alive but not "
                        "producing)" % (outstanding, _PULL_TIMEOUT_S))
                continue
            tag = msg[0]
            if tag == "ready":      # handshake straggler (restarted
                continue            # pools); consumed in _start
            if tag == "batch" and msg[1] != self._epoch:
                self._owners[msg[2]] = -1   # stale (pre-reset straggler)
                self._free_q.put(msg[2])
                continue
            if tag in ("eoe", "error", "corrupt") and \
                    msg[1] != self._epoch:
                continue
            if tag == "corrupt":
                # a worker quarantined one record: book it — counter,
                # flight-recorder event, quarantine JSONL naming
                # file/offset — and keep pulling (the batch it came
                # from still arrives, just short)
                from .. import integrity as _integ
                _integ.quarantine_record(
                    self._path, msg[3], msg[4],
                    epoch=self._epoch, wid=msg[2])
                continue
            if tag == "eoe":
                self._eoe_wids.add(msg[2])
                if len(self._eoe_wids) >= self._workers_n:
                    self._exhausted = True
                    raise StopIteration
                continue
            if tag == "error":
                self._eoe_wids.add(msg[2])  # the worker left the epoch
                self._exhausted = True
                if str(msg[3]).startswith(
                        "CorruptRecordBudgetExceeded"):
                    # the typed loud failure: the epoch's data is
                    # sick, not blipping (budget counted pool-wide)
                    raise CorruptRecordBudgetExceeded(
                        self._path,
                        int(self._corrupt_n.value)
                        if self._corrupt_n is not None else -1,
                        self._corrupt_budget)
                raise RuntimeError("decode worker %d failed: %s"
                                   % (msg[2], msg[3]))
            if msg[3] == 0:         # batch: every record quarantined —
                self._owners[msg[2]] = -1   # recycle the slot, advance
                self._delivered[msg[4]] = int(msg[5]) + 1   # resume pt
                self._free_q.put(msg[2])
                continue            # keep pulling
            break
        slot, count, wid, seq = msg[2:6]
        # delivery: the slot's owner mark clears (a respawn must not
        # reclaim a slot the consumer holds) and the worker's resume
        # point advances to the batch after this one
        self._owners[slot] = -1
        self._delivered[wid] = int(seq) + 1
        wait_s = time.perf_counter() - t0
        events.add_time("io.decode.wait_us", wait_s)
        if depth >= 0:
            events.observe("io.decode.queue_depth", depth)
        wait_us = int(wait_s * 1e6)
        if wait_us > _STALL_RECORD_US:
            from ..telemetry import flightrec as _bb
            _bb.record("io", "stall", us=wait_us,
                       qdepth=max(depth, 0))
        # cross-process re-parenting (ISSUE 11): the worker reported
        # its decode interval's wall timing in the message; emit it as
        # an io.decode span in the WORKER's process row, parented
        # under the consumer's innermost open span and stamped with
        # the current global step — one bool read when telemetry is
        # off, and pre-ISSUE-11 6-tuple messages (a drain straggler)
        # simply carry no timing
        trace = None
        if len(msg) >= 8:
            from ..telemetry import spans as _tele
            if _tele.enabled():
                proc = self._procs[wid] if wid < len(self._procs) \
                    else None
                ctx = _tele.emit_foreign(
                    "io.decode", msg[6], msg[7] / 1e6,
                    pid=getattr(proc, "pid", None),
                    wid=int(wid), seq=int(seq), epoch=self._epoch,
                    records=int(count))
                if ctx is not None:
                    trace = _tele.TraceContext(
                        ctx.trace_id, ctx.span_id,
                        _tele.get_global_step())
        dview, lview = self._views[slot]
        sb = SlabBatch(dview[:count], lview[:count], count, wid, seq,
                       self, slot, trace=trace)
        with self._lock:
            self._current = sb
        events.incr("io.decode.batches")
        events.incr("io.decode.records", count)
        events.incr("io.decode.bytes",
                    int(sb.data.nbytes) + int(sb.label.nbytes))
        return sb
