"""ctypes bridge to the native C++ image-record pipeline
(src/io/recordio_pipeline.cc — the ImageRecordIOParser2 equivalent).

The shared library is compiled on first use (g++ is part of the
toolchain; libjpeg is the system decoder) and cached next to the source.
`NativeImageRecordReader` hands out (data, label) float32 numpy batches;
ImageRecordIter wraps it with the prefetch thread + device_put."""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as _np

__all__ = ["available", "NativeImageRecordReader", "build_library"]

_LOCK = threading.Lock()
_LIB = None
_TRIED = False

_SRC = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), "src", "io",
    "recordio_pipeline.cc")
_SO = os.path.join(os.path.dirname(_SRC), "libmxtpu_io.so")


def build_library(force=False, src=None, out=None, march_native=True):
    """Compile the pipeline .so (idempotent; also the ONE compile
    recipe setup.py's wheel build calls — keep flags here)."""
    src = src or _SRC
    out = out or _SO
    if os.path.exists(out) and not force and \
            os.path.getmtime(out) >= os.path.getmtime(src):
        return out
    cmd = ["g++", "-O3", "-shared", "-fPIC", "-pthread"]
    if march_native:
        cmd.append("-march=native")
    cmd += [src, "-ljpeg", "-o", out]
    subprocess.run(cmd, check=True, capture_output=True)
    return out


_PACKAGED_SO = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "libmxtpu_io.so")


def _load():
    global _LIB, _TRIED
    with _LOCK:
        if _LIB is not None or _TRIED:
            return _LIB
        _TRIED = True
        # wheel installs ship the prebuilt library as package data
        # (setup.py); in a dev checkout the mtime-checked compile from
        # src/io wins so C++ edits always take effect, and a stale or
        # wrong-arch packaged copy falls back to compiling
        candidates = []
        if os.path.exists(_PACKAGED_SO) and (
                not os.path.exists(_SRC) or
                os.path.getmtime(_PACKAGED_SO) >=
                os.path.getmtime(_SRC)):
            candidates.append(lambda: _PACKAGED_SO)
        if os.path.exists(_SRC):
            candidates.append(build_library)
        lib = None
        for get_so in candidates:
            try:
                lib = ctypes.CDLL(get_so())
                break
            except (OSError, subprocess.CalledProcessError):
                continue
        if lib is None:
            return None
        lib.mxio_create.restype = ctypes.c_void_p
        lib.mxio_create.argtypes = [
            ctypes.c_char_p, ctypes.c_int, ctypes.c_int, ctypes.c_int,
            ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,
            ctypes.c_int, ctypes.c_int,
            ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_float),
            ctypes.c_uint64, ctypes.c_int]
        lib.mxio_num_records.restype = ctypes.c_int64
        lib.mxio_num_records.argtypes = [ctypes.c_void_p]
        lib.mxio_next.restype = ctypes.c_int
        lib.mxio_next.argtypes = [ctypes.c_void_p,
                                  ctypes.POINTER(ctypes.c_float),
                                  ctypes.POINTER(ctypes.c_float)]
        lib.mxio_next_u8.restype = ctypes.c_int
        lib.mxio_next_u8.argtypes = [ctypes.c_void_p,
                                     ctypes.POINTER(ctypes.c_uint8),
                                     ctypes.POINTER(ctypes.c_float)]
        lib.mxio_reset.argtypes = [ctypes.c_void_p]
        lib.mxio_destroy.argtypes = [ctypes.c_void_p]
        _LIB = lib
        return _LIB


def available():
    return _load() is not None


class NativeImageRecordReader:
    """Batch iterator over a .rec file, decoded/augmented in C++ threads.

    Yields (data, label) float32 arrays; data layout NCHW (default) or
    NHWC, already mean/std-normalized."""

    def __init__(self, rec_path, batch_size, data_shape, resize=0,
                 rand_crop=False, rand_mirror=False, shuffle=False,
                 label_width=1, layout="NCHW", mean=None, std=None,
                 seed=0, num_threads=None, dtype="float32"):
        lib = _load()
        if lib is None:
            raise RuntimeError("native io library unavailable")
        self._lib = lib
        if dtype not in ("float32", "uint8"):
            raise ValueError("dtype must be float32 or uint8")
        # uint8: raw augmented pixels, NO mean/std (normalize on the
        # accelerator) — 4x fewer host->device bytes
        self._u8 = dtype == "uint8"
        if self._u8 and (mean or std):
            raise ValueError("uint8 output skips normalization; "
                             "apply mean/std on device")
        if len(data_shape) != 3 or data_shape[0] != 3:
            raise ValueError("data_shape must be (3, H, W)")
        _, h, w = data_shape
        self._batch = batch_size
        self._h, self._w = h, w
        self._label_width = label_width
        self._nchw = layout == "NCHW"
        mean_arr = (ctypes.c_float * 3)(*(mean or (0.0, 0.0, 0.0)))
        std_arr = (ctypes.c_float * 3)(*(std or (1.0, 1.0, 1.0)))
        nthreads = num_threads or min(os.cpu_count() or 8, 16)
        self._h_ptr = lib.mxio_create(
            rec_path.encode(), batch_size, h, w, resize,
            int(rand_crop), int(rand_mirror), int(shuffle),
            label_width, int(self._nchw), mean_arr, std_arr,
            seed, nthreads)
        if not self._h_ptr:
            raise IOError("cannot open record file %r" % rec_path)

    @property
    def num_records(self):
        return self._lib.mxio_num_records(self._h_ptr)

    def reset(self):
        self._lib.mxio_reset(self._h_ptr)

    def next_batch(self):
        """Returns (data, label) with the actual sample count, or None at
        epoch end. Fresh buffers per batch — safe to hand to device_put."""
        from .. import fault
        fault.maybe_slow("io.slow")
        fault.maybe_raise("io.read", exc_type=fault.InjectedIOError)
        shape = ((self._batch, 3, self._h, self._w) if self._nchw
                 else (self._batch, self._h, self._w, 3))
        label = _np.empty((self._batch, self._label_width), _np.float32)
        if self._u8:
            data = _np.empty(shape, _np.uint8)
            n = self._lib.mxio_next_u8(
                self._h_ptr,
                data.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
                label.ctypes.data_as(ctypes.POINTER(ctypes.c_float)))
        else:
            data = _np.empty(shape, _np.float32)
            n = self._lib.mxio_next(
                self._h_ptr,
                data.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                label.ctypes.data_as(ctypes.POINTER(ctypes.c_float)))
        if n == 0:
            return None
        if n < self._batch:
            data = data[:n]
            label = label[:n]
        return data, label

    def __iter__(self):
        while True:
            b = self.next_batch()
            if b is None:
                return
            yield b

    def close(self):
        """Release the native reader handle (idempotent)."""
        if getattr(self, "_h_ptr", None):
            self._lib.mxio_destroy(self._h_ptr)
            self._h_ptr = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
