"""Data iterators (ref: python/mxnet/io/io.py + src/io/*.cc).

DataBatch/DataDesc/DataIter API preserved.  NDArrayIter covers in-memory
data; CSVIter/LibSVMIter read text formats; ImageRecordIter re-creates the
reference's threaded RecordIO → decode → augment → batch → prefetch
pipeline (src/io/iter_image_recordio_2.cc) with a Python thread pool over
the recordio reader (C++ acceleration slots in behind the same class).
"""
from __future__ import annotations

import concurrent.futures
import os
import threading
from collections import namedtuple

import numpy as _np

from ..base import MXNetError
from ..ndarray.ndarray import NDArray
from .. import ndarray as nd

__all__ = ["DataDesc", "DataBatch", "DataIter", "NDArrayIter", "CSVIter",
           "LibSVMIter", "ImageRecordIter", "MNISTIter", "ResizeIter",
           "PrefetchingIter"]


class DataDesc(namedtuple("DataDesc", ["name", "shape", "dtype", "layout"])):
    def __new__(cls, name, shape, dtype=_np.float32, layout="NCHW"):
        return super().__new__(cls, name, tuple(shape), dtype, layout)


class DataBatch:
    def __init__(self, data, label=None, pad=None, index=None,
                 bucket_key=None, provide_data=None, provide_label=None):
        self.data = data if isinstance(data, (list, tuple)) else [data]
        if label is not None and not isinstance(label, (list, tuple)):
            label = [label]
        self.label = label
        self.pad = pad
        self.index = index
        self.bucket_key = bucket_key
        self.provide_data = provide_data
        self.provide_label = provide_label


class DataIter:
    """ref: io.DataIter."""

    def __init__(self, batch_size=0):
        self.batch_size = batch_size

    def __iter__(self):
        return self

    def reset(self):
        pass

    def next(self):
        if self.iter_next():
            return DataBatch(self.getdata(), self.getlabel(),
                             pad=self.getpad(), index=self.getindex())
        raise StopIteration

    def __next__(self):
        return self.next()

    def iter_next(self):
        raise NotImplementedError

    def getdata(self):
        raise NotImplementedError

    def getlabel(self):
        raise NotImplementedError

    def getindex(self):
        return None

    def getpad(self):
        return 0


class NDArrayIter(DataIter):
    """ref: io.NDArrayIter — in-memory arrays with shuffle/pad."""

    def __init__(self, data, label=None, batch_size=1, shuffle=False,
                 last_batch_handle="pad", data_name="data",
                 label_name="softmax_label"):
        super().__init__(batch_size)
        self.data = _init_data(data, False, data_name)
        self.label = _init_data(label, True, label_name)
        self.num_data = self.data[0][1].shape[0] if self.data else 0
        self.shuffle = shuffle
        self.last_batch_handle = last_batch_handle
        self.cursor = -batch_size
        self._order = _np.arange(self.num_data)
        if shuffle:
            _np.random.shuffle(self._order)

    @property
    def provide_data(self):
        return [DataDesc(k, (self.batch_size,) + v.shape[1:],
                         v.dtype) for k, v in self.data]

    @property
    def provide_label(self):
        return [DataDesc(k, (self.batch_size,) + v.shape[1:],
                         v.dtype) for k, v in self.label]

    def reset(self):
        self.cursor = -self.batch_size
        if self.shuffle:
            _np.random.shuffle(self._order)

    def iter_next(self):
        self.cursor += self.batch_size
        if self.last_batch_handle == "discard":
            return self.cursor + self.batch_size <= self.num_data
        return self.cursor < self.num_data

    def _take(self, arrays):
        out = []
        for _, v in arrays:
            idx = self._order[self.cursor:self.cursor + self.batch_size]
            chunk = v[idx]
            if len(idx) < self.batch_size and \
                    self.last_batch_handle == "pad":
                wrap = self._order[:self.batch_size - len(idx)]
                chunk = _np.concatenate([chunk, v[wrap]])
            out.append(nd.array(chunk))
        return out

    def getdata(self):
        return self._take(self.data)

    def getlabel(self):
        return self._take(self.label)

    def getpad(self):
        if self.last_batch_handle == "pad" and \
                self.cursor + self.batch_size > self.num_data:
            return self.cursor + self.batch_size - self.num_data
        return 0


def _init_data(data, allow_empty, default_name):
    if data is None:
        return []
    if isinstance(data, (NDArray, _np.ndarray)):
        data = [(default_name, data)]
    elif isinstance(data, dict):
        data = list(data.items())
    elif isinstance(data, (list, tuple)):
        data = [("%s_%d" % (default_name, i) if len(data) > 1
                 else default_name, d) for i, d in enumerate(data)]
    out = []
    for k, v in data:
        if isinstance(v, NDArray):
            v = v.asnumpy()
        out.append((k, _np.asarray(v)))
    return out


class CSVIter(DataIter):
    """ref: src/io/iter_csv.cc."""

    def __init__(self, data_csv, data_shape, label_csv=None,
                 label_shape=(1,), batch_size=1, round_batch=True,
                 dtype="float32"):
        super().__init__(batch_size)
        data = _np.loadtxt(data_csv, delimiter=",",
                           dtype=dtype).reshape((-1,) + tuple(data_shape))
        label = None
        if label_csv is not None:
            label = _np.loadtxt(label_csv, delimiter=",", dtype=dtype)
            label = label.reshape((-1,) + tuple(label_shape))
        else:
            label = _np.zeros((data.shape[0],) + tuple(label_shape),
                              dtype=dtype)
        self._inner = NDArrayIter(data, label, batch_size,
                                  last_batch_handle="pad"
                                  if round_batch else "discard")

    def reset(self):
        self._inner.reset()

    def next(self):
        return self._inner.next()


class LibSVMIter(DataIter):
    """ref: src/io/iter_libsvm.cc — sparse libsvm text (Wide&Deep). Rows
    come back as CSR (ndarray.sparse.CSRNDArray)."""

    def __init__(self, data_libsvm, data_shape, label_libsvm=None,
                 label_shape=None, batch_size=1, round_batch=True):
        super().__init__(batch_size)
        self._shape = tuple(data_shape)
        self._labels, self._indptr, self._indices, self._values = \
            self._parse(data_libsvm)
        self.num_data = len(self._labels)
        self.cursor = -batch_size

    @staticmethod
    def _parse(path):
        labels, indptr, indices, values = [], [0], [], []
        with open(path) as f:
            for line in f:
                parts = line.strip().split()
                if not parts:
                    continue
                labels.append(float(parts[0]))
                for kv in parts[1:]:
                    k, v = kv.split(":")
                    indices.append(int(k))
                    values.append(float(v))
                indptr.append(len(indices))
        return (_np.asarray(labels, _np.float32),
                _np.asarray(indptr, _np.int64),
                _np.asarray(indices, _np.int64),
                _np.asarray(values, _np.float32))

    def reset(self):
        self.cursor = -self.batch_size

    def iter_next(self):
        self.cursor += self.batch_size
        return self.cursor < self.num_data

    def getdata(self):
        from ..ndarray.sparse import CSRNDArray
        lo = self.cursor
        hi = min(self.cursor + self.batch_size, self.num_data)
        indptr = self._indptr[lo:hi + 1] - self._indptr[lo]
        sl = slice(self._indptr[lo], self._indptr[hi])
        n = hi - lo
        if n < self.batch_size:    # pad with empty rows
            indptr = _np.concatenate(
                [indptr, _np.full(self.batch_size - n, indptr[-1])])
        return [CSRNDArray(self._values[sl], self._indices[sl], indptr,
                           (self.batch_size,) + self._shape)]

    def getlabel(self):
        lo = self.cursor
        hi = min(self.cursor + self.batch_size, self.num_data)
        lab = self._labels[lo:hi]
        if len(lab) < self.batch_size:
            lab = _np.concatenate(
                [lab, _np.zeros(self.batch_size - len(lab), _np.float32)])
        return [nd.array(lab)]


_NO_SERVICE_WARNED = [False]


def _warn_no_decode_service(why):
    """One-time degradation notice (ISSUE 6 satellite): a sandboxed
    host without shared memory / process spawn must keep every
    existing ImageRecordIter call site working on the threaded
    pipeline, not crash."""
    if _NO_SERVICE_WARNED[0]:
        return
    _NO_SERVICE_WARNED[0] = True
    import warnings
    warnings.warn("multi-process decode service unavailable (%s); "
                  "falling back to the threaded input pipeline — "
                  "decode will be slower" % (why,), RuntimeWarning)


class ImageRecordIter(DataIter):
    """ref: src/io/iter_image_recordio_2.cc ImageRecordIOParser2.

    Threaded pipeline: reader (recordio) → pool of decode+augment workers
    → batcher → double-buffered prefetch, mirroring the reference's
    structure; decode via PIL/RAWI (see recordio._decode_img).

    `dtype="uint8"` ships raw augmented pixels (no mean/std — normalize
    on device, 4x fewer H2D bytes).  `ctx=` replaces the synchronous
    upload with an async `io.device_feed.DeviceFeed`: batches arrive
    as device NDArrays, the NEXT batch's transfer overlapped with the
    consumer's step (`feed_depth` buffers, default MXNET_FEED_DEPTH).
    `workers=N` (N ≥ 1; default `MXNET_IO_WORKERS`) decodes on the
    multi-process shared-memory service (`io.decode_service`) — true
    GIL-free parallelism with zero per-batch pickling; unavailable
    hosts (no shared memory / process spawn) warn ONCE and degrade to
    the threaded pipeline below.
    """

    def __init__(self, path_imgrec, data_shape, batch_size, label_width=1,
                 shuffle=False, mean_r=0.0, mean_g=0.0, mean_b=0.0,
                 std_r=1.0, std_g=1.0, std_b=1.0, rand_crop=False,
                 rand_mirror=False, preprocess_threads=4, prefetch_buffer=2,
                 round_batch=True, seed=0, resize=-1, data_name="data",
                 label_name="softmax_label", dtype="float32", ctx=None,
                 feed_depth=None, workers=None, **kwargs):
        super().__init__(batch_size)
        import collections
        from .recordio import (MXIndexedRecordIO, MXRecordIO,
                               idx_sidecar_path)
        self.data_shape = tuple(data_shape)           # (C, H, W)
        self.label_width = label_width
        self._shuffle = shuffle
        self._rand_crop = rand_crop
        self._rand_mirror = rand_mirror
        self._resize = resize
        self._dtype = dtype
        if dtype == "uint8" and (mean_r or mean_g or mean_b or
                                 std_r != 1.0 or std_g != 1.0 or
                                 std_b != 1.0):
            raise ValueError("dtype='uint8' ships raw pixels; apply "
                             "mean/std on device (io.device_feed."
                             "normalize_transform)")
        self._mean = _np.array([mean_r, mean_g, mean_b],
                               dtype=_np.float32).reshape(3, 1, 1)
        self._std = _np.array([std_r, std_g, std_b],
                              dtype=_np.float32).reshape(3, 1, 1)
        self._rng = _np.random.RandomState(seed)
        self._ctx_feed = None
        self._pads = collections.deque()   # FIFO, parallel to the feed

        # multi-process decode service (io/decode_service.py): worker
        # PROCESSES over sharded readers into a shared-memory slab
        # ring — preferred when the caller asks for workers, because
        # it parallelizes decode without the GIL or the optional C++
        # build.  Unavailable hosts degrade to native/threaded below.
        self._service = None
        self._native = None
        self._nat_fut = None
        if workers is None:
            from .. import config as _config
            workers = _config.get("MXNET_IO_WORKERS")
        want_workers = int(workers or 0)
        if want_workers >= 1 and not (dtype in ("float32", "uint8")
                                      and self.data_shape[0] == 3):
            # requested but ineligible: say so — a silent drop to the
            # threaded path misattributes the resulting throughput
            import warnings
            warnings.warn(
                "workers=%d ignored: the decode service handles "
                "3-channel float32/uint8 batches only (got dtype=%r, "
                "data_shape=%r); using the threaded pipeline"
                % (want_workers, dtype, self.data_shape),
                RuntimeWarning)
        if want_workers >= 1 and dtype in ("float32", "uint8") \
                and self.data_shape[0] == 3:
            from . import decode_service as _dsvc
            try:
                svc = _dsvc.DecodeService(
                    path_imgrec, batch_size, self.data_shape,
                    workers=int(workers), label_width=label_width,
                    shuffle=shuffle, seed=seed, resize=resize,
                    rand_crop=rand_crop, rand_mirror=rand_mirror,
                    dtype="uint8" if dtype == "uint8" else "float32",
                    mean=None if dtype == "uint8"
                    else (mean_r, mean_g, mean_b),
                    std=None if dtype == "uint8"
                    else (std_r, std_g, std_b))
                # start the pool NOW, on the calling thread: a host
                # that cannot bring workers up (startup handshake)
                # falls back HERE, where the threaded pipeline is
                # still constructible — not at first next()
                svc.reset()
                self._service = svc
            except _dsvc.DecodeServiceUnavailable as e:
                _warn_no_decode_service(e)
        if self._service is not None:
            if ctx is not None:
                self._make_feed(ctx, feed_depth)
                return
            self.reset()
            return

        # native C++ pipeline (src/io/recordio_pipeline.cc — the
        # ImageRecordIOParser2 equivalent): GIL-free decode+augment.
        # PIL threadpool below is the always-available fallback.
        # A present .crc integrity sidecar OPTS OUT of the native
        # reader: per-record CRC verification + quarantine live in the
        # python/service decode paths (the C++ pipeline decodes
        # internally, record boundaries invisible), and a caller who
        # wrote a sidecar asked for verification, not speed.
        from .recordio import crc_sidecar_path as _crc_side
        has_crc = os.path.exists(_crc_side(path_imgrec))
        if not has_crc and dtype in ("float32", "uint8") \
                and self.data_shape[0] == 3:
            from . import native as _native
            if _native.available():
                try:
                    self._native = _native.NativeImageRecordReader(
                        path_imgrec, batch_size, self.data_shape,
                        resize=max(resize, 0), rand_crop=rand_crop,
                        rand_mirror=rand_mirror, shuffle=shuffle,
                        label_width=label_width,
                        mean=None if dtype == "uint8"
                        else (mean_r, mean_g, mean_b),
                        std=None if dtype == "uint8"
                        else (std_r, std_g, std_b), seed=seed,
                        num_threads=preprocess_threads, dtype=dtype)
                except (IOError, RuntimeError):
                    self._native = None
        if self._native is not None:
            if ctx is not None:
                self._make_feed(ctx, feed_depth)
                return
            self._pool = concurrent.futures.ThreadPoolExecutor(
                max_workers=1)          # prefetch thread (double buffer)
            self._nat_fut = None
            self.reset()
            return

        idx_path = idx_sidecar_path(path_imgrec)
        if os.path.exists(idx_path):
            self._rec = MXIndexedRecordIO(idx_path, path_imgrec, "r")
            self._keys = list(self._rec.keys)
        else:
            self._rec = MXRecordIO(path_imgrec, "r")
            self._keys = None
        # integrity sidecar (<rec>.crc): payload CRCs verified before
        # decode; a mismatching or undecodable record is QUARANTINED
        # (skipped + counted + ledgered) under MXNET_IO_CORRUPT_BUDGET
        from .recordio import read_crc_sidecar
        from ..integrity import checksum_fn
        self._path = path_imgrec
        self._crc_fn = None
        self._crc_map = None
        sidecar = read_crc_sidecar(path_imgrec)
        if sidecar is not None:
            algo, self._crc_map = sidecar
            self._crc_fn = checksum_fn(algo)
        from .. import config as _config
        self._corrupt_budget = int(
            _config.get("MXNET_IO_CORRUPT_BUDGET"))
        self._corrupt_n = 0         # per-epoch quarantine count
        self._pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=preprocess_threads)
        self._prefetch = max(1, prefetch_buffer)
        self._lock = threading.Lock()
        if ctx is not None:
            self._make_feed(ctx, feed_depth)
            return
        self.reset()

    # -- async device feed (ctx= mode) ---------------------------------
    def _make_feed(self, ctx, feed_depth):
        from .device_feed import DeviceFeed
        # callable source: each epoch gets a fresh generator (the feed's
        # reset discards in-flight batches; the generator re-arms the
        # underlying reader and the pad FIFO itself)
        self._ctx_feed = DeviceFeed(self._host_batches, ctx=ctx,
                                    depth=feed_depth)

    def _pad_batch(self, data, label):
        if self.label_width == 1 and label.ndim == 2:
            label = label[:, 0]
        pad = self.batch_size - data.shape[0]
        if pad:
            data = _np.concatenate([data, _np.repeat(
                data[-1:], pad, axis=0)])
            label = _np.concatenate([label, _np.repeat(
                label[-1:], pad, axis=0)])
        return data, label, pad

    @property
    def io_workers(self):
        """Decode parallelism actually in effect: service worker
        PROCESSES, or 0 on the native/threaded paths (bench reports
        this instead of os.cpu_count(), which lied about what the
        pipeline used)."""
        return self._service.workers if self._service is not None else 0

    def close(self):
        """Release this iterator's resources — decode-service pool,
        device feed, native reader, decode thread pool, and the record
        file handle, whichever path is active (idempotent).  The
        iterator cannot be used afterwards."""
        if self._ctx_feed is not None:
            self._ctx_feed.close()
        if self._service is not None:
            self._service.close()
        if self._native is not None:
            self._native.close()
        if getattr(self, "_pool", None) is not None:
            self._pool.shutdown(wait=False)
        if getattr(self, "_rec", None) is not None:
            self._rec.close()

    def _host_batches(self):
        """One epoch of padded host (data, label) batches — the feed's
        source.  Runs on the feed worker thread; pads are queued on a
        FIFO the consumer pops in the same order."""
        self._pads.clear()
        if self._service is not None:
            # shared-memory slabs straight into the feed's device_put:
            # the slab view stays valid until the service's next pull,
            # which the feed only makes AFTER placing this batch (and
            # _place copies first on CPU targets, where device_put
            # aliases host buffers instead of copying)
            for sb in self._service:
                data, label, pad = self._pad_batch(sb.data, sb.label)
                self._pads.append(pad)
                yield data, label
            return
        if self._native is not None:
            self._native.reset()
            while True:
                b = self._native.next_batch()
                if b is None:
                    return
                data, label, pad = self._pad_batch(*b)
                self._pads.append(pad)
                yield data, label
            return
        # python decode path: same epoch bookkeeping as reset()
        if self._keys is not None:
            self._order = list(self._keys)
            if self._shuffle:
                self._rng.shuffle(self._order)
            self._pos = 0
        else:
            self._rec.reset()
        self._corrupt_n = 0
        while True:
            raws = []
            with self._lock:
                for _ in range(self.batch_size):
                    r = self._read_record()
                    if r is None:
                        break
                    raws.append(r)
            if not raws:
                return
            results = [f.result() for f in
                       [self._pool.submit(self._process, r, off)
                        for r, off in raws]]
            results = [r for r in results if r is not None]
            if not results:         # whole batch quarantined: read on
                continue
            data = _np.stack([r[0] for r in results])
            label = _np.stack([r[1] for r in results])
            data, label, pad = self._pad_batch(data, label)
            self._pads.append(pad)
            yield data, label

    def reset(self):
        if self._ctx_feed is not None:
            self._ctx_feed.reset()
            return
        if self._service is not None:
            self._service.reset()
            return
        if self._native is not None:
            # drain the in-flight prefetch first: Pipeline::Reset must
            # not race mxio_next, and an orphaned future would consume
            # (and drop) the new epoch's first batch
            if self._nat_fut is not None:
                self._nat_fut.result()
                self._nat_fut = None
            self._native.reset()
            self._nat_fut = self._pool.submit(self._native.next_batch)
            return
        if self._keys is not None:
            self._order = list(self._keys)
            if self._shuffle:
                self._rng.shuffle(self._order)
            self._pos = 0
        else:
            self._rec.reset()
        self._corrupt_n = 0
        self._pending = []
        self._fill()

    def _read_record(self):
        """One raw record plus its byte offset (the quarantine ledger
        and the CRC sidecar are keyed by offset), or None at epoch
        end."""
        if self._keys is not None:
            if self._pos >= len(self._order):
                return None
            key = self._order[self._pos]
            rec = self._rec.read_idx(key)
            self._pos += 1
            return rec, self._rec.idx[key]
        off = self._rec.tell()
        rec = self._rec.read()
        return None if rec is None else (rec, off)

    def _quarantine(self, offset, reason):
        """Book one corrupt record (counter + ring event + quarantine
        JSONL) and enforce the per-epoch budget — called from pool
        threads, so the budget count rides the reader lock."""
        from .. import integrity as _integ
        _integ.quarantine_record(self._path, offset, reason)
        with self._lock:
            self._corrupt_n += 1
            n = self._corrupt_n
        if 0 <= self._corrupt_budget < n:
            raise _integ.CorruptRecordBudgetExceeded(
                self._path, n, self._corrupt_budget)

    def _process(self, raw, offset=-1):
        # ONE decode+augment implementation for the threaded pool and
        # the decode-service workers (io/decode_service.py) — the two
        # execution engines cannot drift numerically.  Returns None
        # for a QUARANTINED record (CRC mismatch / undecodable).
        from .. import fault
        from .decode_service import decode_record
        from ..integrity import RecordCorrupt
        try:
            if fault.should_fire("io.corrupt"):
                raw = fault.flip_bits(raw)
            if self._crc_fn is not None:
                want = self._crc_map.get(int(offset), -1)
                if want >= 0 and self._crc_fn(raw) != want:
                    raise RecordCorrupt(self._path, offset,
                                        "payload CRC mismatch")
            return decode_record(raw, self.data_shape, self._resize,
                                 self._rand_crop, self._rand_mirror,
                                 self._rng, mean=self._mean,
                                 std=self._std, dtype=self._dtype)
        except Exception as e:      # noqa: BLE001 — one bad record
            # must not kill the epoch (the budget decides that)
            self._quarantine(offset, "%s: %s" % (type(e).__name__, e))
            return None

    def _fill(self):
        while len(self._pending) < self._prefetch:
            raws = []
            with self._lock:
                for _ in range(self.batch_size):
                    r = self._read_record()
                    if r is None:
                        break
                    raws.append(r)
            if not raws:
                break
            futs = [self._pool.submit(self._process, r, off)
                    for r, off in raws]
            self._pending.append(futs)

    def next(self):
        if self._ctx_feed is not None:
            data, label = next(self._ctx_feed)      # device NDArrays;
            pad = self._pads.popleft() if self._pads else 0
            return DataBatch([data], [label], pad=pad)
        if self._service is not None:
            sb = next(self._service)    # StopIteration = epoch end
            data, label, pad = self._pad_batch(sb.data, sb.label)
            if not pad:                 # padding already copied; else
                data = data.copy()      # copy OUT of the slab — CPU-
                label = label.copy()    # backend nd.array aliases host
                                        # buffers, and the slot recycles
                                        # on the service's next pull
            return DataBatch([nd.array(data)], [nd.array(label)],
                             pad=pad)
        if self._native is not None:
            batch = self._nat_fut.result()
            if batch is None:
                raise StopIteration
            self._nat_fut = self._pool.submit(self._native.next_batch)
            data, label, pad = self._pad_batch(*batch)
            return DataBatch([nd.array(data)], [nd.array(label)], pad=pad)
        while True:
            if not self._pending:
                raise StopIteration
            futs = self._pending.pop(0)
            self._fill()
            results = [r for r in (f.result() for f in futs)
                       if r is not None]
            if results:             # an all-quarantined batch is
                break               # skipped, not emitted empty
        data, label, pad = self._pad_batch(
            _np.stack([r[0] for r in results]),
            _np.stack([r[1] for r in results]))
        return DataBatch([nd.array(data)], [nd.array(label)], pad=pad)


class MNISTIter(NDArrayIter):
    """ref: src/io/iter_mnist.cc — reads idx-ubyte files."""

    def __init__(self, image, label, batch_size=128, shuffle=False,
                 flat=False, **kwargs):
        import gzip
        import struct as _struct
        opener = gzip.open if image.endswith(".gz") else open
        with opener(label, "rb") as f:
            _struct.unpack(">II", f.read(8))
            lab = _np.frombuffer(f.read(), dtype=_np.uint8).astype(
                _np.float32)
        with opener(image, "rb") as f:
            _, _, rows, cols = _struct.unpack(">IIII", f.read(16))
            img = _np.frombuffer(f.read(), dtype=_np.uint8).reshape(
                len(lab), rows, cols).astype(_np.float32) / 255.0
        img = img.reshape(len(lab), -1) if flat else \
            img[:, None, :, :]
        super().__init__(img, lab, batch_size, shuffle)


class ResizeIter(DataIter):
    """ref: io.ResizeIter — wraps an iter to a fixed epoch size."""

    def __init__(self, data_iter, size, reset_internal=True):
        super().__init__(data_iter.batch_size)
        self.data_iter = data_iter
        self.size = size
        self.reset_internal = reset_internal
        self.cur = 0

    def reset(self):
        self.cur = 0
        if self.reset_internal:
            self.data_iter.reset()

    def next(self):
        if self.cur >= self.size:
            raise StopIteration
        try:
            batch = self.data_iter.next()
        except StopIteration:
            self.data_iter.reset()
            batch = self.data_iter.next()
        self.cur += 1
        return batch


class PrefetchingIter(DataIter):
    """ref: io.PrefetchingIter — background-thread double buffering."""

    def __init__(self, iters, rename_data=None, rename_label=None):
        if not isinstance(iters, (list, tuple)):
            iters = [iters]
        super().__init__(iters[0].batch_size)
        self.iters = iters
        self._pool = concurrent.futures.ThreadPoolExecutor(max_workers=1)
        self._future = None
        self._prime()

    def _prime(self):
        def fetch():
            try:
                return [it.next() for it in self.iters]
            except StopIteration:
                return None
        self._future = self._pool.submit(fetch)

    def reset(self):
        if self._future is not None:
            self._future.result()
        for it in self.iters:
            it.reset()
        self._prime()

    def next(self):
        got = self._future.result()
        if got is None:
            raise StopIteration
        self._prime()
        if len(got) == 1:
            return got[0]
        return DataBatch(sum([b.data for b in got], []),
                         sum([b.label for b in got], []))
