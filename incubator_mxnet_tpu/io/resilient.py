"""Retrying I/O wrappers — storage blips must not kill a pod-scale run.

`RetryingReader` wraps any reader object (MXRecordIO,
NativeImageRecordReader, a DataIter, or anything with read-ish
methods) and retries transient failures — IOError/OSError and injected
`fault.TransientFault` — with exponential backoff.  Non-transient
errors (corrupt framing raising ValueError, StopIteration) pass
through untouched — and so do PERMANENT IOErrors: corruption
(`integrity.RecordCorrupt`) and the errno classes that cannot heal
with time (ENOENT, EACCES, EISDIR...) fail FAST on the first attempt
instead of burning the whole backoff budget re-reading bytes that
will never change (`NON_RETRYABLE`).

    reader = RetryingReader(MXRecordIO(path, "r"))
    buf = reader.read()          # survives a flaky NFS mount

Retries are counted on `monitor.events` (``io.retry``); budgets come
from MXNET_RETRY_MAX / MXNET_RETRY_BACKOFF (or MXNET_RETRY_BACKOFF_MS)
unless overridden, and the backoff is jittered-exponential — many
readers tripped by the same storage blip must not hammer it back in
lockstep (``retry_transient``'s policy; pass ``jitter=False`` for a
deterministic full-window sleep).
"""
from __future__ import annotations

from .. import fault
from ..integrity import RecordCorrupt
from ..monitor import events

__all__ = ["RetryingReader", "retry_io", "NON_RETRYABLE"]

#: method names proxied WITH retry; everything else proxies straight
#: through (reset/seek mutate position — retrying those is the
#: caller's decision, not a blanket policy)
_RETRIED = ("read", "read_idx", "next_batch", "next", "__next__")

#: permanent I/O failures: matching exceptions fail FAST even though
#: they are (subclasses of) OSError.  Corruption re-read is the same
#: corruption; a missing file does not appear because we slept; a
#: permission error does not self-grant.  Retrying these turns one
#: clear error into MXNET_RETRY_MAX slow copies of it — and a corrupt
#: record retried forever is exactly how a poisoned file turns into a
#: retry storm.
NON_RETRYABLE = (RecordCorrupt, FileNotFoundError, PermissionError,
                 IsADirectoryError, NotADirectoryError)


def retry_io(fn, retries=None, backoff=None, what="io operation",
             jitter=True, non_retryable=NON_RETRYABLE):
    """Run `fn()` under the transient-I/O retry policy.  Injected
    faults fire INSIDE the reader (fault sites io.read / io.slow at the
    actual I/O boundary), so what is retried here is exactly what a
    real storage blip would raise.  `non_retryable` failures
    (corruption, permanent errnos — see `NON_RETRYABLE`) pass through
    on the FIRST attempt."""
    from ..parallel.resilience import retry_transient
    return retry_transient(fn, retries=retries, backoff=backoff,
                           what=what,
                           retryable=(fault.TransientFault, OSError),
                           non_retryable=non_retryable,
                           event="io.retry", jitter=jitter)


class RetryingReader:
    """Transparent retry proxy around a reader object.

    Retried methods re-invoke the underlying call after a transient
    failure; if the wrapped reader exposes `reset()` and a retried
    sequential `read` keeps failing, the caller still owns recovery
    semantics — this wrapper never silently skips records."""

    def __init__(self, reader, retries=None, backoff=None, jitter=True):
        self._reader = reader
        self._retries = retries
        self._backoff = backoff
        self._jitter = jitter

    def __getattr__(self, name):
        attr = getattr(self._reader, name)
        if name in _RETRIED and callable(attr):
            def wrapped(*args, **kw):
                # sequential file readers: remember the position and
                # rewind before every attempt, so a blip AFTER partial
                # consumption (header read, payload failed) retries the
                # whole record instead of resuming mid-stream
                handle = getattr(self._reader, "handle", None)
                pos = None
                if handle is not None and hasattr(handle, "seek"):
                    try:
                        pos = handle.tell()
                    except (OSError, ValueError):
                        pos = None

                def attempt():
                    if pos is not None:
                        handle.seek(pos)
                    return attr(*args, **kw)
                return retry_io(attempt,
                                retries=self._retries,
                                backoff=self._backoff,
                                jitter=self._jitter,
                                what="%s.%s" % (
                                    type(self._reader).__name__, name))
            return wrapped
        return attr

    def __iter__(self):
        it = iter(self._reader)
        while True:
            try:
                yield retry_io(lambda: next(it),
                               retries=self._retries,
                               backoff=self._backoff,
                               jitter=self._jitter,
                               what="%s iteration" % (
                                   type(self._reader).__name__,))
            except StopIteration:
                return

    def __next__(self):
        return retry_io(lambda: next(self._reader),
                        retries=self._retries, backoff=self._backoff,
                        jitter=self._jitter,
                        what="%s next" % (type(self._reader).__name__,))
