"""RecordIO (ref: 3rdparty/dmlc-core/include/dmlc/recordio.h,
src/recordio.cc; python/mxnet/recordio.py).

Byte-compatible implementation of the dmlc RecordIO framing so .rec files
written by reference tooling (tools/im2rec.py) read unchanged:

  each record:  u32 magic (0xced7230a)
                u32 lrecord = (cflag << 29) | length
                payload bytes, zero-padded to 4-byte boundary
  cflag: 0 = whole record, 1/2/3 = begin/middle/end of a split record.

IRHeader packs (flag, label, id, id2) little-endian as the reference's
image-record header (mx.recordio.IRHeader).

A C++ accelerated reader (src/recordio.cc here) backs the threaded
ImageRecordIter; this module is the always-available pure-python path.
"""
from __future__ import annotations

import collections
import os
import struct

import numpy as _np

__all__ = ["MXRecordIO", "MXIndexedRecordIO", "IndexedRecordIO", "IRHeader",
           "pack", "unpack", "pack_img", "unpack_img", "read_record",
           "list_record_offsets", "idx_sidecar_path",
           "crc_sidecar_path", "write_crc_sidecar", "read_crc_sidecar"]

_MAGIC = 0xced7230a
_CFLAG_BITS = 29
_LEN_MASK = (1 << _CFLAG_BITS) - 1


def read_record(fh):
    """Read one framed record from a binary file object positioned at a
    record boundary; returns the payload bytes, or None at EOF.  The
    standalone framing parser — `MXRecordIO.read` adds the fault hooks
    on top, and decode-service workers call this directly on their own
    file handles (no shared state, no fault registry)."""
    header = fh.read(8)
    if len(header) < 8:
        return None
    magic, lrec = struct.unpack("<II", header)
    if magic != _MAGIC:
        raise IOError("invalid RecordIO magic at offset %d"
                      % (fh.tell() - 8))
    cflag = lrec >> _CFLAG_BITS
    length = lrec & _LEN_MASK
    buf = fh.read(length)
    fh.read((-length) % 4)
    if cflag == 0:
        return buf
    # split record: keep reading continuation chunks
    parts = [buf]
    while cflag not in (0, 3):
        header = fh.read(8)
        if len(header) < 8:
            raise IOError("truncated RecordIO: EOF inside a split "
                          "record at offset %d"
                          % (fh.tell() - len(header)))
        magic, lrec = struct.unpack("<II", header)
        if magic != _MAGIC:
            raise IOError("invalid RecordIO magic at offset %d"
                          % (fh.tell() - 8))
        cflag = lrec >> _CFLAG_BITS
        length = lrec & _LEN_MASK
        parts.append(fh.read(length))
        fh.read((-length) % 4)
    return b"".join(parts)


def list_record_offsets(uri):
    """Byte offset of every record in a .rec file, in file order — the
    non-indexed analogue of the .idx sidecar.  One sequential header
    scan (payloads are seek()ed over, not read), so sharded readers
    (io.decode_service) can partition a plain .rec keyspace exactly the
    way an indexed one partitions its keys.  Continuation chunks of a
    split record do not get their own offset."""
    offsets = []
    with open(uri, "rb") as fh:
        while True:
            pos = fh.tell()
            header = fh.read(8)
            if len(header) < 8:
                break
            magic, lrec = struct.unpack("<II", header)
            if magic != _MAGIC:
                raise IOError("invalid RecordIO magic at offset %d" % pos)
            cflag = lrec >> _CFLAG_BITS
            length = lrec & _LEN_MASK
            fh.seek(length + ((-length) % 4), 1)
            if cflag in (0, 1):     # whole record, or head of a split
                offsets.append(pos)
    return offsets


def idx_sidecar_path(uri):
    """Path of the .idx sidecar for a .rec file: the extension swapped
    for '.idx', or appended when the file has none ('/data/train' →
    '/data/train.idx' — a bare rfind('.') would corrupt the name, or
    match a dot in a parent directory)."""
    base, ext = os.path.splitext(uri)
    return (base if ext else uri) + ".idx"


def crc_sidecar_path(uri):
    """Path of the ``.crc`` integrity sidecar for a .rec file —
    ``<uri>.crc`` verbatim (no extension swap: the sidecar names the
    exact file it covers, and a ``train.rec`` / ``train.idx`` pair
    must not collide with ``train.crc`` meaning either)."""
    return str(uri) + ".crc"


def write_crc_sidecar(uri, offsets=None):
    """Write the per-record CRC sidecar for a .rec file: one
    ``offset<TAB>crc`` line per record over the PAYLOAD bytes
    (what `read_record` returns — framing headers and padding are
    already covered by the magic check), headed by an ``#algo=`` line
    naming the checksum in use (`integrity.checksum_algo`).  Readers
    with the sidecar present verify each payload and QUARANTINE
    mismatches instead of decoding garbage pixels.  Returns the
    sidecar path."""
    from ..integrity import checksum, checksum_algo
    if offsets is None:
        offsets = list_record_offsets(uri)
    path = crc_sidecar_path(uri)
    tmp = path + ".tmp"
    with open(uri, "rb") as fh, open(tmp, "w") as out:
        out.write("#algo=%s\n" % checksum_algo())
        for off in offsets:
            fh.seek(int(off))
            payload = read_record(fh)
            if payload is None:
                raise IOError("EOF at offset %d while writing CRC "
                              "sidecar for %s" % (off, uri))
            out.write("%d\t%d\n" % (int(off), checksum(payload)))
    os.replace(tmp, path)
    return path


def read_crc_sidecar(uri):
    """Load a ``.crc`` sidecar: ``(algo, {offset: crc})``, or ``None``
    when the file has none (verification simply stays off).  A
    malformed sidecar raises IOError — half a safety net is worse
    than none."""
    path = crc_sidecar_path(uri)
    if not os.path.isfile(path):
        return None
    algo = None
    crcs = {}
    try:
        with open(path) as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                if line.startswith("#"):
                    if line.startswith("#algo="):
                        algo = line[len("#algo="):]
                    continue
                off, crc = line.split("\t")
                crcs[int(off)] = int(crc)
    except (ValueError, OSError) as e:
        raise IOError("malformed CRC sidecar %s: %s" % (path, e)) from e
    if algo is None:
        raise IOError("CRC sidecar %s missing the #algo= header" % path)
    return algo, crcs


class MXRecordIO:
    """ref: mx.recordio.MXRecordIO — sequential read/write."""

    def __init__(self, uri, flag):
        self.uri = uri
        self.flag = flag
        self.handle = None
        self.open()

    def open(self):
        if self.flag == "w":
            self.handle = open(self.uri, "wb")
            self.writable = True
        elif self.flag == "r":
            self.handle = open(self.uri, "rb")
            self.writable = False
        else:
            raise ValueError("flag must be 'r' or 'w'")
        self.is_open = True

    def close(self):
        if self.is_open:
            self.handle.close()
            self.is_open = False

    def reset(self):
        self.close()
        self.open()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    def __getstate__(self):
        d = dict(self.__dict__)
        d["handle"] = None
        d["is_open"] = False
        return d

    def __setstate__(self, d):
        self.__dict__.update(d)
        self.open()

    def write(self, buf):
        assert self.writable
        length = len(buf)
        self.handle.write(struct.pack("<II", _MAGIC, length & _LEN_MASK))
        self.handle.write(buf)
        pad = (-length) % 4
        if pad:
            self.handle.write(b"\x00" * pad)

    def tell(self):
        return self.handle.tell()

    def read(self):
        assert not self.writable
        from .. import fault
        fault.maybe_slow("io.slow")
        fault.maybe_raise("io.read", exc_type=fault.InjectedIOError)
        return read_record(self.handle)

    def read_at(self, offset):
        """Seek to a byte offset (from `list_record_offsets` or an .idx
        entry) and read the record there."""
        self.handle.seek(offset)
        return self.read()


class MXIndexedRecordIO(MXRecordIO):
    """ref: mx.recordio.MXIndexedRecordIO — .idx 'key\\toffset' sidecar."""

    def __init__(self, idx_path, uri, flag, key_type=int):
        self.idx_path = idx_path
        self.idx = {}
        self.keys = []
        self.key_type = key_type
        self.fidx = None
        super().__init__(uri, flag)

    def open(self):
        super().open()
        self.idx = {}
        self.keys = []
        if self.flag == "r" and os.path.isfile(self.idx_path):
            with open(self.idx_path) as fin:
                for line in fin:
                    parts = line.strip().split("\t")
                    key = self.key_type(parts[0])
                    self.idx[key] = int(parts[1])
                    self.keys.append(key)
        elif self.flag == "w":
            self.fidx = open(self.idx_path, "w")

    def close(self):
        if self.fidx is not None:
            self.fidx.close()
            self.fidx = None
        super().close()

    def seek(self, idx):
        self.handle.seek(self.idx[idx])

    def read_idx(self, idx):
        self.seek(idx)
        return self.read()

    def write_idx(self, idx, buf):
        key = self.key_type(idx)
        pos = self.tell()
        self.write(buf)
        self.fidx.write("%s\t%d\n" % (str(key), pos))
        self.idx[key] = pos
        self.keys.append(key)


IndexedRecordIO = MXIndexedRecordIO

IRHeader = collections.namedtuple("IRHeader", ["flag", "label", "id", "id2"])
_IR_FORMAT = "<IfQQ"
_IR_SIZE = struct.calcsize(_IR_FORMAT)


def pack(header, s):
    """ref: mx.recordio.pack — IRHeader + payload. Multi-label goes as a
    float vector after the header (flag = label count)."""
    header = IRHeader(*header)
    if isinstance(header.label, (int, float)):
        hdr = struct.pack(_IR_FORMAT, 0, float(header.label), header.id,
                          header.id2)
    else:
        label = _np.asarray(header.label, dtype=_np.float32)
        hdr = struct.pack(_IR_FORMAT, label.size, 0.0, header.id,
                          header.id2) + label.tobytes()
    return hdr + s


def unpack(s):
    flag, label, id_, id2 = struct.unpack(_IR_FORMAT, s[:_IR_SIZE])
    s = s[_IR_SIZE:]
    if flag > 0:
        label = _np.frombuffer(s[:flag * 4], dtype=_np.float32)
        s = s[flag * 4:]
    header = IRHeader(flag, label, id_, id2)
    return header, s


def _encode_img(img, fmt=".jpg", quality=95):
    try:
        from PIL import Image
        import io as _io
        if hasattr(img, "asnumpy"):
            img = img.asnumpy()
        im = Image.fromarray(_np.asarray(img).astype(_np.uint8))
        buf = _io.BytesIO()
        im.save(buf, format="JPEG" if fmt in (".jpg", ".jpeg") else "PNG",
                quality=quality)
        return buf.getvalue()
    except ImportError:
        # raw fallback: shape-prefixed uncompressed (decoder detects magic)
        a = _np.asarray(img).astype(_np.uint8)
        return b"RAWI" + struct.pack("<III", *(
            a.shape if a.ndim == 3 else a.shape + (1,))) + a.tobytes()


def _decode_img(buf, flag=1):
    if buf[:4] == b"RAWI":
        h, w, c = struct.unpack("<III", buf[4:16])
        return _np.frombuffer(buf[16:], dtype=_np.uint8).reshape(h, w, c)
    try:
        from PIL import Image
        import io as _io
        im = Image.open(_io.BytesIO(buf))
        if flag == 0:
            im = im.convert("L")
            return _np.asarray(im)[:, :, None]
        im = im.convert("RGB")
        return _np.asarray(im)
    except ImportError:
        raise IOError("cannot decode image: PIL unavailable and payload "
                      "is not RAWI-framed")


def pack_img(header, img, quality=95, img_fmt=".jpg"):
    """ref: mx.recordio.pack_img."""
    return pack(header, _encode_img(img, img_fmt, quality))


def unpack_img(s, iscolor=1):
    header, buf = unpack(s)
    return header, _decode_img(buf, flag=iscolor)
