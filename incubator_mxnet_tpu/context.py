"""Device/context model.

TPU-native re-design of the reference's Context
(ref: include/mxnet/base.h — Context, DevMask, cpu()/gpu()/cpu_pinned()).

Here a Context names a JAX device: ``cpu(i)`` → host platform device i,
``tpu(i)`` → accelerator chip i.  ``gpu(i)`` is kept as a compatibility
alias for ``tpu(i)`` so reference-era scripts run unchanged.  cpu_pinned
and cpu_shared map to plain host memory (PJRT host buffers are already
DMA-able; there is no separate pinned pool to manage).
"""
from __future__ import annotations

import threading
from typing import Optional

from .base import MXNetError

__all__ = ["Context", "cpu", "gpu", "tpu", "cpu_pinned", "cpu_shared",
           "current_context", "num_gpus", "num_tpus", "device"]

_DEVTYPE_CANON = {
    "cpu": "cpu",
    "tpu": "tpu",
    "gpu": "tpu",          # compat alias: reference scripts say gpu()
    "cpu_pinned": "cpu",
    "cpu_shared": "cpu",
}


class Context:
    """A device context. Every NDArray lives on exactly one Context.

    Mirrors the semantics of the reference Context (device_type +
    device_id, usable as `with ctx:` to set the default) but resolves to a
    JAX/PJRT device instead of a CUDA ordinal.
    """

    _default = threading.local()

    def __init__(self, device_type: str, device_id: int = 0):
        if device_type not in _DEVTYPE_CANON:
            raise MXNetError("unknown device type %r" % (device_type,))
        self.device_type = _DEVTYPE_CANON[device_type]
        self._requested_type = device_type
        self.device_id = int(device_id)

    # -- identity ---------------------------------------------------------
    def __eq__(self, other):
        return (isinstance(other, Context)
                and self.device_type == other.device_type
                and self.device_id == other.device_id)

    def __hash__(self):
        return hash((self.device_type, self.device_id))

    def __repr__(self):
        return "%s(%d)" % (self.device_type, self.device_id)

    # -- JAX resolution ---------------------------------------------------
    @property
    def jax_device(self):
        """Resolve to a concrete jax.Device (raises if absent)."""
        import jax
        # Always bind to PROCESS-LOCAL devices: under jax.distributed
        # (dist kvstore workers) jax.devices() is the GLOBAL list and
        # indexing it would hand out other workers' non-addressable
        # devices (ref: each MXNet worker process owns only its own GPUs).
        if self.device_type == "cpu":
            devs = jax.local_devices(backend="cpu") \
                if jax.default_backend() != "cpu" else jax.local_devices()
        else:
            # Virtual-mesh testing: accelerator contexts fall back to
            # host devices so the same test corpus runs everywhere
            # (ref test strategy: tests/python/gpu reruns the CPU corpus).
            devs = jax.local_devices()
        if self.device_id >= len(devs):
            raise MXNetError(
                "context %r: device id %d out of range (%d devices)"
                % (self, self.device_id, len(devs)))
        return devs[self.device_id]

    # -- default-context management --------------------------------------
    def __enter__(self):
        stack = getattr(Context._default, "stack", None)
        if stack is None:
            stack = Context._default.stack = []
        stack.append(self)
        return self

    def __exit__(self, *exc):
        Context._default.stack.pop()

    @staticmethod
    def default_ctx() -> "Context":
        stack = getattr(Context._default, "stack", None)
        if stack:
            return stack[-1]
        return _DEFAULT


def cpu(device_id: int = 0) -> Context:
    return Context("cpu", device_id)


def cpu_pinned(device_id: int = 0) -> Context:
    return Context("cpu_pinned", device_id)


def cpu_shared(device_id: int = 0) -> Context:
    return Context("cpu_shared", device_id)


def tpu(device_id: int = 0) -> Context:
    return Context("tpu", device_id)


def gpu(device_id: int = 0) -> Context:
    """Compatibility alias: resolves to the accelerator (TPU) context."""
    return Context("gpu", device_id)


def device(device_type: str, device_id: int = 0) -> Context:
    return Context(device_type, device_id)


_DEFAULT = Context("cpu", 0)


def current_context() -> Context:
    return Context.default_ctx()


def num_tpus() -> int:
    import jax
    if jax.default_backend() == "cpu":
        return 0
    return len(jax.devices())


def num_gpus() -> int:
    """Compat alias (ref: mx.context.num_gpus) — counts accelerator chips."""
    return num_tpus()
