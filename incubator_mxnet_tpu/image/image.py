"""Legacy image API (ref: python/mxnet/image/image.py — imread/imresize,
augmenters, ImageIter).  Decode via PIL (cv2-free); augmenters are host
numpy, the same role as the reference's OpenCV-based augment chain.
"""
from __future__ import annotations

import numpy as _np

from ..base import MXNetError
from ..ndarray.ndarray import NDArray
from .. import ndarray as nd

__all__ = ["imread", "imdecode", "imresize", "resize_short", "fixed_crop",
           "center_crop", "random_crop", "color_normalize", "HorizontalFlipAug",
           "CastAug", "ColorNormalizeAug", "ResizeAug", "CenterCropAug",
           "RandomCropAug", "CreateAugmenter", "Augmenter", "ImageIter"]


def _as_np(img):
    return img.asnumpy() if isinstance(img, NDArray) else _np.asarray(img)


def imread(filename, flag=1, to_rgb=True):
    try:
        from PIL import Image
    except ImportError:
        raise MXNetError("PIL unavailable — cannot decode %s" % filename)
    im = Image.open(filename)
    im = im.convert("RGB" if flag else "L")
    a = _np.asarray(im)
    if a.ndim == 2:
        a = a[:, :, None]
    return nd.array(a)


def imdecode(buf, flag=1, to_rgb=True):
    from ..io.recordio import _decode_img
    return nd.array(_decode_img(bytes(buf), flag))


def imresize(src, w, h, interp=1):
    from ..gluon.data.vision.transforms import _resize_np
    a = _as_np(src)
    return nd.array(_resize_np(a, (w, h)).astype(a.dtype))


def resize_short(src, size, interp=1):
    a = _as_np(src)
    H, W = a.shape[:2]
    if H > W:
        w, h = size, int(H * size / W)
    else:
        w, h = int(W * size / H), size
    return imresize(src, w, h, interp)


def fixed_crop(src, x0, y0, w, h, size=None, interp=1):
    a = _as_np(src)[y0:y0 + h, x0:x0 + w]
    if size is not None and (w, h) != size:
        from ..gluon.data.vision.transforms import _resize_np
        a = _resize_np(a, size).astype(a.dtype)
    return nd.array(a)


def center_crop(src, size, interp=1):
    a = _as_np(src)
    H, W = a.shape[:2]
    w, h = size
    x0 = max(0, (W - w) // 2)
    y0 = max(0, (H - h) // 2)
    return fixed_crop(src, x0, y0, w, h, size, interp), (x0, y0, w, h)


def random_crop(src, size, interp=1):
    a = _as_np(src)
    H, W = a.shape[:2]
    w, h = size
    x0 = _np.random.randint(0, max(1, W - w + 1))
    y0 = _np.random.randint(0, max(1, H - h + 1))
    return fixed_crop(src, x0, y0, w, h, size, interp), (x0, y0, w, h)


def color_normalize(src, mean, std=None):
    a = _as_np(src).astype(_np.float32)
    a = a - _as_np(mean)
    if std is not None:
        a = a / _as_np(std)
    return nd.array(a)


class Augmenter:
    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def __call__(self, src):
        raise NotImplementedError


class ResizeAug(Augmenter):
    def __init__(self, size, interp=1):
        super().__init__(size=size)
        self.size = size

    def __call__(self, src):
        return resize_short(src, self.size)


class CenterCropAug(Augmenter):
    def __init__(self, size, interp=1):
        super().__init__(size=size)
        self.size = size

    def __call__(self, src):
        return center_crop(src, self.size)[0]


class RandomCropAug(Augmenter):
    def __init__(self, size, interp=1):
        super().__init__(size=size)
        self.size = size

    def __call__(self, src):
        return random_crop(src, self.size)[0]


class HorizontalFlipAug(Augmenter):
    def __init__(self, p=0.5):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src):
        if _np.random.rand() < self.p:
            return nd.array(_np.ascontiguousarray(_as_np(src)[:, ::-1]))
        return src


class CastAug(Augmenter):
    def __init__(self, typ="float32"):
        super().__init__(type=typ)
        self.typ = typ

    def __call__(self, src):
        return nd.array(_as_np(src).astype(self.typ))


class ColorNormalizeAug(Augmenter):
    def __init__(self, mean, std):
        super().__init__()
        self.mean = _np.asarray(mean, _np.float32)
        self.std = _np.asarray(std, _np.float32)

    def __call__(self, src):
        return color_normalize(src, self.mean, self.std)


def CreateAugmenter(data_shape, resize=0, rand_crop=False, rand_resize=False,
                    rand_mirror=False, mean=None, std=None, brightness=0,
                    contrast=0, saturation=0, hue=0, pca_noise=0,
                    rand_gray=0, inter_method=2):
    """ref: image.CreateAugmenter."""
    auglist = []
    if resize > 0:
        auglist.append(ResizeAug(resize))
    crop_size = (data_shape[2], data_shape[1])
    if rand_crop:
        auglist.append(RandomCropAug(crop_size))
    else:
        auglist.append(CenterCropAug(crop_size))
    if rand_mirror:
        auglist.append(HorizontalFlipAug(0.5))
    auglist.append(CastAug())
    if mean is not None or std is not None:
        auglist.append(ColorNormalizeAug(
            mean if mean is not None else _np.zeros(3),
            std if std is not None else _np.ones(3)))
    return auglist


class ImageIter:
    """ref: image.ImageIter — .rec/.lst driven iterator (python layer)."""

    def __init__(self, batch_size, data_shape, path_imgrec=None,
                 path_imglist=None, shuffle=False, aug_list=None, **kwargs):
        from ..io.io import ImageRecordIter
        if path_imgrec is None:
            raise MXNetError("ImageIter currently requires path_imgrec")
        self._inner = ImageRecordIter(path_imgrec, data_shape, batch_size,
                                      shuffle=shuffle, **kwargs)
        self.batch_size = batch_size

    def __iter__(self):
        return self

    def reset(self):
        self._inner.reset()

    def next(self):
        return self._inner.next()

    __next__ = next
