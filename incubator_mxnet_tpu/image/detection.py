"""Detection augmenters (ref: python/mxnet/image/detection.py).

Augmenters transform `(image, label)` pairs where `label` is an
(N, 4+)-array of `[id, xmin, ymin, xmax, ymax, ...]` rows with
normalised [0, 1] coordinates — the reference's SSD training format.
Host-side numpy like the classification augmenters in `image.py`: the
input pipeline runs on CPU workers; only batched tensors reach the TPU.
"""
from __future__ import annotations

import numpy as _np

from .. import ndarray as nd
from .image import (Augmenter, CastAug, ColorNormalizeAug, ResizeAug,
                    _as_np, imresize)

__all__ = ["DetAugmenter", "DetBorrowAug", "DetRandomSelectAug",
           "DetHorizontalFlipAug", "DetRandomCropAug", "DetRandomPadAug",
           "CreateDetAugmenter"]


class DetAugmenter:
    """Base detection augmenter (ref: detection.DetAugmenter)."""

    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def __call__(self, src, label):
        raise NotImplementedError


class DetBorrowAug(DetAugmenter):
    """Wrap an image-only Augmenter; the label passes through
    (ref: detection.DetBorrowAug)."""

    def __init__(self, augmenter):
        super().__init__(augmenter=augmenter.__class__.__name__)
        self.augmenter = augmenter

    def __call__(self, src, label):
        return self.augmenter(src), label


class DetRandomSelectAug(DetAugmenter):
    """Randomly select ONE of the aug candidates (or skip)
    (ref: detection.DetRandomSelectAug)."""

    def __init__(self, aug_list, skip_prob=0.0):
        super().__init__(skip_prob=skip_prob)
        self.aug_list = list(aug_list)
        self.skip_prob = skip_prob

    def __call__(self, src, label):
        if _np.random.rand() < self.skip_prob or not self.aug_list:
            return src, label
        aug = self.aug_list[_np.random.randint(len(self.aug_list))]
        return aug(src, label)


class DetHorizontalFlipAug(DetAugmenter):
    """Flip image and boxes together (ref: detection.DetHorizontalFlipAug)."""

    def __init__(self, p=0.5):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src, label):
        if _np.random.rand() < self.p:
            img = nd.array(_np.ascontiguousarray(_as_np(src)[:, ::-1]))
            label = _np.array(label, dtype=_np.float32, copy=True)
            valid = label[:, 0] >= 0
            x1 = label[valid, 1].copy()
            label[valid, 1] = 1.0 - label[valid, 3]
            label[valid, 3] = 1.0 - x1
            return img, label
        return src, label


def _bbox_overlap(boxes, crop):
    """IoU-with-crop per box; boxes (N,4), crop (4,) in [0,1] coords."""
    ix1 = _np.maximum(boxes[:, 0], crop[0])
    iy1 = _np.maximum(boxes[:, 1], crop[1])
    ix2 = _np.minimum(boxes[:, 2], crop[2])
    iy2 = _np.minimum(boxes[:, 3], crop[3])
    iw = _np.maximum(0.0, ix2 - ix1)
    ih = _np.maximum(0.0, iy2 - iy1)
    inter = iw * ih
    area = (boxes[:, 2] - boxes[:, 0]) * (boxes[:, 3] - boxes[:, 1])
    return inter / _np.maximum(area, 1e-12)


class DetRandomCropAug(DetAugmenter):
    """Random crop keeping boxes whose overlap with the crop meets
    `min_object_covered`; boxes are clipped and renormalised to the
    crop (ref: detection.DetRandomCropAug, the SSD sampling recipe)."""

    def __init__(self, min_object_covered=0.1, aspect_ratio_range=(0.75, 1.33),
                 area_range=(0.05, 1.0), max_attempts=50):
        super().__init__(min_object_covered=min_object_covered)
        self.min_object_covered = min_object_covered
        self.aspect_ratio_range = aspect_ratio_range
        self.area_range = area_range
        self.max_attempts = int(max_attempts)

    def __call__(self, src, label):
        img = _as_np(src)
        H, W = img.shape[:2]
        label = _np.array(label, dtype=_np.float32, copy=True)
        valid = label[:, 0] >= 0
        boxes = label[valid, 1:5]
        for _ in range(self.max_attempts):
            area = _np.random.uniform(*self.area_range)
            ratio = _np.random.uniform(*self.aspect_ratio_range)
            cw = min(1.0, _np.sqrt(area * ratio))
            ch = min(1.0, _np.sqrt(area / ratio))
            cx = _np.random.uniform(0.0, 1.0 - cw)
            cy = _np.random.uniform(0.0, 1.0 - ch)
            crop = _np.array([cx, cy, cx + cw, cy + ch], _np.float32)
            if boxes.size:
                cov = _bbox_overlap(boxes, crop)
                keep = cov >= self.min_object_covered
                if not keep.any():
                    continue
            else:
                keep = _np.zeros((0,), bool)
            x0, y0 = int(cx * W), int(cy * H)
            x1, y1 = int((cx + cw) * W), int((cy + ch) * H)
            if x1 <= x0 or y1 <= y0:
                continue
            out_img = nd.array(_np.ascontiguousarray(img[y0:y1, x0:x1]))
            # renormalise surviving boxes into crop coordinates
            new_rows = []
            vi = _np.where(valid)[0]
            for j, k in zip(vi, range(len(keep))):
                if not keep[k]:
                    continue
                row = label[j].copy()
                bx1 = (max(row[1], crop[0]) - crop[0]) / cw
                by1 = (max(row[2], crop[1]) - crop[1]) / ch
                bx2 = (min(row[3], crop[2]) - crop[0]) / cw
                by2 = (min(row[4], crop[3]) - crop[1]) / ch
                row[1:5] = [bx1, by1, bx2, by2]
                new_rows.append(row)
            if not new_rows and boxes.size:
                continue
            pad = _np.full((label.shape[0] - len(new_rows),
                            label.shape[1]), -1.0, _np.float32)
            new_label = _np.concatenate(
                [_np.array(new_rows, _np.float32).reshape(
                    -1, label.shape[1]), pad], axis=0) \
                if new_rows else pad
            return out_img, new_label
        return src, label


class DetRandomPadAug(DetAugmenter):
    """Pad the image into a larger canvas (zoom-out), shifting boxes
    (ref: detection.DetRandomPadAug)."""

    def __init__(self, aspect_ratio_range=(0.75, 1.33),
                 area_range=(1.0, 3.0), max_attempts=50,
                 pad_val=(127, 127, 127)):
        super().__init__(area_range=area_range)
        self.aspect_ratio_range = aspect_ratio_range
        self.area_range = area_range
        self.max_attempts = int(max_attempts)
        self.pad_val = pad_val

    def __call__(self, src, label):
        img = _as_np(src)
        H, W, C = img.shape
        label = _np.array(label, dtype=_np.float32, copy=True)
        for _ in range(self.max_attempts):
            area = _np.random.uniform(*self.area_range)
            ratio = _np.random.uniform(*self.aspect_ratio_range)
            scale_w = _np.sqrt(area * ratio)
            scale_h = _np.sqrt(area / ratio)
            if scale_w < 1.0 or scale_h < 1.0:
                continue
            newW, newH = int(W * scale_w), int(H * scale_h)
            ox = _np.random.randint(0, newW - W + 1)
            oy = _np.random.randint(0, newH - H + 1)
            canvas = _np.empty((newH, newW, C), img.dtype)
            canvas[...] = _np.asarray(self.pad_val, img.dtype)
            canvas[oy:oy + H, ox:ox + W] = img
            valid = label[:, 0] >= 0
            label[valid, 1] = (label[valid, 1] * W + ox) / newW
            label[valid, 3] = (label[valid, 3] * W + ox) / newW
            label[valid, 2] = (label[valid, 2] * H + oy) / newH
            label[valid, 4] = (label[valid, 4] * H + oy) / newH
            return nd.array(canvas), label
        return src, label


def CreateDetAugmenter(data_shape, resize=0, rand_crop=0, rand_pad=0,
                       rand_mirror=False, mean=None, std=None,
                       min_object_covered=0.1,
                       aspect_ratio_range=(0.75, 1.33),
                       area_range=(0.05, 3.0), max_attempts=50,
                       pad_val=(127, 127, 127)):
    """Standard detection pipeline factory (ref:
    detection.CreateDetAugmenter): optional random crop/pad (probability
    = rand_crop/rand_pad), flip, resize to data_shape, cast+normalise."""
    auglist = []
    if resize > 0:
        auglist.append(DetBorrowAug(ResizeAug(resize)))
    if rand_crop > 0:
        crop = DetRandomCropAug(min_object_covered, aspect_ratio_range,
                                (area_range[0], min(1.0, area_range[1])),
                                max_attempts)
        auglist.append(DetRandomSelectAug([crop], 1 - rand_crop))
    if rand_pad > 0:
        pad = DetRandomPadAug(aspect_ratio_range,
                              (max(1.0, area_range[0]), area_range[1]),
                              max_attempts, pad_val)
        auglist.append(DetRandomSelectAug([pad], 1 - rand_pad))
    if rand_mirror:
        auglist.append(DetHorizontalFlipAug(0.5))
    # final geometry: force to data_shape (H, W from (C, H, W))
    auglist.append(DetBorrowAug(_ForceResizeAug(data_shape[2],
                                                data_shape[1])))
    auglist.append(DetBorrowAug(CastAug()))
    if mean is not None or std is not None:
        mean = mean if mean is not None else _np.zeros(3, _np.float32)
        std = std if std is not None else _np.ones(3, _np.float32)
        auglist.append(DetBorrowAug(ColorNormalizeAug(mean, std)))
    return auglist


class _ForceResizeAug(Augmenter):
    def __init__(self, w, h):
        super().__init__(size=(w, h))
        self._w, self._h = w, h

    def __call__(self, src):
        return imresize(src, self._w, self._h)
