"""`mx.init` alias namespace (ref: python/mxnet/initializer.py is exposed
as both mx.initializer and mx.init)."""
from .initializer import *          # noqa: F401,F403
from .initializer import (Initializer, Zero, One, Constant, Uniform, Normal,
                          Orthogonal, Xavier, MSRAPrelu, Bilinear, LSTMBias,
                          Mixed, InitDesc, create, register)
