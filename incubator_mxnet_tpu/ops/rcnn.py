"""Region-proposal ops for Faster-RCNN
(ref: src/operator/contrib/{proposal.cc, multi_proposal.cc,
proposal_target.cc} — RPN proposal generation + ROI sampling).

TPU conventions (SURVEY §7.2): every output is FIXED-shape; selection
is expressed as top-k + masking (suppressed/invalid entries carry -1s),
matching the reference's own padded-output contract for box_nms.  All
control flow is vectorised lax — no host loops, fully jittable."""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register, alias


def _make_anchors(base_size, scales, ratios):
    """Generate base anchors (ref: proposal.cc GenerateAnchors)."""
    import numpy as np
    base = np.array([1, 1, base_size, base_size], np.float32) - 1
    w = base[2] - base[0] + 1
    h = base[3] - base[1] + 1
    cx = base[0] + 0.5 * (w - 1)
    cy = base[1] + 0.5 * (h - 1)
    anchors = []
    for r in ratios:
        size = w * h
        ws = np.round(np.sqrt(size / r))
        hs = np.round(ws * r)
        for s in scales:
            wss, hss = ws * s, hs * s
            anchors.append([cx - 0.5 * (wss - 1), cy - 0.5 * (hss - 1),
                            cx + 0.5 * (wss - 1), cy + 0.5 * (hss - 1)])
    return np.array(anchors, np.float32)


def _bbox_transform_inv(boxes, deltas):
    """Apply regression deltas to anchors (ref: bbox_transform_inv)."""
    w = boxes[..., 2] - boxes[..., 0] + 1.0
    h = boxes[..., 3] - boxes[..., 1] + 1.0
    cx = boxes[..., 0] + 0.5 * (w - 1.0)
    cy = boxes[..., 1] + 0.5 * (h - 1.0)
    dx, dy, dw, dh = (deltas[..., 0], deltas[..., 1], deltas[..., 2],
                      deltas[..., 3])
    pcx = dx * w + cx
    pcy = dy * h + cy
    pw = jnp.exp(jnp.clip(dw, -10.0, 10.0)) * w
    ph = jnp.exp(jnp.clip(dh, -10.0, 10.0)) * h
    return jnp.stack([pcx - 0.5 * (pw - 1.0), pcy - 0.5 * (ph - 1.0),
                      pcx + 0.5 * (pw - 1.0), pcy + 0.5 * (ph - 1.0)],
                     axis=-1)


_NMS_BLOCK = 256


def _nms_keep(boxes, scores, thresh, topk):
    """Greedy NMS over score-sorted boxes; returns indices into the
    sorted order with -1 padding (fixed length topk).

    TPU-first: a per-box `fori_loop` is a serial chain of N tiny steps
    (the r4 implementation — ~200 ms at N=2000, the whole Faster-RCNN
    step budget).  This is the blocked-exact formulation (the same
    move as TF's TPU non_max_suppression_padded): one (N, N) pairwise
    IoU matrix up front (MXU work), then a sequential loop over
    N/256 BLOCKS; earlier blocks' verdicts are final, so each block
    only needs (a) suppression by decided-alive earlier boxes — one
    masked reduction — and (b) the within-block greedy fixpoint
    `a[j] = a0[j] & !any_i(sup[i, j] & a[i])`, which converges to the
    exact greedy solution in at most chain-depth iterations (a
    `while_loop`, typically 2-5).  Sequential depth falls from N to
    ~N/256 × ~4; results are bit-identical to the per-box loop
    (test_rcnn parity test)."""
    order = jnp.argsort(-scores)
    b = boxes[order]
    n = b.shape[0]
    B = min(_NMS_BLOCK, n)
    npad = ((n + B - 1) // B) * B
    nb = npad // B
    bp = jnp.pad(b, ((0, npad - n), (0, 0)))

    area = jnp.maximum(bp[:, 2] - bp[:, 0] + 1, 0) * \
        jnp.maximum(bp[:, 3] - bp[:, 1] + 1, 0)
    tl = jnp.maximum(bp[:, None, :2], bp[None, :, :2])
    br = jnp.minimum(bp[:, None, 2:4], bp[None, :, 2:4])
    wh = jnp.maximum(br - tl + 1, 0)
    inter = wh[..., 0] * wh[..., 1]
    iou = inter / jnp.maximum(area[:, None] + area[None, :] - inter,
                              1e-12)
    sup = iou > thresh                       # (npad, npad)
    valid = jnp.arange(npad) < n

    def block_body(k, alive):
        lo = k * B
        blk0 = lax.dynamic_slice(alive, (lo,), (B,))
        # (a) suppression by FINAL earlier-box verdicts: cols of this
        # block vs every decided alive box before it
        sup_cols = lax.dynamic_slice(sup, (0, lo), (npad, B))
        decided = (jnp.arange(npad) < lo) & alive
        blk0 = blk0 & ~jnp.any(sup_cols & decided[:, None], axis=0)
        # (b) within-block greedy fixpoint (i < j suppression only)
        m = lax.dynamic_slice(sup, (lo, lo), (B, B)) & \
            (jnp.arange(B)[:, None] < jnp.arange(B)[None, :])

        def fix_cond(st):
            a, prev, it = st
            return jnp.any(a != prev) & (it < B)

        def fix_body(st):
            a, _, it = st
            return (blk0 & ~jnp.any(m & a[:, None], axis=0), a, it + 1)

        a, _, _ = lax.while_loop(
            fix_cond, fix_body,
            (blk0, jnp.zeros_like(blk0), jnp.int32(0)))
        return lax.dynamic_update_slice(alive, a, (lo,))

    keep = lax.fori_loop(0, nb, block_body, valid)
    # first topk kept indices (positions in sorted order), -1 padded
    idx_sorted = jnp.nonzero(keep[:n], size=topk, fill_value=-1)[0]
    return order, idx_sorted


@register("_contrib_Proposal",
          ndarray_inputs=("cls_prob", "bbox_pred", "im_info"),
          differentiable=False, jit=True)
def proposal(cls_prob, bbox_pred, im_info, rpn_pre_nms_top_n=6000,
             rpn_post_nms_top_n=300, threshold=0.7, rpn_min_size=16,
             scales=(4, 8, 16, 32), ratios=(0.5, 1, 2),
             feature_stride=16, output_score=False, iou_loss=False):
    """RPN proposals (ref: proposal.cc).  cls_prob (N, 2A, H, W) —
    second half are foreground scores; bbox_pred (N, 4A, H, W);
    im_info (N, 3) = (height, width, scale).  Output (N*post, 5) rois
    [batch_idx, x1, y1, x2, y2], -1-padded rows beyond the kept set."""
    N, twoA, H, W = cls_prob.shape
    A = twoA // 2
    anchors = jnp.asarray(_make_anchors(feature_stride, scales, ratios))

    shift_x = jnp.arange(W, dtype=jnp.float32) * feature_stride
    shift_y = jnp.arange(H, dtype=jnp.float32) * feature_stride
    sx, sy = jnp.meshgrid(shift_x, shift_y)
    shifts = jnp.stack([sx.ravel(), sy.ravel(), sx.ravel(), sy.ravel()],
                       axis=1)                       # (HW, 4)
    all_anchors = (anchors[None, :, :] +
                   shifts[:, None, :]).reshape(-1, 4)  # (HW*A, 4)

    def per_image(scores_i, deltas_i, info_i):
        # scores: (A, H, W) foreground → (HW*A,)
        fg = scores_i[A:].transpose(1, 2, 0).reshape(-1)
        dl = deltas_i.reshape(A, 4, H, W).transpose(2, 3, 0, 1) \
            .reshape(-1, 4)
        props = _bbox_transform_inv(all_anchors, dl)
        # clip to image
        im_h, im_w = info_i[0], info_i[1]
        props = jnp.stack([
            jnp.clip(props[:, 0], 0, im_w - 1.0),
            jnp.clip(props[:, 1], 0, im_h - 1.0),
            jnp.clip(props[:, 2], 0, im_w - 1.0),
            jnp.clip(props[:, 3], 0, im_h - 1.0)], axis=1)
        # min-size filter
        ws = props[:, 2] - props[:, 0] + 1
        hs = props[:, 3] - props[:, 1] + 1
        min_size = rpn_min_size * info_i[2]
        valid = (ws >= min_size) & (hs >= min_size)
        fg = jnp.where(valid, fg, -1e10)
        # pre-nms top-k
        k = min(rpn_pre_nms_top_n, fg.shape[0])
        top_scores, top_idx = lax.top_k(fg, k)
        top_boxes = props[top_idx]
        # nms → post_nms_top_n
        order, keep = _nms_keep(top_boxes, top_scores, threshold,
                                rpn_post_nms_top_n)
        sorted_boxes = top_boxes[order]
        sorted_scores = top_scores[order]
        sel = jnp.clip(keep, 0, k - 1)
        # min-size-filtered anchors carry the -1e10 sentinel score; when
        # fewer valid proposals survive than post_nms_top_n they must
        # become -1 padding, not leak as real-looking boxes
        ok = (keep >= 0) & (sorted_scores[sel] > -1e9)
        boxes_out = jnp.where(ok[:, None], sorted_boxes[sel], -1.0)
        scores_out = jnp.where(ok, sorted_scores[sel], -1.0)
        return boxes_out, scores_out

    boxes, scores = jax.vmap(per_image)(cls_prob, bbox_pred, im_info)
    batch_idx = jnp.repeat(jnp.arange(N, dtype=jnp.float32),
                           rpn_post_nms_top_n).reshape(
                               N, rpn_post_nms_top_n)
    rois = jnp.concatenate([batch_idx[..., None], boxes], axis=-1) \
        .reshape(N * rpn_post_nms_top_n, 5)
    if output_score:
        return rois, scores.reshape(-1, 1)
    return rois


alias("_contrib_Proposal", "_contrib_MultiProposal")


@register("_contrib_ProposalTarget",
          ndarray_inputs=("rois", "gt_boxes"),
          differentiable=False, num_outputs=4, jit=True)
def proposal_target(rois, gt_boxes, num_classes=21, batch_images=1,
                    batch_rois=128, fg_fraction=0.25, fg_overlap=0.5,
                    box_stds=(0.1, 0.1, 0.2, 0.2)):
    """Sample ROIs into training batches (ref: proposal_target.cc).

    rois (R, 5), gt_boxes (N, G, 5) [x1,y1,x2,y2,cls].  Outputs:
    sampled rois (B, 5), labels (B,), bbox_targets (B, 4*num_classes),
    bbox_weights (B, 4*num_classes) with B = batch_rois total
    (batch_rois // batch_images samples per image, like the reference's
    rois-per-image accounting).  Fixed-shape sampling: top fg_rois by
    overlap, rest background."""
    N = gt_boxes.shape[0]
    per_img = batch_rois // max(batch_images, 1)
    fg_per_img = int(round(per_img * fg_fraction))

    def per_image(i):
        gt = gt_boxes[i]                       # (G, 5)
        gt_valid = gt[:, 4] >= 0
        # append gt boxes as candidate rois (ref proposal_target.cc does
        # this so fg samples exist even before the RPN has learned)
        gt_as_rois = jnp.concatenate(
            [jnp.full((gt.shape[0], 1), i, rois.dtype).astype(rois.dtype),
             gt[:, :4]], axis=1)
        cand = jnp.concatenate([rois, gt_as_rois], axis=0)
        mask = (cand[:, 0] == i.astype(rois.dtype)) & jnp.concatenate(
            [jnp.ones((rois.shape[0],), bool), gt_valid])
        tl = jnp.maximum(cand[:, None, 1:3], gt[None, :, 0:2])
        br = jnp.minimum(cand[:, None, 3:5], gt[None, :, 2:4])
        wh = jnp.maximum(br - tl + 1, 0)
        inter = wh[..., 0] * wh[..., 1]
        area_r = jnp.maximum(cand[:, 3] - cand[:, 1] + 1, 0) * \
            jnp.maximum(cand[:, 4] - cand[:, 2] + 1, 0)
        area_g = jnp.maximum(gt[:, 2] - gt[:, 0] + 1, 0) * \
            jnp.maximum(gt[:, 3] - gt[:, 1] + 1, 0)
        iou = inter / jnp.maximum(
            area_r[:, None] + area_g[None, :] - inter, 1e-12)
        iou = jnp.where(gt_valid[None, :], iou, 0.0)
        max_iou = iou.max(axis=1)
        gt_assign = iou.argmax(axis=1)
        max_iou = jnp.where(mask, max_iou, -1.0)

        is_fg = max_iou >= fg_overlap
        fg_score = jnp.where(is_fg, max_iou, -1e10)
        _, fg_idx = lax.top_k(fg_score, fg_per_img)
        fg_ok = fg_score[fg_idx] > -1e9

        bg_score = jnp.where(mask & ~is_fg, max_iou, -1e10)
        _, bg_idx = lax.top_k(bg_score, per_img - fg_per_img)
        bg_ok = bg_score[bg_idx] > -1e9

        sel = jnp.concatenate([fg_idx, bg_idx])
        sel_fg = jnp.concatenate([fg_ok, jnp.zeros_like(bg_ok)])
        sel_ok = jnp.concatenate([fg_ok, bg_ok])

        r = cand[sel]
        g = gt[gt_assign[sel]]
        labels = jnp.where(sel_fg, g[:, 4] + 1, 0.0)
        labels = jnp.where(sel_ok, labels, -1.0)

        # bbox regression targets (class-specific slots)
        rw = r[:, 3] - r[:, 1] + 1
        rh = r[:, 4] - r[:, 2] + 1
        rcx = r[:, 1] + 0.5 * (rw - 1)
        rcy = r[:, 2] + 0.5 * (rh - 1)
        gw = g[:, 2] - g[:, 0] + 1
        gh = g[:, 3] - g[:, 1] + 1
        gcx = g[:, 0] + 0.5 * (gw - 1)
        gcy = g[:, 1] + 0.5 * (gh - 1)
        stds = jnp.asarray(box_stds, jnp.float32)
        t = jnp.stack([(gcx - rcx) / jnp.maximum(rw, 1) / stds[0],
                       (gcy - rcy) / jnp.maximum(rh, 1) / stds[1],
                       jnp.log(jnp.maximum(gw, 1) /
                               jnp.maximum(rw, 1)) / stds[2],
                       jnp.log(jnp.maximum(gh, 1) /
                               jnp.maximum(rh, 1)) / stds[3]], axis=1)
        cls = jnp.clip(labels, 0, num_classes - 1).astype(jnp.int32)
        targets = jnp.zeros((per_img, 4 * num_classes), jnp.float32)
        weights = jnp.zeros((per_img, 4 * num_classes), jnp.float32)
        cols = cls[:, None] * 4 + jnp.arange(4)[None, :]
        rowi = jnp.arange(per_img)[:, None]
        targets = targets.at[rowi, cols].set(
            jnp.where(sel_fg[:, None], t, 0.0))
        weights = weights.at[rowi, cols].set(
            jnp.where(sel_fg[:, None], 1.0, 0.0))
        return r, labels, targets, weights

    outs = jax.vmap(per_image)(jnp.arange(N, dtype=jnp.int32))
    r, labels, targets, weights = outs
    B = N * per_img
    return (r.reshape(B, 5), labels.reshape(B),
            targets.reshape(B, -1), weights.reshape(B, -1))
