"""Operator registry.

TPU-native re-design of the reference's operator registration model
(ref: nnvm::Op registry + NNVM_REGISTER_OP / FCompute attrs,
src/operator/**; python stubs generated at import in
python/mxnet/ndarray/register.py).

Here every operator is a *pure JAX function* over jax.Array leaves:

    out = fn(*array_args, **params)

plus metadata (number of tensor inputs, differentiability, wrapped-arg
names).  The imperative NDArray stubs, the Symbol front-end, autograd and
hybridize all consume the same registry — a single source of truth exactly
like the reference's op registry, but the "FCompute kernel" is an XLA
computation produced by tracing the pure function (fusion, tiling and
scheduling are the compiler's job; there is no per-op hand kernel except
Pallas ones which register here the same way).
"""
from __future__ import annotations

import functools
from typing import Callable, Dict, Optional, Sequence

__all__ = ["OpDef", "register", "get", "list_ops", "alias"]


class OpDef:
    """Metadata record for one operator."""

    __slots__ = ("name", "fn", "ndarray_inputs", "differentiable",
                 "num_outputs", "visible_outputs", "num_outputs_fn",
                 "doc", "needs_rng", "needs_training", "nograd_argnums",
                 "sparse_invoke")

    def __init__(self, name: str, fn: Callable, *,
                 ndarray_inputs: Optional[Sequence[str]] = None,
                 differentiable: bool = True,
                 num_outputs: int = 1,
                 visible_outputs: Optional[int] = None,
                 num_outputs_fn: Optional[Callable] = None,
                 needs_rng: bool = False,
                 nograd_argnums: Sequence[int] = (),
                 jit: bool = False):
        import inspect
        self.name = name
        if jit:
            fn = _jit_composite(fn)
        self.fn = fn
        self.ndarray_inputs = tuple(ndarray_inputs) if ndarray_inputs else None
        self.differentiable = differentiable
        self.num_outputs = num_outputs
        # NNVM FNumVisibleOutputs analogue: outputs beyond this count
        # are aux-only (e.g. BatchNorm mean/var) — a bare symbol with
        # ONE visible output composes as that output
        self.visible_outputs = (num_outputs if visible_outputs is None
                                else visible_outputs)
        # variadic ops (num_outputs == -1) whose count is statically
        # derivable from attrs provide a resolver attrs -> int so the
        # Symbol layer can build output views (nnvm FNumOutputs)
        self.num_outputs_fn = num_outputs_fn
        try:
            params = inspect.signature(fn).parameters
        except (TypeError, ValueError):
            params = {}
        self.needs_rng = needs_rng or "_rng_key" in params
        self.needs_training = "_training" in params
        self.nograd_argnums = tuple(nograd_argnums)
        # optional FComputeEx-style imperative override: called as
        # sparse_invoke(args, kwargs); returns NotImplemented to fall
        # through to the dense path (ref: FComputeEx dispatch on
        # storage type, src/imperative/imperative_utils.h)
        self.sparse_invoke = None
        self.doc = fn.__doc__

    def __repr__(self):
        return "OpDef(%s)" % self.name


def _jit_composite(fn):
    """Wrap a COMPOSITE op in jax.jit, attrs static.

    Imperative dispatch is eager by design (one primitive ≈ one async
    PJRT program — the engine role, SURVEY §7.0).  That breaks down for
    multi-primitive composite ops (MultiBoxTarget, Proposal, NMS, …):
    eagerly each of their dozens of primitives pays the chip's fixed
    per-program cost.  `jit=True` compiles the whole op to ONE program,
    cached by input shapes + attr values (the FCompute-kernel analogue
    for composites).  Tensor args may be passed as None (optional
    inputs); each None/non-None pattern is part of the cache key via a
    wrapper split."""
    import functools
    import jax

    cache = {}

    @functools.wraps(fn)
    def wrapped(*args, **kwargs):
        arr_pos = tuple(i for i, a in enumerate(args)
                        if isinstance(a, jax.Array))
        # array-valued kwargs (e.g. _rng_key) are traced args, the rest
        # are static attrs in the cache key; lists normalized to tuples.
        # Unhashable statics → eager.
        arr_kw = {k: v for k, v in kwargs.items()
                  if isinstance(v, jax.Array)}
        static_kw = {k: v for k, v in kwargs.items() if k not in arr_kw}
        akey = [(k, tuple(v) if isinstance(v, list) else v)
                for k, v in sorted(static_kw.items())]
        skey = [(i, tuple(args[i]) if isinstance(args[i], list)
                 else args[i])
                for i in range(len(args)) if i not in arr_pos]
        key = (arr_pos, tuple(sorted(arr_kw)), tuple(skey), tuple(akey))
        try:
            cached = cache.get(key)
        except TypeError:           # unhashable static arg
            return fn(*args, **kwargs)
        if cached is None:
            # placeholders at array positions: capturing the first
            # call's device buffers in the closure would pin them in
            # HBM for the cache's lifetime
            template = [None if i in arr_pos else a
                        for i, a in enumerate(args)]

            def call(arrs, akw):
                full = list(template)
                for p, a in zip(arr_pos, arrs):
                    full[p] = a
                return fn(*full, **static_kw, **akw)
            cached = cache[key] = jax.jit(call)
        return cached([args[i] for i in arr_pos], arr_kw)
    return wrapped


_REGISTRY: Dict[str, OpDef] = {}


def register(name: Optional[str] = None, **meta):
    """Decorator: register a pure-jax operator function.

    Usage::

        @register("broadcast_add")
        def broadcast_add(lhs, rhs):
            return jnp.add(lhs, rhs)
    """
    def deco(fn):
        opname = name or fn.__name__
        if opname in _REGISTRY:
            raise ValueError("operator %r already registered" % opname)
        _REGISTRY[opname] = OpDef(opname, fn, **meta)
        return fn
    return deco


def alias(existing: str, *names: str):
    """Register extra names for an existing op (ref: nnvm op aliases,
    e.g. `elemwise_add` vs `_plus`)."""
    od = _REGISTRY[existing]
    for n in names:
        if n in _REGISTRY:
            raise ValueError("operator %r already registered" % n)
        _REGISTRY[n] = od


def get(name: str) -> OpDef:
    return _REGISTRY[name]


def list_ops():
    return sorted(_REGISTRY.keys())
