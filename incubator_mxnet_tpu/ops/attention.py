"""Fused (flash) multi-head self-attention.

TPU-native replacement for the reference's fused attention contrib ops
(ref: src/operator/contrib/transformer.cc `interleaved_matmul_selfatt_qk`
/ `_valatt`, which exist to keep the score matmul inside one kernel).
Here the whole softmax(QK^T)V is ONE Pallas kernel using the online-
softmax (flash) recurrence, so the T×T score matrix never hits HBM:

  grid = (batch*heads, T/bq, T/bk), k-dimension innermost ("arbitrary"),
  VMEM scratch carries (m, l, acc) across k blocks; outputs are written
  on the last k step.  Forward also emits the log-sum-exp row statistics
  so the backward pass can rebuild P = exp(S - lse) block-free in XLA
  (one fused executable; dispatch cost matters more than HBM here, see
  PROFILE.md).

Fallback: plain jnp einsum-softmax path (identical math) when not on a
TPU backend, when shapes don't tile (T % block != 0), or when
MXNET_USE_PALLAS=0.  MXNET_PALLAS_INTERPRET=1 forces the Pallas kernel
in interpreter mode so the CPU test suite exercises the real kernel.
"""
from __future__ import annotations

import functools
import math
import os

import jax
import jax.numpy as jnp

from .registry import register

try:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    _PALLAS_OK = True
except Exception:                                       # pragma: no cover
    pl = pltpu = None
    _PALLAS_OK = False

# jax renamed pltpu.TPUCompilerParams -> CompilerParams across 0.4->0.5;
# resolve whichever this jaxlib ships so the kernels build on both
_CompilerParams = getattr(pltpu, "CompilerParams", None) or \
    getattr(pltpu, "TPUCompilerParams", None) if pltpu is not None \
    else None

__all__ = ["flash_attention", "naive_attention"]

_NEG_INF = -1e30


def _largest_divisor(T, cap):
    """Largest divisor of T that is ≤ cap and a multiple of 8 (TPU
    sublane), or T itself if T ≤ cap."""
    if T <= cap:
        return T
    for b in range(cap, 7, -1):
        if T % b == 0 and b % 8 == 0:
            return b
    return 0


def _block_sizes(T):
    """Measured on this chip (PROFILE.md): per-grid-step overhead is
    ~0.1–0.3 ms, so fewer+bigger blocks win.  Defaults keep the f32
    score block ≤ 8 MB of VMEM."""
    from .. import config as _cfg
    bq = int(_cfg.get("MXNET_FLASH_BLOCK_Q")) \
        or _largest_divisor(T, 1024)
    bk = int(_cfg.get("MXNET_FLASH_BLOCK_K")) \
        or _largest_divisor(T, max(128, (2 * 1024 * 1024) // max(bq, 1)))
    return min(bq, T), min(bk, T)


def _interpret():
    from .. import config as _cfg
    return bool(_cfg.get("MXNET_PALLAS_INTERPRET"))


def _tiles_ok(T, d):
    bq, bk = _block_sizes(T)
    return (bq and bk and T % bq == 0 and T % bk == 0
            and (bq % 8 == 0 or bq == T) and (bk % 8 == 0 or bk == T))


def _pallas_enabled(BH, T, d):
    """Dispatch policy, measured on this chip (see PROFILE.md):
    the one-fused-XLA-program path is HBM-roofline-bound and faster up
    to ~T=4096, but its B·H·T·T f32 score matrix stops compiling well
    before T=8192; the Pallas kernel streams k/v blocks through VMEM
    and keeps working.  MXNET_USE_PALLAS: 0=never, 1=auto (score bytes
    > MXNET_FLASH_AUTO_BYTES), 2=always."""
    from .. import config as _cfg
    mode = _cfg.get("MXNET_USE_PALLAS")
    if mode == "0" or not _PALLAS_OK:
        return False
    if not _tiles_ok(T, d):
        return False
    if _interpret():
        return True
    if jax.default_backend() != "tpu" or d > 256:
        return False
    if mode == "2":
        return True
    auto_bytes = float(_cfg.get("MXNET_FLASH_AUTO_BYTES"))
    return BH * T * T * 4.0 > auto_bytes


# ---------------------------------------------------------------------------
# naive (XLA) reference path — also the backward building block
# ---------------------------------------------------------------------------

def naive_attention(q, k, v, scale, causal=False, bias=None):
    """softmax(q k^T * scale [+ bias]) v over (..., T, d) operands."""
    f32 = jnp.float32
    s = jnp.einsum("...qd,...kd->...qk", q.astype(f32), k.astype(f32))
    s = s * scale
    if bias is not None:
        s = s + bias.astype(f32)
    if causal:
        T, S = s.shape[-2], s.shape[-1]
        mask = jnp.tril(jnp.ones((T, S), bool))
        s = jnp.where(mask, s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("...qk,...kd->...qd", p.astype(q.dtype), v)


# ---------------------------------------------------------------------------
# Pallas flash kernel (forward)
# ---------------------------------------------------------------------------

def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_s, l_s, acc_s, *,
                scale, causal, bq, bk):
    iq = pl.program_id(1)
    ik = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ik == 0)
    def _init():
        m_s[:] = jnp.full(m_s.shape, _NEG_INF, m_s.dtype)
        l_s[:] = jnp.zeros(l_s.shape, l_s.dtype)
        acc_s[:] = jnp.zeros(acc_s.shape, acc_s.dtype)

    # causal: skip k blocks strictly above the diagonal band
    should_run = (ik * bk <= iq * bq + (bq - 1)) if causal else (ik >= 0)

    @pl.when(should_run)
    def _body():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale        # (bq, bk)
        if causal:
            qpos = iq * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            kpos = ik * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
            s = jnp.where(qpos >= kpos, s, _NEG_INF)
        m_prev = m_s[:, :1]                                    # (bq, 1)
        l_prev = l_s[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)                                 # (bq, bk) f32
        alpha = jnp.exp(m_prev - m_new)                        # (bq, 1)
        l_new = l_prev * alpha + jnp.sum(p, axis=1, keepdims=True)
        pv = jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)                # (bq, d)
        acc_s[:] = acc_s[:] * alpha + pv
        m_s[:] = jnp.broadcast_to(m_new, m_s.shape)
        l_s[:] = jnp.broadcast_to(l_new, l_s.shape)

    @pl.when(ik == nk - 1)
    def _done():
        o_ref[0] = (acc_s[:] / l_s[:, :1]).astype(o_ref.dtype)
        # lse replicated across the 128 lanes (TPU tiling needs a full
        # lane-dim block; caller slices [..., 0])
        lse_ref[0] = m_s[:] + jnp.log(l_s[:])


def _flash_fwd(q, k, v, scale, causal):
    """q,k,v: (BH, T, d) → out (BH, T, d), lse (BH, T) f32."""
    BH, T, d = q.shape
    bq, bk = _block_sizes(T)
    grid = (BH, T // bq, T // bk)
    kernel = functools.partial(_fwd_kernel, scale=scale, causal=causal,
                               bq=bq, bk=bk)
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bk, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, bk, d), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bq, 128), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, T, d), q.dtype),
            jax.ShapeDtypeStruct((BH, T, 128), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, 128), jnp.float32),
            pltpu.VMEM((bq, 128), jnp.float32),
            pltpu.VMEM((bq, d), jnp.float32),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=_interpret(),
    )(q, k, v)
    # lse kept lane-replicated (BH, T, 128): the backward kernels read
    # it blockwise without a sublane↔lane transpose
    return out, lse


# ---------------------------------------------------------------------------
# Pallas flash kernels (backward): dq and dk/dv, block recompute from
# the lse residuals — no T×T slab in HBM (FlashAttention-2 schedule)
# ---------------------------------------------------------------------------

def _bwd_block_sizes(T):
    """Smaller slabs than forward: the backward keeps ~4 live (bq, bk)
    f32 intermediates (s, p, dp, ds) in VMEM (~16 MB/core).  Explicit
    MXNET_FLASH_BLOCK_Q/K overrides apply here too."""
    from .. import config as _cfg
    bq = int(_cfg.get("MXNET_FLASH_BLOCK_Q")) or _largest_divisor(T, 512)
    bk = int(_cfg.get("MXNET_FLASH_BLOCK_K")) or \
        _largest_divisor(T, max(128, (1024 * 1024) // max(bq, 1)))
    return min(bq, T), min(bk, T)


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, o_ref, lse_ref, dq_ref,
               dq_s, dD_s, *, scale, causal, bq, bk):
    iq = pl.program_id(1)
    ik = pl.program_id(2)
    nk = pl.num_programs(2)
    f32 = jnp.float32

    @pl.when(ik == 0)
    def _init():
        dq_s[:] = jnp.zeros(dq_s.shape, f32)
        do32 = do_ref[0].astype(f32)
        o32 = o_ref[0].astype(f32)
        dD_s[:] = jnp.broadcast_to(
            jnp.sum(do32 * o32, axis=-1, keepdims=True), dD_s.shape)

    should_run = (ik * bk <= iq * bq + (bq - 1)) if causal else (ik >= 0)

    @pl.when(should_run)
    def _body():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=f32) * scale                # (bq, bk)
        if causal:
            qpos = iq * bq + jax.lax.broadcasted_iota(jnp.int32,
                                                      (bq, bk), 0)
            kpos = ik * bk + jax.lax.broadcasted_iota(jnp.int32,
                                                      (bq, bk), 1)
            s = jnp.where(qpos >= kpos, s, _NEG_INF)
        p = jnp.exp(s - lse_ref[0][:, :1])                     # (bq, bk)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=f32)                        # (bq, bk)
        ds = p * (dp - dD_s[:, :1]) * scale
        dq_s[:] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=f32)                        # (bq, d)

    @pl.when(ik == nk - 1)
    def _done():
        dq_ref[0] = dq_s[:].astype(dq_ref.dtype)


def _dkv_kernel(k_ref, v_ref, q_ref, do_ref, o_ref, lse_ref, dk_ref,
                dv_ref, dk_s, dv_s, *, scale, causal, bq, bk):
    jk = pl.program_id(1)
    iq = pl.program_id(2)
    nq = pl.num_programs(2)
    f32 = jnp.float32

    @pl.when(iq == 0)
    def _init():
        dk_s[:] = jnp.zeros(dk_s.shape, f32)
        dv_s[:] = jnp.zeros(dv_s.shape, f32)

    # causal: q blocks entirely above the diagonal contribute nothing
    should_run = (iq * bq + (bq - 1) >= jk * bk) if causal else (iq >= 0)

    @pl.when(should_run)
    def _body():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=f32) * scale                # (bq, bk)
        if causal:
            qpos = iq * bq + jax.lax.broadcasted_iota(jnp.int32,
                                                      (bq, bk), 0)
            kpos = jk * bk + jax.lax.broadcasted_iota(jnp.int32,
                                                      (bq, bk), 1)
            s = jnp.where(qpos >= kpos, s, _NEG_INF)
        p = jnp.exp(s - lse_ref[0][:, :1])                     # (bq, bk)
        # dv += p^T do — contraction over the q (sublane) dim
        dv_s[:] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=f32)                        # (bk, d)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=f32)                        # (bq, bk)
        D = jnp.sum(do.astype(f32) * o_ref[0].astype(f32), axis=-1,
                    keepdims=True)                             # (bq, 1)
        ds = p * (dp - D) * scale
        dk_s[:] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=f32)                        # (bk, d)

    @pl.when(iq == nq - 1)
    def _done():
        dk_ref[0] = dk_s[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_s[:].astype(dv_ref.dtype)


def _flash_bwd_pallas(q, k, v, out, lse, do, scale, causal):
    """dq/dk/dv via two Pallas kernels (dq: k-inner; dkv: q-inner)."""
    BH, T, d = q.shape
    bq, bk = _bwd_block_sizes(T)
    interp = _interpret()

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, scale=scale, causal=causal,
                          bq=bq, bk=bk),
        grid=(BH, T // bq, T // bk),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bk, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, bk, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bq, 128), lambda b, i, j: (b, i, 0)),
        ],
        out_specs=pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((BH, T, d), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((bq, d), jnp.float32),
            pltpu.VMEM((bq, 128), jnp.float32),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interp,
    )(q, k, v, do, out, lse)

    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, scale=scale, causal=causal,
                          bq=bq, bk=bk),
        grid=(BH, T // bk, T // bq),
        in_specs=[
            pl.BlockSpec((1, bk, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, bk, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, bq, d), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, bq, d), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, bq, d), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, bq, 128), lambda b, j, i: (b, i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bk, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, bk, d), lambda b, j, i: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, T, d), k.dtype),
            jax.ShapeDtypeStruct((BH, T, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((bk, d), jnp.float32),
            pltpu.VMEM((bk, d), jnp.float32),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interp,
    )(k, v, q, do, out, lse)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# custom VJP: pallas forward, fused-XLA backward from lse residuals
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _flash_attention(q, k, v, scale, causal):
    out, _ = _flash_fwd(q, k, v, scale, causal)
    return out


def _flash_attention_fwd(q, k, v, scale, causal):
    out, lse = _flash_fwd(q, k, v, scale, causal)
    # persist only the (BH, T) column — XLA DCEs the replicated lanes;
    # the backward re-broadcasts transiently for the kernels
    return out, (q, k, v, out, lse[..., 0])


def _flash_attention_bwd(scale, causal, res, do):
    """Backward from the saved lse row statistics: P = exp(S - lse)
    rebuilt blockwise.  Default path is the Pallas dq/dkv kernel pair —
    O(T·d) HBM traffic like the forward, which is what makes seq-4k/8k
    training fit (VERDICT r3 #4).  MXNET_FLASH_BWD_PALLAS=0 falls back
    to a fused-XLA `lax.scan` whose live score slab is bounded by
    MXNET_FLASH_BWD_BYTES."""
    q, k, v, out, lse = res
    from .. import config as _cfg
    mode = _cfg.get("MXNET_FLASH_BWD_PALLAS")
    if mode != "0":
        BH_, T_, _ = q.shape
        bq, bk = _bwd_block_sizes(T_)
        # measured on this chip (PROFILE.md): the fused-XLA path wins
        # under grid overhead at short T; Pallas wins once the score
        # slab outgrows MXNET_FLASH_BWD_BYTES (and is the only path
        # whose HBM stays O(T·d) at seq 4k/8k)
        want = (mode == "2" or
                BH_ * T_ * T_ * 4.0 >
                float(_cfg.get("MXNET_FLASH_BWD_BYTES")))
        if want and bq and bk and T_ % bq == 0 and T_ % bk == 0:
            lse128 = jnp.broadcast_to(lse[..., None],
                                      (BH_, T_, 128))
            return _flash_bwd_pallas(q, k, v, out, lse128, do,
                                     scale, causal)
    BH, T, d = q.shape
    f32 = jnp.float32
    qf, kf, vf, dof = (t.astype(f32) for t in (q, k, v, do))
    D = jnp.sum(dof * out.astype(f32), axis=-1, keepdims=True)  # (BH, T, 1)

    limit = float(_cfg.get("MXNET_FLASH_BWD_BYTES"))
    bk = T
    while BH * T * bk * 4.0 > limit and bk % 2 == 0:
        bk //= 2
    nk = T // bk

    def block_grads(kb, vb, k0):
        s = jnp.einsum("bqd,bkd->bqk", qf, kb) * scale
        if causal:
            qpos = jnp.arange(T)[:, None]
            kpos = k0 + jnp.arange(bk)[None, :]
            s = jnp.where(qpos >= kpos, s, _NEG_INF)
        p = jnp.exp(s - lse[..., None])                         # (BH, T, bk)
        dvb = jnp.einsum("bqk,bqd->bkd", p, dof)
        dp = jnp.einsum("bqd,bkd->bqk", dof, vb)
        ds = p * (dp - D) * scale
        dq_part = jnp.einsum("bqk,bkd->bqd", ds, kb)
        dkb = jnp.einsum("bqk,bqd->bkd", ds, qf)
        return dq_part, dkb, dvb

    if nk == 1:
        dq, dk, dv = block_grads(kf, vf, 0)
    else:
        def body(dq, ik):
            k0 = ik * bk
            kb = jax.lax.dynamic_slice_in_dim(kf, k0, bk, axis=1)
            vb = jax.lax.dynamic_slice_in_dim(vf, k0, bk, axis=1)
            dq_part, dkb, dvb = block_grads(kb, vb, k0)
            return dq + dq_part, (dkb, dvb)

        dq, (dks, dvs) = jax.lax.scan(body, jnp.zeros_like(qf),
                                      jnp.arange(nk))
        dk = dks.transpose(1, 0, 2, 3).reshape(BH, T, d)
        dv = dvs.transpose(1, 0, 2, 3).reshape(BH, T, d)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


_flash_attention.defvjp(_flash_attention_fwd, _flash_attention_bwd)


def flash_attention(q, k, v, scale=None, causal=False, bias=None):
    """Fused attention over (B, H, T, d) operands (any leading batch dims
    folded by the caller).  Returns (B, H, T, d)."""
    *lead, T, d = q.shape
    if scale is None:
        scale = 1.0 / math.sqrt(d)
    BH = 1
    for n in lead:
        BH *= n
    if bias is None and _pallas_enabled(BH, T, d):
        q3 = q.reshape(BH, T, d)
        k3 = k.reshape(BH, T, d)
        v3 = v.reshape(BH, T, d)
        out = _flash_attention(q3, k3, v3, float(scale), bool(causal))
        return out.reshape(*lead, T, d)
    return naive_attention(q, k, v, scale, causal=causal, bias=bias)


# ---------------------------------------------------------------------------
# registry entry: (B, T, C) projected q/k/v, heads handled inside
# ---------------------------------------------------------------------------

@register("_contrib_flash_attention",
          ndarray_inputs=("query", "key", "value"))
def _contrib_flash_attention(query, key, value, num_heads=1, scale=None,
                             causal=False):
    """Fused multi-head attention core: softmax(QK^T/sqrt(d))V.

    query/key/value: (B, T, C) post-projection activations; C = H*d.
    Returns (B, T, C).  Pallas flash kernel on TPU, fused XLA fallback
    elsewhere (ref: contrib interleaved_matmul_* fused attention ops,
    src/operator/contrib/transformer.cc).
    """
    B, T, C = query.shape
    H = int(num_heads)
    d = C // H

    def split(x):
        return x.reshape(B, T, H, d).transpose(0, 2, 1, 3)

    out = flash_attention(split(query), split(key), split(value),
                          scale=scale, causal=causal)
    return out.transpose(0, 2, 1, 3).reshape(B, T, C)
