"""Random sampling operators.

TPU-native equivalent of the reference random op group
(ref: src/operator/random/sample_op.*, multisample_op.*, and the
per-device PRNG Resource in src/common/random_generator.h).

Design (SURVEY §7.2 "RNG semantics"): JAX threefry keys are stateless; the
framework keeps a *stateful facade* — a per-context key in
``incubator_mxnet_tpu.random`` that is split on every sampling call, so
``mx.random.seed(n)`` gives the reference's reproducibility contract while
each op body stays a pure function of an explicit `_rng_key`.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register, alias
from ..base import dtype_np


@register("_random_uniform", ndarray_inputs=(), differentiable=False,
          needs_rng=True)
def _random_uniform(low=0.0, high=1.0, shape=(), dtype="float32",
                    _rng_key=None):
    return jax.random.uniform(_rng_key, tuple(shape), dtype_np(dtype),
                              minval=low, maxval=high)


@register("_random_normal", ndarray_inputs=(), differentiable=False,
          needs_rng=True)
def _random_normal(loc=0.0, scale=1.0, shape=(), dtype="float32",
                   _rng_key=None):
    d = dtype_np(dtype)
    return jax.random.normal(_rng_key, tuple(shape), d) * \
        jnp.asarray(scale, d) + jnp.asarray(loc, d)


@register("_random_gamma", ndarray_inputs=(), differentiable=False,
          needs_rng=True)
def _random_gamma(alpha=1.0, beta=1.0, shape=(), dtype="float32",
                  _rng_key=None):
    d = dtype_np(dtype)
    return jax.random.gamma(_rng_key, alpha, tuple(shape), d) * \
        jnp.asarray(beta, d)


@register("_random_exponential", ndarray_inputs=(), differentiable=False,
          needs_rng=True)
def _random_exponential(lam=1.0, shape=(), dtype="float32", _rng_key=None):
    d = dtype_np(dtype)
    return jax.random.exponential(_rng_key, tuple(shape), d) / \
        jnp.asarray(lam, d)


@register("_random_poisson", ndarray_inputs=(), differentiable=False,
          needs_rng=True)
def _random_poisson(lam=1.0, shape=(), dtype="float32", _rng_key=None):
    out = jax.random.poisson(_rng_key, lam, tuple(shape))
    return out.astype(dtype_np(dtype))


@register("_random_randint", ndarray_inputs=(), differentiable=False,
          needs_rng=True)
def _random_randint(low=0, high=1, shape=(), dtype="int32", _rng_key=None):
    return jax.random.randint(_rng_key, tuple(shape), int(low), int(high),
                              dtype_np(dtype))


@register("_random_negative_binomial", ndarray_inputs=(),
          differentiable=False, needs_rng=True)
def _random_negative_binomial(k=1, p=1.0, shape=(), dtype="float32",
                              _rng_key=None):
    k1, k2 = jax.random.split(_rng_key)
    lam = jax.random.gamma(k1, float(k), tuple(shape)) * (1.0 - p) / p
    out = jax.random.poisson(k2, lam, tuple(shape))
    return out.astype(dtype_np(dtype))


@register("_random_generalized_negative_binomial", ndarray_inputs=(),
          differentiable=False, needs_rng=True)
def _random_generalized_negative_binomial(mu=1.0, alpha=1.0, shape=(),
                                          dtype="float32", _rng_key=None):
    k1, k2 = jax.random.split(_rng_key)
    if alpha == 0.0:
        out = jax.random.poisson(k1, mu, tuple(shape))
    else:
        r = 1.0 / alpha
        lam = jax.random.gamma(k1, r, tuple(shape)) * (mu * alpha)
        out = jax.random.poisson(k2, lam, tuple(shape))
    return out.astype(dtype_np(dtype))


# sample_* family: per-element distribution params (tensor inputs)

@register("_sample_uniform", ndarray_inputs=("low", "high"),
          differentiable=False, needs_rng=True)
def _sample_uniform(low, high, shape=(), dtype="float32", _rng_key=None):
    s = tuple(low.shape) + tuple(shape)
    u = jax.random.uniform(_rng_key, s, dtype_np(dtype))
    ext = low.reshape(low.shape + (1,) * len(shape))
    exth = high.reshape(high.shape + (1,) * len(shape))
    return ext + u * (exth - ext)


@register("_sample_normal", ndarray_inputs=("mu", "sigma"),
          differentiable=False, needs_rng=True)
def _sample_normal(mu, sigma, shape=(), dtype="float32", _rng_key=None):
    s = tuple(mu.shape) + tuple(shape)
    n = jax.random.normal(_rng_key, s, dtype_np(dtype))
    return mu.reshape(mu.shape + (1,) * len(shape)) + \
        n * sigma.reshape(sigma.shape + (1,) * len(shape))


@register("_sample_gamma", ndarray_inputs=("alpha", "beta"),
          differentiable=False, needs_rng=True)
def _sample_gamma(alpha, beta, shape=(), dtype="float32", _rng_key=None):
    s = tuple(alpha.shape) + tuple(shape)
    a = alpha.reshape(alpha.shape + (1,) * len(shape))
    g = jax.random.gamma(_rng_key, jnp.broadcast_to(a, s), dtype=dtype_np(dtype))
    return g * beta.reshape(beta.shape + (1,) * len(shape))


@register("_sample_multinomial", ndarray_inputs=("data",),
          differentiable=False, needs_rng=True)
def _sample_multinomial(data, shape=(), get_prob=False, dtype="int32",
                        _rng_key=None):
    """ref: src/operator/random/multisample_op — categorical draws from
    (batched) probability rows."""
    n = int(jnp.prod(jnp.asarray(shape))) if shape else 1
    logits = jnp.log(jnp.maximum(data, 1e-30))
    if data.ndim == 1:
        draws = jax.random.categorical(_rng_key, logits, shape=(n,))
        out = draws.reshape(tuple(shape)) if shape else draws[0]
    else:
        draws = jax.random.categorical(_rng_key, logits[:, None, :],
                                       axis=-1,
                                       shape=(data.shape[0], n))
        out = draws.reshape((data.shape[0],) + tuple(shape)) if shape \
            else draws[:, 0]
    out = out.astype(dtype_np(dtype))
    if get_prob:
        lp = jnp.take_along_axis(
            jnp.log(jnp.maximum(data, 1e-30)),
            out.astype(jnp.int32).reshape(data.shape[0], -1)
            if data.ndim > 1 else out.astype(jnp.int32).reshape(-1),
            axis=-1)
        return out, lp.reshape(out.shape)
    return out


@register("_shuffle", ndarray_inputs=("data",), differentiable=False,
          needs_rng=True)
def _shuffle(data, _rng_key=None):
    return jax.random.permutation(_rng_key, data, axis=0)


@register("_sample_unique_zipfian", ndarray_inputs=(), differentiable=False,
          needs_rng=True)
def _sample_unique_zipfian(range_max=1, shape=(), _rng_key=None):
    """ref: src/operator/random/unique_sample_op.cc — log-uniform
    (zipfian) candidate sampler for sampled softmax, WITHOUT replacement:
    p(k) ∝ log(1 + 1/(k+1)); drawn per leading row via weighted
    choice(replace=False)."""
    shape = tuple(shape)
    n = shape[-1] if shape else 1
    lead = 1
    for s in shape[:-1]:
        lead *= s
    k = jnp.arange(int(range_max))
    p = jnp.log1p(1.0 / (k + 1.0))
    p = p / jnp.sum(p)
    keys = jax.random.split(_rng_key, lead)
    rows = jax.vmap(lambda key: jax.random.choice(
        key, int(range_max), shape=(n,), replace=False, p=p))(keys)
    return rows.reshape(shape).astype(jnp.int64)
