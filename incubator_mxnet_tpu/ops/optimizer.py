"""Fused optimizer update operators.

TPU-native equivalent of the reference optimizer op group
(ref: src/operator/optimizer_op.{cc,cu}, optimizer_op-inl.h:
sgd_update/sgd_mom_update/adam_update/nag_mom_update/rmsprop_update/
ftrl_update/lamb_update_phase1+2, multi-tensor `multi_sgd_*`, and the
mixed-precision `mp_*` variants keeping fp32 master weights).

Key design point carried over (SURVEY §2.2): *the update runs as an op*,
not Python arithmetic.  Each body is a pure function returning the new
state; the imperative stub rebinds the weight NDArray's buffer with
donation, so under jit the update is a single fused XLA computation per
(dtype, shape) — the multi-tensor `multi_*` variants concatenate updates
in one executable the way `multi_sgd_mom_update` batched kernels did.
"""
from __future__ import annotations

import jax.numpy as jnp

from .registry import register

# NOTE on signatures: `rescale_grad`, `clip_gradient`, `wd` follow the
# reference semantics: grad = grad * rescale_grad, clipped, then weight
# decay added as wd * weight.


def _prep_grad(grad, rescale_grad, clip_gradient):
    g = grad * rescale_grad
    if clip_gradient is not None and clip_gradient > 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    return g


@register("sgd_update", ndarray_inputs=("weight", "grad"),
          differentiable=False)
def sgd_update(weight, grad, lr=0.01, wd=0.0, rescale_grad=1.0,
               clip_gradient=-1.0, lazy_update=True):
    g = _prep_grad(grad, rescale_grad, clip_gradient)
    return weight - lr * (g + wd * weight)


@register("sgd_mom_update", ndarray_inputs=("weight", "grad", "mom"),
          differentiable=False, num_outputs=2)
def sgd_mom_update(weight, grad, mom, lr=0.01, momentum=0.0, wd=0.0,
                   rescale_grad=1.0, clip_gradient=-1.0, lazy_update=True):
    g = _prep_grad(grad, rescale_grad, clip_gradient)
    new_mom = momentum * mom - lr * (g + wd * weight)
    return weight + new_mom, new_mom


@register("mp_sgd_update", ndarray_inputs=("weight", "grad", "weight32"),
          differentiable=False, num_outputs=2)
def mp_sgd_update(weight, grad, weight32, lr=0.01, wd=0.0, rescale_grad=1.0,
                  clip_gradient=-1.0, lazy_update=True):
    g = _prep_grad(grad.astype(jnp.float32), rescale_grad, clip_gradient)
    w32 = weight32 - lr * (g + wd * weight32)
    return w32.astype(weight.dtype), w32


@register("mp_sgd_mom_update",
          ndarray_inputs=("weight", "grad", "mom", "weight32"),
          differentiable=False, num_outputs=3)
def mp_sgd_mom_update(weight, grad, mom, weight32, lr=0.01, momentum=0.0,
                      wd=0.0, rescale_grad=1.0, clip_gradient=-1.0,
                      lazy_update=True):
    g = _prep_grad(grad.astype(jnp.float32), rescale_grad, clip_gradient)
    new_mom = momentum * mom - lr * (g + wd * weight32)
    w32 = weight32 + new_mom
    return w32.astype(weight.dtype), new_mom, w32


@register("nag_mom_update", ndarray_inputs=("weight", "grad", "mom"),
          differentiable=False, num_outputs=2)
def nag_mom_update(weight, grad, mom, lr=0.01, momentum=0.0, wd=0.0,
                   rescale_grad=1.0, clip_gradient=-1.0):
    g = _prep_grad(grad, rescale_grad, clip_gradient) + wd * weight
    new_mom = momentum * mom + g
    return weight - lr * (g + momentum * new_mom), new_mom


@register("adam_update", ndarray_inputs=("weight", "grad", "mean", "var"),
          differentiable=False, num_outputs=3)
def adam_update(weight, grad, mean, var, lr=0.001, beta1=0.9, beta2=0.999,
                epsilon=1e-8, wd=0.0, rescale_grad=1.0, clip_gradient=-1.0,
                lazy_update=True):
    g = _prep_grad(grad, rescale_grad, clip_gradient) + wd * weight
    m = beta1 * mean + (1.0 - beta1) * g
    v = beta2 * var + (1.0 - beta2) * jnp.square(g)
    w = weight - lr * m / (jnp.sqrt(v) + epsilon)
    return w, m, v


@register("rmsprop_update", ndarray_inputs=("weight", "grad", "n"),
          differentiable=False, num_outputs=2)
def rmsprop_update(weight, grad, n, lr=0.001, gamma1=0.9, epsilon=1e-8,
                   wd=0.0, rescale_grad=1.0, clip_gradient=-1.0,
                   clip_weights=-1.0):
    g = _prep_grad(grad, rescale_grad, clip_gradient) + wd * weight
    new_n = gamma1 * n + (1.0 - gamma1) * jnp.square(g)
    w = weight - lr * g / jnp.sqrt(new_n + epsilon)
    if clip_weights is not None and clip_weights > 0:
        w = jnp.clip(w, -clip_weights, clip_weights)
    return w, new_n


@register("rmspropalex_update",
          ndarray_inputs=("weight", "grad", "n", "g", "delta"),
          differentiable=False, num_outputs=4)
def rmspropalex_update(weight, grad, n, g, delta, lr=0.001, gamma1=0.95,
                       gamma2=0.9, epsilon=1e-8, wd=0.0, rescale_grad=1.0,
                       clip_gradient=-1.0, clip_weights=-1.0):
    gr = _prep_grad(grad, rescale_grad, clip_gradient) + wd * weight
    new_n = gamma1 * n + (1.0 - gamma1) * jnp.square(gr)
    new_g = gamma1 * g + (1.0 - gamma1) * gr
    new_delta = gamma2 * delta - lr * gr / \
        jnp.sqrt(new_n - jnp.square(new_g) + epsilon)
    w = weight + new_delta
    if clip_weights is not None and clip_weights > 0:
        w = jnp.clip(w, -clip_weights, clip_weights)
    return w, new_n, new_g, new_delta


@register("ftrl_update", ndarray_inputs=("weight", "grad", "z", "n"),
          differentiable=False, num_outputs=3)
def ftrl_update(weight, grad, z, n, lr=0.1, lamda1=0.01, beta=1.0, wd=0.0,
                rescale_grad=1.0, clip_gradient=-1.0):
    g = _prep_grad(grad, rescale_grad, clip_gradient)
    new_n = n + jnp.square(g)
    sigma = (jnp.sqrt(new_n) - jnp.sqrt(n)) / lr
    new_z = z + g - sigma * weight
    w = jnp.where(
        jnp.abs(new_z) <= lamda1,
        jnp.zeros_like(weight),
        -(new_z - jnp.sign(new_z) * lamda1) /
        ((beta + jnp.sqrt(new_n)) / lr + wd))
    return w, new_z, new_n


@register("adagrad_update", ndarray_inputs=("weight", "grad", "history"),
          differentiable=False, num_outputs=2)
def adagrad_update(weight, grad, history, lr=0.01, epsilon=1e-7, wd=0.0,
                   rescale_grad=1.0, clip_gradient=-1.0):
    """ref: _sparse_adagrad_update in optimizer_op.cc (dense form here;
    row_sparse form in ops/sparse.py)."""
    g = _prep_grad(grad, rescale_grad, clip_gradient)
    new_h = history + jnp.square(g)
    w = weight - lr * (g / (jnp.sqrt(new_h) + epsilon) + wd * weight)
    return w, new_h


@register("signsgd_update", ndarray_inputs=("weight", "grad"),
          differentiable=False)
def signsgd_update(weight, grad, lr=0.01, wd=0.0, rescale_grad=1.0,
                   clip_gradient=-1.0):
    g = _prep_grad(grad, rescale_grad, clip_gradient)
    return weight - lr * (jnp.sign(g) + wd * weight)


@register("signum_update", ndarray_inputs=("weight", "grad", "mom"),
          differentiable=False, num_outputs=2)
def signum_update(weight, grad, mom, lr=0.01, momentum=0.0, wd=0.0,
                  rescale_grad=1.0, clip_gradient=-1.0, wd_lh=0.0):
    g = _prep_grad(grad, rescale_grad, clip_gradient)
    new_mom = momentum * mom - (1.0 - momentum) * g
    w = weight + lr * (jnp.sign(new_mom) - wd_lh * weight) - lr * wd * weight
    return w, new_mom


@register("lamb_update_phase1", ndarray_inputs=("weight", "grad", "mean",
                                                "var"),
          differentiable=False, num_outputs=3)
def lamb_update_phase1(weight, grad, mean, var, beta1=0.9, beta2=0.999,
                       epsilon=1e-6, t=1, bias_correction=True, wd=0.0,
                       rescale_grad=1.0, clip_gradient=-1.0):
    g = _prep_grad(grad, rescale_grad, clip_gradient)
    m = beta1 * mean + (1.0 - beta1) * g
    v = beta2 * var + (1.0 - beta2) * jnp.square(g)
    if bias_correction:
        mh = m / (1.0 - beta1 ** t)
        vh = v / (1.0 - beta2 ** t)
    else:
        mh, vh = m, v
    update = mh / (jnp.sqrt(vh) + epsilon) + wd * weight
    return update, m, v


@register("lamb_update_phase2", ndarray_inputs=("weight", "g", "r1", "r2"),
          differentiable=False)
def lamb_update_phase2(weight, g, r1, r2, lr=0.01,
                       lower_bound=-1.0, upper_bound=-1.0):
    r1c = r1
    if lower_bound is not None and lower_bound > 0:
        r1c = jnp.maximum(r1c, lower_bound)
    if upper_bound is not None and upper_bound > 0:
        r1c = jnp.minimum(r1c, upper_bound)
    ratio = jnp.where(jnp.logical_and(r1c > 0, r2 > 0), r1c / r2,
                      jnp.ones_like(r1c))
    return weight - lr * ratio * g


# --- multi-tensor fused variants (ref: multi_sgd_update etc.) -------------
# The imperative stub feeds lists; bodies fold over them so the whole group
# compiles into ONE executable (same goal as the reference's horizontally
# fused multi-tensor kernels).

@register("multi_sgd_update", ndarray_inputs=None, differentiable=False,
          num_outputs=-1)
def multi_sgd_update(*arrays, lrs=(), wds=(), rescale_grad=1.0,
                     clip_gradient=-1.0, num_weights=1):
    outs = []
    for i in range(num_weights):
        w, g = arrays[2 * i], arrays[2 * i + 1]
        outs.append(sgd_update(w, g, lr=lrs[i], wd=wds[i],
                               rescale_grad=rescale_grad,
                               clip_gradient=clip_gradient))
    return tuple(outs)


@register("multi_sgd_mom_update", ndarray_inputs=None, differentiable=False,
          num_outputs=-1)
def multi_sgd_mom_update(*arrays, lrs=(), wds=(), momentum=0.0,
                         rescale_grad=1.0, clip_gradient=-1.0,
                         num_weights=1):
    outs = []
    for i in range(num_weights):
        w, g, m = arrays[3 * i], arrays[3 * i + 1], arrays[3 * i + 2]
        gg = _prep_grad(g, rescale_grad, clip_gradient)
        nm = momentum * m - lrs[i] * (gg + wds[i] * w)
        outs.extend([w + nm, nm])
    return tuple(outs)
