"""Tensor/math operators (pure-JAX bodies).

TPU-native equivalents of the reference tensor op groups
(ref: src/operator/tensor/elemwise_binary_{op,broadcast_op}*,
elemwise_unary_op*, broadcast_reduce_op*, matrix_op*, indexing_op*,
ordering_op*, init_op*, dot-inl.h).

Design notes:
- Every body is a pure function over jax.Array; XLA fuses elementwise
  chains into surrounding matmuls automatically, which replaces the
  reference's mshadow expression templates and `mxnet_op::Kernel::Launch`.
- MXNet distinguishes `elemwise_*` (same-shape) from `broadcast_*`
  (numpy broadcasting). jnp broadcasts everywhere, so the two families
  share bodies; both names are registered for API parity.
- Reduce ops keep MXNet's `axis=None/int/tuple`, `keepdims`, `exclude`
  parameter surface.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as _np

from ..base import dtype_np as _dtype_np

from .registry import register, alias

# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------


def _norm_axis(axis, ndim, exclude=False):
    """MXNet reduce-axis semantics: None = all axes; exclude inverts."""
    if axis is None:
        ax = tuple(range(ndim))
        return ax if not exclude else ()
    if isinstance(axis, int):
        axis = (axis,)
    ax = tuple(a % ndim for a in axis)
    if exclude:
        ax = tuple(a for a in range(ndim) if a not in ax)
    return ax


# ---------------------------------------------------------------------------
# binary elementwise / broadcast family
# ---------------------------------------------------------------------------

_BINARY = {
    "add": jnp.add, "sub": jnp.subtract, "mul": jnp.multiply,
    "div": jnp.divide, "mod": jnp.mod, "power": jnp.power,
    "maximum": jnp.maximum, "minimum": jnp.minimum,
    "hypot": jnp.hypot,
    "equal": lambda a, b: (a == b).astype(jnp.result_type(a, b)),
    "not_equal": lambda a, b: (a != b).astype(jnp.result_type(a, b)),
    "greater": lambda a, b: (a > b).astype(jnp.result_type(a, b)),
    "greater_equal": lambda a, b: (a >= b).astype(jnp.result_type(a, b)),
    "lesser": lambda a, b: (a < b).astype(jnp.result_type(a, b)),
    "lesser_equal": lambda a, b: (a <= b).astype(jnp.result_type(a, b)),
    "logical_and": lambda a, b: jnp.logical_and(a, b).astype(jnp.result_type(a, b)),
    "logical_or": lambda a, b: jnp.logical_or(a, b).astype(jnp.result_type(a, b)),
    "logical_xor": lambda a, b: jnp.logical_xor(a, b).astype(jnp.result_type(a, b)),
}

for _name, _jf in _BINARY.items():
    def _make(jf):
        def body(lhs, rhs):
            return jf(lhs, rhs)
        return body
    _b = _make(_jf)
    _b.__name__ = "broadcast_" + _name
    register("broadcast_" + _name, ndarray_inputs=("lhs", "rhs"))(_b)

alias("broadcast_add", "elemwise_add", "_plus", "_add")
alias("broadcast_sub", "elemwise_sub", "_minus", "_sub")
alias("broadcast_mul", "elemwise_mul", "_mul")
alias("broadcast_div", "elemwise_div", "_div")
alias("broadcast_mod", "_mod")
alias("broadcast_power", "_power", "pow")
alias("broadcast_maximum", "_maximum")
alias("broadcast_minimum", "_minimum")
alias("broadcast_hypot", "_hypot")
alias("broadcast_equal", "_equal")
alias("broadcast_not_equal", "_not_equal")
alias("broadcast_greater", "_greater")
alias("broadcast_greater_equal", "_greater_equal")
alias("broadcast_lesser", "_lesser")
alias("broadcast_lesser_equal", "_lesser_equal")


# scalar variants (ref: *_scalar ops — kept because the NDArray operator
# overloads lower to them)
@register("_plus_scalar", ndarray_inputs=("data",))
def _plus_scalar(data, scalar=0.0):
    return data + jnp.asarray(scalar, dtype=data.dtype)


@register("_minus_scalar", ndarray_inputs=("data",))
def _minus_scalar(data, scalar=0.0):
    return data - jnp.asarray(scalar, dtype=data.dtype)


@register("_rminus_scalar", ndarray_inputs=("data",))
def _rminus_scalar(data, scalar=0.0):
    return jnp.asarray(scalar, dtype=data.dtype) - data


@register("_mul_scalar", ndarray_inputs=("data",))
def _mul_scalar(data, scalar=1.0):
    return data * jnp.asarray(scalar, dtype=data.dtype)


@register("_div_scalar", ndarray_inputs=("data",))
def _div_scalar(data, scalar=1.0):
    return data / jnp.asarray(scalar, dtype=data.dtype)


@register("_rdiv_scalar", ndarray_inputs=("data",))
def _rdiv_scalar(data, scalar=1.0):
    return jnp.asarray(scalar, dtype=data.dtype) / data


@register("_power_scalar", ndarray_inputs=("data",))
def _power_scalar(data, scalar=1.0):
    return jnp.power(data, jnp.asarray(scalar, dtype=data.dtype))


@register("_rpower_scalar", ndarray_inputs=("data",))
def _rpower_scalar(data, scalar=1.0):
    return jnp.power(jnp.asarray(scalar, dtype=data.dtype), data)


@register("_mod_scalar", ndarray_inputs=("data",))
def _mod_scalar(data, scalar=1.0):
    return jnp.mod(data, jnp.asarray(scalar, dtype=data.dtype))


@register("_rmod_scalar", ndarray_inputs=("data",))
def _rmod_scalar(data, scalar=1.0):
    return jnp.mod(jnp.asarray(scalar, dtype=data.dtype), data)


@register("_maximum_scalar", ndarray_inputs=("data",))
def _maximum_scalar(data, scalar=0.0):
    return jnp.maximum(data, jnp.asarray(scalar, dtype=data.dtype))


@register("_minimum_scalar", ndarray_inputs=("data",))
def _minimum_scalar(data, scalar=0.0):
    return jnp.minimum(data, jnp.asarray(scalar, dtype=data.dtype))


for _cmp, _fn in [("_equal_scalar", lambda d, s: (d == s)),
                  ("_not_equal_scalar", lambda d, s: (d != s)),
                  ("_greater_scalar", lambda d, s: (d > s)),
                  ("_greater_equal_scalar", lambda d, s: (d >= s)),
                  ("_lesser_scalar", lambda d, s: (d < s)),
                  ("_lesser_equal_scalar", lambda d, s: (d <= s))]:
    def _mk(fn):
        def body(data, scalar=0.0):
            return fn(data, scalar).astype(data.dtype)
        return body
    _f = _mk(_fn)
    _f.__name__ = _cmp
    register(_cmp, ndarray_inputs=("data",), differentiable=False)(_f)


# ---------------------------------------------------------------------------
# unary elementwise family
# ---------------------------------------------------------------------------

_UNARY = {
    "abs": jnp.abs, "sign": jnp.sign, "rint": jnp.rint,
    "ceil": jnp.ceil, "floor": jnp.floor, "trunc": jnp.trunc,
    "fix": jnp.trunc, "square": jnp.square, "sqrt": jnp.sqrt,
    "rsqrt": lambda x: jax.lax.rsqrt(x), "cbrt": jnp.cbrt,
    "rcbrt": lambda x: 1.0 / jnp.cbrt(x), "exp": jnp.exp,
    "expm1": jnp.expm1, "log": jnp.log, "log10": jnp.log10,
    "log2": jnp.log2, "log1p": jnp.log1p, "sin": jnp.sin,
    "cos": jnp.cos, "tan": jnp.tan, "arcsin": jnp.arcsin,
    "arccos": jnp.arccos, "arctan": jnp.arctan,
    "degrees": jnp.degrees, "radians": jnp.radians,
    "sinh": jnp.sinh, "cosh": jnp.cosh, "tanh": jnp.tanh,
    "arcsinh": jnp.arcsinh, "arccosh": jnp.arccosh,
    "arctanh": jnp.arctanh,
    "reciprocal": lambda x: 1.0 / x,
    "negative": jnp.negative,
    "erf": jax.scipy.special.erf,
    "erfinv": jax.scipy.special.erfinv,
    "gamma": lambda x: jnp.exp(jax.scipy.special.gammaln(x)),
    "gammaln": jax.scipy.special.gammaln,
    "relu": jax.nn.relu,
    "sigmoid": jax.nn.sigmoid,
    "softsign": jax.nn.soft_sign,
}

for _name, _jf in _UNARY.items():
    def _mku(jf):
        def body(data):
            return jf(data)
        return body
    _u = _mku(_jf)
    _u.__name__ = _name
    register(_name, ndarray_inputs=("data",))(_u)


@register("logical_not", ndarray_inputs=("data",), differentiable=False)
def logical_not(data):
    return jnp.logical_not(data).astype(data.dtype)


@register("round", ndarray_inputs=("data",), differentiable=False)
def round_(data):
    return jnp.round(data)


@register("BlockGrad", ndarray_inputs=("data",))
def block_grad(data):
    """ref: src/operator/tensor/elemwise_unary_op_basic.cc BlockGrad —
    identity forward, zero gradient (== jax.lax.stop_gradient)."""
    return jax.lax.stop_gradient(data)


alias("BlockGrad", "stop_gradient")


@register("identity", ndarray_inputs=("data",))
def identity(data):
    return data


alias("identity", "_copy")


@register("cast", ndarray_inputs=("data",))
def cast(data, dtype="float32"):
    from ..base import dtype_np
    return data.astype(dtype_np(dtype))


alias("cast", "Cast")


@register("amp_cast", ndarray_inputs=("data",))
def amp_cast(data, dtype="float32"):
    """ref: src/operator/tensor/amp_cast.cc AMPCastCompute — the cast
    the AMP graph pass inserts.  Unlike Cast it only touches floating
    inputs (int indices/labels pass through), and XLA fuses it into the
    consumer so a carried cast costs nothing at runtime."""
    from ..base import dtype_np
    if not jnp.issubdtype(jnp.asarray(data).dtype, jnp.floating):
        return data
    return data.astype(dtype_np(dtype))


def _amp_multicast_nout(attrs):
    return int(attrs.get("num_outputs", 1))


@register("amp_multicast", ndarray_inputs=None, num_outputs=-1,
          num_outputs_fn=_amp_multicast_nout)
def amp_multicast(*data, num_outputs=1, cast_narrow=False):
    """ref: amp_multicast — common-dtype cast across inputs: widest
    floating dtype wins (narrowest with cast_narrow), non-float inputs
    pass through untouched."""
    fdts = [d.dtype for d in data
            if jnp.issubdtype(jnp.asarray(d).dtype, jnp.floating)]
    if not fdts:
        return tuple(data) if len(data) > 1 else data[0]
    if cast_narrow:
        target = min(fdts, key=lambda t: jnp.dtype(t).itemsize)
    else:
        target = functools.reduce(jnp.promote_types, fdts)
    outs = tuple(d.astype(target)
                 if jnp.issubdtype(jnp.asarray(d).dtype, jnp.floating)
                 else d for d in data)
    return outs if len(outs) > 1 else outs[0]


@register("clip", ndarray_inputs=("data",))
def clip(data, a_min=0.0, a_max=1.0):
    return jnp.clip(data, a_min, a_max)


# ---------------------------------------------------------------------------
# init ops
# ---------------------------------------------------------------------------


@register("zeros_like", ndarray_inputs=("data",))
def zeros_like(data):
    return jnp.zeros_like(data)


@register("ones_like", ndarray_inputs=("data",))
def ones_like(data):
    return jnp.ones_like(data)


@register("_zeros", ndarray_inputs=(), differentiable=False)
def _zeros(shape=(), dtype="float32"):
    from ..base import dtype_np
    return jnp.zeros(shape, dtype=dtype_np(dtype))


@register("_ones", ndarray_inputs=(), differentiable=False)
def _ones(shape=(), dtype="float32"):
    from ..base import dtype_np
    return jnp.ones(shape, dtype=dtype_np(dtype))


@register("_full", ndarray_inputs=(), differentiable=False)
def _full(shape=(), value=0.0, dtype="float32"):
    from ..base import dtype_np
    return jnp.full(shape, value, dtype=dtype_np(dtype))


@register("_arange", ndarray_inputs=(), differentiable=False)
def _arange(start=0.0, stop=None, step=1.0, repeat=1, dtype="float32"):
    from ..base import dtype_np
    out = jnp.arange(start, stop, step, dtype=dtype_np(dtype))
    if repeat != 1:
        out = jnp.repeat(out, repeat)
    return out


@register("_linspace", ndarray_inputs=(), differentiable=False)
def _linspace(start=0.0, stop=1.0, num=50, endpoint=True, dtype="float32"):
    from ..base import dtype_np
    return jnp.linspace(start, stop, int(num), endpoint=endpoint,
                        dtype=dtype_np(dtype))


@register("_eye", ndarray_inputs=(), differentiable=False)
def _eye(N=1, M=0, k=0, dtype="float32"):
    from ..base import dtype_np
    M = int(M) or None
    return jnp.eye(int(N), M, int(k), dtype=dtype_np(dtype))


@register("arange_like", ndarray_inputs=("data",), differentiable=False)
def arange_like(data, start=0.0, step=1.0, repeat=1, axis=None):
    if axis is None:
        n = data.size
        shape = data.shape
    else:
        n = data.shape[axis]
        shape = (n,)
    out = jnp.arange(start, start + step * n, step, dtype=data.dtype)[:n]
    if repeat != 1:
        out = jnp.repeat(out, repeat)
    return out.reshape(shape)


# ---------------------------------------------------------------------------
# reductions
# ---------------------------------------------------------------------------

def _reduce(jf):
    def body(data, axis=None, keepdims=False, exclude=False):
        ax = _norm_axis(axis, data.ndim, exclude)
        if ax == () and axis is not None:
            return data
        return jf(data, axis=ax if ax else None, keepdims=keepdims)
    return body


for _name, _jf in [("sum", jnp.sum), ("mean", jnp.mean), ("prod", jnp.prod),
                   ("nansum", jnp.nansum), ("nanprod", jnp.nanprod),
                   ("max", jnp.max), ("min", jnp.min)]:
    _r = _reduce(_jf)
    _r.__name__ = _name
    register(_name, ndarray_inputs=("data",))(_r)

alias("sum", "sum_axis")
alias("max", "max_axis")
alias("min", "min_axis")


@register("norm", ndarray_inputs=("data",))
def norm(data, ord=2, axis=None, keepdims=False, out_dtype=None):
    ax = None if axis is None else (axis if isinstance(axis, tuple) else (axis,))
    if ord == 2:
        sq = jnp.sum(jnp.square(data), axis=ax, keepdims=keepdims)
        out = jnp.sqrt(sq)
    elif ord == 1:
        out = jnp.sum(jnp.abs(data), axis=ax, keepdims=keepdims)
    else:
        raise ValueError("norm only supports ord=1|2 (ref parity)")
    if out_dtype is not None:
        from ..base import dtype_np
        out = out.astype(dtype_np(out_dtype))
    return out


@register("argmax", ndarray_inputs=("data",), differentiable=False)
def argmax(data, axis=None, keepdims=False, dtype="float32"):
    # dtype param follows the reference's large-tensor pattern (topk/
    # argsort grew one so positions past 2**24 survive the float cast)
    out = jnp.argmax(data, axis=axis)
    if keepdims and axis is not None:
        out = jnp.expand_dims(out, axis)
    return out.astype(_dtype_np(dtype))   # MXNet default: float indices


@register("argmin", ndarray_inputs=("data",), differentiable=False)
def argmin(data, axis=None, keepdims=False, dtype="float32"):
    out = jnp.argmin(data, axis=axis)
    if keepdims and axis is not None:
        out = jnp.expand_dims(out, axis)
    return out.astype(_dtype_np(dtype))


@register("argmax_channel", ndarray_inputs=("data",), differentiable=False)
def argmax_channel(data):
    return jnp.argmax(data, axis=-1).astype(jnp.float32)


# ---------------------------------------------------------------------------
# shape manipulation
# ---------------------------------------------------------------------------


@register("reshape", ndarray_inputs=("data",))
def reshape(data, shape=None, reverse=False):
    """Supports MXNet's magic values 0 (copy dim), -1 (infer), -2 (copy
    rest), -3 (merge two), -4 (split) — ref: matrix_op-inl.h ReshapeShape."""
    shape = tuple(shape)
    if not any(s in (0, -2, -3, -4) for s in shape):
        return jnp.reshape(data, shape)
    src = list(data.shape[::-1] if reverse else data.shape)
    out = []
    i = 0
    it = iter(range(len(shape)))
    shape_l = list(shape[::-1] if reverse else shape)
    k = 0
    while k < len(shape_l):
        s = shape_l[k]
        if s == 0:
            out.append(src[i]); i += 1
        elif s == -1:
            out.append(-1); i += 1
        elif s == -2:
            out.extend(src[i:]); i = len(src)
        elif s == -3:
            out.append(src[i] * src[i + 1]); i += 2
        elif s == -4:
            a, b = shape_l[k + 1], shape_l[k + 2]
            if a == -1:
                a = src[i] // b
            if b == -1:
                b = src[i] // a
            out.extend([a, b]); i += 1; k += 2
        else:
            out.append(s); i += 1
        k += 1
    if reverse:
        out = out[::-1]
    return jnp.reshape(data, tuple(out))


alias("reshape", "Reshape")


@register("reshape_like", ndarray_inputs=("lhs", "rhs"))
def reshape_like(lhs, rhs):
    return jnp.reshape(lhs, rhs.shape)


@register("shape_array", ndarray_inputs=("data",), differentiable=False)
def shape_array(data):
    return jnp.asarray(data.shape, dtype=jnp.int64)


@register("size_array", ndarray_inputs=("data",), differentiable=False)
def size_array(data):
    return jnp.asarray([data.size], dtype=jnp.int64)


@register("Flatten", ndarray_inputs=("data",))
def flatten(data):
    return jnp.reshape(data, (data.shape[0], -1))


alias("Flatten", "flatten")


@register("expand_dims", ndarray_inputs=("data",))
def expand_dims(data, axis=0):
    return jnp.expand_dims(data, axis)


@register("squeeze", ndarray_inputs=("data",))
def squeeze(data, axis=None):
    return jnp.squeeze(data, axis=axis)


@register("transpose", ndarray_inputs=("data",))
def transpose(data, axes=None):
    if axes is not None and len(axes) == 0:
        axes = None
    return jnp.transpose(data, axes=axes)


@register("swapaxes", ndarray_inputs=("data",))
def swapaxes(data, dim1=0, dim2=0):
    return jnp.swapaxes(data, dim1, dim2)


alias("swapaxes", "SwapAxis")


@register("flip", ndarray_inputs=("data",))
def flip(data, axis=0):
    return jnp.flip(data, axis=axis)


alias("flip", "reverse")


@register("tile", ndarray_inputs=("data",))
def tile(data, reps=()):
    return jnp.tile(data, tuple(reps))


@register("repeat", ndarray_inputs=("data",))
def repeat(data, repeats=1, axis=None):
    return jnp.repeat(data, repeats, axis=axis)


@register("broadcast_to", ndarray_inputs=("data",))
def broadcast_to(data, shape=()):
    shape = tuple(int(data.shape[i]) if s == 0 else int(s)
                  for i, s in enumerate(shape))
    return jnp.broadcast_to(data, shape)


@register("broadcast_like", ndarray_inputs=("lhs", "rhs"))
def broadcast_like(lhs, rhs, lhs_axes=None, rhs_axes=None):
    if lhs_axes is None:
        return jnp.broadcast_to(lhs, rhs.shape)
    shape = list(lhs.shape)
    for la, ra in zip(lhs_axes, rhs_axes):
        shape[la] = rhs.shape[ra]
    return jnp.broadcast_to(lhs, tuple(shape))


@register("broadcast_axis", ndarray_inputs=("data",))
def broadcast_axis(data, axis=(), size=()):
    if isinstance(axis, int):
        axis = (axis,)
        size = (size,)
    shape = list(data.shape)
    for a, s in zip(axis, size):
        shape[a] = s
    return jnp.broadcast_to(data, tuple(shape))


alias("broadcast_axis", "broadcast_axes")


@register("concat", ndarray_inputs=None)
def concat(*data, dim=1):
    return jnp.concatenate(data, axis=dim)


alias("concat", "Concat")


@register("stack", ndarray_inputs=None)
def stack(*data, axis=0):
    return jnp.stack(data, axis=axis)


@register("split", ndarray_inputs=("data",), num_outputs=-1)
def split(data, num_outputs=1, axis=1, squeeze_axis=False):
    parts = jnp.split(data, int(num_outputs), axis=axis)
    if squeeze_axis:
        parts = [jnp.squeeze(p, axis=axis) for p in parts]
    return tuple(parts)


alias("split", "SliceChannel")


@register("slice", ndarray_inputs=("data",))
def slice_(data, begin=(), end=(), step=()):
    idx = []
    step = tuple(step) if step else (None,) * len(begin)
    for b, e, s in zip(begin, end, step):
        idx.append(builtins_slice(b, e, s))
    return data[tuple(idx)]


def builtins_slice(b, e, s):
    return slice(b, e, s)


@register("slice_axis", ndarray_inputs=("data",))
def slice_axis(data, axis=0, begin=0, end=None):
    idx = [slice(None)] * data.ndim
    idx[axis] = slice(begin, end)
    return data[tuple(idx)]


@register("slice_like", ndarray_inputs=("data", "shape_like"))
def slice_like(data, shape_like, axes=()):
    idx = [slice(None)] * data.ndim
    axes = axes or range(min(data.ndim, shape_like.ndim))
    for a in axes:
        idx[a] = slice(0, shape_like.shape[a])
    return data[tuple(idx)]


@register("pad", ndarray_inputs=("data",))
def pad(data, mode="constant", pad_width=(), constant_value=0.0):
    pw = [(pad_width[2 * i], pad_width[2 * i + 1])
          for i in range(len(pad_width) // 2)]
    jmode = {"constant": "constant", "edge": "edge", "reflect": "reflect"}[mode]
    if jmode == "constant":
        return jnp.pad(data, pw, mode=jmode, constant_values=constant_value)
    return jnp.pad(data, pw, mode=jmode)


alias("pad", "Pad")


@register("depth_to_space", ndarray_inputs=("data",))
def depth_to_space(data, block_size=1):
    n, c, h, w = data.shape
    b = block_size
    x = data.reshape(n, b, b, c // (b * b), h, w)
    x = x.transpose(0, 3, 4, 1, 5, 2)
    return x.reshape(n, c // (b * b), h * b, w * b)


@register("space_to_depth", ndarray_inputs=("data",))
def space_to_depth(data, block_size=1):
    n, c, h, w = data.shape
    b = block_size
    x = data.reshape(n, c, h // b, b, w // b, b)
    x = x.transpose(0, 3, 5, 1, 2, 4)
    return x.reshape(n, c * b * b, h // b, w // b)


# ---------------------------------------------------------------------------
# indexing / gather / scatter
# ---------------------------------------------------------------------------


def _idx(indices):
    """Index dtype for gathers: int32 (TPU-native) unless the
    large-tensor flag enabled 64-bit index math (MXNET_INT64_TENSOR_SIZE
    ≙ ref USE_INT64_TENSOR_SIZE — positions past 2**31 would wrap)."""
    return indices.astype(
        jnp.int64 if jax.config.jax_enable_x64 else jnp.int32)


@register("take", ndarray_inputs=("a", "indices"), nograd_argnums=(1,))
def take(a, indices, axis=0, mode="clip"):
    jmode = {"clip": "clip", "wrap": "wrap", "raise": "clip"}[mode]
    return jnp.take(a, _idx(indices), axis=axis, mode=jmode)


@register("pick", ndarray_inputs=("data", "index"), nograd_argnums=(1,))
def pick(data, index, axis=-1, keepdims=False, mode="clip"):
    idx = jnp.clip(_idx(index), 0, data.shape[axis] - 1)
    out = jnp.take_along_axis(data, jnp.expand_dims(idx, axis), axis=axis)
    if not keepdims:
        out = jnp.squeeze(out, axis=axis)
    return out


@register("gather_nd", ndarray_inputs=("data", "indices"), nograd_argnums=(1,))
def gather_nd(data, indices):
    """ref: tensor/indexing_op.h GatherNDForward. indices shape (M, ...)"""
    idx = tuple(_idx(indices))
    return data[idx]


@register("scatter_nd", ndarray_inputs=("data", "indices"), nograd_argnums=(1,))
def scatter_nd(data, indices, shape=()):
    out = jnp.zeros(tuple(shape), dtype=data.dtype)
    idx = tuple(_idx(indices))
    return out.at[idx].set(data)


@register("_scatter_set_nd", ndarray_inputs=("lhs", "rhs", "indices"),
          nograd_argnums=(2,))
def _scatter_set_nd(lhs, rhs, indices, shape=()):
    idx = tuple(_idx(indices))
    return lhs.at[idx].set(rhs)


@register("one_hot", ndarray_inputs=("indices",), differentiable=False)
def one_hot(indices, depth=1, on_value=1.0, off_value=0.0, dtype="float32"):
    from ..base import dtype_np
    d = dtype_np(dtype)
    oh = jax.nn.one_hot(indices.astype(jnp.int32), int(depth), dtype=d)
    return oh * jnp.asarray(on_value, d) + (1 - oh) * jnp.asarray(off_value, d)


@register("where", ndarray_inputs=("condition", "x", "y"), nograd_argnums=(0,))
def where(condition, x, y):
    return jnp.where(condition.astype(bool), x, y)


@register("boolean_mask", ndarray_inputs=("data", "index"), differentiable=False)
def boolean_mask(data, index, axis=0):
    """Dynamic-shape op: on TPU we return *padded* results + valid count is
    not expressible under jit; imperative-only (ref: contrib/boolean_mask.cc).
    """
    mask = _np.asarray(index).astype(bool)
    return jnp.compress(mask, data, axis=axis)


@register("diag", ndarray_inputs=("data",))
def diag(data, k=0, axis1=0, axis2=1):
    if data.ndim == 1:
        return jnp.diag(data, k=k)
    return jnp.diagonal(data, offset=k, axis1=axis1, axis2=axis2)


# ---------------------------------------------------------------------------
# linear algebra
# ---------------------------------------------------------------------------


@register("dot", ndarray_inputs=("lhs", "rhs"))
def dot(lhs, rhs, transpose_a=False, transpose_b=False):
    """ref: src/operator/tensor/dot-inl.h. 2-D (and N-D trailing-contraction)
    matmul; on TPU this is THE MXU op — keep inputs bf16/fp32 and let XLA
    tile. Sparse (csr) variants live in ops/sparse.py."""
    a = lhs.T if transpose_a else lhs
    b = rhs.T if transpose_b else rhs
    if a.ndim == 1 and b.ndim == 1:
        return jnp.dot(a, b)
    if a.ndim > 2 or b.ndim > 2:
        # MXNet dot contracts last axis of a with first of b
        return jnp.tensordot(a, b, axes=([a.ndim - 1], [0]))
    return jnp.dot(a, b)


@register("batch_dot", ndarray_inputs=("lhs", "rhs"))
def batch_dot(lhs, rhs, transpose_a=False, transpose_b=False):
    a = jnp.swapaxes(lhs, -1, -2) if transpose_a else lhs
    b = jnp.swapaxes(rhs, -1, -2) if transpose_b else rhs
    return jnp.matmul(a, b)


@register("khatri_rao", ndarray_inputs=None)
def khatri_rao(*mats):
    out = mats[0]
    for m in mats[1:]:
        out = jnp.einsum("i...,j...->ij...", out, m).reshape(
            out.shape[0] * m.shape[0], *out.shape[1:])
    return out


@register("L2Normalization", ndarray_inputs=("data",))
def l2_normalization(data, eps=1e-10, mode="instance"):
    if mode == "instance":
        ax = tuple(range(1, data.ndim))
    elif mode == "channel":
        ax = (1,)
    elif mode == "spatial":
        ax = tuple(range(2, data.ndim))
    else:
        raise ValueError(mode)
    nrm = jnp.sqrt(jnp.sum(jnp.square(data), axis=ax, keepdims=True) + eps)
    return data / nrm


# ---------------------------------------------------------------------------
# ordering
# ---------------------------------------------------------------------------


@register("sort", ndarray_inputs=("data",))
def sort(data, axis=-1, is_ascend=True):
    out = jnp.sort(data, axis=axis)
    if not is_ascend:
        out = jnp.flip(out, axis=axis)
    return out


@register("argsort", ndarray_inputs=("data",), differentiable=False)
def argsort(data, axis=-1, is_ascend=True, dtype="float32"):
    from ..base import dtype_np
    out = jnp.argsort(data, axis=axis)
    if not is_ascend:
        out = jnp.flip(out, axis=axis)
    return out.astype(dtype_np(dtype))


@register("topk", ndarray_inputs=("data",), differentiable=False)
def topk(data, axis=-1, k=1, ret_typ="indices", is_ascend=False,
         dtype="float32"):
    from ..base import dtype_np
    k = int(k)
    d = jnp.moveaxis(data, axis, -1)
    neg = not is_ascend
    vals, idxs = jax.lax.top_k(d if neg else -d, k)
    if not neg:
        vals = -vals
    vals = jnp.moveaxis(vals, -1, axis)
    idxs = jnp.moveaxis(idxs, -1, axis).astype(dtype_np(dtype))
    if ret_typ == "indices":
        return idxs
    if ret_typ == "value":
        return vals
    if ret_typ == "both":
        return (vals, idxs)
    if ret_typ == "mask":
        ii = jnp.moveaxis(idxs, axis, -1).astype(jnp.int32)
        zeros = jnp.zeros(d.shape, dtype=data.dtype)
        mask = jnp.moveaxis(
            jnp.put_along_axis(zeros, ii, jnp.ones((), data.dtype),
                               axis=-1, inplace=False), -1, axis)
        return mask
    raise ValueError(ret_typ)


# ---------------------------------------------------------------------------
# sequence ops (ref: src/operator/sequence_*.cc)
# ---------------------------------------------------------------------------


@register("SequenceMask", ndarray_inputs=("data", "sequence_length"),
          nograd_argnums=(1,))
def sequence_mask(data, sequence_length=None, use_sequence_length=False,
                  value=0.0, axis=0):
    if not use_sequence_length or sequence_length is None:
        return data
    maxlen = data.shape[axis]
    steps = jnp.arange(maxlen)
    # (T, B) layout when axis=0, (B, T) when axis=1
    if axis == 0:
        mask = steps[:, None] < sequence_length[None, :].astype(steps.dtype)
    else:
        mask = steps[None, :] < sequence_length[:, None].astype(steps.dtype)
    mask = mask.reshape(mask.shape + (1,) * (data.ndim - 2))
    return jnp.where(mask, data, jnp.asarray(value, data.dtype))


@register("SequenceLast", ndarray_inputs=("data", "sequence_length"),
          nograd_argnums=(1,))
def sequence_last(data, sequence_length=None, use_sequence_length=False,
                  axis=0):
    if not use_sequence_length or sequence_length is None:
        idx = [slice(None)] * data.ndim
        idx[axis] = -1
        return data[tuple(idx)]
    last = (sequence_length.astype(jnp.int32) - 1)
    d = jnp.moveaxis(data, axis, 0)          # (T, B, ...)
    out = jnp.take_along_axis(
        d, last.reshape((1, -1) + (1,) * (d.ndim - 2)), axis=0)
    return jnp.squeeze(out, axis=0)


@register("SequenceReverse", ndarray_inputs=("data", "sequence_length"),
          nograd_argnums=(1,))
def sequence_reverse(data, sequence_length=None, use_sequence_length=False,
                     axis=0):
    if not use_sequence_length or sequence_length is None:
        return jnp.flip(data, axis=0)
    T = data.shape[0]
    steps = jnp.arange(T)[:, None]
    L = sequence_length.astype(jnp.int32)[None, :]
    src = jnp.where(steps < L, L - 1 - steps, steps)    # (T, B)
    d = data
    out = jnp.take_along_axis(
        d, src.reshape(src.shape + (1,) * (d.ndim - 2)), axis=0)
    return out
