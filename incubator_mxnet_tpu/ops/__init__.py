"""Operator registry + all built-in operator groups.

Importing this package populates the registry (the analogue of the
reference's static NNVM_REGISTER_OP initialisers linked into libmxnet.so).
"""
from . import registry
from .registry import register, get, list_ops, alias, OpDef

# op groups — import order irrelevant; each registers into the registry
from . import tensor          # noqa: F401
from . import nn              # noqa: F401
from . import random          # noqa: F401
from . import optimizer       # noqa: F401
from . import control_flow    # noqa: F401
from . import rnn             # noqa: F401
from . import contrib         # noqa: F401
from . import attention       # noqa: F401
from . import quantization    # noqa: F401
from . import rcnn            # noqa: F401

__all__ = ["register", "get", "list_ops", "alias", "OpDef", "registry"]
