"""Control-flow operators.

TPU-native equivalent of the reference higher-order control-flow ops
(ref: src/operator/control_flow.cc — `_foreach`, `_while_loop`, `_cond`
taking subgraphs).  These map directly onto `lax.scan` / `lax.while_loop`
/ `lax.cond`, which is exactly the compiler-friendly structure XLA wants
(SURVEY §2.2: "maps beautifully to lax.scan/while/cond").

The API here is functional (callables, not Symbols): the Gluon/symbol
layers pass traced callables in.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register


@register("foreach", ndarray_inputs=None)
def foreach(body, data, init_states):
    """Scan `body(x_t, states) -> (out_t, new_states)` over axis 0 of data.

    `data` may be one array or a list; same for states. Returns
    (stacked outputs, final states).
    """
    multi_data = isinstance(data, (list, tuple))
    multi_state = isinstance(init_states, (list, tuple))
    xs = tuple(data) if multi_data else (data,)
    init = tuple(init_states) if multi_state else (init_states,)

    def step(carry, x):
        xa = x if multi_data else x[0]
        out, new_states = body(xa, list(carry) if multi_state else carry[0])
        ns = tuple(new_states) if multi_state else (new_states,)
        return ns, out

    final, outs = lax.scan(step, init, xs)
    return outs, (list(final) if multi_state else final[0])


@register("while_loop", ndarray_inputs=None)
def while_loop(cond, func, loop_vars, max_iterations=None):
    """ref: `_while_loop`. `func(vars) -> (step_output, new_vars)`.

    The reference stacks per-step outputs up to `max_iterations` with a
    valid-length; on TPU dynamic output length is not jittable, so outputs
    are padded to `max_iterations` (zeros beyond the exit step) and the
    actual iteration count is returned — the documented TPU convention
    (pad + mask, SURVEY §7.2 dynamic shapes).
    """
    multi = isinstance(loop_vars, (list, tuple))
    lv = tuple(loop_vars) if multi else (loop_vars,)

    if max_iterations is None:
        def c(state):
            return cond(list(state) if multi else state[0])

        def b(state):
            _, new = func(list(state) if multi else state[0])
            return tuple(new) if multi else (new,)
        out = lax.while_loop(c, b, lv)
        return None, (list(out) if multi else out[0])

    # padded scan version with per-step outputs
    sample_out, _ = jax.eval_shape(
        lambda s: func(list(s) if multi else s[0]), lv)

    def step(carry, _):
        state, t, active = carry
        pred = jnp.logical_and(active,
                               cond(list(state) if multi else state[0]))

        def run(s):
            o, n = func(list(s) if multi else s[0])
            return o, (tuple(n) if multi else (n,))

        def skip(s):
            z = jax.tree_util.tree_map(
                lambda a: jnp.zeros(a.shape, a.dtype), sample_out)
            return z, s
        out, new_state = lax.cond(pred, run, skip, state)
        return (new_state, t + jnp.asarray(pred, jnp.int32), pred), out

    (final, count, _), outs = lax.scan(
        step, (lv, jnp.zeros((), jnp.int32), jnp.asarray(True)),
        None, length=int(max_iterations))
    return outs, (list(final) if multi else final[0])


@register("cond", ndarray_inputs=None)
def cond(pred, then_func, else_func, inputs):
    """ref: `_cond`. Both branches trace; XLA picks at runtime."""
    multi = isinstance(inputs, (list, tuple))
    iv = tuple(inputs) if multi else (inputs,)
    p = pred(list(iv) if multi else iv[0]) if callable(pred) else pred
    p = jnp.reshape(jnp.asarray(p, bool), ())
    return lax.cond(p,
                    lambda s: then_func(list(s) if multi else s[0]),
                    lambda s: else_func(list(s) if multi else s[0]),
                    iv)
