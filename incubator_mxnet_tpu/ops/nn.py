"""Neural-network operators (pure-JAX bodies).

TPU-native equivalents of the reference NN op group
(ref: src/operator/nn/{convolution,fully_connected,batch_norm,layer_norm,
pooling,activation,dropout,softmax}* and their cuDNN fast paths under
src/operator/nn/cudnn/).  On TPU there is no per-op kernel library to
wrap: each body lowers to XLA (conv → MXU convolution, norms/activations
fused by XLA), which *is* the cuDNN-equivalent fast path.  Layout is kept
NCHW at the API for parity; XLA relayouts internally for the MXU.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as _np
from jax import lax

from .registry import register, alias
from .. import config as _config


# ---------------------------------------------------------------------------
# FullyConnected / Dense
# ---------------------------------------------------------------------------


@register("FullyConnected", ndarray_inputs=("data", "weight", "bias"))
def fully_connected(data, weight, bias=None, num_hidden=0, no_bias=False,
                    flatten=True):
    """ref: src/operator/nn/fully_connected-inl.h FullyConnectedOp.
    weight is (num_hidden, in_units) as in the reference; the matmul is the
    MXU hot path — XLA fuses the bias add."""
    if flatten and data.ndim > 2:
        data = data.reshape(data.shape[0], -1)
    if weight.dtype != data.dtype:      # amp: follow activation dtype
        weight = weight.astype(data.dtype)
    out = jnp.matmul(data, weight.T)
    if not no_bias and bias is not None:
        out = out + bias.astype(out.dtype)
    return out


# ---------------------------------------------------------------------------
# Convolution / Deconvolution
# ---------------------------------------------------------------------------

def _conv_dim_numbers(ndim):
    if ndim == 3:
        return ("NCH", "OIH", "NCH")
    if ndim == 4:
        return ("NCHW", "OIHW", "NCHW")
    if ndim == 5:
        return ("NCDHW", "OIDHW", "NCDHW")
    raise ValueError("conv expects 3/4/5-d input")


@register("Convolution", ndarray_inputs=("data", "weight", "bias"))
def convolution(data, weight, bias=None, kernel=(), stride=(), dilate=(),
                pad=(), num_filter=0, num_group=1, no_bias=False,
                workspace=1024, cudnn_tune=None, cudnn_off=False,
                layout=None):
    """ref: src/operator/nn/convolution-inl.h ConvolutionOp (cuDNN path:
    nn/cudnn/cudnn_convolution-inl.h).  Direct map to
    lax.conv_general_dilated; `workspace`/`cudnn_*` knobs accepted and
    ignored (XLA autotunes)."""
    nd = data.ndim
    k = len(kernel) if kernel else nd - 2
    stride = tuple(stride) if stride else (1,) * k
    dilate = tuple(dilate) if dilate else (1,) * k
    pad = tuple(pad) if pad else (0,) * k
    if weight.dtype != data.dtype:      # amp: follow activation dtype
        weight = weight.astype(data.dtype)
    dn = lax.conv_dimension_numbers(data.shape, weight.shape,
                                    _conv_dim_numbers(nd))
    out = lax.conv_general_dilated(
        data, weight, window_strides=stride,
        padding=[(p, p) for p in pad],
        rhs_dilation=dilate, dimension_numbers=dn,
        feature_group_count=num_group,
        preferred_element_type=None)
    if not no_bias and bias is not None:
        out = out + bias.astype(out.dtype).reshape(
            (1, -1) + (1,) * (nd - 2))
    return out


@register("Deconvolution", ndarray_inputs=("data", "weight", "bias"))
def deconvolution(data, weight, bias=None, kernel=(), stride=(), dilate=(),
                  pad=(), adj=(), target_shape=(), num_filter=0, num_group=1,
                  no_bias=True, workspace=512, cudnn_tune=None,
                  cudnn_off=False, layout=None):
    """ref: src/operator/nn/deconvolution-inl.h — gradient of conv w.r.t.
    input, i.e. transposed convolution."""
    nd = data.ndim
    k = len(kernel) if kernel else nd - 2
    stride = tuple(stride) if stride else (1,) * k
    dilate = tuple(dilate) if dilate else (1,) * k
    pad = tuple(pad) if pad else (0,) * k
    adj = tuple(adj) if adj else (0,) * k
    # weight layout (in_channel, out_channel/group, *kernel) as reference
    pads = []
    for i in range(k):
        kk = (weight.shape[2 + i] - 1) * dilate[i] + 1
        pads.append((kk - 1 - pad[i], kk - 1 - pad[i] + adj[i]))
    weight = weight.astype(data.dtype)       # amp: follow activations
    if num_group != 1:
        # grouped transposed conv as ONE grouped conv: weight
        # (Cin, Cout/g, *k) → per-group (out, in) swap →
        # (Cout, Cin/g, *k), then feature_group_count does the rest
        cin_g = weight.shape[0] // num_group
        out_g = weight.shape[1]
        w = weight.reshape((num_group, cin_g, out_g) + weight.shape[2:])
        w = jnp.swapaxes(w, 1, 2).reshape(
            (num_group * out_g, cin_g) + weight.shape[2:])
    else:
        w = jnp.swapaxes(weight, 0, 1)
    w = jnp.flip(w, axis=tuple(range(2, nd)))
    dn = lax.conv_dimension_numbers(data.shape, w.shape,
                                    _conv_dim_numbers(nd))
    out = lax.conv_general_dilated(
        data, w, window_strides=(1,) * k, padding=pads,
        lhs_dilation=stride, rhs_dilation=dilate,
        feature_group_count=num_group, dimension_numbers=dn)
    if not no_bias and bias is not None:
        out = out + bias.reshape((1, -1) + (1,) * (nd - 2)).astype(
            out.dtype)
    return out


# ---------------------------------------------------------------------------
# Pooling
# ---------------------------------------------------------------------------


@register("Pooling", ndarray_inputs=("data",))
def pooling(data, kernel=(), pool_type="max", global_pool=False,
            cudnn_off=False, pooling_convention="valid", stride=(), pad=(),
            p_value=2, count_include_pad=True, layout=None):
    """ref: src/operator/nn/pooling-inl.h PoolingOp.  Reduce-window on XLA.
    `pooling_convention='full'` (ceil) kept for parity with legacy nets."""
    nd = data.ndim
    k = nd - 2
    if global_pool:
        kernel = data.shape[2:]
        stride = (1,) * k
        pad = (0,) * k
    kernel = tuple(kernel)
    stride = tuple(stride) if stride else (1,) * k
    pad = tuple(pad) if pad else (0,) * k

    window = (1, 1) + kernel
    strides = (1, 1) + stride
    if pooling_convention == "full":
        # ceil-mode: pad high edge enough that ceil division is covered
        extra = []
        for i in range(k):
            in_sz = data.shape[2 + i] + 2 * pad[i]
            out_sz = -(-(in_sz - kernel[i]) // stride[i]) + 1
            need = (out_sz - 1) * stride[i] + kernel[i] - in_sz
            extra.append(max(0, need))
        pads = ((0, 0), (0, 0)) + tuple(
            (pad[i], pad[i] + extra[i]) for i in range(k))
    else:
        pads = ((0, 0), (0, 0)) + tuple((p, p) for p in pad)

    # NOTE: init values must be python scalars — jax only recognises the
    # differentiable monoid reducers (reduce_window_max/sum) for scalar
    # identities; array inits fall back to the non-differentiable generic.
    if pool_type == "max":
        init = -_np.inf if jnp.issubdtype(data.dtype, jnp.floating) \
            else int(jnp.iinfo(data.dtype).min)
        return lax.reduce_window(data, init, lax.max, window, strides, pads)
    if pool_type in ("avg", "sum"):
        summed = lax.reduce_window(data, 0.0 if jnp.issubdtype(
            data.dtype, jnp.floating) else 0, lax.add, window, strides, pads)
        if pool_type == "sum":
            return summed
        if count_include_pad:
            denom = _np.prod(kernel)
            return summed / jnp.asarray(denom, data.dtype)
        ones = jnp.ones_like(data)
        counts = lax.reduce_window(ones, 0.0, lax.add, window, strides,
                                   pads)
        return summed / counts
    if pool_type == "lp":
        powd = jnp.power(jnp.abs(data), p_value)
        summed = lax.reduce_window(powd, 0.0, lax.add, window, strides,
                                   pads)
        return jnp.power(summed, 1.0 / p_value)
    raise ValueError(pool_type)


# ---------------------------------------------------------------------------
# Activations
# ---------------------------------------------------------------------------


@register("Activation", ndarray_inputs=("data",))
def activation(data, act_type="relu"):
    """ref: src/operator/nn/activation-inl.h."""
    if act_type == "relu":
        return jax.nn.relu(data)
    if act_type == "sigmoid":
        return jax.nn.sigmoid(data)
    if act_type == "tanh":
        return jnp.tanh(data)
    if act_type == "softrelu":
        return jax.nn.softplus(data)
    if act_type == "softsign":
        return jax.nn.soft_sign(data)
    raise ValueError(act_type)


@register("LeakyReLU", ndarray_inputs=("data", "gamma"))
def leaky_relu(data, gamma=None, act_type="leaky", slope=0.25,
               lower_bound=0.125, upper_bound=0.334):
    """ref: src/operator/leaky_relu-inl.h — leaky/prelu/elu/selu/gelu/rrelu."""
    if act_type == "leaky":
        return jax.nn.leaky_relu(data, slope)
    if act_type == "prelu":
        g = gamma.reshape((1, -1) + (1,) * (data.ndim - 2)) \
            if gamma is not None and gamma.ndim == 1 and data.ndim > 2 else gamma
        return jnp.where(data >= 0, data, g * data)
    if act_type == "elu":
        return jnp.where(data >= 0, data, slope * jnp.expm1(data))
    if act_type == "selu":
        return jax.nn.selu(data)
    if act_type == "gelu":
        return jax.nn.gelu(data, approximate=False)
    if act_type == "rrelu":   # eval-mode deterministic slope
        mid = (lower_bound + upper_bound) / 2.0
        return jnp.where(data >= 0, data, mid * data)
    raise ValueError(act_type)


@register("softmax", ndarray_inputs=("data",))
def softmax(data, axis=-1, temperature=None, length=None, use_length=False,
            dtype=None):
    if temperature is not None and temperature != 1.0:
        data = data / temperature
    out = jax.nn.softmax(data, axis=axis)
    if dtype is not None:
        from ..base import dtype_np
        out = out.astype(dtype_np(dtype))
    return out


@register("log_softmax", ndarray_inputs=("data",))
def log_softmax(data, axis=-1, temperature=None, dtype=None):
    if temperature is not None and temperature != 1.0:
        data = data / temperature
    out = jax.nn.log_softmax(data, axis=axis)
    if dtype is not None:
        from ..base import dtype_np
        out = out.astype(dtype_np(dtype))
    return out


@register("softmin", ndarray_inputs=("data",))
def softmin(data, axis=-1, temperature=None, dtype=None):
    if temperature is not None and temperature != 1.0:
        data = data / temperature
    out = jax.nn.softmax(-data, axis=axis)
    if dtype is not None:
        from ..base import dtype_np
        out = out.astype(dtype_np(dtype))
    return out


# ---------------------------------------------------------------------------
# Normalisation
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _bn_train(x, g, b, axis, eps):
    """Training BatchNorm core with a memory-exact custom vjp.

    Plain autodiff of the f32-upcast formulation saves an f32 copy of
    EVERY activation as a residual (2.5× the bf16 activation footprint —
    OOMs ResNet-50 b128 on a 16G chip).  Here the residuals are only the
    bf16 input + per-channel f32 stats; the backward recomputes x̂ on the
    fly inside one fused executable — exactly the cuDNN BN training
    kernel contract (save_mean/save_inv_var).

    Returns (out, mean, var): the batch stats ride out of the SAME
    computation (aux, zero-grad) — r4 computed them a second time
    behind a stop_gradient for the op's aux outputs, an extra full
    read of x per BatchNorm on an HBM-bound model."""
    (out, mean, var), _ = _bn_train_fwd(x, g, b, axis, eps)
    return out, mean, var


def _bn_stats(x, axis):
    """One-pass moments: sum and sum-of-squares fuse into a SINGLE
    multi-output reduction over one read of x (jnp.var's
    E[(x-mean)^2] form costs a second full pass — VERDICT r4 weak #3:
    the ResNet step is HBM-bound, activation reads ARE the step time).
    f32 accumulation keeps E[x^2]-E[x]^2 cancellation benign for
    normalized activations; clamped at 0 for safety.

    `MXNET_BN_STABLE_VAR=1` switches to the shifted two-pass form
    E[(x-mean)^2] (ADVICE.md round 5): when |mean| >> std — f32 nets
    fed unnormalized inputs — E[x^2] and E[x]^2 agree to within f32
    ulp of a HUGE number and their difference is pure rounding noise
    (clamped to 0 → rsqrt(eps) blows the output up).  The two-pass
    path pays a second read of x, which is why it is a knob and not
    the default on the HBM-bound bf16 training path."""
    red = tuple(i for i in range(x.ndim) if i != axis)
    x32 = x.astype(jnp.float32)
    m1 = jnp.mean(x32, axis=red)
    if _config.get("MXNET_BN_STABLE_VAR"):
        bshape = tuple(x.shape[axis] if i == axis else 1
                       for i in range(x.ndim))
        d = x32 - m1.reshape(bshape)
        return m1, jnp.mean(jnp.square(d), axis=red)
    m2 = jnp.mean(jnp.square(x32), axis=red)
    return m1, jnp.maximum(m2 - jnp.square(m1), 0.0)


def _bn_train_fwd(x, g, b, axis, eps):
    red = tuple(i for i in range(x.ndim) if i != axis)
    bshape = tuple(x.shape[axis] if i == axis else 1
                   for i in range(x.ndim))
    mean, var = _bn_stats(x, axis)
    inv = lax.rsqrt(var + eps)
    scale = (g.astype(jnp.float32) * inv).reshape(bshape)
    shift = (b.astype(jnp.float32) -
             mean * g.astype(jnp.float32) * inv).reshape(bshape)
    # compute in the activation dtype: scale/shift are per-channel f32
    # folded to x.dtype — no full-size f32 intermediate is ever live
    out = x * scale.astype(x.dtype) + shift.astype(x.dtype)
    return (out, mean, var), (x, g, mean, inv, red, bshape)


def _bn_train_core_fwd(x, g, b, axis, eps):
    (out, mean, var), res = _bn_train_fwd(x, g, b, axis, eps)
    return (out, mean, var), res


def _bn_train_core_bwd(axis, eps, res, cots):
    dy, _dmean, _dvar = cots        # stats are aux: cotangents ignored
    x, g, mean, inv, red, bshape = res
    n = 1
    for i in red:
        n *= x.shape[i]
    x32 = x.astype(jnp.float32)
    dy32 = dy.astype(jnp.float32)
    xhat = (x32 - mean.reshape(bshape)) * inv.reshape(bshape)
    dbeta = jnp.sum(dy32, axis=red)
    dgamma = jnp.sum(dy32 * xhat, axis=red)
    m1 = (dbeta / n).reshape(bshape)
    m2 = (dgamma / n).reshape(bshape)
    dx = (g.astype(jnp.float32) * inv).reshape(bshape) * \
        (dy32 - m1 - xhat * m2)
    return dx.astype(x.dtype), dgamma.astype(g.dtype), dbeta.astype(g.dtype)


_bn_train.defvjp(_bn_train_core_fwd, _bn_train_core_bwd)


@register("BatchNorm",
          ndarray_inputs=("data", "gamma", "beta", "moving_mean",
                          "moving_var"),
          num_outputs=3, visible_outputs=1)
def batch_norm(data, gamma, beta, moving_mean, moving_var, eps=1e-3,
               momentum=0.9, fix_gamma=True, use_global_stats=False,
               output_mean_var=False, axis=1, cudnn_off=False,
               _training=True):
    """ref: src/operator/nn/batch_norm-inl.h BatchNormOp.

    Returns (out, batch_mean, batch_var). The imperative wrapper updates the
    running stats (the reference mutates `moving_*` in-place inside the
    kernel; here mutation lives at the NDArray layer, keeping the body pure
    so it jits).  `fix_gamma=True` ⇒ gamma treated as 1 (reference default).
    Batch statistics are auxiliary (non-differentiated) outputs, as in the
    reference.
    """
    bshape = tuple(data.shape[axis] if i == axis else 1
                   for i in range(data.ndim))
    g = jnp.ones_like(gamma) if fix_gamma else gamma
    if _training and not use_global_stats:
        # stats come out of the same pass as the normalization (aux,
        # zero-grad) — no second read of data
        return _bn_train(data, g, beta, axis, eps)
    mean = moving_mean.astype(jnp.float32)
    var = moving_var.astype(jnp.float32)
    inv = lax.rsqrt(var + eps)
    scale = (g.astype(jnp.float32) * inv).reshape(bshape)
    shift = (beta.astype(jnp.float32) - mean * g.astype(jnp.float32) *
             inv).reshape(bshape)
    out = data * scale.astype(data.dtype) + shift.astype(data.dtype)
    return out, mean, var


# --- fused sparse softmax cross-entropy (memory-exact vjp) -----------
#
# Plain autodiff through log_softmax + pick saves the f32 probability
# slab over the FULL vocab as a residual — at BERT scale (B·T=16k rows
# x 30522 vocab) that is multiple 2 GB tensors and is what OOMs b>=16
# on a 16 GB chip.  Here the residuals are the logits the caller
# already holds, the labels, and a per-row f32 lse; the backward
# recomputes softmax from them in one fused kernel.


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def _softmax_ce_core(pred, label, axis):
    loss, _ = _softmax_ce_fwd(pred, label, axis)
    return loss


def _softmax_ce_fwd(pred, label, axis):
    p32 = pred.astype(jnp.float32)
    m = jnp.max(p32, axis=axis, keepdims=True)
    lse = m + jnp.log(jnp.sum(jnp.exp(p32 - m), axis=axis,
                              keepdims=True))
    idx = jnp.expand_dims(label.astype(jnp.int32), axis)
    picked = jnp.take_along_axis(p32, idx, axis=axis)
    loss = (lse - picked).squeeze(axis)
    return loss, (pred, label, lse)


def _softmax_ce_core_fwd(pred, label, axis):
    return _softmax_ce_fwd(pred, label, axis)


def _softmax_ce_core_bwd(axis, res, dy):
    pred, label, lse = res
    p = jnp.exp(pred.astype(jnp.float32) - lse)      # softmax, f32 math
    onehot = jax.nn.one_hot(label.astype(jnp.int32), pred.shape[axis],
                            axis=axis, dtype=jnp.float32)
    dpred = (p - onehot) * jnp.expand_dims(
        dy.astype(jnp.float32), axis)
    return dpred.astype(pred.dtype), None


_softmax_ce_core.defvjp(_softmax_ce_core_fwd, _softmax_ce_core_bwd)


@register("_fused_softmax_ce", ndarray_inputs=("pred", "label"),
          nograd_argnums=(1,))
def fused_softmax_ce(pred, label, axis=-1):
    """-log softmax(pred)[label] per row, with a memory-exact custom
    vjp (residuals: logits + labels + per-row lse; the backward
    recomputes softmax).  The gluon SoftmaxCrossEntropyLoss hot path
    (ref: the SoftmaxOutput fused kernel, src/operator/softmax_output*
    — fused fwd+bwd was the reference's answer to the same problem)."""
    ax = axis % pred.ndim
    return _softmax_ce_core(pred, label, ax)


# --- chunked projection + CE: the (rows, vocab) logits never exist ---
#
# For LM/MLM heads the loss-side memory wall is the logits tensor
# itself (BERT-base MLM at batch 32: 16384×30522 ≥ 1 GB per
# materialisation, several live at once through autodiff).  This op
# fuses the vocab projection INTO the loss and scans row-chunks:
# forward keeps only per-row lse; backward recomputes each chunk's
# logits and accumulates dW/db in f32.  Same reasoning as the
# reference's fused SoftmaxOutput kernel, taken one matmul further.


@functools.partial(jax.custom_vjp, nondiff_argnums=(4,))
def _linear_ce_core(hidden, weight, bias, label, nchunk):
    loss, _ = _linear_ce_fwd_impl(hidden, weight, bias, label, nchunk)
    return loss


def _linear_ce_chunk_logits(hc, weight, bias):
    # bf16 MXU matmul with f32 accumulation
    logits = jnp.dot(hc, weight.T,
                     preferred_element_type=jnp.float32)
    return logits + bias.astype(jnp.float32)


def _linear_ce_fwd_impl(hidden, weight, bias, label, nchunk):
    n, d = hidden.shape
    c = n // nchunk
    h3 = hidden.reshape(nchunk, c, d)
    l2 = label.astype(jnp.int32).reshape(nchunk, c)

    def body(_, hl):
        hc, lc = hl
        logits = _linear_ce_chunk_logits(hc, weight, bias)
        m = jnp.max(logits, axis=-1, keepdims=True)
        lse = (m + jnp.log(jnp.sum(jnp.exp(logits - m), axis=-1,
                                   keepdims=True))).squeeze(-1)
        picked = jnp.take_along_axis(logits, lc[:, None],
                                     axis=-1).squeeze(-1)
        return None, (lse - picked, lse)

    _, (loss, lse) = lax.scan(body, None, (h3, l2))
    return loss.reshape(n), (hidden, weight, bias, l2, lse)


def _linear_ce_core_fwd(hidden, weight, bias, label, nchunk):
    return _linear_ce_fwd_impl(hidden, weight, bias, label, nchunk)


def _linear_ce_core_bwd(nchunk, res, dy):
    hidden, weight, bias, l2, lse = res
    n, d = hidden.shape
    v = weight.shape[0]
    c = n // nchunk
    h3 = hidden.reshape(nchunk, c, d)
    dy3 = dy.astype(jnp.float32).reshape(nchunk, c)

    def body(carry, hl):
        dw, db = carry
        hc, lc, lsec, dyc = hl
        logits = _linear_ce_chunk_logits(hc, weight, bias)
        p = jnp.exp(logits - lsec[:, None])
        onehot = jax.nn.one_hot(lc, v, dtype=jnp.float32)
        dlogits = (p - onehot) * dyc[:, None]
        dl16 = dlogits.astype(hidden.dtype)
        dh = jnp.dot(dl16, weight,
                     preferred_element_type=jnp.float32)
        dw = dw + jnp.dot(dl16.T, hc,
                          preferred_element_type=jnp.float32)
        db = db + jnp.sum(dlogits, axis=0)
        return (dw, db), dh.astype(hidden.dtype)

    (dw, db), dh = lax.scan(
        body, (jnp.zeros((v, d), jnp.float32), jnp.zeros((v,), jnp.float32)),
        (h3, l2, lse, dy3))
    return (dh.reshape(n, d), dw.astype(weight.dtype),
            db.astype(bias.dtype), None)


_linear_ce_core.defvjp(_linear_ce_core_fwd, _linear_ce_core_bwd)


@register("_fused_linear_softmax_ce",
          ndarray_inputs=("hidden", "weight", "bias", "label"),
          nograd_argnums=(3,))
def fused_linear_softmax_ce(hidden, weight, bias, label, num_chunks=0):
    """Per-row -log softmax(hidden @ weight.T + bias)[label] without
    materialising the (rows, vocab) logits.  hidden: (N, D); weight:
    (V, D) (FullyConnected layout); bias: (V,); label: (N,) int.
    num_chunks=0 auto-chunks: it picks the largest chunk size in
    [256, 2048] that divides N (falling back to a single unchunked pass,
    with a warning when N > 4096, if N has no divisor in that range —
    e.g. prime N); N must be divisible by the chunk count."""
    n = hidden.shape[0]
    nchunk = int(num_chunks)
    if nchunk <= 0:
        # largest chunk size in [256, 2048] that divides n — not just
        # powers of two, so odd-but-composite row counts still chunk;
        # a prime n degrades to one chunk (full logits) loudly
        nchunk = 1
        for chunk in range(min(n, 2048), 255, -1):
            if n % chunk == 0:
                nchunk = n // chunk
                break
        if nchunk == 1 and n > 4096:
            import warnings
            warnings.warn(
                "_fused_linear_softmax_ce: %d rows have no divisor in "
                "[256, 2048]; computing UNCHUNKED (full logits "
                "materialise) — pass num_chunks explicitly" % n)
    if n % nchunk != 0:
        raise ValueError(
            "_fused_linear_softmax_ce: %d rows not divisible into %d "
            "chunks" % (n, nchunk))
    return _linear_ce_core(hidden, weight, bias, label, nchunk)


# --- SyncBatchNorm: cross-replica moments over a named mesh axis -----
#
# TPU-first note: under pjit/GSPMD (ShardedTrainer), a plain BatchNorm's
# batch reduction is ALREADY global — XLA inserts the collectives when
# the batch axis is sharded, which is the in-compiler equivalent of the
# reference's hand-rolled cross-GPU sync (src/operator/contrib/
# sync_batch_norm-inl.h key-based AllReduce).  This op exists for the
# shard_map path, where per-device bodies see only their local shard
# and the moments must be pmean'd explicitly.


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _bn_train_sync(x, g, b, axis, eps, axis_name):
    """Returns (out, mean, var): the global moments come out of the SAME
    collective pass as the normalisation (no second pmean); their
    cotangents are discarded in the vjp — stop_gradient semantics, as
    in the non-sync op."""
    (out, mean, var), _ = _bn_train_sync_fwd(x, g, b, axis, eps,
                                             axis_name)
    return out, mean, var


def _bn_sync_stats(x, axis, axis_name):
    red = tuple(i for i in range(x.ndim) if i != axis)
    x32 = x.astype(jnp.float32)
    mean = lax.pmean(jnp.mean(x32, axis=red), axis_name)
    if _config.get("MXNET_BN_STABLE_VAR"):
        # shifted two-pass (see _bn_stats): GLOBAL mean subtracted
        # before squaring, then the squared deviations pmean'd — still
        # unbiased over the global batch, one extra read of x
        bshape = tuple(x.shape[axis] if i == axis else 1
                       for i in range(x.ndim))
        d = x32 - mean.reshape(bshape)
        return mean, lax.pmean(jnp.mean(jnp.square(d), axis=red),
                               axis_name)
    # E[x²] − E[x]² over the GLOBAL batch (per-shard var would bias);
    # clamped at 0 like _bn_stats — cancellation noise can go NEGATIVE
    # past eps, and rsqrt of a negative is NaN across the whole layer
    msq = lax.pmean(jnp.mean(x32 * x32, axis=red), axis_name)
    return mean, jnp.maximum(msq - mean * mean, 0.0)


def _bn_train_sync_fwd(x, g, b, axis, eps, axis_name):
    red = tuple(i for i in range(x.ndim) if i != axis)
    bshape = tuple(x.shape[axis] if i == axis else 1
                   for i in range(x.ndim))
    mean, var = _bn_sync_stats(x, axis, axis_name)
    inv = lax.rsqrt(var + eps)
    scale = (g.astype(jnp.float32) * inv).reshape(bshape)
    shift = (b.astype(jnp.float32) -
             mean * g.astype(jnp.float32) * inv).reshape(bshape)
    out = x * scale.astype(x.dtype) + shift.astype(x.dtype)
    return (out, mean, var), (x, g, mean, inv, red, bshape)


def _bn_train_sync_core_fwd(x, g, b, axis, eps, axis_name):
    outs, res = _bn_train_sync_fwd(x, g, b, axis, eps, axis_name)
    return outs, res


def _bn_train_sync_core_bwd(axis, eps, axis_name, res, cots):
    dy = cots[0]            # d_mean/d_var discarded (aux stats)
    x, g, mean, inv, red, bshape = res
    n_local = 1
    for i in red:
        n_local *= x.shape[i]
    n = n_local * lax.psum(1, axis_name)
    x32 = x.astype(jnp.float32)
    dy32 = dy.astype(jnp.float32)
    xhat = (x32 - mean.reshape(bshape)) * inv.reshape(bshape)
    # per-channel reductions must span the GLOBAL batch, like the
    # forward moments
    dbeta = lax.psum(jnp.sum(dy32, axis=red), axis_name)
    dgamma = lax.psum(jnp.sum(dy32 * xhat, axis=red), axis_name)
    m1 = (dbeta / n).reshape(bshape)
    m2 = (dgamma / n).reshape(bshape)
    dx = (g.astype(jnp.float32) * inv).reshape(bshape) * \
        (dy32 - m1 - xhat * m2)
    return dx.astype(x.dtype), dgamma.astype(g.dtype), dbeta.astype(g.dtype)


_bn_train_sync.defvjp(_bn_train_sync_core_fwd, _bn_train_sync_core_bwd)


@register("_contrib_SyncBatchNorm",
          ndarray_inputs=("data", "gamma", "beta", "moving_mean",
                          "moving_var"),
          num_outputs=3, visible_outputs=1)
def sync_batch_norm(data, gamma, beta, moving_mean, moving_var,
                    eps=1e-3, momentum=0.9, fix_gamma=True,
                    use_global_stats=False, output_mean_var=False,
                    axis=1, ndev=1, key="", axis_name="",
                    _training=True):
    """ref: src/operator/contrib/sync_batch_norm-inl.h.

    With `axis_name` set, batch moments (and the backward's per-channel
    reductions) are pmean/psum'd over that shard_map mesh axis — global
    statistics over the device-sharded batch.  With it empty this IS
    BatchNorm (the reference degrades the same way at ndev=1; under
    pjit the compiler already globalises the reduction).  `ndev`/`key`
    are accepted for API parity — the mesh axis replaces the key-based
    rendezvous."""
    if not axis_name or not _training or use_global_stats:
        return batch_norm(data, gamma, beta, moving_mean, moving_var,
                          eps=eps, momentum=momentum,
                          fix_gamma=fix_gamma,
                          use_global_stats=use_global_stats,
                          output_mean_var=output_mean_var, axis=axis,
                          _training=_training)
    g = jnp.ones_like(gamma) if fix_gamma else gamma
    out, mean, var = _bn_train_sync(data, g, beta, axis, eps, axis_name)
    return out, lax.stop_gradient(mean), lax.stop_gradient(var)


@register("LayerNorm", ndarray_inputs=("data", "gamma", "beta"))
def layer_norm(data, gamma, beta, axis=-1, eps=1e-5, output_mean_var=False):
    """ref: src/operator/nn/layer_norm-inl.h."""
    mean = jnp.mean(data, axis=axis, keepdims=True)
    var = jnp.var(data, axis=axis, keepdims=True)
    out = (data - mean) * lax.rsqrt(var + eps)
    bshape = tuple(data.shape[axis] if (i % data.ndim) == (axis % data.ndim)
                   else 1 for i in range(data.ndim))
    return out * gamma.reshape(bshape) + beta.reshape(bshape)


@register("InstanceNorm", ndarray_inputs=("data", "gamma", "beta"))
def instance_norm(data, gamma, beta, eps=1e-3):
    red = tuple(range(2, data.ndim))
    mean = jnp.mean(data, axis=red, keepdims=True)
    var = jnp.var(data, axis=red, keepdims=True)
    out = (data - mean) * lax.rsqrt(var + eps)
    bshape = (1, -1) + (1,) * (data.ndim - 2)
    return out * gamma.reshape(bshape) + beta.reshape(bshape)


@register("GroupNorm", ndarray_inputs=("data", "gamma", "beta"))
def group_norm(data, gamma, beta, num_groups=1, eps=1e-5):
    n, c = data.shape[:2]
    g = num_groups
    x = data.reshape((n, g, c // g) + data.shape[2:])
    red = tuple(range(2, x.ndim))
    mean = jnp.mean(x, axis=red, keepdims=True)
    var = jnp.var(x, axis=red, keepdims=True)
    x = (x - mean) * lax.rsqrt(var + eps)
    x = x.reshape(data.shape)
    bshape = (1, -1) + (1,) * (data.ndim - 2)
    return x * gamma.reshape(bshape) + beta.reshape(bshape)


@register("LRN", ndarray_inputs=("data",))
def lrn(data, alpha=1e-4, beta=0.75, knorm=2.0, nsize=5):
    """ref: src/operator/nn/lrn-inl.h — local response norm across channels."""
    sq = jnp.square(data)
    half = nsize // 2
    padded = jnp.pad(sq, ((0, 0), (half, half)) + ((0, 0),) * (data.ndim - 2))
    acc = jnp.zeros_like(data)
    for i in range(nsize):
        acc = acc + lax.dynamic_slice_in_dim(padded, i, data.shape[1], axis=1)
    return data / jnp.power(knorm + alpha * acc / nsize, beta)


# ---------------------------------------------------------------------------
# Dropout (stateless threefry behind the stateful facade — ref:
# src/operator/nn/dropout-inl.h; RNG design per SURVEY §7.2)
# ---------------------------------------------------------------------------


@register("Dropout", ndarray_inputs=("data",), needs_rng=True)
def dropout(data, p=0.5, mode="training", axes=(), cudnn_off=False,
            _training=True, _rng_key=None):
    if not _training and mode != "always":
        return data
    if p <= 0.0:
        return data
    shape = data.shape
    if axes:
        shape = tuple(1 if i in axes else s for i, s in enumerate(data.shape))
    keep = 1.0 - p
    mask = jax.random.bernoulli(_rng_key, keep, shape).astype(data.dtype)
    return data * mask / jnp.asarray(keep, data.dtype)


# ---------------------------------------------------------------------------
# Embedding
# ---------------------------------------------------------------------------


@register("Embedding", ndarray_inputs=("data", "weight"), nograd_argnums=(0,))
def embedding(data, weight, input_dim=0, output_dim=0, dtype="float32",
              sparse_grad=False):
    """ref: src/operator/tensor/indexing_op.h EmbeddingOp.  sparse_grad's
    row_sparse gradient is realised at the autograd layer via segment-sum
    (see ops/sparse.py)."""
    return jnp.take(weight, data.astype(jnp.int32), axis=0)


# ---------------------------------------------------------------------------
# Losses / output ops
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4, 5, 6, 7, 8))
def _softmax_output_cvjp(data, label, grad_scale, ignore_label,
                         use_ignore, normalization, multi_output,
                         smooth_alpha, out_grad):
    return jax.nn.softmax(data, axis=1 if multi_output else -1)


def _softmax_output_fwd(data, label, grad_scale, ignore_label,
                        use_ignore, normalization, multi_output,
                        smooth_alpha, out_grad):
    p = jax.nn.softmax(data, axis=1 if multi_output else -1)
    return p, (p, label)


def _softmax_output_bwd(grad_scale, ignore_label, use_ignore,
                        normalization, multi_output, smooth_alpha,
                        out_grad, res, cot):
    # reference semantics: the head IS the loss — the data gradient is
    # (softmax − one_hot(label)) * grad_scale, independent of the
    # incoming cotangent unless out_grad=True scales by it
    # (ref: SoftmaxOutputOp::Backward)
    p, label = res
    axis = 1 if multi_output else -1
    C = p.shape[axis]
    oh = jax.nn.one_hot(label.astype(jnp.int32), C, dtype=p.dtype,
                        axis=axis)
    if smooth_alpha:
        # label smoothing: true class 1−α, the rest α/(C−1)
        oh = oh * (1.0 - smooth_alpha) \
            + (1.0 - oh) * (smooth_alpha / max(C - 1, 1))
    g = (p - oh) * grad_scale
    if use_ignore:
        keep = (label != ignore_label).astype(p.dtype)
        g = g * jnp.expand_dims(keep, axis)
    if normalization == "batch":
        g = g / p.shape[0]
    elif normalization == "valid":
        if use_ignore:
            n = jnp.maximum(jnp.sum(label != ignore_label), 1)
        else:
            n = label.size
        g = g / jnp.asarray(n, p.dtype)
    if out_grad:
        g = g * cot
    return g, jnp.zeros_like(label)


_softmax_output_cvjp.defvjp(_softmax_output_fwd, _softmax_output_bwd)


@register("SoftmaxOutput", ndarray_inputs=("data", "label"),
          nograd_argnums=(1,))
def softmax_output(data, label, grad_scale=1.0, ignore_label=-1.0,
                   multi_output=False, use_ignore=False, preserve_shape=False,
                   normalization="null", out_grad=False,
                   smooth_alpha=0.0):
    """ref: src/operator/softmax_output-inl.h.  Forward = softmax
    (axis 1 when multi_output else last axis); backward is the op's own
    rule (softmax − smoothed one_hot), attached via jax.custom_vjp so
    EVERY consumer — imperative tape, executor vjp, hybridized graphs —
    gets the reference gradient."""
    return _softmax_output_cvjp(data, label, float(grad_scale),
                                float(ignore_label), bool(use_ignore),
                                str(normalization), bool(multi_output),
                                float(smooth_alpha), bool(out_grad))


@register("smooth_l1", ndarray_inputs=("data",))
def smooth_l1(data, scalar=1.0):
    s2 = scalar * scalar
    return jnp.where(jnp.abs(data) < 1.0 / s2,
                     0.5 * s2 * jnp.square(data),
                     jnp.abs(data) - 0.5 / s2)


@register("MakeLoss", ndarray_inputs=("data",))
def make_loss(data, grad_scale=1.0, valid_thresh=0.0, normalization="null"):
    return data


@register("CTCLoss", ndarray_inputs=("data", "label"), nograd_argnums=(1,))
def ctc_loss(data, label, data_lengths=None, label_lengths=None,
             use_data_lengths=False, use_label_lengths=False,
             blank_label="first"):
    """ref: src/operator/contrib/ctc_loss-inl.h. Forward-backward in log
    space via lax.scan over time — compiler-friendly (no host loop)."""
    T, B, A = data.shape
    logp = jax.nn.log_softmax(data, axis=-1)
    L = label.shape[1]
    blank = 0 if blank_label == "first" else A - 1
    lab = label.astype(jnp.int32)
    if blank_label == "last":
        lab = lab  # labels already 0..A-2
    # extended label seq: blank, l1, blank, l2, ... blank  (len 2L+1)
    ext = jnp.full((B, 2 * L + 1), blank, dtype=jnp.int32)
    ext = ext.at[:, 1::2].set(lab)
    if use_label_lengths and label_lengths is not None:
        lab_len = label_lengths.astype(jnp.int32)
    else:
        lab_len = jnp.sum((lab >= 0) & (lab != blank) if blank == 0
                          else (lab >= 0), axis=1).astype(jnp.int32)
        lab_len = jnp.sum(lab > 0, axis=1).astype(jnp.int32) if blank == 0 \
            else lab_len
    S = 2 * L + 1
    ninf = jnp.asarray(-1e30, logp.dtype)

    def emit(t_logp):   # (B, S) log prob of ext symbol at t
        return jnp.take_along_axis(t_logp, ext, axis=1)

    same = jnp.concatenate(
        [jnp.zeros((B, 2), dtype=bool),
         ext[:, 2:] == ext[:, :-2]], axis=1)

    a0 = jnp.full((B, S), ninf)
    a0 = a0.at[:, 0].set(logp[0, :, blank])
    a0 = a0.at[:, 1].set(jnp.take_along_axis(logp[0], ext[:, 1:2],
                                             axis=1)[:, 0])

    def step(alpha, t_logp):
        shift1 = jnp.concatenate([jnp.full((B, 1), ninf), alpha[:, :-1]],
                                 axis=1)
        shift2 = jnp.concatenate([jnp.full((B, 2), ninf), alpha[:, :-2]],
                                 axis=1)
        shift2 = jnp.where(same, ninf, shift2)
        merged = jnp.logaddexp(jnp.logaddexp(alpha, shift1), shift2)
        new = merged + emit(t_logp)
        return new, None

    if use_data_lengths and data_lengths is not None:
        dl = data_lengths.astype(jnp.int32)

        def stepm(carry, xs):
            alpha, t = carry
            t_logp = xs
            new, _ = step(alpha, t_logp)
            alpha = jnp.where((t < dl)[:, None], new, alpha)
            return (alpha, t + 1), None
        (alphaT, _), _ = lax.scan(stepm, (a0, jnp.ones((), jnp.int32)),
                                  logp[1:])
    else:
        alphaT, _ = lax.scan(step, a0, logp[1:])
    send = 2 * lab_len
    p_end = jnp.take_along_axis(alphaT, send[:, None], axis=1)[:, 0]
    p_end1 = jnp.take_along_axis(alphaT, jnp.maximum(send - 1, 0)[:, None],
                                 axis=1)[:, 0]
    return -jnp.logaddexp(p_end, p_end1)


alias("CTCLoss", "ctc_loss")


# ---------------------------------------------------------------------------
# Up/Down sampling & resize
# ---------------------------------------------------------------------------


@register("UpSampling", ndarray_inputs=None)
def upsampling(*data, scale=1, sample_type="nearest", num_args=1,
               num_filter=0, multi_input_mode="concat", workspace=512):
    """ref: src/operator/nn/upsampling-inl.h (nearest only; bilinear via
    Deconvolution in the reference — here jax.image)."""
    x = data[0]
    n, c, h, w = x.shape
    if sample_type == "nearest":
        out = jnp.repeat(jnp.repeat(x, scale, axis=2), scale, axis=3)
    else:
        out = jax.image.resize(x, (n, c, h * scale, w * scale), "bilinear")
    if len(data) > 1:
        outs = [out]
        for d in data[1:]:
            s = h * scale // d.shape[2]
            outs.append(jnp.repeat(jnp.repeat(d, s, axis=2), s, axis=3))
        out = jnp.concatenate(outs, axis=1)
    return out


@register("GridGenerator", ndarray_inputs=("data",))
def grid_generator(data, transform_type="affine", target_shape=(0, 0)):
    h, w = target_shape
    if transform_type == "affine":
        ys = jnp.linspace(-1, 1, h)
        xs = jnp.linspace(-1, 1, w)
        gx, gy = jnp.meshgrid(xs, ys)
        ones = jnp.ones_like(gx)
        grid = jnp.stack([gx.ravel(), gy.ravel(), ones.ravel()])  # (3, HW)
        theta = data.reshape(-1, 2, 3)
        out = jnp.matmul(theta, grid)            # (N, 2, HW)
        return out.reshape(-1, 2, h, w)
    if transform_type == "warp":
        # ref: grid_generator-inl.h warp — data is an (N, 2, H, W) flow
        # field added to the identity pixel grid, then normalized to
        # [-1, 1] (x by (W-1)/2, y by (H-1)/2)
        n, _two, fh, fw = data.shape
        xs = jnp.arange(fw, dtype=jnp.float32)
        ys = jnp.arange(fh, dtype=jnp.float32)
        gx, gy = jnp.meshgrid(xs, ys)
        px = data[:, 0] + gx[None]
        py = data[:, 1] + gy[None]
        nx = px * 2.0 / jnp.maximum(fw - 1, 1) - 1.0
        ny = py * 2.0 / jnp.maximum(fh - 1, 1) - 1.0
        return jnp.stack([nx, ny], axis=1)
    raise ValueError("GridGenerator: unknown transform_type %r"
                     % (transform_type,))
