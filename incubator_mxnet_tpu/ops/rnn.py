"""Fused RNN operator.

TPU-native equivalent of the reference fused `RNN` op
(ref: src/operator/rnn.cc, rnn-inl.h; cuDNN path nn/cudnn/cudnn_rnn-inl.h).

Semantics preserved: one op runs a whole (multi-layer, optionally
bidirectional) LSTM/GRU/vanilla-RNN over the padded sequence, taking the
cuDNN-style *flat parameter vector*.  Realisation: `lax.scan` over time
per layer — the scan body is a dense gate matmul (MXU) + elementwise
(VPU), which XLA pipelines; layers/directions unrolled at trace time.

Weight packing order (documented contract, mirrors the cuDNN packing the
reference used): for each layer, for each direction: W_x then W_h for
every gate (gate order LSTM=[i,f,g,o], GRU=[r,z,n]); after ALL weights,
the biases in the same order (b_x then b_h).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register

_GATES = {"rnn_relu": 1, "rnn_tanh": 1, "lstm": 4, "gru": 3}


def rnn_param_size(mode, num_layers, input_size, state_size,
                   bidirectional=False, projection_size=None):
    """Total flat-parameter length (ref: rnn-inl.h GetRnnParamSize)."""
    g = _GATES[mode]
    d = 2 if bidirectional else 1
    size = 0
    for layer in range(num_layers):
        in_sz = input_size if layer == 0 else state_size * d
        size += d * g * (state_size * in_sz + state_size * state_size
                         + 2 * state_size)
    return size


def _unpack(params, mode, num_layers, input_size, state_size, bidirectional):
    """Split the flat vector into per-(layer, dir) weight/bias arrays."""
    g = _GATES[mode]
    d = 2 if bidirectional else 1
    ws, bs = [], []
    off = 0
    for layer in range(num_layers):
        in_sz = input_size if layer == 0 else state_size * d
        layer_ws, layer_bs = [], []
        for direction in range(d):
            wx = params[off:off + g * state_size * in_sz].reshape(
                g * state_size, in_sz)
            off += g * state_size * in_sz
            wh = params[off:off + g * state_size * state_size].reshape(
                g * state_size, state_size)
            off += g * state_size * state_size
            layer_ws.append((wx, wh))
        ws.append(layer_ws)
    for layer in range(num_layers):
        layer_bs = []
        for direction in range(d):
            bx = params[off:off + g * state_size]
            off += g * state_size
            bh = params[off:off + g * state_size]
            off += g * state_size
            layer_bs.append((bx, bh))
        bs.append(layer_bs)
    return ws, bs


def _cell_step(mode, state_size):
    if mode == "lstm":
        def step(carry, gates):
            h, c = carry
            i, f, gg, o = jnp.split(gates, 4, axis=-1)
            i = jax.nn.sigmoid(i)
            f = jax.nn.sigmoid(f)
            gg = jnp.tanh(gg)
            o = jax.nn.sigmoid(o)
            new_c = f * c + i * gg
            new_h = o * jnp.tanh(new_c)
            return (new_h, new_c)
        return step
    if mode == "gru":
        # gru handled specially (gates depend on r·(Wh h)); see _run_layer
        return None
    act = jnp.tanh if mode == "rnn_tanh" else jax.nn.relu

    def step(carry, gates):
        (h,) = carry
        return (act(gates),)
    return step


def _run_layer(x, h0, c0, wx, wh, bx, bh, mode, reverse=False,
               seq_len=None):
    """x: (T, B, I). Returns (outputs (T,B,H), hT, cT).

    seq_len (B,) int — cuDNN-style variable-length semantics (ref:
    rnn-inl.h use_sequence_length): per-row state updates FREEZE past
    that row's length (so hT/cT are the states AT each sequence's end,
    not after running over padding) and outputs at padded positions
    are zeroed.  For the reverse direction the padded prefix of the
    flipped sequence is skipped the same way, so a reversed scan sees
    exactly the real tokens in reverse order.  This is the exactness
    contract generation prefill rides on: right-padding a prompt to a
    shape bucket must not change the encoder state handed to decode."""
    T = x.shape[0]
    state_size = wh.shape[1]
    xg = jnp.einsum("tbi,gi->tbg", x, wx) + bx     # (T, B, G*H) — MXU
    if reverse:
        xg = jnp.flip(xg, axis=0)
    if seq_len is None:
        keep = None
    else:
        # valid step mask per (t, row): forward keeps t < len; in the
        # flipped order pads come FIRST, so reverse keeps t >= T - len
        t_idx = jnp.arange(T)[:, None]              # (T, 1)
        sl = seq_len.astype(jnp.int32)[None, :]     # (1, B)
        keep = (t_idx >= T - sl) if reverse else (t_idx < sl)
        keep = keep[:, :, None]                     # (T, B, 1)

    def _freeze(step):
        """Wrap a scan body: frozen rows keep their carry and emit 0."""
        if keep is None:
            return lambda carry, inp: step(carry, inp)

        def frozen(carry, inp):
            xg_t, k_t = inp
            new, y = step(carry, xg_t)
            new = tuple(jnp.where(k_t, n, o)
                        for n, o in zip(new, carry))
            return new, jnp.where(k_t, y, jnp.zeros_like(y))
        return frozen

    xs = xg if keep is None else (xg, keep)

    if mode == "gru":
        def step(carry, xg_t):
            (h,) = carry
            hg = jnp.matmul(h, wh.T) + bh           # (B, 3H)
            xr, xz, xn = jnp.split(xg_t, 3, axis=-1)
            hr, hz, hn = jnp.split(hg, 3, axis=-1)
            r = jax.nn.sigmoid(xr + hr)
            z = jax.nn.sigmoid(xz + hz)
            n = jnp.tanh(xn + r * hn)
            new_h = (1 - z) * n + z * h
            return (new_h,), new_h
        (hT,), ys = lax.scan(_freeze(step), (h0,), xs)
        cT = None
    elif mode == "lstm":
        cell = _cell_step(mode, state_size)

        def step(carry, xg_t):
            h, c = carry
            gates = xg_t + jnp.matmul(h, wh.T) + bh
            new = cell((h, c), gates)
            return new, new[0]
        (hT, cT), ys = lax.scan(_freeze(step), (h0, c0), xs)
    else:
        cell = _cell_step(mode, state_size)

        def step(carry, xg_t):
            (h,) = carry
            gates = xg_t + jnp.matmul(h, wh.T) + bh
            new = cell((h,), gates)
            return new, new[0]
        (hT,), ys = lax.scan(_freeze(step), (h0,), xs)
        cT = None
    if reverse:
        ys = jnp.flip(ys, axis=0)
    return ys, hT, cT


def _rnn_num_outputs(attrs):
    """y always; +h (and +c for lstm) when state_outputs (default on,
    as gluon.rnn_layer calls it)."""
    so = attrs.get("state_outputs", True)
    if isinstance(so, str):
        so = so.lower() not in ("false", "0")
    if not so:
        return 1
    return 3 if str(attrs.get("mode", "lstm")) == "lstm" else 2


@register("RNN", ndarray_inputs=("data", "parameters", "state", "state_cell"),
          num_outputs=-1, num_outputs_fn=_rnn_num_outputs, needs_rng=True,
          jit=True)
def rnn(data, parameters, state, state_cell=None, state_size=0,
        num_layers=1, bidirectional=False, mode="lstm", p=0.0,
        state_outputs=True, projection_size=None, use_sequence_length=False,
        sequence_length=None, lstm_state_clip_min=None,
        lstm_state_clip_max=None, _training=True, _rng_key=None):
    """data: (T, B, I) (TNC layout, as the reference's default `rnn` call
    from gluon.rnn_layer).  state: (L*D, B, H); lstm also state_cell.

    use_sequence_length + sequence_length (B,): cuDNN variable-length
    semantics — per-row recurrence freezes at that row's length (final
    states are the states AT the length), outputs past it are zeroed,
    and the reverse direction of a bidirectional stack starts at each
    row's last REAL token.  Right-padding then cannot perturb any
    valid position (the generation-prefill exactness contract)."""
    T, B, I = data.shape
    d = 2 if bidirectional else 1
    ws, bs = _unpack(parameters, mode, num_layers, I, state_size,
                     bidirectional)
    seq_len = None
    if use_sequence_length and sequence_length is not None:
        seq_len = jnp.reshape(sequence_length, (-1,))
    hs_out, cs_out = [], []
    x = data
    key = _rng_key
    for layer in range(num_layers):
        outs = []
        for direction in range(d):
            idx = layer * d + direction
            wx, wh = ws[layer][direction]
            bx, bh = bs[layer][direction]
            h0 = state[idx]
            c0 = state_cell[idx] if state_cell is not None else None
            ys, hT, cT = _run_layer(x, h0, c0, wx, wh, bx, bh, mode,
                                    reverse=(direction == 1),
                                    seq_len=seq_len)
            outs.append(ys)
            hs_out.append(hT)
            if cT is not None:
                cs_out.append(cT)
        x = outs[0] if d == 1 else jnp.concatenate(outs, axis=-1)
        if p > 0.0 and _training and layer < num_layers - 1:
            key, sub = jax.random.split(key)
            mask = jax.random.bernoulli(sub, 1.0 - p, x.shape)
            x = jnp.where(mask, x / (1.0 - p), 0.0).astype(x.dtype)
    outputs = [x]
    if state_outputs:
        outputs.append(jnp.stack(hs_out, axis=0))
        if mode == "lstm":
            outputs.append(jnp.stack(cs_out, axis=0))
    return tuple(outputs) if len(outputs) > 1 else outputs[0]


@register("RNN_varlen",
          ndarray_inputs=("data", "parameters", "state", "state_cell",
                          "sequence_length"),
          num_outputs=-1, num_outputs_fn=_rnn_num_outputs,
          needs_rng=True, jit=True)
def rnn_varlen(data, parameters, state, state_cell=None,
               sequence_length=None, state_size=0, num_layers=1,
               bidirectional=False, mode="lstm", p=0.0,
               state_outputs=True, _training=True, _rng_key=None):
    """Variable-length `RNN`: `sequence_length` (B,) int rides as a
    POSITIONAL tensor input (imperative dispatch unwraps positional
    NDArrays only, so the length vector cannot be a keyword attr).
    Same semantics as `RNN(use_sequence_length=True, ...)`: per-row
    state freezing at the length, zeroed outputs past it, reverse
    direction anchored at each row's last real token.  Non-lstm modes
    pass ``state_cell=None`` positionally."""
    return rnn(data, parameters, state, state_cell=state_cell,
               state_size=state_size, num_layers=num_layers,
               bidirectional=bidirectional, mode=mode, p=p,
               state_outputs=state_outputs, use_sequence_length=True,
               sequence_length=sequence_length, _training=_training,
               _rng_key=_rng_key)
