"""Contrib operators: detection boxes/NMS/ROI, resize, adaptive pooling.

TPU-native equivalents of ref: src/operator/contrib/{bounding_box.cc,
multibox_prior.cc, multibox_target.cc, multibox_detection.cc,
roi_align.cc, adaptive_avg_pooling.cc, bilinear_resize.cc} and
src/operator/roi_pooling.cc.

Dynamic-output ops (NMS) follow the TPU convention (SURVEY §7.2): fixed
shapes, suppressed entries marked with -1 — which is exactly the
reference's `box_nms` contract, so no API change was needed.  Greedy NMS
is a `lax.fori_loop` over score-ranked boxes with vectorised suppression
masks (no host loop, jittable).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as _np
from jax import lax

from .registry import register, alias


# ---------------------------------------------------------------------------
# box primitives
# ---------------------------------------------------------------------------

def _iou_corner(a, b):
    """IoU of (..., 4) corner boxes vs (..., M, 4) — broadcasting."""
    tl = jnp.maximum(a[..., None, :2], b[..., None, :, :2])
    br = jnp.minimum(a[..., None, 2:4], b[..., None, :, 2:4])
    wh = jnp.maximum(br - tl, 0.0)
    inter = wh[..., 0] * wh[..., 1]
    area_a = jnp.maximum(a[..., 2] - a[..., 0], 0.0) * \
        jnp.maximum(a[..., 3] - a[..., 1], 0.0)
    area_b = jnp.maximum(b[..., 2] - b[..., 0], 0.0) * \
        jnp.maximum(b[..., 3] - b[..., 1], 0.0)
    union = area_a[..., None] + area_b[..., None, :] - inter
    return jnp.where(union > 0, inter / union, 0.0)


@register("box_iou", ndarray_inputs=("lhs", "rhs"))
def box_iou(lhs, rhs, format="corner"):
    """ref: bounding_box.cc box_iou — pairwise IoU."""
    if format == "center":
        def c2c(x):
            cx, cy, w, h = (x[..., 0], x[..., 1], x[..., 2], x[..., 3])
            return jnp.stack([cx - w / 2, cy - h / 2, cx + w / 2,
                              cy + h / 2], axis=-1)
        lhs, rhs = c2c(lhs), c2c(rhs)
    la = lhs.reshape(-1, 4)
    rb = rhs.reshape(-1, 4)
    out = _iou_corner(la, rb)
    return out.reshape(lhs.shape[:-1] + rhs.shape[:-1])


@register("box_nms", ndarray_inputs=("data",), differentiable=False,
          jit=True)
def box_nms(data, overlap_thresh=0.5, valid_thresh=0.0, topk=-1,
            coord_start=2, score_index=1, id_index=-1,
            background_id=-1, force_suppress=False, in_format="corner",
            out_format="corner"):
    """ref: bounding_box.cc box_nms. Input (..., N, K); output same shape
    with suppressed boxes' score set to -1 (fixed shape — TPU friendly
    and reference-compatible)."""
    shape = data.shape
    d = data.reshape((-1,) + shape[-2:])       # (B, N, K)
    B, N, K = d.shape
    scores = d[..., score_index]
    boxes = lax.dynamic_slice_in_dim(d, coord_start, 4, axis=2)
    if in_format == "center":
        cx, cy, w, h = (boxes[..., 0], boxes[..., 1], boxes[..., 2],
                        boxes[..., 3])
        boxes = jnp.stack([cx - w / 2, cy - h / 2, cx + w / 2,
                           cy + h / 2], axis=-1)
    cls = d[..., id_index] if id_index >= 0 else jnp.zeros_like(scores)
    valid = scores > valid_thresh
    if id_index >= 0 and background_id >= 0:
        valid = jnp.logical_and(valid, cls != background_id)

    order = jnp.argsort(-jnp.where(valid, scores, -jnp.inf), axis=1)
    if topk > 0:
        keep_rank = jnp.arange(N) < topk
    else:
        keep_rank = jnp.ones((N,), bool)

    sboxes = jnp.take_along_axis(boxes, order[..., None], axis=1)
    svalid = jnp.take_along_axis(valid, order, axis=1) & keep_rank
    scls = jnp.take_along_axis(cls, order, axis=1)

    iou = _iou_corner(sboxes, sboxes)          # (B, N, N)
    same_cls = scls[..., :, None] == scls[..., None, :]
    suppress_pair = iou > overlap_thresh
    if not force_suppress:
        suppress_pair = suppress_pair & same_cls

    def body(i, keep):
        # box i suppresses later boxes if it is kept & valid
        row = suppress_pair[:, i, :] & (jnp.arange(N) > i)
        ki = keep[:, i] & svalid[:, i]
        return jnp.where(ki[:, None], keep & ~row, keep)

    keep = lax.fori_loop(0, N, body, jnp.ones((B, N), bool))
    keep = keep & svalid
    # scatter back to original order
    inv = jnp.argsort(order, axis=1)
    keep_orig = jnp.take_along_axis(keep, inv, axis=1)
    new_scores = jnp.where(keep_orig, scores, -jnp.ones_like(scores))
    out = d.at[..., score_index].set(new_scores)
    return out.reshape(shape)


@register("box_encode", ndarray_inputs=("samples", "matches", "anchors",
                                        "refs"), differentiable=False)
def box_encode(samples, matches, anchors, refs, means=(0., 0., 0., 0.),
               stds=(0.1, 0.1, 0.2, 0.2)):
    """ref: bounding_box.cc box_encode — corner gt vs center anchors."""
    m = matches.astype(jnp.int32)
    ref = jnp.take_along_axis(refs, m[..., None], axis=1)
    def corner2center(x):
        w = x[..., 2] - x[..., 0]
        h = x[..., 3] - x[..., 1]
        return (x[..., 0] + w / 2, x[..., 1] + h / 2, w, h)
    gx, gy, gw, gh = corner2center(ref)
    ax, ay, aw, ah = corner2center(anchors)
    t0 = ((gx - ax) / aw - means[0]) / stds[0]
    t1 = ((gy - ay) / ah - means[1]) / stds[1]
    t2 = (jnp.log(jnp.maximum(gw / aw, 1e-12)) - means[2]) / stds[2]
    t3 = (jnp.log(jnp.maximum(gh / ah, 1e-12)) - means[3]) / stds[3]
    targets = jnp.stack([t0, t1, t2, t3], axis=-1)
    mask = (samples > 0.5)[..., None]
    return jnp.where(mask, targets, 0.0), \
        jnp.broadcast_to(mask, targets.shape).astype(targets.dtype)


@register("box_decode", ndarray_inputs=("data", "anchors"))
def box_decode(data, anchors, std0=1.0, std1=1.0, std2=1.0, std3=1.0,
               clip=-1.0, format="corner"):
    aw = anchors[..., 2] - anchors[..., 0]
    ah = anchors[..., 3] - anchors[..., 1]
    ax = anchors[..., 0] + aw / 2
    ay = anchors[..., 1] + ah / 2
    ox = data[..., 0] * std0 * aw + ax
    oy = data[..., 1] * std1 * ah + ay
    dw = data[..., 2] * std2
    dh = data[..., 3] * std3
    if clip > 0:
        dw = jnp.minimum(dw, clip)
        dh = jnp.minimum(dh, clip)
    ow = jnp.exp(dw) * aw / 2
    oh = jnp.exp(dh) * ah / 2
    return jnp.stack([ox - ow, oy - oh, ox + ow, oy + oh], axis=-1)


@register("bipartite_matching", ndarray_inputs=("data",),
          differentiable=False, num_outputs=2)
def bipartite_matching(data, threshold=0.5, is_ascend=False, topk=-1):
    """ref: bounding_box.cc bipartite_matching — greedy row/col matching
    on a (B, N, M) score matrix."""
    B, N, M = data.shape
    score = -data if is_ascend else data          # always maximize
    K = min(N, M) if topk <= 0 else min(topk, N, M)
    ar = jnp.arange(B)

    def step(carry, _):
        s, row_match, col_match = carry
        flat = s.reshape(B, N * M)
        idx = jnp.argmax(flat, axis=1)
        best = jnp.take_along_axis(flat, idx[:, None], axis=1)[:, 0]
        r = idx // M
        c = idx % M
        orig = -best if is_ascend else best       # user-scale score
        ok = (orig < threshold) if is_ascend else (orig > threshold)
        row_match = row_match.at[ar, r].set(
            jnp.where(ok, c.astype(jnp.int32), row_match[ar, r]))
        col_match = col_match.at[ar, c].set(
            jnp.where(ok, r.astype(jnp.int32), col_match[ar, c]))
        rmask = jnp.arange(N)[None, :] == r[:, None]
        cmask = jnp.arange(M)[None, :] == c[:, None]
        blank = rmask[:, :, None] | cmask[:, None, :]
        s = jnp.where(ok[:, None, None] & blank, -jnp.inf, s)
        return (s, row_match, col_match), None

    init = (score,
            jnp.full((B, N), -1, jnp.int32),
            jnp.full((B, M), -1, jnp.int32))
    (_, row_match, col_match), _ = lax.scan(step, init, None, length=K)
    return row_match.astype(jnp.float32), col_match.astype(jnp.float32)


# ---------------------------------------------------------------------------
# MultiBox family (SSD config)
# ---------------------------------------------------------------------------


@register("MultiBoxPrior", ndarray_inputs=("data",), differentiable=False,
          jit=True)
def multibox_prior(data, sizes=(1.0,), ratios=(1.0,), clip=False,
                   steps=(-1.0, -1.0), offsets=(0.5, 0.5)):
    """ref: multibox_prior.cc — anchors for one feature map (1, H*W*A, 4)."""
    H, W = data.shape[2], data.shape[3]
    sizes = tuple(sizes)
    ratios = tuple(ratios)
    step_y = steps[1] if steps[1] > 0 else 1.0 / H
    step_x = steps[0] if steps[0] > 0 else 1.0 / W
    ys = (jnp.arange(H) + offsets[1]) * step_y
    xs = (jnp.arange(W) + offsets[0]) * step_x
    cy, cx = jnp.meshgrid(ys, xs, indexing="ij")
    # anchor shapes: (sizes[0], r) for all ratios + (s, 1) for sizes[1:]
    ws, hs = [], []
    for r in ratios:
        sr = _np.sqrt(r)
        ws.append(sizes[0] * sr)
        hs.append(sizes[0] / sr)
    for s in sizes[1:]:
        ws.append(s)
        hs.append(s)
    ws = jnp.asarray(ws)
    hs = jnp.asarray(hs)
    A = ws.shape[0]
    cxe = cx[..., None]
    cye = cy[..., None]
    boxes = jnp.stack([cxe - ws / 2, cye - hs / 2,
                       cxe + ws / 2, cye + hs / 2], axis=-1)  # (H,W,A,4)
    if clip:
        boxes = jnp.clip(boxes, 0.0, 1.0)
    return boxes.reshape(1, H * W * A, 4)


@register("MultiBoxTarget", ndarray_inputs=("anchor", "label", "cls_pred"),
          jit=True,
          differentiable=False, num_outputs=3)
def multibox_target(anchor, label, cls_pred, overlap_threshold=0.5,
                    ignore_label=-1.0, negative_mining_ratio=-1.0,
                    negative_mining_thresh=0.5, minimum_negative_samples=0,
                    variances=(0.1, 0.1, 0.2, 0.2)):
    """ref: multibox_target.cc — SSD training targets.

    anchor (1, N, 4) corner; label (B, M, 5) [cls, x1, y1, x2, y2] with
    -1 padding.  Returns (loc_target (B, N*4), loc_mask (B, N*4),
    cls_target (B, N))."""
    anchors = anchor.reshape(-1, 4)
    N = anchors.shape[0]
    B, M, _ = label.shape

    def per_sample(lab):
        gt_valid = lab[:, 0] >= 0
        gt_boxes = lab[:, 1:5]
        iou = _iou_corner(anchors, gt_boxes)[..., :]    # (N, M)
        iou = jnp.where(gt_valid[None, :], iou, -1.0)
        best_gt = jnp.argmax(iou, axis=1)               # per anchor
        best_iou = jnp.max(iou, axis=1)
        # force-match: best anchor per gt
        best_anchor = jnp.argmax(iou, axis=0)           # (M,)
        forced = jnp.zeros((N,), bool).at[best_anchor].set(gt_valid)
        pos = forced | (best_iou >= overlap_threshold)
        matched_gt = best_gt
        cls_t = jnp.where(
            pos, lab[matched_gt, 0] + 1.0, 0.0)          # 0 = background
        # location targets (center encoding with variances)
        mg = gt_boxes[matched_gt]
        aw = anchors[:, 2] - anchors[:, 0]
        ah = anchors[:, 3] - anchors[:, 1]
        ax = anchors[:, 0] + aw / 2
        ay = anchors[:, 1] + ah / 2
        gw = jnp.maximum(mg[:, 2] - mg[:, 0], 1e-8)
        gh = jnp.maximum(mg[:, 3] - mg[:, 1], 1e-8)
        gx = mg[:, 0] + gw / 2
        gy = mg[:, 1] + gh / 2
        t = jnp.stack([(gx - ax) / aw / variances[0],
                       (gy - ay) / ah / variances[1],
                       jnp.log(gw / aw) / variances[2],
                       jnp.log(gh / ah) / variances[3]], axis=-1)
        mask = pos[:, None].astype(t.dtype) * jnp.ones((1, 4), t.dtype)
        t = t * mask
        return t.reshape(-1), mask.reshape(-1), cls_t

    loc_t, loc_m, cls_t = jax.vmap(per_sample)(label)
    return loc_t, loc_m, cls_t


@register("MultiBoxDetection", ndarray_inputs=("cls_prob", "loc_pred",
                                               "anchor"),
          differentiable=False, jit=True)
def multibox_detection(cls_prob, loc_pred, anchor, clip=True,
                       threshold=0.01, background_id=0, nms_threshold=0.5,
                       force_suppress=False, variances=(0.1, 0.1, 0.2, 0.2),
                       nms_topk=-1):
    """ref: multibox_detection.cc — decode + per-class NMS.
    cls_prob (B, C, N), loc_pred (B, N*4), anchor (1, N, 4).
    Output (B, N, 6) rows [cls_id, score, x1, y1, x2, y2], -1 padded."""
    B, C, N = cls_prob.shape
    anchors = anchor.reshape(-1, 4)
    loc = loc_pred.reshape(B, N, 4)
    aw = anchors[:, 2] - anchors[:, 0]
    ah = anchors[:, 3] - anchors[:, 1]
    ax = anchors[:, 0] + aw / 2
    ay = anchors[:, 1] + ah / 2
    ox = loc[..., 0] * variances[0] * aw + ax
    oy = loc[..., 1] * variances[1] * ah + ay
    ow = jnp.exp(loc[..., 2] * variances[2]) * aw / 2
    oh = jnp.exp(loc[..., 3] * variances[3]) * ah / 2
    boxes = jnp.stack([ox - ow, oy - oh, ox + ow, oy + oh], axis=-1)
    if clip:
        boxes = jnp.clip(boxes, 0.0, 1.0)
    # best non-background class per anchor
    fg = cls_prob[:, 1:, :] if background_id == 0 else cls_prob
    cls_id = jnp.argmax(fg, axis=1).astype(jnp.float32)
    score = jnp.max(fg, axis=1)
    keep = score > threshold
    cls_id = jnp.where(keep, cls_id, -1.0)
    score = jnp.where(keep, score, -1.0)
    det = jnp.concatenate([cls_id[..., None], score[..., None], boxes],
                          axis=-1)
    return box_nms(det, overlap_thresh=nms_threshold, valid_thresh=0.0,
                   topk=nms_topk, coord_start=2, score_index=1, id_index=0,
                   force_suppress=force_suppress)


# ---------------------------------------------------------------------------
# ROI ops (Faster-RCNN config)
# ---------------------------------------------------------------------------


_ROI_CHUNK = 32


@register("ROIAlign", ndarray_inputs=("data", "rois"), nograd_argnums=(1,),
          jit=True)
def roi_align(data, rois, pooled_size=(7, 7), spatial_scale=1.0,
              sample_ratio=2, position_sensitive=False, aligned=False):
    """ref: contrib/roi_align.cc — bilinear-sampled ROI pooling.
    data (B, C, H, W); rois (R, 5) [batch_idx, x1, y1, x2, y2].

    TPU-first: bilinear sampling is SEPARABLE, so instead of 4-tap
    gathers per sample point (the r4 implementation — 58 ms fwd for
    256 rois on a (2, 1024, 38, 50) map, plus a scatter-heavy
    backward), each roi builds two tiny interpolation matrices
    Wy (PH·S, H) / Wx (PW·S, W) and the sampling becomes three
    einsums (batch one-hot select, y-contract, x-contract) — all MXU
    matmuls, gather/scatter-free in both directions.  Rois run in
    chunks of 32 under `lax.scan` to bound the (chunk, C, PH·S, W)
    intermediate.  A padded roi (batch_idx -1) one-hot-selects
    nothing and pools to exact zeros."""
    PH, PW = pooled_size
    S = max(1, int(sample_ratio))
    offset = 0.5 if aligned else 0.0
    B, C, H, W = data.shape
    R = rois.shape[0]

    def weights_1d(coords, n):
        """(P,) sample coords → (P, n) bilinear row weights, with the
        reference's edge semantics: taps floor/floor+1 clipped into
        range, whole row zeroed outside [-1, n]."""
        c0 = jnp.floor(coords)
        w1 = coords - c0
        w0 = 1.0 - w1
        i0 = jnp.clip(c0.astype(jnp.int32), 0, n - 1)
        i1 = jnp.clip(c0.astype(jnp.int32) + 1, 0, n - 1)
        inb = (coords >= -1) & (coords <= n)
        idx = jnp.arange(n)
        wm = (w0[:, None] * (idx[None, :] == i0[:, None]) +
              w1[:, None] * (idx[None, :] == i1[:, None]))
        return jnp.where(inb[:, None], wm, 0.0)

    def one_roi_mats(roi):
        x1 = roi[1] * spatial_scale - offset
        y1 = roi[2] * spatial_scale - offset
        x2 = roi[3] * spatial_scale - offset
        y2 = roi[4] * spatial_scale - offset
        rw = jnp.maximum(x2 - x1, 1.0 if not aligned else 1e-6)
        rh = jnp.maximum(y2 - y1, 1.0 if not aligned else 1e-6)
        ys = y1 + (jnp.arange(PH * S) + 0.5) * (rh / PH / S)
        xs = x1 + (jnp.arange(PW * S) + 0.5) * (rw / PW / S)
        b = roi[0].astype(jnp.int32)
        bh = (jnp.arange(B) == b).astype(jnp.float32)
        return bh, weights_1d(ys, H), weights_1d(xs, W)

    if R == 0:      # empty roi set: empty pooled output (vmap parity)
        return jnp.zeros((0, C, PH, PW), data.dtype)
    bh, wy, wx = jax.vmap(one_roi_mats)(rois)
    # the S×S sample mean is linear — fold it into the matrices, so
    # the contractions produce the POOLED (PH, PW) output directly
    wy = wy.reshape(R, PH, S, H).mean(axis=2)
    wx = wx.reshape(R, PW, S, W).mean(axis=2)
    ch = min(_ROI_CHUNK, R)
    rpad = ((R + ch - 1) // ch) * ch
    bh = jnp.pad(bh, ((0, rpad - R), (0, 0)))
    wy = jnp.pad(wy, ((0, rpad - R), (0, 0), (0, 0)))
    wx = jnp.pad(wx, ((0, rpad - R), (0, 0), (0, 0)))
    nc = rpad // ch
    # bf16 features: bf16 operands + f32 MXU accumulation.  f32
    # features need Precision.HIGHEST — the MXU's default truncates
    # f32 operands to bf16 (preferred_element_type only widens the
    # accumulator), which would silently cost ~3 decimal digits
    odt = data.dtype if data.dtype != jnp.float64 else jnp.float32
    prec = (lax.Precision.HIGHEST if odt == jnp.float32 else None)
    ein = functools.partial(jnp.einsum, precision=prec,
                            preferred_element_type=jnp.float32)

    def chunk_fn(_, mats):
        bhc, wyc, wxc = mats
        img = ein("rb,bchw->rchw", bhc.astype(odt), data)
        t = ein("rph,rchw->rcpw", wyc.astype(odt), img.astype(odt))
        s = ein("rqw,rcpw->rcpq", wxc.astype(odt), t.astype(odt))
        return None, s.astype(data.dtype)

    _, out = lax.scan(chunk_fn, None,
                      (bh.reshape(nc, ch, B),
                       wy.reshape(nc, ch, PH, H),
                       wx.reshape(nc, ch, PW, W)))
    return out.reshape(rpad, C, PH, PW)[:R]


@register("ROIPooling", ndarray_inputs=("data", "rois"), nograd_argnums=(1,),
          jit=True)
def roi_pooling(data, rois, pooled_size=(7, 7), spatial_scale=1.0):
    """ref: src/operator/roi_pooling.cc — quantised max pooling."""
    PH, PW = pooled_size
    B, C, H, W = data.shape

    def one_roi(roi):
        b = roi[0].astype(jnp.int32)
        x1 = jnp.round(roi[1] * spatial_scale)
        y1 = jnp.round(roi[2] * spatial_scale)
        x2 = jnp.round(roi[3] * spatial_scale)
        y2 = jnp.round(roi[4] * spatial_scale)
        rw = jnp.maximum(x2 - x1 + 1, 1.0)
        rh = jnp.maximum(y2 - y1 + 1, 1.0)
        img = data[b]
        ph = jnp.arange(PH)
        pw = jnp.arange(PW)
        hstart = jnp.floor(ph * rh / PH) + y1
        hend = jnp.ceil((ph + 1) * rh / PH) + y1
        wstart = jnp.floor(pw * rw / PW) + x1
        wend = jnp.ceil((pw + 1) * rw / PW) + x1
        yy = jnp.arange(H)[None, :]
        in_h = (yy >= hstart[:, None]) & (yy < hend[:, None])  # (PH, H)
        xx = jnp.arange(W)[None, :]
        in_w = (xx >= wstart[:, None]) & (xx < wend[:, None])  # (PW, W)
        m = in_h[:, None, :, None] & in_w[None, :, None, :]    # PH PW H W
        big = jnp.where(m[None], img[:, None, None, :, :], -jnp.inf)
        return jnp.max(big, axis=(3, 4))                       # (C, PH, PW)

    return jax.vmap(one_roi)(rois)


# ---------------------------------------------------------------------------
# resize / adaptive pooling
# ---------------------------------------------------------------------------


@register("BilinearResize2D", ndarray_inputs=("data",), jit=True)
def bilinear_resize_2d(data, height=0, width=0, scale_height=None,
                       scale_width=None, mode="size",
                       align_corners=True):
    """ref: contrib/bilinear_resize.cc."""
    n, c, h, w = data.shape
    if height == 0 or mode != "size":
        height = int(h * (scale_height or 1.0))
        width = int(w * (scale_width or 1.0))
    return jax.image.resize(data, (n, c, int(height), int(width)),
                            method="bilinear")


@register("AdaptiveAvgPooling2D", ndarray_inputs=("data",))
def adaptive_avg_pooling_2d(data, output_size=(1, 1)):
    """ref: contrib/adaptive_avg_pooling.cc — exact torch-style binning."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    OH, OW = output_size
    n, c, H, W = data.shape
    if H % OH == 0 and W % OW == 0:
        return data.reshape(n, c, OH, H // OH, OW, W // OW).mean(
            axis=(3, 5))
    rows = []
    for oh in range(OH):
        h0 = (oh * H) // OH
        h1 = -(-((oh + 1) * H) // OH)
        cols = []
        for ow in range(OW):
            w0 = (ow * W) // OW
            w1 = -(-((ow + 1) * W) // OW)
            cols.append(data[:, :, h0:h1, w0:w1].mean(axis=(2, 3)))
        rows.append(jnp.stack(cols, axis=-1))
    return jnp.stack(rows, axis=-2)


@register("count_sketch", ndarray_inputs=("data", "h", "s"),
          differentiable=False)
def count_sketch(data, h, s, out_dim=0, processing_batch_size=32):
    """ref: contrib/count_sketch.cc — compact bilinear pooling hash."""
    idx = h.astype(jnp.int32).reshape(-1)
    sign = s.reshape(-1)
    out = jnp.zeros(data.shape[:-1] + (int(out_dim),), data.dtype)
    return out.at[..., idx].add(data * sign)


@register("index_copy", ndarray_inputs=("old", "index", "new"),
          nograd_argnums=(1,))
def index_copy(old, index, new):
    """ref: contrib/index_copy.cc."""
    return old.at[index.astype(jnp.int32)].set(new)


@register("getnnz", ndarray_inputs=("data",), differentiable=False)
def getnnz(data, axis=None):
    nz = (data != 0)
    if axis is None:
        return jnp.sum(nz).astype(jnp.int64).reshape(1)
    return jnp.sum(nz, axis=axis).astype(jnp.int64)


# interleaved attention kernels (ref: contrib/transformer.cc — BERT path);
# XLA fuses these patterns natively, bodies provided for API parity.

@register("interleaved_matmul_selfatt_qk",
          ndarray_inputs=("queries_keys_values",))
def interleaved_matmul_selfatt_qk(queries_keys_values, heads=1):
    """qkv: (T, B, 3*C) interleaved per head. Returns (B*H, T, T)."""
    T, B, C3 = queries_keys_values.shape
    C = C3 // 3
    d = C // heads
    qkv = queries_keys_values.reshape(T, B, heads, 3, d)
    q = qkv[:, :, :, 0, :].transpose(1, 2, 0, 3).reshape(B * heads, T, d)
    k = qkv[:, :, :, 1, :].transpose(1, 2, 0, 3).reshape(B * heads, T, d)
    return jnp.matmul(q, k.transpose(0, 2, 1)) / _np.sqrt(d)


@register("interleaved_matmul_selfatt_valatt",
          ndarray_inputs=("queries_keys_values", "attention"))
def interleaved_matmul_selfatt_valatt(queries_keys_values, attention,
                                      heads=1):
    T, B, C3 = queries_keys_values.shape
    C = C3 // 3
    d = C // heads
    qkv = queries_keys_values.reshape(T, B, heads, 3, d)
    v = qkv[:, :, :, 2, :].transpose(1, 2, 0, 3).reshape(B * heads, T, d)
    out = jnp.matmul(attention, v)                 # (B*H, T, d)
    return out.reshape(B, heads, T, d).transpose(2, 0, 1, 3) \
        .reshape(T, B, C)
