"""INT8 quantization operators.

TPU-native re-design of ref: src/operator/quantization/{quantize.cc,
quantize_v2.cc, dequantize.cc, requantize.cc, quantized_conv.cc,
quantized_fully_connected.cc, quantized_pooling.cc, quantized_flatten.cc,
quantized_elemwise_add.cc}.

Range convention (identical to the reference): a quantized tensor is the
triple (q, min_range, max_range); the real value is
``q * MaxAbs(min_range, max_range) / Q`` with Q = 127 for int8,
2^31-1 for int32 (symmetric signed), and an affine mapping for uint8.

TPU mapping: int8×int8 `lax.dot_general`/`conv_general_dilated` with
``preferred_element_type=int32`` lowers onto the MXU's native 8-bit
multiply / 32-bit accumulate path — the cuDNN-int8 analogue, but picked
by the compiler instead of a runtime autotuner.
"""
from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from .registry import register, alias

INT8_Q = 127.0
INT32_Q = float(2 ** 31 - 1)


def _max_abs(mn, mx):
    return jnp.maximum(jnp.abs(mn), jnp.abs(mx))


def _scale_of(mn, mx, out_type):
    if out_type == "uint8":
        return (mx - mn) / 255.0
    q = INT8_Q if out_type == "int8" else INT32_Q
    return _max_abs(mn, mx) / q


@register("_contrib_quantize", ndarray_inputs=("data", "min_range",
                                               "max_range"),
          differentiable=False, num_outputs=3, jit=True)
def quantize(data, min_range, max_range, out_type="uint8"):
    """ref: quantize.cc — float → int8/uint8 given a range."""
    mn = jnp.min(min_range)
    mx = jnp.max(max_range)
    if out_type == "uint8":
        scale = (mx - mn) / 255.0
        q = jnp.clip(jnp.round((data - mn) / scale), 0, 255).astype(
            jnp.uint8)
        return q, mn, mx
    amax = _max_abs(mn, mx)
    scale = amax / INT8_Q
    q = jnp.clip(jnp.round(data / scale), -INT8_Q, INT8_Q).astype(jnp.int8)
    return q, -amax, amax


@register("_contrib_quantize_v2", ndarray_inputs=("data",),
          differentiable=False, num_outputs=3, jit=True)
def quantize_v2(data, out_type="int8", min_calib_range=None,
                max_calib_range=None):
    """ref: quantize_v2.cc — range from calibration attrs, or from the
    data itself when uncalibrated."""
    if min_calib_range is None or max_calib_range is None:
        mn = jnp.min(data)
        mx = jnp.max(data)
    else:
        mn = jnp.asarray(min_calib_range, jnp.float32)
        mx = jnp.asarray(max_calib_range, jnp.float32)
    return quantize(data, mn, mx, out_type=out_type)


@register("_contrib_dequantize", ndarray_inputs=("data", "min_range",
                                                 "max_range"),
          differentiable=False, jit=True)
def dequantize(data, min_range, max_range, out_type="float32"):
    """ref: dequantize.cc — int8/int32/uint8 → float."""
    mn = jnp.min(min_range)
    mx = jnp.max(max_range)
    if data.dtype == jnp.uint8:
        scale = (mx - mn) / 255.0
        return data.astype(jnp.float32) * scale + mn
    q = INT8_Q if data.dtype == jnp.int8 else INT32_Q
    scale = _max_abs(mn, mx) / q
    return data.astype(jnp.float32) * scale


@register("_contrib_requantize", ndarray_inputs=("data", "min_range",
                                                 "max_range"),
          differentiable=False, num_outputs=3, jit=True)
def requantize(data, min_range, max_range, min_calib_range=None,
               max_calib_range=None, out_type="int8"):
    """ref: requantize.cc — int32 accumulator → int8 with a (calibrated)
    narrower range."""
    real = dequantize(data, min_range, max_range)
    if min_calib_range is None or max_calib_range is None:
        mn = jnp.min(real)
        mx = jnp.max(real)
    else:
        mn = jnp.asarray(min_calib_range, jnp.float32)
        mx = jnp.asarray(max_calib_range, jnp.float32)
    return quantize(real, mn, mx, out_type=out_type)


def _int32_out_range(min_d, max_d, min_w, max_w):
    """Output range of an int8×int8→int32 accumulation (ref:
    quantization_utils.h QuantizationRangeForMultiplication)."""
    s = (_max_abs(jnp.min(min_d), jnp.max(max_d)) / INT8_Q) * \
        (_max_abs(jnp.min(min_w), jnp.max(max_w)) / INT8_Q)
    mx = s * INT32_Q
    return -mx, mx


@register("_contrib_quantized_fully_connected",
          ndarray_inputs=("data", "weight", "bias", "min_data", "max_data",
                          "min_weight", "max_weight", "min_bias",
                          "max_bias"),
          differentiable=False, num_outputs=3, jit=True)
def quantized_fully_connected(data, weight, bias, min_data, max_data,
                              min_weight, max_weight, min_bias, max_bias,
                              num_hidden=None, no_bias=False,
                              flatten=True):
    """ref: quantized_fully_connected.cc — int8 GEMM, int32 accum.

    Output is the raw int32 accumulator plus its range; follow with
    `_contrib_requantize` (calibrated) or `_contrib_dequantize`."""
    if data.dtype == jnp.uint8 or weight.dtype == jnp.uint8:
        # affine uint8 codes cannot be fed to the symmetric int8 MXU
        # path (values ≥128 would wrap negative and the range math is
        # maxabs/127-based); quantize with out_type='int8'
        raise ValueError("quantized_fully_connected requires symmetric "
                         "int8 inputs, got uint8")
    if flatten and data.ndim > 2:
        data = data.reshape(data.shape[0], -1)
    acc = lax.dot_general(
        data.astype(jnp.int8), weight.astype(jnp.int8),
        (((data.ndim - 1,), (1,)), ((), ())),
        preferred_element_type=jnp.int32)
    mn_o, mx_o = _int32_out_range(min_data, max_data, min_weight,
                                  max_weight)
    if bias is not None and not no_bias:
        # bias arrives int8 with its own scale; rescale into the
        # accumulator's scale (s_d * s_w) before adding
        s_b = _max_abs(jnp.min(min_bias), jnp.max(max_bias)) / INT8_Q
        s_acc = mx_o / INT32_Q
        b32 = jnp.round(bias.astype(jnp.float32) * (s_b / s_acc)).astype(
            jnp.int32)
        acc = acc + b32
    return acc, mn_o, mx_o


@register("_contrib_quantized_conv",
          ndarray_inputs=("data", "weight", "bias", "min_data", "max_data",
                          "min_weight", "max_weight", "min_bias",
                          "max_bias"),
          differentiable=False, num_outputs=3, jit=True)
def quantized_conv(data, weight, bias, min_data, max_data, min_weight,
                   max_weight, min_bias, max_bias, kernel=None,
                   stride=(1, 1), pad=(0, 0), dilate=(1, 1),
                   num_filter=0, num_group=1, no_bias=False,
                   layout="NCHW"):
    """ref: quantized_conv.cc — int8 convolution, int32 accumulate on
    the MXU."""
    if data.dtype == jnp.uint8 or weight.dtype == jnp.uint8:
        raise ValueError("quantized_conv requires symmetric int8 inputs, "
                         "got uint8")
    if isinstance(stride, int):
        stride = (stride, stride)
    if isinstance(pad, int):
        pad = (pad, pad)
    if isinstance(dilate, int):
        dilate = (dilate, dilate)
    dn = lax.conv_dimension_numbers(data.shape, weight.shape,
                                    ("NCHW", "OIHW", "NCHW"))
    acc = lax.conv_general_dilated(
        data.astype(jnp.int8), weight.astype(jnp.int8),
        window_strides=tuple(stride),
        padding=[(pad[0], pad[0]), (pad[1], pad[1])],
        rhs_dilation=tuple(dilate),
        dimension_numbers=dn,
        feature_group_count=num_group,
        preferred_element_type=jnp.int32)
    mn_o, mx_o = _int32_out_range(min_data, max_data, min_weight,
                                  max_weight)
    if bias is not None and not no_bias:
        s_b = _max_abs(jnp.min(min_bias), jnp.max(max_bias)) / INT8_Q
        s_acc = mx_o / INT32_Q
        b32 = jnp.round(bias.astype(jnp.float32) * (s_b / s_acc)).astype(
            jnp.int32)
        acc = acc + b32[None, :, None, None]
    return acc, mn_o, mx_o


@register("_contrib_quantized_pooling",
          ndarray_inputs=("data", "min_data", "max_data"),
          differentiable=False, num_outputs=3, jit=True)
def quantized_pooling(data, min_data, max_data, kernel=(2, 2),
                      pool_type="max", stride=None, pad=(0, 0),
                      global_pool=False, **_):
    """ref: quantized_pooling.cc — max/avg pool directly on int8 (range
    is unchanged for max; avg dequantizes-free since it's linear)."""
    if stride is None:
        stride = kernel
    if isinstance(kernel, int):
        kernel = (kernel, kernel)
    if isinstance(stride, int):
        stride = (stride, stride)
    if isinstance(pad, int):
        pad = (pad, pad)
    if global_pool:
        kernel = data.shape[2:]
        stride = (1, 1)
        pad = (0, 0)
    window = (1, 1) + tuple(kernel)
    strides = (1, 1) + tuple(stride)
    pads = ((0, 0), (0, 0), (pad[0], pad[0]), (pad[1], pad[1]))
    if pool_type == "max":
        init = jnp.array(jnp.iinfo(data.dtype).min, data.dtype)
        out = lax.reduce_window(data, init, lax.max,
                                window, strides, pads)
    else:
        s = lax.reduce_window(data.astype(jnp.int32), 0, lax.add,
                              window, strides, pads)
        n = kernel[0] * kernel[1]
        out = jnp.round(s.astype(jnp.float32) / n).astype(jnp.int8)
    return out, jnp.min(min_data), jnp.max(max_data)


@register("_contrib_quantized_flatten",
          ndarray_inputs=("data", "min_data", "max_data"),
          differentiable=False, num_outputs=3, jit=True)
def quantized_flatten(data, min_data, max_data):
    return (data.reshape(data.shape[0], -1), jnp.min(min_data),
            jnp.max(max_data))


@register("_contrib_quantized_act",
          ndarray_inputs=("data", "min_data", "max_data"),
          differentiable=False, num_outputs=3, jit=True)
def quantized_act(data, min_data, max_data, act_type="relu"):
    """ref: quantized_activation.cc — relu on int8 keeps the scale."""
    if act_type != "relu":
        raise ValueError("quantized_act supports relu only")
    return (jnp.maximum(data, 0), jnp.min(min_data), jnp.max(max_data))


@register("_contrib_quantized_elemwise_add",
          ndarray_inputs=("lhs", "rhs", "min_lhs", "max_lhs", "min_rhs",
                          "max_rhs"),
          differentiable=False, num_outputs=3, jit=True)
def quantized_elemwise_add(lhs, rhs, min_lhs, max_lhs, min_rhs, max_rhs):
    """ref: quantized_elemwise_add.cc — align scales into int32."""
    s_l = _max_abs(jnp.min(min_lhs), jnp.max(max_lhs)) / INT8_Q
    s_r = _max_abs(jnp.min(min_rhs), jnp.max(max_rhs)) / INT8_Q
    s_o = jnp.maximum(s_l, s_r) / (INT32_Q / (2 * INT8_Q))
    acc = (jnp.round(lhs.astype(jnp.float32) * (s_l / s_o)) +
           jnp.round(rhs.astype(jnp.float32) * (s_r / s_o))).astype(
               jnp.int32)
    mx = s_o * INT32_Q
    return acc, -mx, mx
