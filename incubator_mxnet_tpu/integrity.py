"""End-to-end integrity: detect, quarantine and recover from silent
corruption (ISSUE 9 tentpole).

The resilience stack (PRs 1/7/8) survives crashes, replica loss and
overload — but every layer still TRUSTS its bytes.  A flipped bit in a
checkpoint loads silently (or kills the resume the checkpoints exist
to guarantee), a corrupt RecordIO payload is retried forever as if the
storage blip were transient (or decodes into garbage pixels), and
nothing ever re-verifies the core data-parallel invariant — that
replicated parameters stay bit-identical across the mesh ("Automatic
Cross-Replica Sharding of Weight Update in Data-Parallel Training",
PAPERS.md).  At pod scale silent data corruption (SDC) is a when, not
an if.  This module is the shared integrity layer under the three
trust boundaries:

1. **Checkpoints** — `write_manifest` drops a per-file + per-leaf
   CRC/byte-size manifest (`integrity_manifest.json`) inside each
   atomic checkpoint before it publishes; `verify_checkpoint` replays
   it and raises a typed `CheckpointCorrupt` NAMING the bad file and
   (best effort, via a raw orbax restore) the bad pytree leaf.
   `ResilientTrainer` verifies on load (``MXNET_CKPT_VERIFY``) and its
   `resume()` walks keep-K back to the newest verifiable checkpoint —
   salvage instead of death — leaving a black-box dump with the trail.
2. **Record pipeline** — an optional ``<file>.crc`` sidecar
   (`io.recordio.write_crc_sidecar`) carries a per-record payload CRC;
   readers (decode-service workers and the threaded `ImageRecordIter`
   path) verify it and QUARANTINE corrupt records — skipped, counted
   (``io.decode.records_corrupt``), ring-evented and appended to a
   quarantine JSONL naming file/offset — under a per-epoch
   ``MXNET_IO_CORRUPT_BUDGET`` that fails the epoch loudly
   (`CorruptRecordBudgetExceeded`) when exceeded.  `RecordCorrupt` is
   classified NON-transient, so retry paths fail fast instead of
   burning their backoff budget on a permanent error.
3. **Mesh audit** — `audit_replicas` hashes every replicated
   param/optimizer-state shard per replica (grouping shards by their
   global index, so ZeRO-sharded leaves are compared only where copies
   actually exist) and reports any replica whose bytes diverge: an SDC
   detection, answered by checkpoint rollback (`ResilientTrainer`) or
   replica eviction (`ElasticTrainer`, reusing the elastic shrink
   path).  Digests round-trip through the kvstore when one is passed
   (`parallel.elastic.ReplicaHealth`), mirroring the heartbeat layer.

Checksum algorithm: CRC32C (Castagnoli) via `google_crc32c` when the
wheel is present, else zlib's CRC32 — both C-speed; the algorithm in
use is RECORDED in every manifest/sidecar and verification dispatches
on the recorded name, so artifacts move between hosts with different
wheels.

This module is deliberately jax-free at import time (decode-service
worker processes import it); the audit helpers import jax lazily.
"""
from __future__ import annotations

import json
import os
import threading
import zlib

from . import config as _cfg
from .monitor import events

__all__ = [
    "IntegrityError", "CheckpointCorrupt", "RecordCorrupt",
    "CorruptRecordBudgetExceeded", "SDCDetected",
    "checksum", "checksum_algo", "checksum_fn",
    "MANIFEST", "write_manifest", "verify_checkpoint", "named_leaves",
    "quarantine_record", "quarantine_path",
    "audit_replicas", "AuditReport",
]

MANIFEST = "integrity_manifest.json"
_MANIFEST_SCHEMA = "mxtpu-integrity/1"


# ---------------------------------------------------------------------------
# typed errors — corruption is never a generic IOError
# ---------------------------------------------------------------------------

class IntegrityError(Exception):
    """Base class: data failed an integrity check."""


class CheckpointCorrupt(IntegrityError):
    """A checkpoint's bytes do not match its manifest.  `ckpt` is the
    checkpoint directory, `files` the mismatching relative paths with
    reasons, `leaves` the pytree leaves identified as bad (best
    effort — empty when the serialized blobs cannot even be restored
    to map file damage back to a leaf), `kind` the failure family
    (``file`` / ``manifest``)."""

    def __init__(self, ckpt, files=None, leaves=None, kind="file",
                 detail=""):
        self.ckpt = str(ckpt)
        self.files = dict(files or {})
        self.leaves = sorted(leaves or [])
        self.kind = kind
        what = ", ".join("%s (%s)" % kv for kv in
                         sorted(self.files.items())[:4]) or detail
        leaf = (" — bad leaf(s): %s" % ", ".join(self.leaves[:4])
                if self.leaves else "")
        super().__init__(
            "checkpoint %s failed integrity verification [%s]: %s%s"
            % (self.ckpt, kind, what, leaf))


class RecordCorrupt(IntegrityError, IOError):
    """A RecordIO payload failed its CRC or could not be decoded.
    Subclasses IOError so legacy handlers see an I/O failure, but it
    is classified NON-transient: `io.resilient` fails fast instead of
    retrying (re-reading corrupt bytes yields the same corrupt
    bytes)."""

    def __init__(self, uri, offset, reason):
        self.uri = str(uri)
        self.offset = int(offset)
        self.reason = str(reason)
        super().__init__("corrupt record in %s at offset %d: %s"
                         % (self.uri, self.offset, self.reason))


class CorruptRecordBudgetExceeded(IntegrityError, RuntimeError):
    """More records were quarantined this epoch than
    ``MXNET_IO_CORRUPT_BUDGET`` tolerates — the input data is sick,
    not blipping; the epoch fails loudly instead of silently training
    on a shrinking dataset."""

    def __init__(self, uri, count, budget):
        self.uri = str(uri)
        self.count = int(count)
        self.budget = int(budget)
        super().__init__(
            "corrupt-record budget exceeded for %s: %d quarantined "
            "this epoch > MXNET_IO_CORRUPT_BUDGET=%d"
            % (uri, count, budget))


class SDCDetected(IntegrityError):
    """A replica's replicated state diverged from the mesh — silent
    data corruption caught by the cross-replica audit — and no
    checkpoint exists to roll back to."""

    def __init__(self, replicas, leaves, step):
        self.replicas = sorted(int(r) for r in replicas)
        self.leaves = sorted(leaves)
        self.step = int(step)
        super().__init__(
            "cross-replica SDC audit failed at step %d: replica(s) %s "
            "diverge on %s (no checkpoint to roll back to)"
            % (self.step, self.replicas, self.leaves[:4]))


# ---------------------------------------------------------------------------
# checksums
# ---------------------------------------------------------------------------

try:                                    # hardware CRC32C when the wheel
    import google_crc32c as _crc32c     # is present (it usually is —
except ImportError:                     # orbax pulls it in)
    _crc32c = None


def checksum_algo() -> str:
    """Name of the checksum recorded by `checksum` on THIS host."""
    return "crc32c" if _crc32c is not None else "crc32"


def checksum(data) -> int:
    """CRC of a bytes-like object with this host's preferred algorithm
    (`checksum_algo`).  Writers record the algorithm name next to the
    values; readers verify with `checksum_fn(recorded_algo)`."""
    return checksum_fn(checksum_algo())(data)


def checksum_fn(algo: str):
    """The CRC callable for a RECORDED algorithm name — artifacts
    written on a host with the crc32c wheel must verify on one
    without it (and vice versa) by failing loudly, not by comparing
    incompatible sums."""
    if algo == "crc32c":
        if _crc32c is None:
            raise IntegrityError(
                "artifact was checksummed with crc32c but the "
                "google_crc32c wheel is not importable on this host")
        return lambda b: int(_crc32c.value(bytes(b)))
    if algo == "crc32":
        return lambda b: zlib.crc32(bytes(b)) & 0xFFFFFFFF
    raise IntegrityError("unknown checksum algorithm %r" % (algo,))


# ---------------------------------------------------------------------------
# checkpoint manifests
# ---------------------------------------------------------------------------

def _walk_files(ckpt_dir):
    for root, _dirs, files in os.walk(ckpt_dir):
        for f in files:
            fp = os.path.join(root, f)
            rel = os.path.relpath(fp, ckpt_dir)
            if rel == MANIFEST:
                continue
            yield rel.replace(os.sep, "/"), fp


def named_leaves(params, opt_state=None):
    """``[(leaf_name, array)]`` over a trainer's state: params by their
    own names (``params/<name>``), optimizer state by its tree path
    (``opt_state/<path>``).  jax imported lazily — never from a decode
    worker."""
    import jax
    out = []
    for name in sorted(params):
        out.append(("params/%s" % name, params[name]))
    if opt_state is not None:
        flat = jax.tree_util.tree_flatten_with_path(opt_state)[0]
        for path, leaf in flat:
            out.append(("opt_state/%s" % jax.tree_util.keystr(path),
                        leaf))
    return out


def _leaf_bytes(arr):
    import numpy as _np
    a = _np.asarray(arr)
    return _np.ascontiguousarray(a).tobytes()


def write_manifest(ckpt_dir, leaves=None) -> str:
    """Write ``integrity_manifest.json`` into `ckpt_dir` (typically the
    hidden temp dir BEFORE the atomic publish, so the manifest is
    covered by the same rename): per-file byte size + CRC over every
    file, and — when `leaves` (from `named_leaves`) is given — a
    per-leaf CRC over the in-memory values, which is what lets a later
    verification failure NAME the bad leaf instead of an opaque
    content-hashed blob path."""
    algo = checksum_algo()
    fn = checksum_fn(algo)
    files = {}
    for rel, fp in _walk_files(ckpt_dir):
        files[rel] = [os.path.getsize(fp), _stream_crc(fp, algo)]
    doc = {"schema": _MANIFEST_SCHEMA, "algo": algo, "files": files}
    if leaves:
        doc["leaves"] = {
            name: [int(len(b)), fn(b)]
            for name, b in ((n, _leaf_bytes(a)) for n, a in leaves)}
    path = os.path.join(ckpt_dir, MANIFEST)
    with open(path, "w") as f:
        json.dump(doc, f)
    return path


def _stream_crc(path, algo, chunk=1 << 20):
    """CRC of a file's contents without holding it in memory."""
    acc = None
    with open(path, "rb") as fh:
        while True:
            buf = fh.read(chunk)
            if not buf:
                break
            if acc is None:
                acc = checksum_fn(algo)(buf)
            elif algo == "crc32c":
                acc = int(_crc32c.extend(acc, buf))
            else:
                acc = zlib.crc32(buf, acc) & 0xFFFFFFFF
    return 0 if acc is None else acc


def _load_manifest(ckpt_dir):
    path = os.path.join(ckpt_dir, MANIFEST)
    if not os.path.exists(path):
        return None
    try:
        with open(path) as f:
            doc = json.load(f)
        if doc.get("schema") != _MANIFEST_SCHEMA or \
                "files" not in doc or "algo" not in doc:
            raise ValueError("bad schema %r" % doc.get("schema"))
        return doc
    except (ValueError, KeyError, OSError) as e:
        raise CheckpointCorrupt(
            ckpt_dir, kind="manifest",
            detail="manifest unreadable: %s" % e) from e


def _leaf_tokens(name):
    """Dotted-path tokens orbax/tensorstore use for a manifest leaf
    name: ``params/w`` → ``params.w``; an opt_state keystr like
    ``opt_state/['w']['m']`` → ``opt_state.w.m``."""
    import re
    head, _, rest = name.partition("/")
    keys = re.findall(r"\['([^']+)'\]", rest) or \
        ([rest] if rest else [])
    return ".".join([head] + keys)


def _find_bad_leaves(ckpt_dir, doc):
    """Best-effort mapping of file damage back to pytree leaves.  Two
    routes: a raw orbax restore (no template — the saved structure)
    with per-leaf re-CRC against the manifest's ``leaves`` section —
    the route that catches corruption orbax itself loads silently —
    and, when the corrupted blob will not even deserialize, scanning
    the restore error for the dotted leaf paths tensorstore names in
    its DATA_LOSS diagnostics.  Anything else degrades to an empty
    list — the file name still gets reported."""
    want = doc.get("leaves")
    if not want:
        return []
    try:
        import orbax.checkpoint as ocp
        restored = ocp.PyTreeCheckpointer().restore(ckpt_dir)
    except Exception as e:          # noqa: BLE001 — blob unreadable:
        msg = str(e)                # mine the error for leaf paths
        return sorted(n for n in want if _leaf_tokens(n) in msg)
    try:
        got = dict(named_leaves(restored.get("params", {}),
                                restored.get("opt_state")))
        fn = checksum_fn(doc["algo"])
        bad = []
        for name, (nbytes, crc) in want.items():
            if name not in got:
                bad.append(name)
                continue
            b = _leaf_bytes(got[name])
            if len(b) != nbytes or fn(b) != crc:
                bad.append(name)
        return bad
    except Exception:               # noqa: BLE001 — forensic best effort
        return []


def verify_checkpoint(ckpt_dir, name_leaves=True) -> dict:
    """Verify a checkpoint directory against its manifest.  Returns a
    report dict; raises `CheckpointCorrupt` naming every mismatching
    file (missing / truncated / CRC) and — when `name_leaves` and the
    blobs still deserialize — the bad pytree leaves.

    A checkpoint WITHOUT a manifest (written before this subsystem, or
    by an external tool) is reported ``verified=False`` and counted
    (``integrity.ckpt_unverified``) but tolerated — verification is a
    property of manifests, not a retroactive rejection of history."""
    ckpt_dir = os.path.abspath(ckpt_dir)
    if not os.path.isdir(ckpt_dir):
        raise CheckpointCorrupt(ckpt_dir, kind="file",
                                detail="checkpoint directory missing")
    doc = _load_manifest(ckpt_dir)
    if doc is None:
        events.incr("integrity.ckpt_unverified")
        return {"ckpt": ckpt_dir, "verified": False,
                "reason": "no manifest", "files": 0}
    algo = doc["algo"]
    checksum_fn(algo)               # unknown algo fails here, loudly
    bad = {}
    for rel, (nbytes, crc) in doc["files"].items():
        fp = os.path.join(ckpt_dir, rel.replace("/", os.sep))
        if not os.path.isfile(fp):
            bad[rel] = "missing"
            continue
        size = os.path.getsize(fp)
        if size != int(nbytes):
            bad[rel] = "size %d != %d" % (size, nbytes)
            continue
        if _stream_crc(fp, algo) != int(crc):
            bad[rel] = "crc mismatch"
    if bad:
        leaves = _find_bad_leaves(ckpt_dir, doc) if name_leaves else []
        events.incr("integrity.ckpt_corrupt")
        _ring("ckpt_corrupt", ckpt=os.path.basename(ckpt_dir),
              files=sorted(bad)[:4], leaves=leaves[:4])
        raise CheckpointCorrupt(ckpt_dir, files=bad, leaves=leaves)
    return {"ckpt": ckpt_dir, "verified": True, "algo": algo,
            "files": len(doc["files"]),
            "leaves": len(doc.get("leaves", {}))}


# ---------------------------------------------------------------------------
# record quarantine
# ---------------------------------------------------------------------------

_QUAR_LOCK = threading.Lock()


def quarantine_path() -> str:
    """This process's quarantine ledger (JSON lines, one per record):
    ``<MXNET_BLACKBOX_DIR>/io-quarantine-p<pid>.jsonl`` — next to the
    black-box dumps, because it answers the same forensic question."""
    import tempfile
    # same default as the black-box dumps (flightrec._resolve_path):
    # scratch, never the launch directory — a quarantine hit outside
    # bench/tests must not litter the checkout
    d = _cfg.get("MXNET_BLACKBOX_DIR") or tempfile.gettempdir()
    try:
        os.makedirs(d, exist_ok=True)
    except OSError:
        d = tempfile.gettempdir()
    return os.path.join(d, "io-quarantine-p%d.jsonl" % os.getpid())


def quarantine_record(uri, offset, reason, epoch=None, wid=None) -> str:
    """Book one corrupt record into quarantine: count it
    (``io.decode.records_corrupt``), leave a flight-recorder event,
    and append a JSONL entry naming file/offset so an operator (or
    `tools/im2rec` re-run) can locate the poisoned bytes.  Returns the
    ledger path.  Never raises — quarantine is bookkeeping, the
    CALLER owns skip/budget semantics."""
    events.incr("io.decode.records_corrupt")
    _ring("record_corrupt", file=os.path.basename(str(uri)),
          offset=int(offset), reason=str(reason)[:80],
          epoch=epoch, wid=wid)
    path = quarantine_path()
    entry = {"file": str(uri), "offset": int(offset),
             "reason": str(reason)[:200]}
    if epoch is not None:
        entry["epoch"] = int(epoch)
    if wid is not None:
        entry["wid"] = int(wid)
    try:
        with _QUAR_LOCK, open(path, "a") as f:
            f.write(json.dumps(entry) + "\n")
    except OSError:
        pass
    return path


def _ring(name, **data):
    """Flight-recorder event of kind ``integrity`` (never raises —
    forensics must not change integrity semantics)."""
    try:
        from .telemetry import flightrec as _bb
        _bb.record("integrity", name,
                   **{k: v for k, v in data.items() if v is not None})
    except Exception:               # noqa: BLE001
        pass


# ---------------------------------------------------------------------------
# cross-replica SDC audit
# ---------------------------------------------------------------------------

class AuditReport:
    """Result of one cross-replica audit round.

    divergent:  {rid: [leaf names]} — replicas whose replicated bytes
                differ from the mesh consensus (empty = clean)
    digests:    {rid: int} — per-replica fold over every comparable
                (leaf, shard-index) group, the value that round-trips
                through the kvstore
    groups:     number of comparable groups (a leaf sharded so that no
                two replicas hold the same slice contributes none —
                there is nothing to compare)
    """

    __slots__ = ("step", "divergent", "digests", "groups")

    def __init__(self, step, divergent, digests, groups):
        self.step = int(step)
        self.divergent = divergent
        self.digests = digests
        self.groups = int(groups)

    @property
    def ok(self) -> bool:
        return not self.divergent

    def victims(self):
        return sorted(self.divergent)

    def leaves(self):
        out = set()
        for ls in self.divergent.values():
            out.update(ls)
        return sorted(out)


def _fold(crcs) -> int:
    """Order-independent-deterministic fold of (key, crc) pairs into
    one digest (sorted before folding, so every replica computes the
    same value from the same bytes)."""
    acc = 0
    for key, crc in sorted(crcs):
        acc = zlib.crc32(
            ("%s:%d" % (key, crc)).encode(), acc) & 0xFFFFFFFF
    return acc


def audit_replicas(trainer, step=0, rid_of=None, kv=None,
                   inject=True) -> AuditReport:
    """Hash every replicated param/optimizer-state shard per replica
    and compare across the mesh.

    Shards are grouped by (leaf name, global shard index): two devices
    holding the SAME slice of the SAME leaf must hold bit-identical
    bytes — the data-parallel invariant.  ZeRO-sharded leaves compare
    only where replication actually exists (a slice held by one
    replica contributes nothing).  The deviant in a group is whoever
    disagrees with the modal CRC; a 1-vs-1 tie (2-replica mesh) blames
    the higher rid — deterministic, and the safe response (rollback)
    is identical either way.

    rid_of: ``{device: replica_id}`` (default: enumeration order of
    ``trainer.mesh.devices.flat`` — `ElasticTrainer` passes its
    original-rid mapping so eviction names the right replica).
    kv: optional kvstore — per-replica digests are pushed to
    ``__mesh__/audit/<rid>`` and the PULLED values are compared as a
    second, wire-level verdict (a replica whose published digest
    disagrees with the modal one is divergent even when the per-group
    table missed it), so the comparison exercises the same channel
    the heartbeats use.  Note the shard CRCs themselves come from
    ``addressable_shards`` — on a multi-controller mesh each process
    hashes only its local shards, and the digest round-trip is what
    carries the comparison across processes.
    inject: whether the ``mesh.replica_divergence`` fault site may
    fire this round (the elastic supervisor passes first-visit only,
    the replay-safety rule every injected fault follows)."""
    import numpy as _np
    from . import fault

    params = trainer.params
    opt_state = trainer.opt_state
    if rid_of is None:
        rid_of = {d: i for i, d in
                  enumerate(trainer.mesh.devices.flat)}
    groups = {}
    for name, arr in named_leaves(params, opt_state):
        shards = getattr(arr, "addressable_shards", None)
        if not shards:
            continue
        for sh in shards:
            rid = rid_of.get(sh.device)
            if rid is None:
                continue
            data = _np.ascontiguousarray(_np.asarray(sh.data))
            groups.setdefault((name, str(sh.index)), {})[rid] = \
                checksum(data.tobytes())
    comparable = {k: v for k, v in groups.items() if len(v) > 1}

    if inject and comparable and \
            fault.should_fire("mesh.replica_divergence", step):
        # deterministic SDC injection: the victim (highest rid) gets
        # ONE leaf's CRC perturbed — detection, blame and response all
        # run the production comparison below
        key = sorted(comparable)[0]
        victim = max(comparable[key])
        comparable[key][victim] ^= 0x1
        import logging
        logging.getLogger(__name__).warning(
            "fault: injected replica divergence at step %d "
            "(replica %d, leaf %s)", step, victim, key[0])

    divergent = {}
    for (name, _idx), crcs in comparable.items():
        vals = sorted(crcs.values())
        if vals[0] == vals[-1]:
            continue
        counts = {}
        for c in crcs.values():
            counts[c] = counts.get(c, 0) + 1
        modal = max(sorted(counts), key=lambda c: counts[c])
        deviants = [r for r, c in crcs.items() if c != modal]
        if len(deviants) == len(crcs) - len(deviants):
            # 1-vs-1 (or N-vs-N) tie: blame the higher rid(s),
            # deterministically
            deviants = sorted(crcs)[len(crcs) - len(deviants):]
        for r in deviants:
            divergent.setdefault(int(r), []).append(name)

    rids = sorted({r for crcs in comparable.values() for r in crcs})
    digests = {
        r: _fold(((name, idx), crcs[r])
                 for (name, idx), crcs in comparable.items()
                 if r in crcs)
        for r in rids}

    if kv is not None and rids:
        # the digests each replica PUBLISHED are what gets compared:
        # a replica whose pulled digest disagrees with the modal one
        # is divergent even if the per-group table missed it (the
        # group comparison names the leaf; the digest comparison is
        # the wire-level cross-check through the heartbeat channel)
        digests = _kv_roundtrip(kv, digests, step)
        counts = {}
        for dg in digests.values():
            counts[dg] = counts.get(dg, 0) + 1
        if len(counts) > 1:
            modal = max(sorted(counts), key=lambda d: counts[d])
            for r, dg in digests.items():
                if dg != modal and int(r) not in divergent:
                    divergent[int(r)] = ["<digest>"]

    events.incr("integrity.audits")
    report = AuditReport(step, divergent, digests, len(comparable))
    if divergent:
        events.incr("integrity.sdc")
        _ring("sdc", step=int(step), replicas=report.victims(),
              leaves=report.leaves()[:4])
    return report


_AUDIT_KEY = "__mesh__/audit/%d"
_AUDIT_INITED = "_mx_integrity_audit_keys"


def _kv_roundtrip(kv, digests, step):
    """Publish each replica's digest through the kvstore and read the
    comparison inputs back from the PULLED values — the same channel
    (and the same membership-generation discipline) the heartbeat
    layer uses.  Falls back to the host-side digests on any kvstore
    failure: the audit must degrade to a weaker comparison, never
    block training."""
    try:
        from .ndarray.ndarray import NDArray
        import numpy as _np
        inited = getattr(kv, _AUDIT_INITED, set())
        out = {}
        for rid, dg in digests.items():
            key = _AUDIT_KEY % rid
            val = NDArray(_np.asarray([float(step), float(dg)],
                                      _np.float64))
            if rid not in inited:
                kv.init(key, NDArray(_np.zeros(2, _np.float64)))
                inited.add(rid)
            kv.push(key, val)
        setattr(kv, _AUDIT_INITED, inited)
        for rid in digests:
            buf = NDArray(_np.zeros(2, _np.float64))
            kv.pull(_AUDIT_KEY % rid, out=buf)
            s, dg = (float(x) for x in buf.asnumpy())
            out[rid] = int(dg) if int(s) == int(step) \
                else digests[rid]
        return out
    except Exception:               # noqa: BLE001 — audit must degrade,
        return digests              # not block training
