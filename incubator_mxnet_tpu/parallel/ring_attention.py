"""Sequence/context parallelism: ring attention + Ulysses all-to-all.

The reference has NO sequence/context parallelism (SURVEY §2.3 marks
TP/PP/SP/CP "ABSENT in MXNet" — long sequences were handled only by
bucketing).  This module is the TPU-first extension the survey calls
for: attention over sequences sharded across the mesh, so context
length scales with the number of chips.

Two standard schemes, both pure collectives-over-ICI:

- **ring_attention** (Liu et al., Ring Attention with Blockwise
  Transformers): K/V blocks rotate around the ring via `lax.ppermute`
  while each device's Q stays put; partial attention is merged with the
  flash-attention online-softmax recurrence, so the full T×T score
  matrix never materializes on any chip.  Memory per chip: O(T_local²),
  compute overlapped with the rotation by XLA's latency-hiding
  scheduler.
- **ulysses_attention** (DeepSpeed-Ulysses): `lax.all_to_all` reshards
  sequence-sharding → head-sharding, runs ordinary local attention on
  full sequences for H/n heads, then reshards back.  Cheaper collectives
  for moderate T when H divides the axis.

Both are written against `shard_map` body semantics: call them INSIDE a
`shard_map`/`pjit` region with `axis_name` bound to the mesh axis the
sequence is sharded over (see tests/python/unittest/test_ring_attention.py
and __graft_entry__.dryrun_multichip for the wiring)."""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["ring_attention", "ulysses_attention", "local_attention"]


def local_attention(q, k, v, *, causal=False, q_offset=0, k_offset=0,
                    scale=None):
    """Plain blockwise attention on local tensors.

    q: (B, Tq, H, D), k/v: (B, Tk, H, D).  q_offset/k_offset are the
    GLOBAL positions of element 0 (for causal masking across shards).
    Returns (out_unnormalized, running_max (B,Tq,H), denom (B,Tq,H)) so
    callers can merge partial results with the online-softmax rule."""
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    s = jnp.einsum("bqhd,bkhd->bqhk", q, k) * scale
    if causal:
        qpos = q_offset + jnp.arange(q.shape[1])
        kpos = k_offset + jnp.arange(k.shape[1])
        mask = qpos[:, None] >= kpos[None, :]          # (Tq, Tk)
        s = jnp.where(mask[None, :, None, :], s, -jnp.inf)
    m = jnp.max(s, axis=-1)                            # (B, Tq, H)
    # fully-masked rows (causal, early shards): keep exp well-defined
    m_safe = jnp.where(jnp.isneginf(m), 0.0, m)
    p = jnp.exp(s - m_safe[..., None])
    p = jnp.where(jnp.isneginf(s), 0.0, p)
    l = jnp.sum(p, axis=-1)                            # (B, Tq, H)
    o = jnp.einsum("bqhk,bkhd->bqhd", p, v)            # unnormalized
    return o, m_safe, l


def ring_attention(q, k, v, axis_name, *, causal=False, scale=None):
    """Ring attention over a sequence-sharded axis.

    Call inside shard_map. q/k/v: (B, T_local, H, D), the global
    sequence being the concatenation over `axis_name` in axis-index
    order. Returns the exact softmax attention output (B, T_local, H, D)
    for this shard — numerically identical to full attention on the
    gathered sequence."""
    n = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    t_local = q.shape[1]
    q_off = idx * t_local

    o = jnp.zeros(q.shape, jnp.float32)
    m = jnp.full(q.shape[:3], -jnp.inf, jnp.float32)
    l = jnp.zeros(q.shape[:3], jnp.float32)
    kc, vc = k, v

    perm = [(i, (i + 1) % n) for i in range(n)]
    for step in range(n):
        # after `step` rotations device idx holds chunk (idx - step) % n
        src = (idx - step) % n
        k_off = src * t_local
        oi, mi, li = local_attention(
            q.astype(jnp.float32), kc.astype(jnp.float32),
            vc.astype(jnp.float32), causal=causal,
            q_offset=q_off, k_offset=k_off, scale=scale)
        # first merge: m is -inf → exp(-inf - mi) handled by where
        mm = jnp.maximum(m, mi)
        a_prev = jnp.where(jnp.isneginf(m), 0.0, jnp.exp(m - mm))
        a_new = jnp.exp(mi - mm)
        o = o * a_prev[..., None] + oi * a_new[..., None]
        l = l * a_prev + li * a_new
        m = mm
        if step != n - 1:
            kc = lax.ppermute(kc, axis_name, perm)
            vc = lax.ppermute(vc, axis_name, perm)

    denom = jnp.maximum(l, 1e-20)[..., None]
    return (o / denom).astype(q.dtype)


def ulysses_attention(q, k, v, axis_name, *, causal=False, scale=None):
    """DeepSpeed-Ulysses sequence parallelism.

    Inside shard_map with q/k/v (B, T_local, H, D), H divisible by the
    axis size: all_to_all to (B, T_global, H/n, D), local full-sequence
    attention, all_to_all back."""
    n = lax.psum(1, axis_name)
    # (B, T_l, H, D) -> heads split across devices, sequence gathered
    def seq_to_head(x):
        # split heads into n groups along axis 2, exchange with the
        # sequence dimension
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)

    def head_to_seq(x):
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    qg = seq_to_head(q)          # (B, T_global, H/n, D)
    kg = seq_to_head(k)
    vg = seq_to_head(v)
    o, mx_, l = local_attention(qg.astype(jnp.float32),
                                kg.astype(jnp.float32),
                                vg.astype(jnp.float32),
                                causal=causal, scale=scale)
    out = o / jnp.maximum(l, 1e-20)[..., None]
    return head_to_seq(out.astype(q.dtype))
