"""Functional bridge: stateful Gluon block → pure jax function.

This is the seam between the imperative/Gluon surface (reference parity)
and the pjit/mesh world (TPU-native scaling).  `functionalize(block)`
returns a pure function over an explicit param dict — the same trick the
cached-op machinery uses, exposed so sharded training steps, multi-chip
dryruns and benchmarks can jit/pjit whole train steps with shardings.
"""
from __future__ import annotations

from typing import Callable, Dict, Tuple

import jax

from ..ndarray.ndarray import NDArray
from .. import autograd as _ag
from .. import random as _rnd
from ..gluon.block import _STATE

__all__ = ["functionalize", "extract_params", "load_params"]


def extract_params(block) -> Dict[str, "jax.Array"]:
    """Pull the block's parameters out as a flat {name: jax.Array} dict."""
    pd = block.collect_params()
    out = {}
    for name, p in pd.items():
        if p._data is None and p._deferred_init:
            p._finish_deferred_init()
        out[name] = p.data()._data
    return out


def load_params(block, params: Dict[str, "jax.Array"]):
    """Write a param dict back into the block (post-training sync).

    Mesh-sharded values are gathered to the param's own device — block
    params are single-device arrays (imperative surface)."""
    import numpy as _np
    pd = block.collect_params()
    for name, val in params.items():
        p = pd[name]
        for ctx in list(p._data.keys()):
            tgt = p._data[ctx]
            p._data[ctx]._data = jax.device_put(
                _np.asarray(val), ctx.jax_device).astype(tgt._data.dtype)
            break


def functionalize(block, training: bool = False) -> Callable:
    """Return pure(params_dict, *inputs, rng_bits=None) →
    (outputs, new_state_dict).

    `new_state_dict` carries BatchNorm-style running-stat updates (empty
    when training=False or the net has none).  The callable is traceable:
    wrap in jax.jit / pjit with shardings freely.
    """
    pd = block.collect_params()
    names = list(pd.keys())
    params = [pd[n] for n in names]

    def pure(pvals: Dict[str, "jax.Array"], *ivals, rng_bits=None):
        saved = []
        for p in params:
            ctx0 = next(iter(p._data))
            saved.append((p, ctx0, p._data[ctx0]))
            p._data[ctx0] = NDArray(pvals[p.name], ctx=ctx0)
        states = []
        prev_state, _STATE.active = _STATE.active, states
        prev_rec = _ag.set_recording(False)
        prev_train = _ag.set_training(training)
        holder = None
        if rng_bits is not None:
            holder = _rnd.KeyHolder(jax.random.wrap_key_data(rng_bits))
            _rnd.push_trace_key(holder)
        try:
            from ..gluon.block import Block
            nd_in = [NDArray(v) if not isinstance(v, NDArray) else v
                     for v in ivals]
            # bypass any hybridize cache: trace the plain forward
            out = Block.__call__(block, *nd_in)
        finally:
            if holder is not None:
                _rnd.pop_trace_key()
            _ag.set_training(prev_train)
            _ag.set_recording(prev_rec)
            _STATE.active = prev_state
            for p, ctx0, orig in saved:
                p._data[ctx0] = orig
        if isinstance(out, (tuple, list)):
            out_j = type(out)(o._data for o in out)
        else:
            out_j = out._data
        state_dict = {p.name: v for p, v in states}
        return out_j, state_dict

    return pure
