"""Fault-tolerant training — the layer that survives pod-scale reality.

`ResilientTrainer` wraps a `ShardedTrainer` and keeps a run alive
through the three failure families that kill long jobs:

1. **Numeric blow-ups** — the train step is re-jitted as a GUARDED
   step: loss finiteness, gradient finiteness and a loss-spike
   threshold are evaluated INSIDE the executable, and the parameter /
   optimizer-state update is applied only when the step is good
   (``jnp.where`` select — the old state passes through, donation and
   sharding intact).  Bad steps also drive an AMP ``LossScaler``-style
   backoff; after N consecutive bad steps the trainer rolls back to
   the last checkpoint.
2. **Preemption** — a SIGTERM (real, or injected via `fault`) sets a
   flag; the loop finishes the in-flight step, writes an atomic
   checkpoint plus a ``PREEMPTED`` resumable marker, and raises
   `fault.Preempted`.  `resume()` restores params, optimizer state,
   step counter AND the per-step RNG derivation, so the resumed run is
   bit-identical to an uninterrupted one on the same topology.
3. **Transient I/O / collective failures** — step dispatch and
   checkpoint writes retry with exponential backoff on
   `fault.TransientFault` / OSError.

Checkpoints are atomic by construction: orbax writes into a hidden
temp directory, run metadata (step, RNG seed, loss EMA, loss scale)
is added, and one ``os.replace`` publishes the complete directory as
``step_<n>``; a ``LATEST`` pointer file is replaced the same way.
Keep-last-K garbage collection runs after each successful publish, and
`resume()` falls back through older checkpoints when the newest is
corrupt or partial.

Every recovery action is counted on `monitor.events`
(``resilience.*`` counters) so survival is observable, not silent.
"""
from __future__ import annotations

import functools
import json
import logging
import os
import shutil
import signal
import time
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from .. import fault
from .. import integrity
from ..monitor import events
from ..telemetry import costs as _costs
from ..telemetry import flightrec as _bb
from ..telemetry import spans as _tele
from ..telemetry.stepstats import StepTelemetry
from ..contrib.amp.loss_scaler import LossScaler

__all__ = ["ResilientTrainer", "retry_transient"]

log = logging.getLogger(__name__)

_LATEST = "LATEST"
_PREEMPT_MARKER = "PREEMPTED"
_META = "resilience_meta.json"
_STEP_PREFIX = "step_"
_TMP_PREFIX = ".tmp_"


def retry_transient(fn, retries=None, backoff=None, what="operation",
                    retryable=(fault.TransientFault, OSError),
                    non_retryable=(), event="resilience.retry",
                    jitter=True):
    """Call `fn()`, retrying `retries` times with JITTERED exponential
    backoff on transient failures: the window doubles per attempt and
    each sleep is drawn uniformly from [window/2, window], so a fleet
    of workers tripped by the same storage/collective blip does not
    retry in lockstep (the thundering herd that turns one blip into
    three).  `backoff` seeds the window; when None it comes from
    MXNET_RETRY_BACKOFF_MS (milliseconds, when > 0) else
    MXNET_RETRY_BACKOFF (seconds).  `jitter=False` sleeps the full
    window deterministically (tests).  Each retry increments `event`
    on monitor.events (callers pick their own counter so concurrent
    retries in different subsystems don't pollute each other).

    `non_retryable` carves PERMANENT failures out of the retryable
    families: an exception matching it is re-raised immediately even
    when it also matches `retryable` — corruption
    (`integrity.RecordCorrupt` is an IOError) and permanent errno
    classes (ENOENT, EACCES) would otherwise burn the whole backoff
    budget re-reading bytes that cannot change."""
    import random
    from .. import config
    if retries is None:
        retries = int(config.get("MXNET_RETRY_MAX"))
    if backoff is None:
        ms = float(config.get("MXNET_RETRY_BACKOFF_MS"))
        backoff = ms / 1e3 if ms > 0 else \
            float(config.get("MXNET_RETRY_BACKOFF"))
    attempt = 0
    while True:
        try:
            return fn()
        except retryable as e:
            if non_retryable and isinstance(e, non_retryable):
                raise               # permanent: fail fast, loudly
            attempt += 1
            if attempt > retries:
                raise
            events.incr(event)
            delay = backoff if not jitter else \
                random.uniform(backoff / 2.0, backoff)
            log.warning("%s failed (%s); retry %d/%d in %.3fs",
                        what, e, attempt, retries, delay)
            time.sleep(delay)
            backoff *= 2.0


class ResilientTrainer:
    """Resilient wrapper around a `ShardedTrainer`.

    trainer:        the ShardedTrainer whose params/opt_state this
                    wrapper owns and protects
    ckpt_dir:       checkpoint directory (created; None disables
                    checkpointing, rollback and preemption saves)
    ckpt_interval:  steps between periodic checkpoints
                    (default: MXNET_CKPT_INTERVAL)
    keep:           checkpoints retained (default: MXNET_CKPT_KEEP)
    spike_factor:   skip the update when loss > factor × running mean
                    (default: MXNET_LOSS_SPIKE_FACTOR; 0 = off)
    rollback_after: consecutive bad steps before rolling back to the
                    last checkpoint (default: MXNET_BAD_STEP_ROLLBACK;
                    0 = skip-only)
    seed:           base seed for the per-step RNG stream —
                    ``fold_in(key(seed), step)`` — which makes resume
                    bit-deterministic with no key state to carry
    loss_scaler:    optional amp.LossScaler driving loss scaling with
                    backoff on bad steps (default: scale 1.0, or the
                    amp= default below)
    amp:            mixed-precision compute dtype, or None =
                    MXNET_AMP_DTYPE (empty = off).  'bfloat16' turns
                    on the op-registry cast policy (contrib.amp.init;
                    f32 master weights, no scaling needed — bf16
                    shares f32's exponent range).  'float16' is the
                    parity path: the default loss_scaler becomes a
                    dynamic LossScaler(2^16) whose overflow verdict IS
                    this trainer's NaN-guard — the guarded step checks
                    the SCALED grads for finiteness inside the
                    executable, a bad step skips the update and backs
                    the scale off, scale_window clean steps grow it.
                    Scale transitions land on monitor.events
                    (amp.loss_scale_*) and in the flight recorder
    handle_sigterm: install a SIGTERM handler that converts preemption
                    into checkpoint-and-clean-exit (main thread only)
    audit_interval: cross-replica SDC audit cadence in steps (default:
                    MXNET_SDC_AUDIT_STEPS; 0 = off).  Every N steps
                    the replicated params/opt state are hashed per
                    replica and compared (`integrity.audit_replicas`);
                    a divergent replica triggers a black-box dump and
                    a rollback to the last verifiable checkpoint

    Checkpoints carry an integrity manifest (per-file + per-leaf CRCs,
    `integrity.write_manifest`) written INSIDE the temp dir, so the
    atomic publish covers it.  With MXNET_CKPT_VERIFY (default on),
    `resume()` verifies before restoring and walks keep-K back to the
    newest VERIFIABLE checkpoint when the newest is corrupt — salvage,
    not death — leaving a `ckpt.salvage` black-box dump with the
    trail.

    Cost model: unlike ShardedTrainer.step (async dispatch, loss left
    on device), every guarded step materialises `loss`/`ok` on the
    host — the guard decisions (skip accounting, spike EMA, scaler
    backoff, rollback trigger) are host control flow.  That forfeits
    dispatch/compute overlap; runs that want raw throughput keep using
    ShardedTrainer directly and accept blow-ups, or checkpoint
    externally.  Amortising the sync (check every K steps) is a
    follow-up.
    """

    def __init__(self, trainer, ckpt_dir: Optional[str] = None,
                 ckpt_interval: Optional[int] = None,
                 keep: Optional[int] = None,
                 spike_factor: Optional[float] = None,
                 rollback_after: Optional[int] = None,
                 seed: int = 0, ema_decay: float = 0.9,
                 loss_scaler: Optional[LossScaler] = None,
                 handle_sigterm: bool = True,
                 audit_interval: Optional[int] = None,
                 amp: Optional[str] = None):
        from .. import config
        from ..contrib import amp as _amp_mod
        self.trainer = trainer
        # AMP (ISSUE 15): arm the cast policy before the guarded step
        # is traced; f16 gets the dynamic scaler whose overflow
        # backstop is this trainer's in-executable NaN-guard, bf16
        # needs none (f32 exponent range) so scale stays 1.0
        self.amp = _amp_mod.normalize_dtype(
            amp if amp is not None else config.get("MXNET_AMP_DTYPE"))
        if self.amp:
            _amp_mod.init(self.amp)
            if loss_scaler is None:
                loss_scaler = LossScaler(
                    init_scale=2.0 ** 16 if self.amp == "float16"
                    else 1.0)
            events.incr("amp.trainer_init")
            _bb.record("amp", "init", target=self.amp,
                       trainer="resilient")
        self.ckpt_dir = os.path.abspath(ckpt_dir) if ckpt_dir else None
        self.ckpt_interval = int(ckpt_interval if ckpt_interval is not None
                                 else config.get("MXNET_CKPT_INTERVAL"))
        self.keep = int(keep if keep is not None
                        else config.get("MXNET_CKPT_KEEP"))
        self.spike_factor = float(
            spike_factor if spike_factor is not None
            else config.get("MXNET_LOSS_SPIKE_FACTOR"))
        self.rollback_after = int(
            rollback_after if rollback_after is not None
            else config.get("MXNET_BAD_STEP_ROLLBACK"))
        self.audit_interval = int(
            audit_interval if audit_interval is not None
            else config.get("MXNET_SDC_AUDIT_STEPS"))
        self.seed = int(seed)
        self.ema_decay = float(ema_decay)
        self.loss_ema = None               # running mean of good losses
        self.scaler = loss_scaler or LossScaler(init_scale=1.0)
        self.bad_steps = 0                 # consecutive skipped steps
        self._tele = None                  # StepTelemetry, lazy on
        self._gstep = None                 # telemetry.enabled()
        self._trace_count = 0              # this wrapper's gstep traces
        self._preempted = False
        self._prev_sigterm = None
        if self.ckpt_dir:
            os.makedirs(self.ckpt_dir, exist_ok=True)
        # cached so the per-step path never lists ckpt_dir (which can be
        # a remote mount); maintained by checkpoint()/resume()
        self._have_ckpt = bool(self._list_checkpoints())
        if handle_sigterm:
            self._install_sigterm()
        # a resilient run is exactly what black-box forensics exist
        # for: arm the uncaught-exception/SIGUSR2 dump triggers
        # (idempotent; MXNET_BLACKBOX=0 disarms)
        _bb.install_crash_hooks()

    # -- signal / preemption -------------------------------------------
    def _install_sigterm(self):
        def _on_sigterm(signum, frame):
            # flag only: the in-flight step finishes, then the loop
            # checkpoints from a consistent state (signal-safe)
            self._preempted = True
        try:
            self._prev_sigterm = signal.signal(signal.SIGTERM, _on_sigterm)
        except ValueError:
            # not the main thread: preemption can still be requested
            # programmatically via request_preemption()
            self._prev_sigterm = None

    def uninstall_sigterm(self):
        if self._prev_sigterm is not None:
            try:
                signal.signal(signal.SIGTERM, self._prev_sigterm)
            except ValueError:
                pass
            self._prev_sigterm = None

    def request_preemption(self):
        """Programmatic SIGTERM equivalent (tests, cluster agents)."""
        self._preempted = True

    @property
    def step_number(self) -> int:
        return self.trainer._n_step

    # -- the guarded step ----------------------------------------------
    def _build_guarded_step(self):
        t = self.trainer
        fwd = t._fwd
        loss_fn = t.loss_fn
        opt_update = t._opt_update
        constrain = functools.partial(
            t._place_opt_tree, place=jax.lax.with_sharding_constraint) \
            if t.zero else (lambda tree, **_: tree)

        def gstep(params, opt_state, batch, labels, rng_bits,
                  poison, spike_thresh, loss_scale):
            # trace-time side effect only (the serve.traces pattern):
            # the counter meters guarded-step recompiles, a jit-cache
            # hit never runs this python body; the per-wrapper count
            # keeps multi-trainer attribution straight
            events.incr("train.traces")
            self._trace_count += 1

            def lf(p):
                out, states = fwd(p, batch, rng_bits=rng_bits)
                return loss_fn(out, labels) * loss_scale, states
            (scaled_loss, states), grads = jax.value_and_grad(
                lf, has_aux=True)(params)
            # fault injection rides in as a traced scalar (1.0 or NaN /
            # spike multiplier): no recompile on the poisoned step
            scaled_loss = scaled_loss * poison
            grads = jax.tree_util.tree_map(lambda g: g * poison, grads)
            loss = scaled_loss / loss_scale
            # overflow check on the SCALED grads (the AMP contract),
            # spike check on the unscaled loss
            ok = jnp.isfinite(loss) & (loss <= spike_thresh)
            for g in jax.tree_util.tree_leaves(grads):
                ok &= jnp.all(jnp.isfinite(g))
            # unscale explicitly — custom (init, update) optimizer pairs
            # need not accept a scale kwarg
            grads = jax.tree_util.tree_map(
                lambda g: g / loss_scale, grads)
            new_params, new_opt = opt_update(params, grads, opt_state)
            new_opt = constrain(new_opt)
            for k, v in states.items():
                if k in new_params:
                    new_params[k] = v.astype(new_params[k].dtype)
            # guarded commit: bad step → the old state passes through
            sel = lambda new, old: jax.tree_util.tree_map(
                lambda n, o: jnp.where(ok, n, o), new, old)
            new_params = sel(new_params, params)
            new_opt = sel(new_opt, opt_state)
            new_params = {
                n: jax.lax.with_sharding_constraint(
                    v, t._param_shardings[n])
                for n, v in new_params.items()}
            return new_params, new_opt, loss, ok

        # metered: the guarded fused train step gets a cost-registry
        # row (FLOPs/bytes + invocation counts) — the headline line of
        # a training run's black-box dump
        return _costs.metered_jit(gstep, donate_argnums=(0, 1),
                                  kind="train", label="resilient.gstep")

    def _rng_bits(self, step: int):
        """Per-step RNG stream: a pure function of (seed, step), so the
        checkpoint only needs the step counter for bit-exact resume."""
        return jax.random.key_data(
            jax.random.fold_in(jax.random.key(self.seed), step))

    # -- training ------------------------------------------------------
    def step(self, batch, labels):
        """One guarded train step.  Returns (loss, ok): `loss` as a
        float (NaN on a skipped step), `ok` whether the update was
        applied.  Raises `fault.Preempted` after a preemption was
        handled (state is checkpointed and resumable)."""
        t = self.trainer
        stepno = t._n_step
        if self._gstep is None:
            self._gstep = self._build_guarded_step()
        if self.ckpt_dir and not self._have_ckpt:
            # rollback target before the first update
            self.checkpoint()

        if fault.should_fire("preempt", stepno):
            # injected preemption goes through the REAL signal path
            signal.raise_signal(signal.SIGTERM)

        tele = self._tele
        if tele is None and _tele.enabled():
            # baseline on this wrapper's trace count (mid-run enable
            # must not flag the next step as compiling)
            tele = self._tele = StepTelemetry(
                own_traces=self._trace_count)

        poison = 1.0
        if fault.should_fire("grad_nan", stepno):
            poison = float("nan")
        elif fault.should_fire("loss_spike", stepno):
            poison = 1e4
        spike_thresh = float("inf")
        if self.spike_factor > 0 and self.loss_ema is not None:
            spike_thresh = self.spike_factor * self.loss_ema

        # global-step stamp (ISSUE 11): every span completed during
        # this step — here, in the feed, in the kvstore — carries the
        # step id, the cross-process correlation key
        _tele.set_global_step(stepno)
        step_span = _tele.span("train.step")
        step_span.start()
        t0 = time.perf_counter()
        try:
            batch_g = t._place_batch(batch, t._batch_sharding)
            labels_g = t._place_batch(
                labels, NamedSharding(t.mesh, P(t.batch_axis)))
            t1 = time.perf_counter()

            def dispatch():
                # transient collective failures surface at dispatch time
                fault.maybe_raise("collective", stepno)
                return self._gstep(t.params, t.opt_state, batch_g,
                                   labels_g, self._rng_bits(stepno),
                                   poison, spike_thresh,
                                   self.scaler.loss_scale)
            new_params, new_opt, loss, ok = retry_transient(
                dispatch, what="train step %d" % stepno,
                retryable=(fault.TransientFault,))
            t.params, t.opt_state = new_params, new_opt
            t._n_step = stepno + 1

            # the guarded step is host-synchronous by design (the guard
            # decisions are host control flow), so compute wall is
            # observable here: dispatch → loss/ok materialized
            ok = bool(ok)
            loss = float(loss)
        except Exception as e:
            # allocator OOM through the guarded path: capture a
            # blackbox dump with the memory attribution join before
            # the unwind releases the arrays (ISSUE 20)
            from ..telemetry import memwatch as _mw
            _mw.guard_oom("train.step", e)
            raise
        finally:
            step_span.stop()
        t2 = time.perf_counter()
        # always-on flight-recorder step record (one ring append): the
        # last-N step timeline a black-box dump replays
        _bb.record("step", "resilient", step=stepno,
                   loss=(loss if loss == loss else None), ok=ok,
                   us=int((t2 - t0) * 1e6),
                   **({"amp": self.amp} if self.amp else {}))
        if self.amp:
            # labeled AMP step-wall ring (ISSUE 15): percentiles of
            # the bf16/f16 guarded step next to the unlabeled series
            events.observe_time("train.step_us", t2 - t0,
                                labels={"amp": self.amp})
        if tele is not None:
            tele.record_step(loss=loss, ok=ok, wall_s=t2 - t0,
                             data_wait_s=t1 - t0, compute_s=t2 - t1,
                             traces=self._trace_count)
        # autotune probe from the guarded step's measured wall (ISSUE
        # 19 satellite) — OK steps only: a skipped/overflowed step's
        # wall is not batch-size evidence.  Cadence-gated, past the
        # compiling first step.
        if ok and t._n_step % 128 == 2:
            try:
                from ..compile import autotune as _autotune
                rows = int(batch_g.shape[0]) if batch_g.shape else 1
                _autotune.note_probe(
                    "batch_size", "resilient.step", rows,
                    (t2 - t0) * 1e6 / max(1, rows),
                    source="resilient.step", step=stepno)
            except Exception:       # noqa: BLE001
                pass
        self.scaler.update(overflow=not ok)
        if ok:
            self.bad_steps = 0
            self.loss_ema = loss if self.loss_ema is None else \
                self.ema_decay * self.loss_ema + \
                (1.0 - self.ema_decay) * loss
        else:
            self.bad_steps += 1
            events.incr("resilience.step_skipped")
            log.warning("step %d skipped (non-finite or spiking loss); "
                        "%d consecutive bad steps", stepno, self.bad_steps)
            if self.ckpt_dir and self.rollback_after and \
                    self.bad_steps >= self.rollback_after:
                self.rollback()

        if self.audit_interval > 0 and \
                t._n_step % self.audit_interval == 0 and \
                getattr(t, "data_parallel_size", 1) > 1:
            # cross-replica SDC audit: replicated state must be
            # bit-identical across the mesh; divergence rolls back
            self.audit(t._n_step)

        if self._preempted:
            self._handle_preemption()
        elif self.ckpt_dir and self.ckpt_interval > 0 and \
                t._n_step % self.ckpt_interval == 0:
            # interval <= 0: no periodic checkpoints (preemption and
            # rollback saves still work off the initial one)
            self.checkpoint()
        return loss, ok

    # -- cross-replica SDC audit ---------------------------------------
    def audit(self, step: Optional[int] = None, inject: bool = True):
        """One cross-replica integrity audit round
        (`integrity.audit_replicas`): hash every replicated
        param/opt-state shard per replica and compare.  Divergence is
        an SDC detection — black-box dump naming replica + leaf, then
        rollback to the newest verifiable checkpoint (which re-places
        one consistent copy on every replica).  With no checkpoint to
        roll back to, raises `integrity.SDCDetected`.  Returns the
        `AuditReport`."""
        step = int(step if step is not None else self.trainer._n_step)
        report = integrity.audit_replicas(self.trainer, step=step,
                                          inject=inject)
        if report.ok:
            return report
        log.error("cross-replica SDC at step %d: replica(s) %s "
                  "diverge on %s", step, report.victims(),
                  report.leaves()[:4])
        # dump BEFORE the response: the ring still holds the audit
        # trail that condemned the replica
        _bb.crash_dump("sdc")
        if self.ckpt_dir and self._have_ckpt:
            scale = self.scaler.loss_scale
            if self.resume():
                self.scaler.loss_scale = scale
                self.bad_steps = 0
                events.incr("integrity.sdc_rollback")
                _bb.record("integrity", "sdc_rollback", step=step,
                           restored=int(self.trainer._n_step))
                log.warning("SDC response: rolled back to step %d "
                            "(consistent state re-placed on every "
                            "replica)", self.trainer._n_step)
                return report
        raise integrity.SDCDetected(report.victims(), report.leaves(),
                                    step)

    # -- checkpointing -------------------------------------------------
    def _ckpt_name(self, step):
        return "%s%08d" % (_STEP_PREFIX, step)

    def _list_checkpoints(self):
        """[(step, dirname)] ascending; only completed (published)
        checkpoints — temp dirs are invisible by construction."""
        if not self.ckpt_dir or not os.path.isdir(self.ckpt_dir):
            return []
        out = []
        for name in os.listdir(self.ckpt_dir):
            if name.startswith(_STEP_PREFIX):
                try:
                    out.append((int(name[len(_STEP_PREFIX):]), name))
                except ValueError:
                    continue
        return sorted(out)

    def checkpoint(self):
        """Atomic checkpoint of params + optimizer state + step + run
        metadata: orbax-write into a temp dir, publish with one rename,
        update LATEST, garbage-collect beyond keep-K."""
        if not self.ckpt_dir:
            raise ValueError("ResilientTrainer built without ckpt_dir")
        t = self.trainer
        step = t._n_step
        final = os.path.join(self.ckpt_dir, self._ckpt_name(step))
        if os.path.isdir(final):
            # a checkpoint for this exact step already exists (typical
            # right after rollback: the restored step is the one the
            # periodic schedule fires on).  The params/opt state for a
            # step are deterministic within a run, so rewriting would
            # only re-serialize identical data — and deleting the
            # published dir to make room would break the no-window
            # atomicity guarantee.  Point LATEST at it and move on —
            # UNLESS the existing directory fails its integrity
            # manifest (a post-shrink replay can revisit the step a
            # bitflip landed on): re-pointing LATEST at known-corrupt
            # bytes would undo the salvage, so the corpse is removed
            # and this step's state is re-serialized fresh.
            from .. import config
            bad = None
            if config.get("MXNET_CKPT_VERIFY"):
                try:
                    integrity.verify_checkpoint(final,
                                                name_leaves=False)
                except integrity.CheckpointCorrupt as e:
                    bad = e
            if bad is None:
                self._publish_latest(self._ckpt_name(step))
                self._have_ckpt = True
                return final
            log.warning("existing checkpoint %s is corrupt (%s); "
                        "rewriting it", final, bad)
            shutil.rmtree(final, ignore_errors=True)
        tmp = os.path.join(self.ckpt_dir,
                           _TMP_PREFIX + self._ckpt_name(step))

        def write():
            fault.maybe_raise("checkpoint.save", step,
                              exc_type=fault.InjectedIOError)
            if os.path.exists(tmp):
                shutil.rmtree(tmp)
            t.save_checkpoint(tmp)
            meta = {"step": step, "seed": self.seed,
                    "loss_ema": self.loss_ema,
                    "loss_scale": self.scaler.loss_scale,
                    "scaler_unskipped": self.scaler._unskipped,
                    "bad_steps": self.bad_steps,
                    # forensics for elastic restores: which mesh wrote
                    # this (load_checkpoint re-places onto ANY mesh;
                    # the size delta is logged, not rejected)
                    "mesh_devices": len(list(t.mesh.devices.flat))}
            with open(os.path.join(tmp, _META), "w") as f:
                json.dump(meta, f)
            # integrity manifest LAST, inside the temp dir: per-file
            # CRCs over everything just serialized (meta included) +
            # per-leaf CRCs over the in-memory values — covered by the
            # same atomic rename as the data it guards
            integrity.write_manifest(
                tmp, leaves=integrity.named_leaves(t.params,
                                                   t.opt_state))
            os.replace(tmp, final)

        t_ck = time.perf_counter()
        with _tele.span("train.checkpoint"):
            retry_transient(write, what="checkpoint step %d" % step)
            self._publish_latest(self._ckpt_name(step))
        if fault.should_fire("ckpt.bitflip", step):
            # injected silent storage corruption: one bit of the
            # largest data blob in the PUBLISHED checkpoint flips —
            # invisible now, caught by the manifest at restore time
            self._inject_ckpt_bitflip(final, step)
        self._have_ckpt = True
        events.incr("resilience.checkpoint_written")
        _bb.record("ckpt", "written", step=step,
                   us=int((time.perf_counter() - t_ck) * 1e6))
        # checkpoint boundaries are the natural cadence for the HBM
        # watermark + counter-delta samples the timeline carries
        _bb.hbm_sample(tag="checkpoint")
        _bb.sample_counters()
        # ... and for the durable history (ISSUE 12): the marker
        # outlives the process where the ring does not, and a trainer
        # without a periodic exporter still leaves a trend
        try:
            from ..telemetry import history as _hist
            _hist.note_event("ckpt", step=int(step))
            _hist.tick()
        except Exception:               # noqa: BLE001 — durability is
            pass                        # never worth a failed ckpt
        if _tele.enabled():
            if self._tele is None:
                self._tele = StepTelemetry(
                    own_traces=self._trace_count)
            self._tele.record_checkpoint(time.perf_counter() - t_ck)
        self._gc()
        return final

    def _inject_ckpt_bitflip(self, final, step):
        """ckpt.bitflip fault site body: flip one bit of the largest
        data blob (the orbax ``d/`` payload dir when present, so the
        damage lands on leaf BYTES and the verify failure can name the
        leaf) of the published checkpoint."""
        cands = []
        for root, _dirs, files in os.walk(final):
            for f in files:
                if f == integrity.MANIFEST:
                    continue
                fp = os.path.join(root, f)
                in_data = os.path.basename(root) == "d"
                cands.append((in_data, os.path.getsize(fp), fp))
        if not cands:
            return
        _in_data, _size, target = max(cands)
        pos = fault.flip_file_bit(target)
        log.warning("fault: flipped bit at byte %d of %s (checkpoint "
                    "step %d) — silent until verified", pos, target,
                    step)
        _bb.record("fault", "ckpt.bitflip", step=int(step),
                   file=os.path.relpath(target, final))

    def _publish_latest(self, name):
        latest_tmp = os.path.join(self.ckpt_dir, _LATEST + ".tmp")
        with open(latest_tmp, "w") as f:
            f.write(name)
        os.replace(latest_tmp, os.path.join(self.ckpt_dir, _LATEST))

    def _gc(self):
        if self.keep <= 0:
            return
        for _, name in self._list_checkpoints()[:-self.keep]:
            shutil.rmtree(os.path.join(self.ckpt_dir, name),
                          ignore_errors=True)

    def rollback(self):
        """Roll back to the last good checkpoint after rollback_after
        consecutive bad steps: params, optimizer state, step counter
        and RNG derivation all rewind; the backed-off loss scale is
        KEPT so the replayed steps run at the reduced scale."""
        scale = self.scaler.loss_scale
        if not self.resume():
            raise RuntimeError(
                "rollback requested but no usable checkpoint in %s"
                % self.ckpt_dir)
        self.scaler.loss_scale = scale
        self.bad_steps = 0
        events.incr("resilience.rollback")
        _bb.record("rollback", "bad_steps", step=self.trainer._n_step)
        try:
            from ..telemetry import history as _hist
            _hist.note_event("rollback", step=int(self.trainer._n_step))
        except Exception:               # noqa: BLE001
            pass
        # a rollback means the run just survived something that kills
        # unguarded jobs — leave the forensic file while the evidence
        # (bad-step timeline, loss samples, counters) is still in ring
        _bb.crash_dump("rollback")
        log.warning("rolled back to step %d after repeated bad steps",
                    self.trainer._n_step)

    def _handle_preemption(self):
        self._preempted = False
        step = self.trainer._n_step
        _bb.record("preempt", "sigterm", step=step,
                   ckpt=bool(self.ckpt_dir))
        if self.ckpt_dir:
            self.checkpoint()
            marker_tmp = os.path.join(self.ckpt_dir,
                                      _PREEMPT_MARKER + ".tmp")
            with open(marker_tmp, "w") as f:
                json.dump({"step": step}, f)
            os.replace(marker_tmp,
                       os.path.join(self.ckpt_dir, _PREEMPT_MARKER))
        events.incr("resilience.preemption")
        try:
            from ..telemetry import history as _hist
            _hist.note_event("preemption", step=int(step))
            _hist.tick()        # the final durable batch — the dump
        except Exception:       # noqa: BLE001 — below is forensics,
            pass                # this is the trend record
        # the black box is the last thing written before the process
        # dies: it carries this preemption AND any earlier rollback
        # markers still in the ring (the acceptance scenario)
        _bb.crash_dump("preemption")
        log.warning("preemption handled at step %d; checkpoint saved",
                    step)
        raise fault.Preempted(step, self.ckpt_dir)

    @staticmethod
    def was_preempted(ckpt_dir) -> bool:
        return os.path.exists(os.path.join(ckpt_dir, _PREEMPT_MARKER))

    # -- restore -------------------------------------------------------
    def _restore_from(self, name) -> bool:
        from .. import config
        path = os.path.join(self.ckpt_dir, name)
        if config.get("MXNET_CKPT_VERIFY"):
            # verify BEFORE restoring: a corrupt checkpoint raises a
            # typed CheckpointCorrupt naming the bad file/leaf instead
            # of loading flipped bits into device memory (or dying in
            # the deserializer); resume() then walks keep-K
            integrity.verify_checkpoint(path)
        meta_path = os.path.join(path, _META)
        with open(meta_path) as f:
            meta = json.load(f)
        self.trainer.load_checkpoint(path)
        if int(meta["step"]) != self.trainer._n_step:
            raise ValueError(
                "checkpoint %s metadata step %s != restored step %d"
                % (name, meta["step"], self.trainer._n_step))
        if int(meta.get("seed", self.seed)) != self.seed:
            raise ValueError(
                "checkpoint %s was written with RNG seed %s but this "
                "trainer uses seed %d — resume would not be "
                "deterministic" % (name, meta.get("seed"), self.seed))
        here = len(list(self.trainer.mesh.devices.flat))
        wrote = meta.get("mesh_devices")
        if wrote is not None and int(wrote) != here:
            # elastic shrink/grow: state saved on an N-way mesh lands
            # re-placed (and, under zero=1, re-SHARDED) on this one
            events.incr("resilience.mesh_resize_restore")
            log.info("checkpoint %s written on a %s-device mesh, "
                     "restored onto %d devices (state re-sharded)",
                     name, wrote, here)
        self.loss_ema = meta.get("loss_ema")
        self.scaler.loss_scale = float(meta.get("loss_scale", 1.0))
        self.scaler._unskipped = int(meta.get("scaler_unskipped", 0))
        self.bad_steps = int(meta.get("bad_steps", 0))
        return True

    def resume(self) -> bool:
        """Restore the newest VERIFIABLE checkpoint, falling back
        through older keep-K checkpoints when the newest is corrupt or
        partial (manifest verification under MXNET_CKPT_VERIFY raises
        typed `integrity.CheckpointCorrupt` naming the bad leaf; other
        damage surfaces as OSError/ValueError).  A LATEST pointer
        naming a missing/deleted directory is counted and skipped —
        the keep-K walk is the same one the salvage path uses.
        Returns True when a checkpoint was restored (and clears any
        PREEMPTED marker), False for a fresh start.  When corruption
        forced a fallback, the restore leaves a ``ckpt.salvage``
        black-box dump carrying the whole trail."""
        if not self.ckpt_dir:
            return False
        candidates = [name for _, name in reversed(self._list_checkpoints())]
        latest_path = os.path.join(self.ckpt_dir, _LATEST)
        if os.path.exists(latest_path):
            with open(latest_path) as f:
                latest = f.read().strip()
            if latest in candidates:
                candidates.remove(latest)
                candidates.insert(0, latest)
            elif latest:
                # LATEST names a checkpoint that no longer exists
                # (deleted by an aggressive GC, a partial sync, an
                # operator): not fatal — fall back through keep-K
                events.incr("resilience.latest_dangling")
                _bb.record("integrity", "latest_dangling",
                           latest=latest)
                log.warning("LATEST names %s which does not exist in "
                            "%s; falling back through keep-K", latest,
                            self.ckpt_dir)
        salvage_trail = []          # [(name, why)] skipped candidates
        corrupt_seen = False
        for name in candidates:
            try:
                self._restore_from(name)
            except integrity.CheckpointCorrupt as e:
                corrupt_seen = True
                salvage_trail.append((name, "corrupt: %s" %
                                      (e.leaves or sorted(e.files))))
                events.incr("resilience.restore_fallback")
                log.error("checkpoint %s failed integrity "
                          "verification (%s); falling back to the "
                          "previous one", name, e)
                continue
            except (OSError, ValueError, KeyError) as e:
                salvage_trail.append((name, str(e)[:120]))
                events.incr("resilience.restore_fallback")
                log.warning("checkpoint %s unusable (%s); falling back "
                            "to the previous one", name, e)
                continue
            marker = os.path.join(self.ckpt_dir, _PREEMPT_MARKER)
            if os.path.exists(marker):
                os.remove(marker)
            self._have_ckpt = True
            events.incr("resilience.restored")
            if corrupt_seen:
                # salvage: a corrupt checkpoint was walked past and an
                # older verifiable one restored — forensic dump while
                # the ckpt_corrupt trail is still in the ring
                events.incr("integrity.ckpt_salvaged")
                _bb.record("integrity", "ckpt_salvaged",
                           restored=name,
                           step=int(self.trainer._n_step),
                           skipped=[n for n, _ in salvage_trail])
                _bb.crash_dump("ckpt.salvage")
                log.warning(
                    "salvaged: restored %s at step %d after skipping "
                    "%s", name, self.trainer._n_step,
                    ["%s (%s)" % t for t in salvage_trail])
            log.info("resumed from %s at step %d", name,
                     self.trainer._n_step)
            return True
        if corrupt_seen:
            # every keep-K candidate was corrupt: nothing salvageable —
            # dump the evidence before the caller decides what a fresh
            # start means
            _bb.crash_dump("ckpt.salvage_failed")
        return False
