"""mx.parallel — TPU-native scaling (mesh, shardings, one-executable
training steps).  This package is the TPU-first replacement for the
reference's kvstore/NCCL/ps-lite stack (SURVEY §2.3, §5.8); the KVStore
facade remains for API parity while this is the performance path.
"""
from .mesh import make_mesh, Mesh, NamedSharding, P, replicated, \
    batch_sharded, default_dp_mesh, mesh_devices, surviving_mesh
from .functional import functionalize, extract_params, load_params
from .trainer import (ShardedTrainer, softmax_ce_loss, sgd_momentum_tree,
                      adam_tree)
from .zero import BucketPlan, overlap_schedule, zero_level_default
from .dispatch import DispatchPool
from .resilience import ResilientTrainer, retry_transient
from .elastic import ElasticTrainer, ReplicaHealth
from .pipeline import (pipeline_apply, split_microbatches,
                       stack_stage_params)
from .moe import switch_route, moe_apply, moe_ffn
from .ring_attention import (ring_attention, ulysses_attention,
                             local_attention)

__all__ = ["make_mesh", "Mesh", "NamedSharding", "P", "replicated",
           "pipeline_apply", "split_microbatches", "stack_stage_params",
           "switch_route", "moe_apply", "moe_ffn",
           "batch_sharded", "default_dp_mesh", "mesh_devices",
           "surviving_mesh", "functionalize",
           "extract_params", "load_params", "ShardedTrainer",
           "ResilientTrainer", "ElasticTrainer", "ReplicaHealth",
           "retry_transient",
           "softmax_ce_loss", "sgd_momentum_tree", "adam_tree",
           "BucketPlan", "overlap_schedule", "zero_level_default",
           "DispatchPool",
           "ring_attention", "ulysses_attention", "local_attention"]
