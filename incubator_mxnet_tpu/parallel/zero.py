"""ZeRO-2/3 bucket planning + explicit overlap-first collectives
(ISSUE 10 tentpole).

The existing ``zero=1`` path ("Automatic Cross-Replica Sharding of
Weight Update in Data-Parallel Training", PAPERS.md) hands XLA's
partitioner a sharding constraint on the optimizer state and hopes the
all-reduce lowers to reduce-scatter + sharded update + all-gather.  On
TPU it does; on the host-bound virtual mesh MULTICHIP_r05 measured, it
does not — the monolithic gradient all-reduce and the N redundant full
optimizer updates sit on the critical path and weak-scaling efficiency
lands at 0.13.

Levels 2 and 3 stop hoping and say it explicitly.  ``BucketPlan``
splits the param tree two ways:

- **solo set** — params with a data-divisible axis and at least
  ``MXNET_ZERO_SOLO_KB`` bytes get their OWN ``psum_scatter`` along
  that axis (no flatten, no concat copy — for a 45 MB ResNet tree the
  concat alone measured ~430 ms/step on the 8-dev virtual mesh).
- **concat buckets** — everything small or indivisible is flattened
  and concatenated into buckets capped at ``MXNET_ZERO_BUCKET_MB``
  (one param larger than the cap gets a bucket of its own), summed
  with ONE ``psum`` per bucket and updated replicated.  Bucketing
  exists because per-param collectives pay a fixed rendezvous
  (~0.35 ms on the 8-dev CPU mesh) that would dwarf the bytes of a
  BatchNorm gamma.

Grad/param WIRE SEMANTICS per level (all on the ``data`` axis):

====  ======================  =========================  ==============
zero  gradients               optimizer state            parameters
====  ======================  =========================  ==============
2     reduce-scattered        sharded (solo axes)        replicated;
      per bucket                                         all-gather of
                                                         the updated
                                                         shards at step
                                                         END
3     reduce-scattered        sharded (solo axes)        STORED sharded
      per bucket                                         (persistent
                                                         memory ~1/N);
                                                         all-gather on
                                                         demand at step
                                                         START
====  ======================  =========================  ==============

Collective SCHEDULE (``MXNET_ZERO_OVERLAP``): ``bwd`` leaves each
bucket's reduce-scatter datum-dependent only on that bucket's grads, so
a backend with async collectives overlaps them with the rest of
backward ("launch as soon as ready" — the bucketed-overlap scheme of
DDP/ZeRO).  ``trail`` inserts one optimization barrier after backward
so every collective fires from a synchronized point: on oversubscribed
CPU meshes (more device threads than cores) a mid-backward rendezvous
convoys — devices arrive staggered and the early ones burn the cores
the late ones need; measured ~10x the isolated collective cost.
``auto`` picks trail on CPU backends, bwd elsewhere.

Global shapes are preserved everywhere — sharding is placement
metadata (NamedSharding over the param's own shape), never a shape
change — so checkpoints written under any level restore under any
other, and the elastic shrink path re-shards ZeRO-2/3 state onto the
surviving mesh through the same ``load_checkpoint`` re-placement that
handles ZeRO-1 (a 7-survivor mesh simply demotes now-indivisible
params to the replicated bucket set).
"""
from __future__ import annotations

from typing import Dict, List, Optional

import numpy as _np

from .. import config as _cfg
from ..monitor import events
from ..telemetry import costs as _costs
from ..telemetry import flightrec as _bb

__all__ = ["BucketPlan", "zero_level_default", "overlap_schedule"]


def zero_level_default(zero):
    """Resolve a ShardedTrainer ``zero=`` argument: None reads the
    MXNET_ZERO_LEVEL knob, anything else is validated and returned."""
    if zero is None:
        zero = _cfg.get("MXNET_ZERO_LEVEL")
    zero = int(zero)
    if not 0 <= zero <= 3:
        raise ValueError("zero=%r: ZeRO level must be 0..3" % (zero,))
    return zero


def overlap_schedule(devices):
    """'bwd' | 'trail' for these mesh devices (resolves 'auto': CPU
    backends convoy on mid-backward rendezvous, so they trail)."""
    mode = str(_cfg.get("MXNET_ZERO_OVERLAP"))
    if mode != "auto":
        return mode
    cpu = all(getattr(d, "platform", "") == "cpu" for d in devices)
    return "trail" if cpu else "bwd"


class BucketPlan:
    """Collective plan for one param tree on an n-way data mesh.

    ``solo``: {name: axis} — per-param reduce-scatter/all-gather along
    ``axis`` (dim divisible by ``n_shards``).
    ``buckets``: list of name lists — flatten+concat groups, each
    summed by one ``psum`` and updated replicated.
    """

    def __init__(self, shapes: Dict[str, tuple], n_shards: int,
                 cap_mb: Optional[float] = None,
                 solo_min_kb: Optional[int] = None,
                 order: Optional[List[str]] = None,
                 itemsize: int = 4, label: Optional[str] = None):
        self.n_shards = int(n_shards)
        cap_mb = float(cap_mb if cap_mb is not None
                       else _cfg.get("MXNET_ZERO_BUCKET_MB"))
        total = sum(int(_np.prod(s)) * itemsize for s in shapes.values())
        if cap_mb <= 0:
            # compile-loop steering (ISSUE 18): the autotuner resolves
            # the cap from measured cross-run history (probe rows,
            # then cost rows), falling back to the one-shot registry
            # heuristic when history is cold — which then warns that
            # it was the deciding input
            try:
                from ..compile import autotune as _autotune
                cap_mb = _autotune.suggest_bucket_cap(total, n_shards,
                                                      label=label)
            except Exception:   # noqa: BLE001 — the tuner is
                # best-effort; a broken history dir must not block
                # building the plan
                cap_mb = _costs.suggest_bucket_mb(total, n_shards,
                                                  label_prefix=label)
        self.cap_bytes = int(cap_mb * 1e6)
        self.cap_mb = cap_mb
        solo_min = int(solo_min_kb if solo_min_kb is not None
                       else _cfg.get("MXNET_ZERO_SOLO_KB")) * 1024
        self.solo: Dict[str, int] = {}
        self.buckets: List[List[str]] = []
        # reverse layer order: in backward, the LAST layer's grads are
        # ready first — plan order is collective launch order under the
        # 'bwd' schedule
        names = list(order if order is not None else shapes)[::-1]
        cur, cur_bytes = [], 0
        for n in names:
            shape = tuple(shapes[n])
            nbytes = int(_np.prod(shape)) * itemsize if shape else itemsize
            ax = None
            if self.n_shards > 1:
                for i, d in enumerate(shape):
                    if d % self.n_shards == 0 and d >= self.n_shards:
                        ax = i
                        break
            if ax is not None and nbytes >= solo_min:
                self.solo[n] = ax
                continue
            # a single param above the cap still becomes a (solo)
            # bucket of one — the cap splits groups, never params
            if cur and cur_bytes + nbytes > self.cap_bytes:
                self.buckets.append(cur)
                cur, cur_bytes = [], 0
            cur.append(n)
            cur_bytes += nbytes
        if cur:
            self.buckets.append(cur)
        self._shapes = {n: tuple(shapes[n]) for n in shapes}
        self._itemsize = itemsize
        self._cost_keys = []        # collective registry rows

    # -- introspection ---------------------------------------------------
    def bytes_of(self, names):
        return sum(int(_np.prod(self._shapes[n])) * self._itemsize
                   for n in names)

    def describe(self):
        """Summary dict for bench JSON / blackbox dumps."""
        return {
            "n_shards": self.n_shards,
            "bucket_cap_mb": round(self.cap_mb, 3),
            "solo_params": len(self.solo),
            "solo_bytes": self.bytes_of(self.solo),
            "concat_buckets": len(self.buckets),
            "concat_bytes": sum(self.bytes_of(b) for b in self.buckets),
        }

    # -- cost attribution ------------------------------------------------
    def register_cost_rows(self, label):
        """One kind="collective" row per solo reduce-scatter bucket and
        per concat-psum bucket (+ the all-gather legs), so teletop and
        bench JSON attribute bytes-on-wire per bucket rather than
        folding them into the step executable's row.  Idempotent per
        plan instance."""
        if self._cost_keys or self.n_shards <= 1:
            return self._cost_keys
        for n, ax in self.solo.items():
            b = self.bytes_of([n])
            self._cost_keys.append(_costs.note_collective(
                "%s:rs:%s" % (label, n), "reduce_scatter", b,
                self.n_shards))
            self._cost_keys.append(_costs.note_collective(
                "%s:ag:%s" % (label, n), "all_gather", b,
                self.n_shards))
        for i, names in enumerate(self.buckets):
            self._cost_keys.append(_costs.note_collective(
                "%s:psum[b%d]" % (label, i), "psum",
                self.bytes_of(names), self.n_shards))
        return self._cost_keys

    def invoke_cost_rows(self):
        """Bump every bucket row's invocation count (once per step;
        gated on the flight recorder like every other hot-path
        attribution)."""
        if not _bb.enabled():
            return
        for k in self._cost_keys:
            _costs.invoke(k)

    # -- in-step collective machinery (traced inside shard_map) ----------
    def shard_slice(self, value, name, axis_index):
        """``value``'s shard of param ``name`` along its solo axis for
        the device at ``axis_index`` (a traced value)."""
        import jax
        ax = self.solo[name]
        span = value.shape[ax] // self.n_shards
        return jax.lax.dynamic_slice_in_dim(
            value, axis_index * span, span, ax)

    def gather_params(self, params, axis_name):
        """ZeRO-3 gather-on-demand: all-gather every solo param's
        shards back to the full tensor at step start (the concat/
        indivisible set is stored replicated at every level)."""
        import jax
        if self.n_shards <= 1:
            return dict(params)
        full = dict(params)
        for n, ax in self.solo.items():
            full[n] = jax.lax.all_gather(params[n], axis_name, axis=ax,
                                         tiled=True)
        return full

    def reduce_scatter_grads(self, grads, axis_name):
        """The tentpole's bucketed reduce path: per-solo-param
        ``psum_scatter`` along the plan axis (mean over shards), one
        ``psum`` per concat bucket.  Returns ``(solo_shards,
        bucket_flats)`` — each solo entry is THIS device's grad shard
        (grad memory 1/N, ZeRO-2), each bucket flat the replicated
        mean of that bucket's small grads."""
        import jax
        import jax.numpy as jnp
        n = self.n_shards
        solo_shards = {}
        for name, ax in self.solo.items():
            g = jax.lax.psum_scatter(grads[name], axis_name,
                                     scatter_dimension=ax, tiled=True)
            solo_shards[name] = g / n
        bucket_flats = []
        for names in self.buckets:
            flat = jnp.concatenate(
                [grads[nm].reshape(-1) for nm in names]) \
                if len(names) > 1 or grads[names[0]].ndim != 1 \
                else grads[names[0]]
            bucket_flats.append(jax.lax.psum(flat, axis_name) / n)
        return solo_shards, bucket_flats

    def split_bucket(self, flat, names):
        """Un-flatten one concat bucket back into its param shapes."""
        import jax
        out = {}
        off = 0
        for n in names:
            shape = self._shapes[n]
            size = int(_np.prod(shape)) if shape else 1
            piece = jax.lax.dynamic_slice(flat, (off,), (size,))
            out[n] = piece.reshape(shape)
            off += size
        return out

    def all_gather_updated(self, shards, axis_name):
        """ZeRO-2 step-end gather: updated solo shards back to full
        (replicated) params."""
        import jax
        return {n: jax.lax.all_gather(shards[n], axis_name,
                                      axis=self.solo[n], tiled=True)
                for n in shards}


def record_plan(label, plan, zero, schedule):
    """Flight-recorder breadcrumb: the bucket plan a trainer compiled
    with — a blackbox dump of a host-bound step should name its
    collective layout, not make the reader reverse-engineer it."""
    d = plan.describe()
    events.incr("zero.plans")
    _bb.record("zero", "plan", label=label, level=int(zero),
               schedule=schedule, **d)
