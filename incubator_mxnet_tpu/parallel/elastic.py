"""Elastic mesh: survive replica loss and re-admission mid-run
(ISSUE 7 tentpole).

MULTICHIP_r05 proves an 8-way virtual mesh runs SP/PP/MoE/ZeRO-1, but
mesh membership was a LAUNCH-TIME constant: lose one replica and the
kvstore barrier times out, the job dies — despite preemption-safe
checkpoints (PR 1) and deterministic fault injection already being
in-tree.  This module makes membership a runtime variable:

``ElasticTrainer`` is a supervisor around the ``ShardedTrainer`` /
``ResilientTrainer`` pair that walks the state machine

    healthy → draining → shrunk → (re-admitting → healthy)

1. **Detection** — a heartbeat/health layer on the kvstore
   (`ReplicaHealth`): every active replica posts a per-step heartbeat
   key tagged with the current membership generation; the poll marks a
   replica SLOW after ``MXNET_ELASTIC_STALE_STEPS`` missed beats and
   DOWN after ``MXNET_ELASTIC_DOWN_STEPS``.  The fault sites
   ``mesh.replica_down`` / ``mesh.replica_slow`` (``MXNET_FAULT_PLAN``)
   only SUPPRESS the victim's beats — detection always goes through
   the real staleness path, so the virtual-mesh test exercises the
   production mechanism, not a shortcut.
2. **Shrink** — drain the in-flight step (block on device state),
   leave forensics (a ``mesh.shrink`` black-box dump naming the lost
   replica), advance the kvstore membership generation (a stale rank
   can not rejoin a barrier of the new mesh — `StaleMembership`),
   release the old trainer's device state, re-form a smaller mesh from
   the survivors via `mesh.make_mesh`/`surviving_mesh`, rebuild the
   trainer through the caller's factory (global batch and LR scale
   with the replica count), and resume from the last atomic
   checkpoint.  ZeRO-1 optimizer state re-shards on restore:
   `load_checkpoint` pulls every leaf to host and re-places it on the
   NEW mesh's shardings ("Automatic Cross-Replica Sharding of Weight
   Update in Data-Parallel Training", PAPERS.md).  The continuation is
   bit-deterministic: params/opt state come from the checkpoint, the
   per-step RNG is ``fold_in(seed, step)``, and the survivor order is
   preserved — so the shrunk run equals a from-checkpoint N-1-way run
   bit for bit.
3. **Re-admission** — at the next epoch boundary the supervisor probes
   the down replicas; a recovered one is re-admitted by checkpointing
   at the current step, advancing the generation again and rebuilding
   on the grown mesh (no steps lost on grow — the checkpoint IS the
   handoff).

Every transition is counted on `monitor.events` (``mesh.*``) and
recorded in the flight-recorder ring (kind ``mesh``), so a dump's
timeline replays the whole membership history of a run.
"""
from __future__ import annotations

import logging
import time
from typing import Callable, Optional

import numpy as _np
import jax

from .. import fault
from .. import integrity
from ..monitor import events
from ..telemetry import flightrec as _bb
from ..telemetry import spans as _tele
from ..telemetry.fleet import FleetTelemetry
from .mesh import surviving_mesh
from .resilience import ResilientTrainer

__all__ = ["ElasticTrainer", "ReplicaHealth"]

log = logging.getLogger(__name__)

_HB_KEY = "__mesh__/hb/%d"


class ReplicaHealth:
    """Heartbeat/health layer on the kvstore.

    Each active replica posts a per-step heartbeat — a kvstore key
    ``__mesh__/hb/<rid>`` holding ``[step, generation]`` — and the
    supervisor polls staleness: ``step - last_beat >= stale_steps`` →
    SLOW (observed, counted), ``>= down_steps`` → DOWN (the mesh
    shrinks).  Beats tagged with an old membership generation are
    REJECTED (``mesh.stale_rank_beat``): a rank the mesh re-formed
    without cannot heartbeat its way back in — re-admission is the
    supervisor's explicit, epoch-boundary decision.

    Failure *injection* is deliberately indirect: the fault sites
    ``mesh.replica_down`` / ``mesh.replica_slow`` suppress the victim's
    beats (the victim is the highest active rid — deterministic), and
    detection then runs the same staleness arithmetic a really-dead
    replica would trip.
    """

    def __init__(self, kv, n_replicas: int, stale_steps=None,
                 down_steps=None):
        from .. import config
        from ..ndarray.ndarray import NDArray
        self.kv = kv
        self.n = int(n_replicas)
        self.stale = int(stale_steps if stale_steps is not None
                         else config.get("MXNET_ELASTIC_STALE_STEPS"))
        self.down = int(down_steps if down_steps is not None
                        else config.get("MXNET_ELASTIC_DOWN_STEPS"))
        self.generation = int(getattr(kv, "generation", 0))
        self._suppressed = set()        # rids whose beats stopped (down)
        self._slow_until = {}           # rid -> step beats resume
        self._state = {}                # rid -> last reported verdict
        self._observed_slow = set()     # rids the fleet telemetry
        #                                 (straggler detector) flagged
        for rid in range(self.n):
            kv.init(_HB_KEY % rid, NDArray(
                _np.asarray([-1.0, 0.0], _np.float64)))

    # -- beats ----------------------------------------------------------
    def set_generation(self, generation: int):
        self.generation = int(generation)

    def suppress(self, rid: int):
        """Stop a replica's beats (it died).  Cleared by `restore`."""
        self._suppressed.add(int(rid))

    def restore(self, rid: int):
        """The replica came back (re-admission): beats resume."""
        self._suppressed.discard(int(rid))
        self._slow_until.pop(int(rid), None)
        self._state.pop(int(rid), None)
        self._observed_slow.discard(int(rid))

    # -- fleet-telemetry feed (ISSUE 11) --------------------------------
    def note_observed_slow(self, rid: int, step: int,
                           source: str = "straggler") -> None:
        """Feed the "slow (observed)" state from TELEMETRY rather than
        heartbeat staleness: the straggler detector saw this replica's
        published step times skew while its beats are still fresh —
        the alive-but-slow case staleness alone can never see.  The
        verdict is sticky across polls until `clear_observed_slow`
        (otherwise every fresh beat would flip it healthy and the next
        detector round would re-count the same degradation)."""
        rid = int(rid)
        self._observed_slow.add(rid)
        if self._state.get(rid) != "slow":
            self._state[rid] = "slow"
            events.incr("mesh.replica_slow")
            _bb.record_mesh("replica_slow", replica=rid,
                            step=int(step), source=source)

    def clear_observed_slow(self, rid: int) -> None:
        """The detector reports the replica back under the line; the
        next poll may return it to "healthy" (no event — recovery to
        steady state is not a transition worth a counter)."""
        self._observed_slow.discard(int(rid))

    def beat(self, rid: int, step: int, generation=None) -> bool:
        """Post one heartbeat for `rid` (tagged with the CURRENT
        generation unless overridden — the stale-rank test path).
        Returns False when the beat was suppressed or rejected."""
        from ..ndarray.ndarray import NDArray
        gen = self.generation if generation is None else int(generation)
        if gen != int(getattr(self.kv, "generation", self.generation)):
            # a rank from a previous mesh generation is heartbeating:
            # reject — it must re-enter through explicit re-admission
            events.incr("mesh.stale_rank_beat")
            _bb.record_mesh("stale_rank_beat", replica=int(rid),
                            gen=gen, step=int(step))
            return False
        if rid in self._suppressed:
            return False
        if step < self._slow_until.get(rid, -1):
            return False
        # the beat is a kvstore push tagged (replica, step, gen): on
        # the merged cross-process timeline a replica's heartbeats are
        # attributable spans, not anonymous store traffic (ISSUE 11)
        with _tele.span("kv.heartbeat", replica=int(rid),
                        step=int(step), gen=gen):
            self.kv.push(_HB_KEY % rid, NDArray(
                _np.asarray([float(step), float(gen)], _np.float64)))
        return True

    def beat_all(self, step: int, active, inject: bool = True) -> None:
        """One heartbeat round for every active replica.  The fault
        sites fire HERE (this is where a real replica's beat would
        originate): ``mesh.replica_down`` permanently suppresses the
        victim, ``mesh.replica_slow`` suppresses it for one staleness
        window.  ``inject=False`` skips the fault sites: the elastic
        supervisor passes it for REPLAYED steps (a post-shrink
        checkpoint rewind revisits step numbers at or below the fault
        step — re-evaluating ``site@K`` there would kill a fresh
        victim on every replay pass and cascade the mesh down to
        ``min_replicas``; one planned failure must mean one failure)."""
        active = list(active)
        if inject and active and \
                fault.should_fire("mesh.replica_down", step):
            victim = max(active)
            self.suppress(victim)
            log.warning("fault: replica %d stops heartbeating at step "
                        "%d", victim, step)
        cands = [r for r in active if r not in self._suppressed]
        if inject and cands and \
                fault.should_fire("mesh.replica_slow", step):
            victim = max(cands)
            # miss exactly `stale` beats: enough for the poll to
            # report SLOW (age == stale), one short of DOWN — slow is
            # an observation, never a shrink (age never reaches
            # down_steps > stale_steps)
            self._slow_until[victim] = step + self.stale
        for rid in active:
            self.beat(rid, step)

    # -- verdicts -------------------------------------------------------
    def _last_beat(self, rid: int):
        from ..ndarray.ndarray import NDArray
        out = NDArray(_np.zeros(2, _np.float64))
        self.kv.pull(_HB_KEY % rid, out=out)
        step, gen = (float(x) for x in out.asnumpy())
        if int(gen) != self.generation:
            return None             # never beaten under this generation
        return step

    def poll(self, step: int, active) -> dict:
        """{rid: "healthy" | "slow" | "down"} for the active set, from
        heartbeat staleness alone.  Transitions (not steady states) are
        counted and ring-recorded, so the forensic timeline shows WHEN
        each replica degraded, once."""
        out = {}
        for rid in active:
            last = self._last_beat(rid)
            age = self.down if last is None or last < 0 \
                else step - last
            if age >= self.down:
                verdict = "down"
            elif age >= self.stale:
                verdict = "slow"
            else:
                verdict = "healthy"
            if verdict == "healthy" and rid in self._observed_slow:
                # the straggler detector condemned this replica from
                # its published step times; fresh beats don't acquit
                # it — only the detector clearing does
                verdict = "slow"
            if self._state.get(rid) != verdict:
                self._state[rid] = verdict
                if verdict == "down":
                    events.incr("mesh.replica_down")
                    _bb.record_mesh("replica_down", replica=int(rid),
                                    step=int(step), missed=int(age))
                elif verdict == "slow":
                    events.incr("mesh.replica_slow")
                    _bb.record_mesh("replica_slow", replica=int(rid),
                                    step=int(step), missed=int(age))
            out[rid] = verdict
        return out


class ElasticTrainer:
    """Supervisor that keeps a data-parallel run alive across replica
    loss and re-admission (module docstring has the state machine).

    build_trainer: ``(mesh, lr_factor) -> ShardedTrainer`` — the
        caller's factory.  It is re-invoked on every mesh transition
        with the new mesh and ``lr_factor = n_active / n_total`` (the
        linear LR-scaling rule: the global batch shrank with the mesh,
        so the LR follows).  For bit-deterministic shrink semantics the
        factory must be pure in its inputs.
    ckpt_dir: the atomic-checkpoint directory (ResilientTrainer's) —
        the ONLY state channel across mesh transitions.
    devices: replica devices (default ``jax.devices()``); replica id
        = index into this list.
    steps_per_epoch: epoch boundary cadence — re-admission happens at
        ``step % steps_per_epoch == 0`` (None: never re-admit).
    kv: kvstore carrying heartbeats + membership generation (default: a
        fresh ``local`` store).
    min_replicas / stale_steps / down_steps / ckpt_interval / keep /
    seed / handle_sigterm: see the MXNET_ELASTIC_* / MXNET_CKPT_*
        knobs and ResilientTrainer.
    audit_interval: cross-replica SDC audit cadence
        (MXNET_SDC_AUDIT_STEPS; 0 = off).  Every N steps the
        supervisor hashes replicated state per replica — digests
        round-trip through THIS trainer's kvstore, the heartbeat
        channel — and a divergent replica is EVICTED through the
        shrink path (black-box dump naming replica + leaf first); at
        min_replicas it falls back to checkpoint rollback.  An
        SDC-evicted replica is eligible for re-admission at the next
        epoch boundary like any other down replica: the rebuild
        restores one consistent checkpoint onto every member, so a
        transient flip does not permanently cost a replica (persistent
        flippers get re-evicted by the next audit round).

    Drive it with ``step(data_fn)`` where ``data_fn(step, n_replicas)
    -> (batch, labels)`` is a pure function — after a shrink the step
    counter REWINDS to the restored checkpoint and the lost steps are
    replayed through the same data_fn, which is what makes the
    continuation equal a from-checkpoint (N-1)-way run bit for bit.
    """

    #: factor by which an injected mesh.replica_slow victim's PUBLISHED
    #: step wall is inflated during its suppression window — the
    #: single-controller stand-in for what a genuinely slow replica's
    #: fleet-telemetry snapshot would report (its steps really take
    #: longer); detection then runs the production skew arithmetic
    SLOW_INJECT_FACTOR = 4.0

    def __init__(self, build_trainer: Callable, ckpt_dir: str,
                 devices=None, steps_per_epoch: Optional[int] = None,
                 min_replicas: Optional[int] = None, seed: int = 0,
                 ckpt_interval: Optional[int] = None,
                 keep: Optional[int] = None, kv=None,
                 stale_steps=None, down_steps=None,
                 handle_sigterm: bool = True,
                 audit_interval: Optional[int] = None,
                 fleet: Optional[bool] = None):
        from .. import config
        from ..kvstore import create as kv_create
        self._build = build_trainer
        self.devices = list(devices if devices is not None
                            else jax.devices())
        self.n_total = len(self.devices)
        self.active = list(range(self.n_total))
        self.down = {}              # rid -> step it was lost at
        self.min_replicas = int(
            min_replicas if min_replicas is not None
            else config.get("MXNET_ELASTIC_MIN_REPLICAS"))
        self.steps_per_epoch = (int(steps_per_epoch)
                                if steps_per_epoch else None)
        self.seed = int(seed)
        self.ckpt_dir = ckpt_dir
        self.ckpt_interval = ckpt_interval
        self.keep = keep
        self.audit_interval = int(
            audit_interval if audit_interval is not None
            else config.get("MXNET_SDC_AUDIT_STEPS"))
        self._sigterm = handle_sigterm
        self.kv = kv if kv is not None else kv_create("local")
        self.health = ReplicaHealth(self.kv, self.n_total,
                                    stale_steps=stale_steps,
                                    down_steps=down_steps)
        # fleet telemetry (ISSUE 11): per-replica snapshots through
        # THIS trainer's kvstore + the straggler detector feeding the
        # health layer's slow-(observed) state.  Default on; fleet=False
        # (or MXNET_FLEET_PUBLISH_STEPS=0) disables
        if fleet is None:
            fleet = int(config.get("MXNET_FLEET_PUBLISH_STEPS")) > 0
        self.fleet = FleetTelemetry(self.kv, self.n_total) \
            if fleet else None
        self.state = "healthy"
        self.transitions = []       # [{kind, step, wall_s, ...}]
        self.last_blackbox = None   # newest mesh-shrink dump path
        self._step_hwm = -1         # highest step already driven once
        self.trainer = None
        self.resilient = None
        self._rebuild(resume=True)

    # -- mesh (re)construction -----------------------------------------
    @property
    def n_replicas(self) -> int:
        return len(self.active)

    @property
    def step_number(self) -> int:
        return self.trainer._n_step

    def _rebuild(self, resume: bool) -> None:
        """(Re)build the trainer + resilient wrapper on the CURRENT
        active set and restore the newest atomic checkpoint."""
        mesh = surviving_mesh(
            self.devices,
            lost=[i for i in range(self.n_total)
                  if i not in self.active])
        lr_factor = self.n_replicas / float(self.n_total)
        preempted = False
        if self.resilient is not None:
            # a SIGTERM that landed during the transition must survive
            # the rebuild: the flag lives on the wrapper being discarded
            preempted = self.resilient._preempted
            self.resilient.uninstall_sigterm()
        if self.trainer is not None:
            self.trainer.release()
        self.trainer = self._build(mesh, lr_factor)
        # audit_interval=0: the SUPERVISOR owns the SDC audit (its
        # response is eviction, not the wrapper's rollback)
        self.resilient = ResilientTrainer(
            self.trainer, ckpt_dir=self.ckpt_dir,
            ckpt_interval=self.ckpt_interval, keep=self.keep,
            seed=self.seed, handle_sigterm=self._sigterm,
            audit_interval=0)
        if resume:
            self.resilient.resume()
        if preempted:
            self.resilient.request_preemption()
        # the first step on a fresh trainer pays the compile: its wall
        # is not a step time, and publishing it would pollute every
        # replica's straggler window with a seconds-scale outlier
        self._fleet_skip_next = True

    def _drain(self) -> None:
        """Drain in-flight work: block until the device state (params +
        optimizer state) of the current generation is materialized, so
        the checkpoint/teardown below never races a dispatched step."""
        leaves = jax.tree_util.tree_leaves(
            (self.trainer.params, self.trainer.opt_state))
        if leaves:
            jax.block_until_ready(leaves)

    # -- transitions ----------------------------------------------------
    def _shrink(self, lost, stepno: int,
                reason: str = "replica_down") -> None:
        survivors = [r for r in self.active if r not in lost]
        if len(survivors) < self.min_replicas:
            raise RuntimeError(
                "elastic mesh cannot shrink below min_replicas=%d "
                "(lost %s at step %d, %d survivors)"
                % (self.min_replicas, sorted(lost), stepno,
                   len(survivors)))
        self.state = "draining"
        t0 = time.perf_counter()
        self._drain()
        # forensics BEFORE teardown: the dying replica's trail — the
        # replica_down marker from poll() (or the integrity.sdc marker
        # from the audit), this shrink marker, and the step/counter
        # timeline — is still in the ring; the dump names the lost
        # replica, its device, and why it is being removed
        _bb.record_mesh(
            "shrink", step=int(stepno), lost=sorted(int(r) for r in lost),
            devices=[repr(self.devices[r]) for r in sorted(lost)],
            survivors=len(survivors), reason=reason)
        self.last_blackbox = _bb.crash_dump("mesh.shrink")
        # membership epoch: every credential of the old mesh dies here
        self.kv.advance_generation("mesh-shrink")
        self.health.set_generation(self.kv.generation)
        for rid in lost:
            self.down[rid] = stepno
            if self.fleet is not None:
                # a removed replica's stale window must not skew the
                # survivors' straggler baseline
                self.fleet.detector.forget(rid)
        self.active = survivors
        old_step = self.trainer._n_step
        self._rebuild(resume=True)
        steps_lost = old_step - self.trainer._n_step
        wall = time.perf_counter() - t0
        events.incr("mesh.shrinks")
        events.incr("mesh.steps_lost", max(0, steps_lost))
        self.transitions.append(
            {"kind": "shrink", "step": int(stepno),
             "reason": reason,
             "lost": sorted(int(r) for r in lost),
             "replicas": self.n_replicas,
             "steps_lost": int(steps_lost),
             "resumed_step": int(self.trainer._n_step),
             "wall_s": round(wall, 4)})
        self.state = "shrunk"
        log.warning("mesh shrank %d->%d at step %d (lost %s); resumed "
                    "from checkpoint step %d (%d step(s) to replay) in "
                    "%.2fs", len(survivors) + len(lost), len(survivors),
                    stepno, sorted(lost), self.trainer._n_step,
                    steps_lost, wall)

    def _probe_recovered(self, rid: int) -> bool:
        """Whether a down replica can rejoin: its device answers a
        trivial computation.  On the virtual mesh a 'dead' replica is
        an addressable device whose beats were suppressed, so the probe
        succeeds — which is the point: recovery is an epoch-boundary
        DECISION, the probe only guards against re-admitting hardware
        that is still gone."""
        try:
            dev = self.devices[rid]
            jax.block_until_ready(
                jax.device_put(_np.zeros(1, _np.float32), dev))
            return True
        except Exception:           # noqa: BLE001 — still dead
            return False

    def _maybe_readmit(self, stepno: int) -> None:
        if not self.down:
            return
        recovered = sorted(r for r in list(self.down)
                           if self._probe_recovered(r))
        if not recovered:
            return
        self.state = "re-admitting"
        t0 = time.perf_counter()
        self._drain()
        # the checkpoint IS the handoff: grow resumes at the SAME step
        self.resilient.checkpoint()
        self.kv.advance_generation("mesh-grow")
        self.health.set_generation(self.kv.generation)
        for rid in recovered:
            self.down.pop(rid, None)
            self.health.restore(rid)
        self.active = sorted(self.active + recovered)
        self._rebuild(resume=True)
        # the re-admitted replicas immediately heartbeat under the new
        # generation so the next poll sees them healthy, not stale
        for rid in recovered:
            self.health.beat(rid, stepno)
        wall = time.perf_counter() - t0
        events.incr("mesh.grows")
        events.incr("mesh.replica_readmitted", len(recovered))
        _bb.record_mesh("grow", step=int(stepno),
                        readmitted=[int(r) for r in recovered],
                        replicas=self.n_replicas)
        self.transitions.append(
            {"kind": "grow", "step": int(stepno),
             "readmitted": [int(r) for r in recovered],
             "replicas": self.n_replicas,
             "wall_s": round(wall, 4)})
        # only a FULL recovery is healthy: with replicas still down
        # (partial re-admission) the mesh stays "shrunk" so callers/
        # monitoring reading `state` see the degradation
        self.state = "healthy" if not self.down else "shrunk"
        log.info("mesh grew to %d replicas at step %d (re-admitted %s) "
                 "in %.2fs%s", self.n_replicas, stepno, recovered, wall,
                 "" if not self.down
                 else " — still down: %s" % sorted(self.down))

    # -- the supervised step -------------------------------------------
    def step(self, data_fn: Callable):
        """One elastic train step.  ``data_fn(step, n_replicas) ->
        (batch, labels)`` must be pure (replay after a shrink calls it
        again for the rewound steps).  Returns ``(loss, ok)`` from the
        guarded resilient step; the step it belongs to is
        ``self.step_number - 1`` after the call (a shrink REWINDS the
        counter to the restored checkpoint first)."""
        stepno = self.trainer._n_step
        if self.steps_per_epoch and stepno % self.steps_per_epoch == 0:
            self._maybe_readmit(stepno)
        # fault sites fire on FIRST-visit steps only: a post-shrink
        # rewind replays step numbers the plan already fired on, and
        # re-injecting there would fell a new victim per replay pass
        # (cascade to min_replicas from one planned failure)
        first_visit = stepno > self._step_hwm
        self._step_hwm = max(self._step_hwm, stepno)
        self.health.beat_all(stepno, self.active, inject=first_visit)
        verdict = self.health.poll(stepno, self.active)
        lost = [r for r in self.active if verdict.get(r) == "down"]
        if lost:
            self._shrink(lost, stepno)
            stepno = self.trainer._n_step
        if self.audit_interval > 0 and stepno > 0 and \
                stepno % self.audit_interval == 0 and \
                self.n_replicas > 1:
            # cross-replica SDC audit through the kvstore; a divergent
            # replica is evicted via the shrink path (rollback when
            # eviction would undershoot min_replicas)
            self._audit(stepno, inject=first_visit)
            stepno = self.trainer._n_step
        batch, labels = data_fn(stepno, self.n_replicas)
        t0 = time.perf_counter()
        loss, ok = self.resilient.step(batch, labels)
        if self.fleet is not None:
            self._fleet_round(stepno, time.perf_counter() - t0)
        return loss, ok

    def _fleet_round(self, stepno: int, wall_s: float) -> None:
        """Publish this step's per-replica telemetry and act on the
        straggler verdicts.  Runs AFTER the step's dispatch returned
        (the device is already busy; the host-side cost is a
        dozen-float kvstore push per replica, at the
        MXNET_FLEET_PUBLISH_STEPS cadence).

        Single-controller stand-in: every replica's wall is the
        measured step wall, except a `mesh.replica_slow` victim — its
        published wall is inflated by SLOW_INJECT_FACTOR for its
        suppression window, which is exactly what a genuinely slow
        replica's own telemetry would report.  Detection and the
        slow-(observed) feed then run the production path."""
        if getattr(self, "_fleet_skip_next", False):
            # compile step (fresh build/rebuild): not a step time
            self._fleet_skip_next = False
            return
        per = {}
        for rid in self.active:
            us = wall_s * 1e6
            if stepno < self.health._slow_until.get(rid, -1):
                us *= self.SLOW_INJECT_FACTOR
            per[rid] = us
        try:
            stragglers = self.fleet.update(stepno, per)
        except Exception:           # noqa: BLE001 — observability must
            return                  # never take the training loop down
        for rid in stragglers:
            if rid in self.active:
                self.health.note_observed_slow(rid, stepno)
        for rid in sorted(self.health._observed_slow):
            if rid not in stragglers:
                self.health.clear_observed_slow(rid)

    def _audit(self, stepno: int, inject: bool = True) -> None:
        rid_of = {self.devices[r]: r for r in self.active}
        report = integrity.audit_replicas(
            self.trainer, step=stepno, rid_of=rid_of, kv=self.kv,
            inject=inject)
        if report.ok:
            return
        victims = [r for r in report.victims() if r in self.active]
        log.error("cross-replica SDC at step %d: replica(s) %s "
                  "diverge on %s", stepno, victims,
                  report.leaves()[:4])
        if not victims:
            return
        if len(self.active) - len(victims) >= self.min_replicas:
            events.incr("mesh.sdc_evicted", len(victims))
            # eviction: the divergent replica leaves through the same
            # drain → dump → generation++ → rebuild path a dead one
            # does; the restore re-places ONE consistent checkpoint on
            # every survivor, so the divergence cannot outlive the
            # transition
            self._shrink(victims, stepno, reason="sdc")
        else:
            # at min_replicas eviction is not an option: dump, then
            # roll every replica back to the last verifiable
            # checkpoint (the ResilientTrainer SDC response)
            _bb.crash_dump("sdc")
            if not self.resilient.resume():
                raise integrity.SDCDetected(victims, report.leaves(),
                                            stepno)
            events.incr("integrity.sdc_rollback")
            log.warning("SDC response at min_replicas: rolled back to "
                        "step %d", self.trainer._n_step)

    def run(self, data_fn: Callable, n_steps: int) -> dict:
        """Drive `step` until `n_steps` steps are COMPLETE (shrink
        replay included), returning ``{step: loss}`` for the surviving
        timeline — replayed steps overwrite their pre-shrink values,
        so the dict is the run as the final mesh history produced it."""
        losses = {}
        while self.trainer._n_step < n_steps:
            loss, _ok = self.step(data_fn)
            losses[self.trainer._n_step - 1] = float(loss)
        return losses
