"""Per-replica dispatch fan-out (ISSUE 10 tentpole c).

MULTICHIP_r05's diagnosis: "the host dispatches replicas nearly
serially".  The serial piece this module owns is batch placement — a
single ``jax.device_put`` of the host batch onto an N-way
NamedSharding uploads the N shards one after another from the calling
thread.  ``DispatchPool`` splits the host batch by replica and
device_puts every shard from its own worker thread (JAX dispatch
releases the GIL into C++, so the uploads genuinely overlap), then
reassembles the global array with
``jax.make_array_from_single_device_arrays`` — bit-identical placement,
parallel wire time.

Every worker times its replica's upload into
``train.dispatch_replica_us{replica=<i>}`` (the PR 8 labeled
percentile rings) and drops a flight-recorder sample, so a host-bound
step's lost microseconds are attributable PER REPLICA in teletop and
blackbox dumps instead of vanishing into one aggregate number.

Engagement (``MXNET_DISPATCH_THREADS``): -1 auto = one thread per
replica (capped at 8), only for multi-replica meshes fed from host
arrays of >= 1 MB (below that the thread handoff costs more than the
overlap buys); 0 off; N exact.  The fan-out only handles the
single-process, batch-dim-divisible case — anything else falls back to
the plain ``device_put`` with identical semantics.
"""
from __future__ import annotations

import time
from concurrent.futures import ThreadPoolExecutor
from typing import Optional

import numpy as _np

from .. import config as _cfg
from ..monitor import events
from ..telemetry import flightrec as _bb

__all__ = ["DispatchPool"]

_MIN_FANOUT_BYTES = 1 << 20


class DispatchPool:
    """Worker pool that fans per-replica batch-shard uploads out of the
    training thread.  One instance per trainer; ``shutdown()`` (or GC)
    retires the threads."""

    def __init__(self, devices, threads: Optional[int] = None):
        self.devices = list(devices)
        n = int(threads if threads is not None
                else _cfg.get("MXNET_DISPATCH_THREADS"))
        if n < 0:                               # auto
            n = min(len(self.devices), 8)
        self.n_threads = n if len(self.devices) > 1 else 0
        self._pool = None

    @property
    def enabled(self):
        # N=1 is honored (uploads serialize through one worker but the
        # per-replica timing attribution is kept — the knob's
        # documented contract); a single-replica mesh has nothing to
        # fan out regardless
        return self.n_threads >= 1 and len(self.devices) > 1

    def _ensure_pool(self):
        if self._pool is None:
            self._pool = ThreadPoolExecutor(
                max_workers=self.n_threads,
                thread_name_prefix="mx-dispatch")
        return self._pool

    def eligible(self, arr, sharding) -> bool:
        """Can (and should) this host array fan out? numpy-like input,
        batch dim divisible across every replica, big enough to beat
        the thread handoff."""
        import jax
        if not self.enabled:
            return False
        if isinstance(arr, jax.Array):
            return False                        # already placed
        shape = getattr(arr, "shape", None)
        if not shape or shape[0] % len(self.devices) != 0:
            return False
        nbytes = getattr(arr, "nbytes", 0)
        return nbytes >= _MIN_FANOUT_BYTES

    def place(self, arr, sharding):
        """Host array -> global array on ``sharding``, one worker per
        replica shard.  Caller checked ``eligible``."""
        import jax
        arr = _np.asarray(arr)
        ndev = len(self.devices)
        rows = arr.shape[0] // ndev
        pool = self._ensure_pool()
        record = _bb.enabled()

        def upload(i):
            t0 = time.perf_counter()
            piece = jax.device_put(arr[i * rows:(i + 1) * rows],
                                   self.devices[i])
            dt = time.perf_counter() - t0
            if record:
                events.observe_time("train.dispatch_replica_us", dt,
                                    labels={"replica": str(i)})
            return piece

        shards = list(pool.map(upload, range(ndev)))
        if record:
            _bb.record("step", "dispatch_fanout", replicas=ndev,
                       bytes=int(arr.nbytes))
        return jax.make_array_from_single_device_arrays(
            arr.shape, sharding, shards)

    def run(self, fn, args_per_replica):
        """Generic per-replica fan-out (the bench's per-replica
        breakdown probes ride on this): apply ``fn`` to each element
        of ``args_per_replica`` concurrently, return results in
        order."""
        if not self.enabled:
            return [fn(a) for a in args_per_replica]
        return list(self._ensure_pool().map(fn, args_per_replica))

    def shutdown(self):
        pool, self._pool = self._pool, None
        if pool is not None:
            pool.shutdown(wait=False)

    def __del__(self):                          # best-effort
        try:
            self.shutdown()
        except Exception:       # noqa: BLE001
            pass
