"""Sharded training step — the pod-scale path.

TPU-native replacement for the reference's NCCL/ps-lite data-parallel
training (ref: kvstore_nccl.h grouped allreduce + optimizer update ops;
SURVEY §5.8 "TPU-native equivalent"): the WHOLE train step — forward,
backward, gradient reduction, fused optimizer update — is ONE jitted XLA
executable over a device Mesh.  Gradient allreduce is not a separate
push/pull: with batch sharded on the 'data' axis and params replicated
(or sharded for tensor parallel), XLA inserts the ICI collectives
automatically.  Buffer donation makes updates in-place in HBM.
"""
from __future__ import annotations

import functools
import time
from typing import Callable, Dict, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .. import config as _config
from .functional import functionalize, extract_params, load_params
from .mesh import make_mesh, mesh_devices
from .zero import BucketPlan, overlap_schedule, record_plan, \
    zero_level_default
from ..monitor import events
from ..telemetry import costs as _costs
from ..telemetry import flightrec as _bb
from ..telemetry import spans as _tele
from ..telemetry.stepstats import StepTelemetry

__all__ = ["ShardedTrainer", "softmax_ce_loss", "sgd_momentum_tree",
           "adam_tree"]


def softmax_ce_loss(logits, labels):
    """Mean softmax cross-entropy with integer labels (pure jax)."""
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    ll = jnp.take_along_axis(logp, labels[:, None].astype(jnp.int32),
                             axis=-1)
    return -jnp.mean(ll)


def sgd_momentum_tree(lr, momentum=0.9, wd=0.0):
    """Fused tree-wide SGD+momentum (ref: multi_sgd_mom_update semantics —
    one executable updates every tensor)."""

    def init(params):
        # zeros from shape/dtype metadata (NOT zeros_like: the params
        # may be multi-controller global arrays, and the state is
        # re-placed onto its own shardings anyway)
        return jax.tree_util.tree_map(
            lambda w: jnp.zeros(w.shape, w.dtype), params)

    def update(params, grads, state, scale=1.0):
        def upd(w, g, m):
            g = g.astype(jnp.float32) * scale + wd * w.astype(jnp.float32)
            new_m = momentum * m - lr * g
            return (w.astype(jnp.float32) + new_m).astype(w.dtype), new_m
        flat = jax.tree_util.tree_map(upd, params, grads, state)
        new_p = jax.tree_util.tree_map(lambda t: t[0], flat,
                                       is_leaf=lambda t: isinstance(t, tuple))
        new_s = jax.tree_util.tree_map(lambda t: t[1], flat,
                                       is_leaf=lambda t: isinstance(t, tuple))
        return new_p, new_s

    return init, update


def adam_tree(lr, beta1=0.9, beta2=0.999, eps=1e-8, wd=0.0):
    def init(params):
        z = jax.tree_util.tree_map(
            lambda w: jnp.zeros(w.shape, jnp.float32), params)
        z2 = jax.tree_util.tree_map(
            lambda w: jnp.zeros(w.shape, jnp.float32), params)
        return {"m": z, "v": z2, "t": jnp.zeros((), jnp.int32)}

    def update(params, grads, state, scale=1.0):
        t = state["t"] + 1
        b1t = 1.0 - beta1 ** t.astype(jnp.float32)
        b2t = 1.0 - beta2 ** t.astype(jnp.float32)

        def upd(w, g, m, v):
            g = g.astype(jnp.float32) * scale + wd * w.astype(jnp.float32)
            new_m = beta1 * m + (1 - beta1) * g
            new_v = beta2 * v + (1 - beta2) * jnp.square(g)
            mhat = new_m / b1t
            vhat = new_v / b2t
            new_w = w.astype(jnp.float32) - lr * mhat / \
                (jnp.sqrt(vhat) + eps)
            return new_w.astype(w.dtype), new_m, new_v
        flat = jax.tree_util.tree_map(upd, params, grads, state["m"],
                                      state["v"])
        leaf = lambda t_: isinstance(t_, tuple)
        return (jax.tree_util.tree_map(lambda x: x[0], flat, is_leaf=leaf),
                {"m": jax.tree_util.tree_map(lambda x: x[1], flat,
                                             is_leaf=leaf),
                 "v": jax.tree_util.tree_map(lambda x: x[2], flat,
                                             is_leaf=leaf),
                 "t": t})

    return init, update


class ShardedTrainer:
    """One-executable train step over a Mesh.

    block: a Gluon (Hybrid)Block (params already initialized)
    loss_fn: pure (outputs, labels) → scalar
    optimizer: "sgd" | "adam" | (init, update) pair
    mesh: jax Mesh (default: 1-d data mesh over all devices)
    param_spec_fn: name, shape → PartitionSpec for tensor-parallel layouts
        (default: fully replicated — pure DP)
    zero: ZeRO stage, or None = MXNET_ZERO_LEVEL.
        0 — fully replicated.
        1 — optimizer state sharded along the data axis via sharding
        constraints (the legacy WSC path: XLA's partitioner picks the
        collectives; bit-compatible with earlier releases; the
        reference's server-side-optimizer semantic, SURVEY §5.8).
        2 — + gradients reduce-scattered in size-capped buckets and
        the update computed shard-locally (parallel/zero.py: explicit
        overlap-first collectives, local BN statistics).
        3 — + parameters STORED sharded (gathered on demand at step
        start; persistent per-replica param memory ~1/N).
        Levels 2-3 need a 1-d data mesh and replicated param specs;
        combine tensor parallelism with zero<=1.
    preprocess: pure jnp fn applied to the batch INSIDE the jitted
        step (e.g. `io.device_feed.make_normalizer` — uint8 wire
        batches are normalized/cast on device, fused with the step)
    amp: mixed-precision compute dtype, or None = MXNET_AMP_DTYPE
        (empty = off).  'bfloat16' turns the op-registry cast policy
        on (`contrib.amp.init`): matmul/conv ops compute in bf16,
        numerically-sensitive ops stay f32, and because the policy
        sits below `invoke`, the SAME casts land inside this
        trainer's jitted step executables — ZeRO-2/3's shard_map
        bodies included.  Master weights and optimizer state stay
        f32 (grads arrive f32 at the update).  'float16' is the
        parity path: bare ShardedTrainer runs it unscaled (bf16-range
        models only); wrap in ResilientTrainer(amp='float16') for the
        dynamic LossScaler backed by the NaN-guard.  The policy is
        process-wide — `contrib.amp.turn_off()` reverts it.
    """

    def __init__(self, block, loss_fn=softmax_ce_loss, optimizer="sgd",
                 lr=0.01, momentum=0.9, wd=0.0, mesh: Optional[Mesh] = None,
                 batch_axis="data", param_spec_fn=None, donate=True,
                 zero=None, preprocess=None, amp=None):
        from ..contrib import amp as _amp_mod
        self.amp = _amp_mod.normalize_dtype(
            amp if amp is not None else _config.get("MXNET_AMP_DTYPE"))
        if self.amp:
            # BEFORE the first trace: the wrapped registry fns are what
            # the lazily-built step executable captures
            _amp_mod.init(self.amp)
            events.incr("amp.trainer_init")
            _bb.record("amp", "init", target=self.amp,
                       trainer="sharded")
        self.block = block
        self.mesh = mesh or make_mesh()
        self.batch_axis = batch_axis
        self.loss_fn = loss_fn
        self.zero = zero_level_default(zero)
        self._preprocess = preprocess
        if optimizer == "sgd":
            self._opt_init, self._opt_update = sgd_momentum_tree(
                lr, momentum, wd)
        elif optimizer == "adam":
            self._opt_init, self._opt_update = adam_tree(lr, wd=wd)
        else:
            self._opt_init, self._opt_update = optimizer

        self._fwd = functionalize(block, training=True)
        self.params = extract_params(block)
        # ZeRO-2/3: explicit bucketed collectives over a pure-DP mesh
        # (parallel/zero.py).  The plan decides which params
        # reduce-scatter solo along a divisible axis and which join
        # size-capped concat buckets; zero=3 additionally STORES the
        # solo params sharded.
        self._zero_plan = None
        self._zero_host_gather = False
        self._zero_ndev = int(self.mesh.shape[self.batch_axis])
        if self.zero >= 2:
            if len(self.mesh.axis_names) != 1 or \
                    self.mesh.axis_names[0] != self.batch_axis:
                raise ValueError(
                    "zero=%d needs a 1-d %r data mesh (got axes %s); "
                    "combine tensor parallelism with zero<=1"
                    % (self.zero, self.batch_axis,
                       tuple(self.mesh.axis_names)))
            if param_spec_fn is not None:
                raise ValueError(
                    "zero=%d shards params itself — param_spec_fn "
                    "(tensor parallel) requires zero<=1" % self.zero)
            self._zero_plan = BucketPlan(
                {n: tuple(v.shape) for n, v in self.params.items()},
                self._zero_ndev, order=list(self.params),
                label="sharded.zstep")
            self._zero_schedule = overlap_schedule(
                mesh_devices(self.mesh))
            # host-bridged broadcast (zero=2, CPU meshes): the updated
            # solo shards gather to ONE host buffer per param and
            # device_put back as zero-copy ALIASES on every replica —
            # all replicas then read the same physical pages in
            # forward (shared cache lines) instead of N private
            # copies, and the in-executable all-gather disappears.
            # CPU-backend device_put aliasing is the verified behavior
            # the decode-service hardening works around; here it is
            # the feature.  Real accelerators keep the in-executable
            # all-gather (H2D per step would be a regression).
            self._zero_host_gather = (
                self.zero == 2 and self._zero_ndev > 1
                and jax.process_count() == 1
                and all(getattr(d, "platform", "") == "cpu"
                        for d in mesh_devices(self.mesh)))
            self._zero_plan.register_cost_rows("sharded.zstep")
            record_plan("sharded.zstep", self._zero_plan, self.zero,
                        self._zero_schedule)
        pspec = param_spec_fn or (lambda name, shape: P())
        self._param_shardings = {
            n: NamedSharding(self.mesh, pspec(n, v.shape))
            for n, v in self.params.items()}
        if self.zero >= 3 and self._zero_ndev > 1:
            # persistent param memory ~1/N: the solo set lives sharded
            # on its plan axis; the concat/indivisible set replicates
            for n, ax in self._zero_plan.solo.items():
                spec = [None] * len(self.params[n].shape)
                spec[ax] = self.batch_axis
                self._param_shardings[n] = NamedSharding(self.mesh,
                                                         P(*spec))
        self.params = {
            n: self._place_value(v, self._param_shardings[n])
            for n, v in self.params.items()}
        # ZeRO stage 1 (zero=1): per-param optimizer state lives SHARDED
        # along the data axis — the TPU-native form of the reference's
        # server-side optimizer (SURVEY §5.8: ps-lite servers each hold
        # a key shard and update it; here each mesh slice holds a state
        # shard and XLA's partitioner turns the gradient all-reduce into
        # reduce-scatter + sharded update + param all-gather).
        self._opt_shardings = {
            n: NamedSharding(self.mesh, self._zero_spec(n, v.shape))
            for n, v in self.params.items()}
        # zeros are created DIRECTLY on their shardings (jit with
        # out_shardings): no full-size host materialisation, so zero=1
        # init never needs the unsharded state to fit one device
        opt_shapes = jax.eval_shape(self._opt_init, self.params)
        opt_out_sh = self._place_opt_tree(opt_shapes,
                                          lambda leaf, sh: sh)
        self.opt_state = jax.jit(
            self._opt_init, out_shardings=opt_out_sh)(self.params)
        self._batch_sharding = NamedSharding(self.mesh, P(batch_axis))
        self._step = None
        self._n_step = 0
        self._tele = None           # StepTelemetry, lazy on enabled()
        self._trace_count = 0       # this trainer's executable traces
        # per-replica dispatch fan-out (ISSUE 10 tentpole c): batch
        # shards upload from a worker pool, one thread per replica,
        # timed into train.dispatch_replica_us{replica=}.  1-d
        # single-process meshes only — elsewhere the shard/device
        # mapping is not row-per-replica
        self._dispatch = None
        if len(self.mesh.axis_names) == 1 and jax.process_count() == 1:
            from .dispatch import DispatchPool
            self._dispatch = DispatchPool(mesh_devices(self.mesh))
        # memory observatory (ISSUE 20): weak-track this trainer so
        # the attribution join can price its parameter placement (and
        # ZeRO plan) against measured device bytes — a WeakSet add,
        # nothing on the step path
        try:
            from ..telemetry import memwatch as _mw
            _mw.track_trainer(self)
        except Exception:           # noqa: BLE001 — observability
            pass                    # must never block construction

    def _place_value(self, value, sharding):
        """Host value → global array on `sharding`.  Multi-controller:
        device_put would need cross-host transfers (unsupported on some
        backends); instead every process fills only its ADDRESSABLE
        shards from the (identical) host value."""
        import numpy as _np
        if jax.process_count() > 1:
            arr = _np.asarray(value)
            return jax.make_array_from_callback(
                arr.shape, sharding, lambda idx: arr[idx])
        # CPU backend only: its device_put zero-copy ALIASES the source
        # buffer (verified in the decode-service hardening), so placing
        # the block's own param array and then DONATING it in the fused
        # step would free the block's buffer out from under it — fatal
        # the moment anything re-reads the block (a second trainer on
        # the same net, an elastic mesh rebuild).  One host copy per
        # param per trainer build is the price there.  Real
        # accelerators H2D-copy anyway — forcing _np.array() on them
        # would turn a device-resident `value` into a D2H round trip
        # per param on every (elastic re)build.
        if any(d.platform == "cpu" for d in sharding.device_set):
            return jax.device_put(_np.array(value, copy=True), sharding)
        return jax.device_put(jnp.asarray(value), sharding)

    def _zero_spec(self, name, shape):
        """PartitionSpec for this param's optimizer-state leaves: the
        param's own spec (TP axes follow the weight layout), plus —
        under zero=1 — the first free axis divisible by the data-mesh
        size sharded on the batch axis.  Under zero>=2 the bucket
        plan's solo axes decide: solo params' state shards with them,
        concat-bucket params update replicated (their state too)."""
        if self.zero >= 2:
            base = [None] * len(shape)
            ax = self._zero_plan.solo.get(name) \
                if self._zero_plan is not None else None
            if ax is not None and self._zero_ndev > 1:
                base[ax] = self.batch_axis
            return P(*base)
        base = list(self._param_shardings[name].spec)
        base += [None] * (len(shape) - len(base))
        if not self.zero:
            return P(*base)
        ndata = self.mesh.shape[self.batch_axis]
        if ndata <= 1 or self.batch_axis in base:
            # a mesh axis may map to only one tensor dim; if the param
            # spec already uses the batch axis, the state follows it
            return P(*base)
        for i, dim in enumerate(shape):
            if base[i] is None and dim % ndata == 0 and dim >= ndata:
                base[i] = self.batch_axis
                return P(*base)
        return P(*base)             # indivisible (biases): replicated

    def _place_opt_tree(self, tree, place):
        """Walk an optimizer-state tree, applying `place(leaf, sharding)`
        — param-name-keyed dicts take the matching state shardings,
        scalars/step counters replicate."""
        rep = NamedSharding(self.mesh, P())
        def walk(sub):
            if isinstance(sub, dict):
                if set(sub) == set(self.params):
                    return {n: place(v, self._opt_shardings[n])
                            for n, v in sub.items()}
                return {k: walk(v) for k, v in sub.items()}
            return place(sub, rep)
        return walk(tree)

    def _build_step(self, donate=True):
        fwd = self._fwd
        loss_fn = self.loss_fn
        opt_update = self._opt_update
        preprocess = self._preprocess
        constrain = functools.partial(self._place_opt_tree,
                                      place=jax.lax.with_sharding_constraint) \
            if self.zero else (lambda tree, **_: tree)

        def step(params, opt_state, batch, labels, rng_bits):
            # trace-time side effect only (the serve.traces pattern):
            # meters train-step recompiles; cache hits never run this.
            # The per-trainer count keeps steps_compiling attribution
            # correct when several trainers share the process ledger
            events.incr("train.traces")
            self._trace_count += 1
            if preprocess is not None:
                # on-device normalize/cast fused into this executable
                # (uint8 stays the wire format — device_feed contract)
                batch = preprocess(batch)

            def lf(p):
                out, states = fwd(p, batch, rng_bits=rng_bits)
                return loss_fn(out, labels), states
            (loss, states), grads = jax.value_and_grad(
                lf, has_aux=True)(params)
            new_params, new_opt = opt_update(params, grads, opt_state)
            # keep optimizer state on its ZeRO shards: the constraint is
            # what makes XLA compute the update on the shard (and lower
            # the gradient sum to reduce-scatter where profitable)
            # instead of re-replicating
            new_opt = constrain(new_opt)
            # fold running-stat updates (BatchNorm) back into params
            for k, v in states.items():
                if k in new_params:
                    new_params[k] = v.astype(new_params[k].dtype)
            new_params = {
                n: jax.lax.with_sharding_constraint(
                    v, self._param_shardings[n])
                for n, v in new_params.items()}
            return new_params, new_opt, loss

        # metered: one cost-registry row per input signature
        # (FLOPs/bytes-accessed + cumulative invocation counts) — the
        # pod-path train step's line in a black-box dump's cost table.
        # expect_donated arms the donation audit: a step built with
        # donate=False warns once by label (params + opt state are
        # donatable by construction — the update consumes them)
        return _costs.metered_jit(
            step, donate_argnums=(0, 1) if donate else (),
            kind="train", label="sharded.step",
            expect_donated=(0, 1))

    def _build_step_zero(self, donate=True):
        """The overlap-first ZeRO-2/3 step (ISSUE 10 tentpole): ONE
        jitted shard_map over the data mesh.

        Per device: local forward/backward (BatchNorm batch statistics
        stay replica-local — the reference's DP semantics, and no
        mid-backward rendezvous), then the bucket plan's collectives —
        per-solo-param reduce-scatter, one psum per concat bucket —
        either interleaved with backward ('bwd') or coalesced behind
        one optimization barrier ('trail', the oversubscribed-host
        default: a staggered-arrival rendezvous convoy measured ~10x
        the isolated collective cost).  The optimizer update then runs
        on SHARDS (1/N of the work per replica instead of N redundant
        full updates), and the updated solo shards all-gather back to
        full params (zero=2) or stay sharded (zero=3, which instead
        gathered params on demand at step start).  Running-stat
        updates (BN) are pmean'd across replicas before folding back.

        Everything donates: params + optimizer state alias in place.
        """
        import jax
        try:
            from jax import shard_map as _shard_map
            shard_map = _shard_map.shard_map if hasattr(
                _shard_map, "shard_map") else _shard_map
        except ImportError:
            from jax.experimental.shard_map import shard_map
        fwd = self._fwd
        loss_fn = self.loss_fn
        opt_update = self._opt_update
        preprocess = self._preprocess
        plan = self._zero_plan
        zero = self.zero
        axis = self.batch_axis
        ndev = self._zero_ndev
        schedule = self._zero_schedule
        host_gather = self._zero_host_gather
        param_dtypes = {n: v.dtype for n, v in self.params.items()}

        def body(params, opt_state, batch, labels, rng_bits):
            events.incr("train.traces")
            self._trace_count += 1
            if preprocess is not None:
                batch = preprocess(batch)
            # decorrelate per-replica RNG (dropout masks must differ
            # across replicas, as they do across rows of the global
            # batch on the single-executable path)
            idx = jax.lax.axis_index(axis)
            key = jax.random.wrap_key_data(rng_bits)
            rbits = jax.random.key_data(jax.random.fold_in(key, idx))
            # zero=3: gather-on-demand — solo params arrive as shards,
            # forward needs them whole; XLA frees the gathered copies
            # after their last use
            full = plan.gather_params(params, axis) if zero >= 3 \
                else dict(params)

            def lf(p):
                out, states = fwd(p, batch, rng_bits=rbits)
                return loss_fn(out, labels), states
            (loss, states), grads = jax.value_and_grad(
                lf, has_aux=True)(full)

            if schedule == "trail":
                # coalesce every bucket collective behind backward:
                # all devices arrive together, no convoy
                grads = jax.lax.optimization_barrier(grads)
            solo_g, bucket_flats = plan.reduce_scatter_grads(grads,
                                                            axis)
            # shard trees for the update: solo params update 1/N
            # locally, concat-bucket params update replicated
            w_sh, g_sh = {}, {}
            for n in plan.solo:
                w_sh[n] = params[n] if zero >= 3 \
                    else plan.shard_slice(full[n], n, idx)
                g_sh[n] = solo_g[n]
            for names, flat in zip(plan.buckets, bucket_flats):
                parts = plan.split_bucket(flat, names)
                for n in names:
                    w_sh[n] = full[n]
                    g_sh[n] = parts[n]
            new_w, new_opt = opt_update(w_sh, g_sh, opt_state)
            new_params = {}
            solo_new = {n: new_w[n] for n in plan.solo}
            if zero >= 3 or host_gather:
                # stay sharded: zero=3 by contract (persistent memory
                # 1/N), host_gather because step() broadcasts the
                # shards through one aliased host buffer instead
                new_params.update(solo_new)
            else:
                new_params.update(
                    plan.all_gather_updated(solo_new, axis))
            for names in plan.buckets:
                for n in names:
                    new_params[n] = new_w[n]
            # fold running-stat updates (BatchNorm) back into params,
            # averaged across replicas (batch stats stayed local)
            for k, v in states.items():
                if k in new_params:
                    u = jax.lax.pmean(v.astype(jnp.float32), axis)
                    if (zero >= 3 or host_gather) and k in plan.solo:
                        u = plan.shard_slice(u, k, idx)
                    new_params[k] = u.astype(param_dtypes[k])
            return new_params, new_opt, jax.lax.pmean(loss, axis)

        pspecs_in = {n: self._param_shardings[n].spec
                     for n in self.params}
        pspecs_out = dict(pspecs_in)
        if host_gather:
            # inputs replicated (aliased host buffers), outputs the
            # updated SHARDS — step() turns them back into aliases
            for n, ax in plan.solo.items():
                spec = [None] * len(self.params[n].shape)
                spec[ax] = axis
                pspecs_out[n] = P(*spec)
        opt_specs = self._place_opt_tree(
            self.opt_state, lambda leaf, sh: sh.spec)
        # donate-everything — EXCEPT the params under host_gather,
        # whose buffers are zero-copy aliases of one shared host
        # allocation (donating one replica's view would free the
        # pages under the other seven)
        if host_gather:
            donate_argnums = (1,) if donate else ()
            expect = (1,)
        else:
            donate_argnums = (0, 1) if donate else ()
            expect = (0, 1)
        smapped = shard_map(
            body, mesh=self.mesh,
            in_specs=(pspecs_in, opt_specs, P(axis), P(axis), P()),
            out_specs=(pspecs_out, opt_specs, P()),
            check_rep=False)
        return _costs.metered_jit(
            smapped, donate_argnums=donate_argnums,
            kind="train", label="sharded.zstep",
            expect_donated=expect)

    def _place_batch(self, arr, sharding):
        """Single-controller: the full global batch device_puts onto the
        mesh.  Multi-controller (jax.distributed, mesh spanning
        processes): each process passes only ITS rows — the per-process
        shard of the global batch — and the global array is assembled
        from the process-local data (SURVEY §5.8: multi-host workers
        each feed their slice, as reference workers read disjoint
        RecordIO partitions)."""
        import numpy as _np
        if isinstance(arr, jax.Array) and \
                getattr(arr, "sharding", None) == sharding:
            # already feed-placed on this mesh (device_feed()): no
            # re-upload, the background transfer was the upload
            return arr
        if jax.process_count() > 1:
            return jax.make_array_from_process_local_data(
                sharding, _np.asarray(arr))
        if self._dispatch is not None and sharding == \
                self._batch_sharding and self._dispatch.eligible(
                    arr, sharding):
            # per-replica fan-out: each replica's rows upload from
            # their own worker thread (bit-identical placement,
            # parallel wire time, per-replica µs attribution)
            return self._dispatch.place(arr, sharding)
        return jax.device_put(jnp.asarray(arr), sharding)

    def step(self, batch, labels, rng_bits=None):
        """batch/labels: jax or numpy arrays (global batch; in
        multi-controller runs, this process's rows of it). Returns loss
        (device scalar — don't block on it every step)."""
        from .. import random as _rnd
        if self._step is None:
            # zero>=2 on a real multi-replica mesh takes the explicit
            # overlap-first path; a 1-replica mesh degenerates to the
            # single-executable step (identical math, no collectives)
            if self.zero >= 2 and self._zero_ndev > 1:
                self._step = self._build_step_zero()
            else:
                self._step = self._build_step()
        # telemetry: one bool read when disabled; enabled, the step
        # records data-wait (placement) vs dispatch wall.  The loss
        # deliberately stays on device (async dispatch), so compute
        # wall is NOT observed here — ResilientTrainer's guarded step,
        # which syncs anyway, records it
        tele = self._tele
        if tele is None and _tele.enabled():
            # baseline on THIS trainer's trace count: enabling
            # telemetry mid-run must not count old compiles as a
            # compiling first step
            tele = self._tele = StepTelemetry(
                own_traces=self._trace_count)
        # global-step stamp (ISSUE 11): spans completed during this
        # step (dispatch fan-out, kvstore, feed) carry the step id —
        # the cross-process correlation key
        _tele.set_global_step(self._n_step)
        t0 = time.perf_counter()
        batch = self._place_batch(batch, self._batch_sharding)
        labels = self._place_batch(
            labels, NamedSharding(self.mesh, P(self.batch_axis)))
        if rng_bits is None:
            rng_bits = jax.random.key_data(_rnd.split_key())
        t1 = time.perf_counter() if tele is not None else 0.0
        try:
            self.params, self.opt_state, loss = self._step(
                self.params, self.opt_state, batch, labels, rng_bits)
        except Exception as e:
            # allocator OOM at dispatch: dump committed-vs-measured
            # per tenant before the unwind frees the evidence
            # (ISSUE 20); zero-cost until an exception actually raises
            from ..telemetry import memwatch as _mw
            _mw.guard_oom("train.step", e)
            raise
        self._n_step += 1
        if self._zero_plan is not None:
            # bytes-on-wire attribution: bump every bucket collective's
            # registry row once per step (gated on the recorder inside)
            self._zero_plan.invoke_cost_rows()
            if getattr(self, "_zero_host_gather", False):
                self._broadcast_solo_params()
        t2 = time.perf_counter()
        # always-on flight-recorder step record (loss stays on device —
        # forcing it here would forfeit dispatch/compute overlap); AMP
        # runs tag their records AND feed a labeled step-wall ring, so
        # /metrics and dumps answer "bf16 step wall vs f32" directly
        _bb.record("step", "sharded", step=self._n_step - 1,
                   us=int((t2 - t0) * 1e6),
                   **({"amp": self.amp} if self.amp else {}))
        if self.amp:
            events.observe_time("train.step_us", t2 - t0,
                                labels={"amp": self.amp})
        if tele is not None:
            tele.record_step(wall_s=t2 - t0, data_wait_s=t1 - t0,
                             dispatch_s=t2 - t1,
                             traces=self._trace_count)
        # autotune probe from the trainer's OWN measured wall (ISSUE
        # 19 satellite: probe writers outside bench/): per-example
        # step wall at THIS batch size, durable evidence for every
        # later run's suggest_batch_size.  Cadence-gated (history is
        # never a per-step cost) and past the compiling first step.
        if self._n_step % 128 == 2:
            try:
                from ..compile import autotune as _autotune
                rows = int(batch.shape[0]) if batch.shape else 1
                _autotune.note_probe(
                    "batch_size", "sharded.step", rows,
                    (t2 - t0) * 1e6 / max(1, rows),
                    source="trainer.step", step=self._n_step - 1)
            except Exception:       # noqa: BLE001
                pass
        return loss

    def _broadcast_solo_params(self):
        """Host-bridged all-gather (zero=2 on CPU meshes): pull each
        updated solo param's shards into ONE host buffer and
        device_put it back as a zero-copy alias on every replica.
        Every replica's forward then reads the SAME physical pages —
        one cache-resident copy of the weights instead of N — and the
        ring all-gather leaves the executable entirely.  Bit-identical
        values; the executable deliberately does not donate params so
        the shared pages can never be freed under a sibling alias."""
        import numpy as _np
        devs = mesh_devices(self.mesh)
        rep = NamedSharding(self.mesh, P())
        plan = self._zero_plan

        def bcast(name):
            t0 = time.perf_counter()
            full = _np.asarray(self.params[name])   # shard gather
            pieces = [jax.device_put(full, d) for d in devs]
            out = jax.make_array_from_single_device_arrays(
                full.shape, rep, pieces)
            events.observe_time("zero.host_gather_us",
                                time.perf_counter() - t0)
            return name, out

        if self._dispatch is not None and self._dispatch.enabled:
            done = self._dispatch.run(bcast, list(plan.solo))
        else:
            done = [bcast(n) for n in plan.solo]
        for name, arr in done:
            self.params[name] = arr

    def device_feed(self, source, depth=None, transform=None):
        """Async feed onto this trainer's mesh: a background thread
        `device_put`s the NEXT (batch, labels) pair — batch sharded on
        the data axis, ONE batched transfer per pytree — while the
        current step executes.  `step()` recognizes the placed arrays
        and skips its own upload.  Pair with `preprocess=` for
        uint8-on-wire feeding (normalize/cast runs inside the step).

        source yields host (batch, labels) pairs (numpy); returns an
        `io.device_feed.DeviceFeed` (per-stage counters on
        `monitor.events` under `feed.*`)."""
        from ..io.device_feed import DeviceFeed
        # one batch-axis sharding, broadcast over every leaf of the
        # batch pytree by DeviceFeed._place_sharded
        return DeviceFeed(source, sharding=self._batch_sharding,
                          depth=depth, transform=transform)

    @property
    def data_parallel_size(self) -> int:
        """Replicas along the batch axis (the elastic supervisor's
        batch/LR scaling denominator)."""
        return int(self.mesh.shape[self.batch_axis])

    def release(self):
        """Drop this trainer's device state — params, optimizer state,
        compiled step.  An elastic supervisor calls this on the OLD
        trainer before materializing its successor on a different
        mesh, so the old copies free before the new ones allocate (at
        pod scale, holding both generations of a ZeRO-sharded state
        doubles the HBM bill exactly when a replica just died).  The
        trainer is unusable afterwards; the state lives on in the
        checkpoint the successor restores."""
        self.params = {}
        self.opt_state = None
        self._step = None
        # the process-global step stamp this trainer was feeding is
        # stale the moment training ends: a span emitted later (a
        # serving request, a checkpoint verify) must not carry the
        # dead run's step id into a cross-process (trace_id, step)
        # join — the false-correlation failure mode of ISSUE 11
        _tele.set_global_step(None)
        if self._dispatch is not None:
            self._dispatch.shutdown()

    def sync_to_block(self):
        """Write trained params back into the Gluon block."""
        load_params(self.block, self.params)

    def serve(self, **kwargs):
        """Train→serve handoff: sync the trained params back into the
        block and build a `serving.InferenceEngine` whose replica set is
        THIS trainer's mesh devices (round-robin bucket dispatch, one
        full parameter copy per device — the inference-side mirror of
        the DP training mesh).  Pass `devices=` to override; all other
        kwargs forward to `InferenceEngine` (buckets, max_batch,
        example_shape, handle_sigterm, ...)."""
        from ..serving import InferenceEngine
        from .mesh import replica_contexts
        self.sync_to_block()
        kwargs.setdefault("devices", replica_contexts(self.mesh))
        return InferenceEngine(self.block, **kwargs)

    # ------------------------------------------------------------------
    # sharded checkpoint/resume (ref: Trainer.save_states/load_states —
    # at pod scale the states are sharded over the mesh, so the
    # checkpoint is written/read distributed via orbax instead of the
    # 0x112 single-host container)
    # ------------------------------------------------------------------
    def save_checkpoint(self, path):
        """Write params + optimizer state + step to `path` (a directory;
        sharded arrays are gathered/written by orbax per host)."""
        import os
        import orbax.checkpoint as ocp
        path = os.path.abspath(path)
        ckpt = ocp.PyTreeCheckpointer()
        ckpt.save(path, {"params": self.params,
                         "opt_state": self.opt_state,
                         "n_step": self._n_step},
                  force=True)

    def load_checkpoint(self, path):
        """Restore params/opt_state/step saved by save_checkpoint,
        re-placing every leaf on this trainer's mesh shardings (works
        across restarts and across a different mesh shape — leaves are
        restored to host memory first, so the saved device layout does
        not constrain the restoring topology)."""
        import os
        import numpy as _np
        import orbax.checkpoint as ocp
        path = os.path.abspath(path)
        ckpt = ocp.PyTreeCheckpointer()
        # restore to host numpy against this trainer's tree template:
        # restoring with the layout recorded at save time would fail on
        # any topology change
        template = {"params": dict(self.params),
                    "opt_state": self.opt_state,
                    "n_step": self._n_step}
        restore_args = jax.tree_util.tree_map(
            lambda _: ocp.RestoreArgs(restore_type=_np.ndarray), template)
        if not os.path.exists(path):
            raise FileNotFoundError("no checkpoint at %s" % path)
        try:
            restored = ckpt.restore(path, item=template,
                                    restore_args=restore_args)
        except OSError:
            raise                       # I/O problems are not mismatches
        except Exception as e:
            raise ValueError(
                "checkpoint at %s does not match this trainer's "
                "param/opt-state tree (%s)" % (path, e)) from e
        params = restored["params"]
        if set(params) != set(self.params):
            raise ValueError(
                "checkpoint/trainer param name mismatch: only in "
                "checkpoint %s; only in trainer %s"
                % (sorted(set(params) - set(self.params))[:5],
                   sorted(set(self.params) - set(params))[:5]))
        for n, v in params.items():
            if tuple(v.shape) != tuple(self.params[n].shape):
                raise ValueError(
                    "checkpoint param %s has shape %s but trainer "
                    "expects %s" % (n, tuple(v.shape),
                                    tuple(self.params[n].shape)))
            if jnp.dtype(v.dtype) != jnp.dtype(self.params[n].dtype):
                raise ValueError(
                    "checkpoint param %s has dtype %s but trainer "
                    "expects %s (mixed-precision config drift?)"
                    % (n, jnp.dtype(v.dtype).name,
                       jnp.dtype(self.params[n].dtype).name))
        self.params = {
            n: self._place_value(v, self._param_shardings[n])
            for n, v in params.items()}

        # optimizer-state subtrees keyed by param name take the matching
        # state shardings (ZeRO shards under zero=1, else the param
        # shardings); scalars (step counters) replicate
        self.opt_state = self._place_opt_tree(
            restored["opt_state"], self._place_value)
        self._n_step = int(restored["n_step"])
        self._step = None          # rebuild with the restored layouts
