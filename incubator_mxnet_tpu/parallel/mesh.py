"""Device mesh helpers.

TPU-native replacement for the reference's device-topology machinery
(ref: src/kvstore/gpu_topology.h link-weight trees; ps-lite node groups):
on TPU the topology is the ICI mesh and XLA owns collective routing —
the framework's job is just to pick mesh axes and shardings
(jax.sharding.Mesh / NamedSharding / PartitionSpec).
"""
from __future__ import annotations

from typing import Optional, Sequence

import numpy as _np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = ["make_mesh", "Mesh", "NamedSharding", "P", "replicated",
           "batch_sharded", "default_dp_mesh", "replica_contexts",
           "mesh_devices", "surviving_mesh"]


_PCACHE_GUARDED = [False]


def _guard_cpu_mesh_pcache(devices):
    """Disable the JAX persistent compilation cache the first time a
    MULTI-DEVICE CPU mesh is built in this process (ISSUE 8 satellite).

    A WARM persistent-cache hit for a multi-device DONATED executable
    segfaults this jaxlib's CPU backend (verified in the PR 7 elastic
    bench: identical runs pass cold and crash mid-step warm) — and
    every mesh consumer (ShardedTrainer steps, the elastic rebuild,
    ZeRO updates) donates buffers.  PR 7 disabled the cache in the
    bench child only; this is the library-level gate, at the one
    chokepoint every CPU-mesh scenario passes through.  Real
    accelerator meshes are untouched, as is the single-device CPU
    path (where the cache is the verified 39s→10s win), and the gate
    only fires when a cache dir is actually configured — without one
    the cache cannot engage anyway."""
    if _PCACHE_GUARDED[0] or len(devices) < 2:
        return
    if not all(getattr(d, "platform", "") == "cpu" for d in devices):
        return
    import os
    if not (os.environ.get("JAX_COMPILATION_CACHE_DIR")
            or getattr(jax.config, "jax_compilation_cache_dir", None)):
        return
    try:
        jax.config.update("jax_enable_compilation_cache", False)
    except Exception:           # noqa: BLE001 — ancient jax: no knob,
        return                  # no cache, nothing to guard
    _PCACHE_GUARDED[0] = True
    import warnings
    warnings.warn(
        "JAX persistent compilation cache disabled: multi-device "
        "donated executables on the CPU backend segfault this jaxlib "
        "on a warm cache hit (single-device processes keep the cache)")
    from ..monitor import events
    events.incr("aot.pcache_disabled")
    try:
        from ..telemetry import flightrec as _bb
        _bb.record("aot", "pcache_disabled", devices=len(devices))
    except Exception:           # noqa: BLE001 — forensics best-effort
        pass


def make_mesh(shape: Sequence[int] = None,
              axis_names: Sequence[str] = ("data",),
              devices=None) -> Mesh:
    """Build a Mesh over available devices.

    make_mesh() → 1-d 'data' mesh over all devices;
    make_mesh((4, 2), ('data', 'model')) → dp×tp grid.
    """
    devices = devices if devices is not None else jax.devices()
    if shape is None:
        shape = (len(devices),)
    arr = _np.asarray(devices[:int(_np.prod(shape))]).reshape(shape)
    _guard_cpu_mesh_pcache(list(arr.flat))
    return Mesh(arr, tuple(axis_names))


def default_dp_mesh() -> Mesh:
    return make_mesh()


def mesh_devices(mesh: Mesh):
    """The mesh's devices as a flat list (replica order: the order
    `make_mesh` laid them out in)."""
    return list(mesh.devices.flat)


def surviving_mesh(devices, lost=(), axis_names=("data",)) -> Mesh:
    """Re-form a 1-d data mesh from `devices` minus the replicas in
    `lost` (indices into `devices`) — the elastic shrink/grow path.
    Delegates to `make_mesh` so mesh construction stays in one place;
    survivor ORDER is preserved, which is what keeps a re-formed mesh
    deterministic: the same survivor set always yields the same device
    layout (and therefore the same shardings and the same compiled
    step)."""
    lost = set(int(i) for i in lost)
    keep = [d for i, d in enumerate(devices) if i not in lost]
    if not keep:
        raise ValueError("no surviving devices (lost=%s of %d)"
                         % (sorted(lost), len(list(devices))))
    return make_mesh((len(keep),), axis_names, devices=keep)


def replica_contexts(mesh: Optional[Mesh] = None):
    """This process's mesh devices as framework Contexts — the replica
    set a `serving.InferenceEngine` round-robins inference buckets
    across (each replica holds a full parameter copy; data-parallel
    serving, the inference-side mirror of the DP training mesh).
    Non-addressable devices (other processes' chips in a
    multi-controller mesh) are skipped: each host serves its own."""
    from ..context import Context
    devs = (list(mesh.devices.flat) if mesh is not None
            else jax.local_devices())
    local_index = {d.id: i for i, d in enumerate(jax.local_devices())}
    out = []
    for d in devs:
        i = local_index.get(d.id)
        if i is None:       # not addressable from this process
            continue
        out.append(Context("cpu" if d.platform == "cpu" else "tpu", i))
    return out


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def batch_sharded(mesh: Mesh, axis: str = "data",
                  batch_dim: int = 0) -> NamedSharding:
    spec = [None] * (batch_dim + 1)
    spec[batch_dim] = axis
    return NamedSharding(mesh, P(*spec))


def squeeze_stage_axis(tree):
    """Strip the leading size-1 axis a P('<axis>')-sharded stacked tree
    carries inside a shard_map body (each device sees its own slice)."""
    import jax as _jax

    def _squeeze(leaf):
        return leaf[0] if getattr(leaf, "ndim", 0) and             leaf.shape[0] == 1 else leaf
    return _jax.tree_util.tree_map(_squeeze, tree)


def mark_varying(x, axis_name):
    """Tag an unvarying value as device-varying for shard_map's vma
    type system (scan carries that become per-device): lax.pcast on
    current jax, lax.pvary fallback, no-op where vma doesn't exist."""
    from jax import lax as _lax
    try:
        return _lax.pcast(x, (axis_name,), to="varying")
    except (AttributeError, TypeError):
        pass
    try:
        return _lax.pvary(x, axis_name)
    except AttributeError:
        return x
