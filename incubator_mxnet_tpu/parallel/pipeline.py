"""Pipeline parallelism: GPipe-style microbatched collective pipeline.

Beyond-reference axis (MXNet 1.x has no pipeline parallelism; its
model-parallel story was ctx_group device placement — SURVEY §2.3).
TPU-first realisation per the scaling-book recipe: every stage lives on
one mesh slice along the `pipe` axis, all stages compute in lockstep on
DIFFERENT microbatches, and activations hop stage→stage with ONE
`ppermute` per step over ICI.  The whole schedule is a `lax.scan`
inside `shard_map` — one compiled program, S+M-1 steps, bubble fraction
(S-1)/(S+M-1).

The backward comes from jax autodiff: the transpose of `ppermute` is
the reverse `ppermute`, so the reverse pipeline schedule is derived,
not hand-written.

Constraint: `stage_fn(stage_params, x) -> y` must preserve the
activation shape/dtype (transformer-block-style stages) — the hop
buffer is shape-static across stages.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["pipeline_apply", "split_microbatches", "stack_stage_params"]


def split_microbatches(x, n_microbatches):
    """(B, ...) → (M, B/M, ...) microbatch axis for pipeline_apply."""
    B = x.shape[0]
    if B % n_microbatches:
        raise ValueError("batch %d not divisible into %d microbatches"
                         % (B, n_microbatches))
    return x.reshape((n_microbatches, B // n_microbatches) + x.shape[1:])


def stack_stage_params(per_stage_params):
    """[stage0_tree, stage1_tree, ...] → one tree with a leading stage
    axis; shard it with PartitionSpec('pipe', ...) so shard_map hands
    each device its own stage's (squeezed) params."""
    return jax.tree_util.tree_map(lambda *ls: jnp.stack(ls),
                                  *per_stage_params)


def pipeline_apply(stage_fn, stage_params, x_mb, axis_name):
    """Run the pipeline INSIDE a shard_map body.

    stage_params: THIS device's stage parameters — a stacked tree
        sharded ``P('pipe')`` arrives with a leading axis of size 1,
        which is squeezed here.
    x_mb: (M, mb, ...) microbatches, replicated across the pipe axis.
    Returns (M, mb, ...) outputs, replicated (masked psum off the last
    stage).
    """
    n_stages = lax.psum(1, axis_name)       # static inside shard_map
    idx = lax.axis_index(axis_name)
    M = x_mb.shape[0]

    from .mesh import squeeze_stage_axis
    params = squeeze_stage_axis(stage_params)

    out_aval = jax.eval_shape(
        stage_fn, params,
        jax.ShapeDtypeStruct(x_mb.shape[1:], x_mb.dtype))
    if tuple(out_aval.shape) != tuple(x_mb.shape[1:]):
        raise ValueError("stage_fn must preserve activation shape, "
                         "got %s -> %s" % (x_mb.shape[1:],
                                           out_aval.shape))

    n_steps = n_stages + M - 1
    # partial permutation: stage 0 always overwrites its incoming state
    # with the next microbatch, so the wrap-around (last→0) hop would
    # be a dead transfer every step — ppermute zero-fills the gap
    perm = [(i, i + 1) for i in range(n_stages - 1)]

    def body(carry, t):
        state, outs = carry
        # stage 0 ingests microbatch t (clipped: steps beyond M feed a
        # repeat that never lands in the output window)
        inp = lax.dynamic_index_in_dim(x_mb, jnp.clip(t, 0, M - 1), 0,
                                       keepdims=False)
        cur = jnp.where(idx == 0, inp, state)
        y = stage_fn(params, cur)
        # the LAST stage emits microbatch (t - (S-1)) at step t
        pos = t - (n_stages - 1)
        upd = lax.dynamic_update_index_in_dim(
            outs, y, jnp.clip(pos, 0, M - 1), 0)
        outs = jnp.where(pos >= 0, upd, outs)
        state = lax.ppermute(y, axis_name, perm)
        return (state, outs), None

    state0 = jnp.zeros(x_mb.shape[1:], x_mb.dtype)
    outs0 = jnp.zeros((M,) + tuple(out_aval.shape), out_aval.dtype)
    # the carry is device-varying (each stage computes its own): mark
    # the unvarying zeros as varying for shard_map's vma type system
    from .mesh import mark_varying
    state0 = mark_varying(state0, axis_name)
    outs0 = mark_varying(outs0, axis_name)
    (_, outs), _ = lax.scan(body, (state0, outs0), jnp.arange(n_steps))
    # only the last stage holds real outputs; mask + psum replicates
    outs = jnp.where(idx == n_stages - 1, outs, jnp.zeros_like(outs))
    return lax.psum(outs, axis_name)
