"""Expert parallelism: switch-routed Mixture-of-Experts over a mesh
axis.

Beyond-reference axis (absent in MXNet 1.x — SURVEY §2.3 lists only
DP + ctx_group).  TPU-first shape, per the Switch-Transformer /
scaling-book recipe: tokens live data-sharded, experts live one (or
more) per device along the `expert` axis, and dispatch/return ride
TWO `all_to_all` collectives over ICI.  Routing is the capacity-
factored top-1 einsum dispatch — fixed shapes, no sorting, fully
XLA-compilable; overflowing tokens are dropped (residual passes them
through, the standard Switch behaviour).

All functions are shard_map-body functions (like ring_attention):
call them inside `shard_map` with `axis_name` bound to the expert
axis.  Gradients flow through `all_to_all`/einsum natively.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["switch_route", "moe_apply", "moe_ffn"]


def switch_route(router_logits, capacity):
    """Top-1 capacity-factored routing (per-device local tokens).

    router_logits: (T, E).  Returns (dispatch (T, E, C) one-hot,
    combine (T, E, C) prob-weighted, aux_loss scalar — the Switch
    load-balancing loss)."""
    T, E = router_logits.shape
    probs = jax.nn.softmax(router_logits.astype(jnp.float32), axis=-1)
    expert = jnp.argmax(probs, axis=-1)                  # (T,)
    mask = jax.nn.one_hot(expert, E, dtype=jnp.float32)  # (T, E)
    # position of each token in its expert's queue
    pos = jnp.cumsum(mask, axis=0) * mask                # 1-based
    keep = (pos <= capacity) * mask                      # (T, E)
    pos_idx = (pos - 1.0) * keep                         # 0-based
    dispatch = keep[..., None] * jax.nn.one_hot(
        pos_idx.astype(jnp.int32), capacity, dtype=jnp.float32)
    gate = jnp.sum(probs * keep, axis=-1, keepdims=True)  # (T, 1)
    combine = dispatch * gate[..., None]
    # load-balancing aux loss: E * sum_e fraction_tokens_e * mean_prob_e
    frac = jnp.mean(mask, axis=0)
    mean_prob = jnp.mean(probs, axis=0)
    aux = E * jnp.sum(frac * mean_prob)
    return dispatch, combine, aux


def moe_apply(x, router_w, expert_fn, expert_params, axis_name,
              capacity_factor=1.25):
    """Expert-parallel switch MoE layer (shard_map body).

    x: (T_local, d) this device's tokens.
    router_w: (d, E_total) router weights (replicated).
    expert_fn(params, tokens) -> tokens: one expert's computation;
        `expert_params` is THIS device's expert's params (tree sharded
        P('expert') outside; a leading axis of 1 is squeezed).
    Returns (T_local, d) combined outputs + aux loss.  Tokens routed
    past capacity are dropped (add x residually outside if desired).
    """
    n_dev = lax.psum(1, axis_name)
    T, d = x.shape
    E = router_w.shape[-1]
    if E % n_dev:
        raise ValueError("experts %d not divisible by axis size %d"
                         % (E, n_dev))
    e_local = E // n_dev
    capacity = int(max(1, (T * capacity_factor) // E))

    from .mesh import squeeze_stage_axis
    eparams = squeeze_stage_axis(expert_params)

    logits = x.astype(jnp.float32) @ router_w.astype(jnp.float32)
    dispatch, combine, aux = switch_route(logits, capacity)

    # gather this device's dispatched tokens: (E, C, d)
    expert_in = jnp.einsum("tec,td->ecd", dispatch,
                           x.astype(jnp.float32))
    # all_to_all: split the expert axis across devices, concat the
    # sender shards — device e receives (e_local, n_dev*C, d): ALL
    # devices' tokens for ITS experts
    expert_in = expert_in.reshape(n_dev, e_local * capacity, d)
    recv = lax.all_to_all(expert_in, axis_name, split_axis=0,
                          concat_axis=0, tiled=False)
    # recv: (n_dev, e_local*C, d) where axis 0 = source device
    recv = recv.reshape(n_dev, e_local, capacity, d) \
        .transpose(1, 0, 2, 3) \
        .reshape(e_local, n_dev * capacity, d)
    # run the local expert(s)
    if e_local == 1:
        out = expert_fn(eparams, recv[0].astype(x.dtype))[None]
    else:
        out = jax.vmap(lambda p, t: expert_fn(p, t.astype(x.dtype)),
                       in_axes=(0, 0))(eparams, recv)
    out = out.astype(jnp.float32)
    # reverse the shuffle
    back = out.reshape(e_local, n_dev, capacity, d) \
        .transpose(1, 0, 2, 3) \
        .reshape(n_dev, e_local * capacity, d)
    sent = lax.all_to_all(back, axis_name, split_axis=0,
                          concat_axis=0, tiled=False)
    sent = sent.reshape(E, capacity, d)
    # combine back to token order, weighted by the router gate
    y = jnp.einsum("tec,ecd->td", combine, sent)
    # aux is averaged across the axis so it is replicated (a scalar
    # loss term addable outside shard_map)
    return y.astype(x.dtype), lax.pmean(aux, axis_name)


def moe_ffn(d_model, d_hidden, n_experts, key=None):
    """Convenience: per-expert FFN params (stacked on the expert axis —
    shard with P('expert')) + the matching expert_fn."""
    import numpy as np
    rs = np.random.RandomState(0 if key is None else key)
    params = {
        "w1": jnp.asarray(rs.randn(n_experts, d_model, d_hidden)
                          * (1.0 / np.sqrt(d_model)), jnp.float32),
        "w2": jnp.asarray(rs.randn(n_experts, d_hidden, d_model)
                          * (1.0 / np.sqrt(d_hidden)), jnp.float32),
    }

    def expert_fn(p, t):
        h = jax.nn.relu(t @ p["w1"])
        return h @ p["w2"]

    return params, expert_fn
