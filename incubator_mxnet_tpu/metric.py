"""Evaluation metrics (ref: python/mxnet/metric.py EvalMetric registry)."""
from __future__ import annotations

import math

import numpy as _np

from .base import MXNetError

__all__ = ["EvalMetric", "CompositeEvalMetric", "Accuracy", "TopKAccuracy",
           "F1", "MAE", "MSE", "RMSE", "CrossEntropy", "NegativeLogLikelihood",
           "Perplexity", "PearsonCorrelation", "Loss", "Caffe", "Torch",
           "CustomMetric", "np", "create", "register"]

_REGISTRY = {}


def register(klass):
    _REGISTRY[klass.__name__.lower()] = klass
    return klass


def create(metric, *args, **kwargs):
    if callable(metric):
        return CustomMetric(metric, *args, **kwargs)
    if isinstance(metric, EvalMetric):
        return metric
    if isinstance(metric, list):
        composite = CompositeEvalMetric()
        for child in metric:
            composite.add(create(child, *args, **kwargs))
        return composite
    key = str(metric).lower()
    aliases = {"acc": "accuracy", "ce": "crossentropy",
               "nll_loss": "negativeloglikelihood", "top_k_accuracy":
               "topkaccuracy", "top_k_acc": "topkaccuracy"}
    key = aliases.get(key, key)
    if key not in _REGISTRY:
        raise MXNetError("unknown metric %r" % metric)
    return _REGISTRY[key](*args, **kwargs)


def _as_numpy(x):
    return x.asnumpy() if hasattr(x, "asnumpy") else _np.asarray(x)


class EvalMetric:
    def __init__(self, name, output_names=None, label_names=None, **kwargs):
        self.name = str(name)
        self.output_names = output_names
        self.label_names = label_names
        self._kwargs = kwargs
        self.reset()

    def reset(self):
        self.num_inst = 0
        self.sum_metric = 0.0

    def update(self, labels, preds):
        raise NotImplementedError

    def get(self):
        if self.num_inst == 0:
            return (self.name, float("nan"))
        return (self.name, self.sum_metric / self.num_inst)

    def get_name_value(self):
        name, value = self.get()
        if not isinstance(name, list):
            name = [name]
        if not isinstance(value, list):
            value = [value]
        return list(zip(name, value))

    def update_dict(self, label, pred):
        if self.output_names is not None:
            pred = [pred[name] for name in self.output_names]
        else:
            pred = list(pred.values())
        if self.label_names is not None:
            label = [label[name] for name in self.label_names]
        else:
            label = list(label.values())
        self.update(label, pred)

    def __str__(self):
        return "EvalMetric: %s" % dict(self.get_name_value())


@register
class CompositeEvalMetric(EvalMetric):
    def __init__(self, metrics=None, name="composite", **kwargs):
        super().__init__(name, **kwargs)
        self.metrics = [create(m) for m in (metrics or [])]

    def add(self, metric):
        self.metrics.append(create(metric))

    def update(self, labels, preds):
        for metric in self.metrics:
            metric.update(labels, preds)

    def reset(self):
        for metric in getattr(self, "metrics", []):
            metric.reset()

    def get(self):
        names, values = [], []
        for metric in self.metrics:
            name, value = metric.get()
            names.append(name)
            values.append(value)
        return names, values


@register
class Accuracy(EvalMetric):
    def __init__(self, axis=1, name="accuracy", **kwargs):
        super().__init__(name, **kwargs)
        self.axis = axis

    def update(self, labels, preds):
        if not isinstance(labels, (list, tuple)):
            labels = [labels]
        if not isinstance(preds, (list, tuple)):
            preds = [preds]
        for label, pred in zip(labels, preds):
            p = _as_numpy(pred)
            l = _as_numpy(label).astype("int64")
            if p.ndim > l.ndim:
                p = p.argmax(axis=self.axis)
            p = p.astype("int64").reshape(-1)
            l = l.reshape(-1)
            self.sum_metric += float((p == l).sum())
            self.num_inst += len(l)


@register
class TopKAccuracy(EvalMetric):
    def __init__(self, top_k=1, name="top_k_accuracy", **kwargs):
        super().__init__(name, **kwargs)
        self.top_k = top_k
        self.name += "_%d" % top_k

    def update(self, labels, preds):
        for label, pred in zip(labels, preds):
            p = _as_numpy(pred)
            l = _as_numpy(label).astype("int64").reshape(-1)
            idx = _np.argsort(-p, axis=1)[:, :self.top_k]
            self.sum_metric += float((idx == l[:, None]).any(axis=1).sum())
            self.num_inst += len(l)


@register
class F1(EvalMetric):
    def __init__(self, name="f1", average="macro", **kwargs):
        super().__init__(name, **kwargs)
        self.average = average
        self.reset_stats()

    def reset_stats(self):
        self.tp = self.fp = self.fn = 0

    def reset(self):
        super().reset()
        self.reset_stats()

    def update(self, labels, preds):
        for label, pred in zip(labels, preds):
            p = _as_numpy(pred)
            l = _as_numpy(label).reshape(-1).astype("int64")
            if p.ndim > 1 and p.shape[-1] > 1:
                p = p.argmax(axis=-1)
            else:
                p = (p.reshape(-1) > 0.5).astype("int64")
            self.tp += int(((p == 1) & (l == 1)).sum())
            self.fp += int(((p == 1) & (l == 0)).sum())
            self.fn += int(((p == 0) & (l == 1)).sum())
            prec = self.tp / max(self.tp + self.fp, 1)
            rec = self.tp / max(self.tp + self.fn, 1)
            f1 = 2 * prec * rec / max(prec + rec, 1e-12)
            self.sum_metric = f1
            self.num_inst = 1


@register
class MAE(EvalMetric):
    def __init__(self, name="mae", **kwargs):
        super().__init__(name, **kwargs)

    def update(self, labels, preds):
        for label, pred in zip(labels, preds):
            l, p = _as_numpy(label), _as_numpy(pred)
            self.sum_metric += float(_np.abs(l.reshape(p.shape) - p).mean())
            self.num_inst += 1


@register
class MSE(EvalMetric):
    def __init__(self, name="mse", **kwargs):
        super().__init__(name, **kwargs)

    def update(self, labels, preds):
        for label, pred in zip(labels, preds):
            l, p = _as_numpy(label), _as_numpy(pred)
            self.sum_metric += float(((l.reshape(p.shape) - p) ** 2).mean())
            self.num_inst += 1


@register
class RMSE(MSE):
    def __init__(self, name="rmse", **kwargs):
        super().__init__(name=name, **kwargs)

    def get(self):
        if self.num_inst == 0:
            return (self.name, float("nan"))
        return (self.name, math.sqrt(self.sum_metric / self.num_inst))


@register
class CrossEntropy(EvalMetric):
    def __init__(self, eps=1e-12, name="cross-entropy", **kwargs):
        super().__init__(name, **kwargs)
        self.eps = eps

    def update(self, labels, preds):
        for label, pred in zip(labels, preds):
            l = _as_numpy(label).ravel().astype("int64")
            p = _as_numpy(pred)
            prob = p[_np.arange(l.shape[0]), l]
            self.sum_metric += float((-_np.log(prob + self.eps)).sum())
            self.num_inst += l.shape[0]


@register
class NegativeLogLikelihood(CrossEntropy):
    def __init__(self, eps=1e-12, name="nll-loss", **kwargs):
        super().__init__(eps=eps, name=name, **kwargs)


@register
class Perplexity(EvalMetric):
    def __init__(self, ignore_label=None, axis=-1, name="perplexity",
                 **kwargs):
        super().__init__(name, **kwargs)
        self.ignore_label = ignore_label
        self.axis = axis

    def update(self, labels, preds):
        loss = 0.0
        num = 0
        for label, pred in zip(labels, preds):
            l = _as_numpy(label).ravel().astype("int64")
            p = _as_numpy(pred).reshape(-1, _as_numpy(pred).shape[-1])
            prob = p[_np.arange(l.shape[0]), l]
            if self.ignore_label is not None:
                ignore = (l == self.ignore_label)
                prob = prob[~ignore]
            loss += float(-_np.log(_np.maximum(prob, 1e-10)).sum())
            num += prob.shape[0]
        self.sum_metric += loss
        self.num_inst += num

    def get(self):
        if self.num_inst == 0:
            return (self.name, float("nan"))
        return (self.name, math.exp(self.sum_metric / self.num_inst))


@register
class PearsonCorrelation(EvalMetric):
    def __init__(self, name="pearsonr", **kwargs):
        super().__init__(name, **kwargs)

    def update(self, labels, preds):
        for label, pred in zip(labels, preds):
            l, p = _as_numpy(label).ravel(), _as_numpy(pred).ravel()
            self.sum_metric += float(_np.corrcoef(l, p)[0, 1])
            self.num_inst += 1


@register
class Loss(EvalMetric):
    def __init__(self, name="loss", **kwargs):
        super().__init__(name, **kwargs)

    def update(self, _, preds):
        if not isinstance(preds, (list, tuple)):
            preds = [preds]
        for pred in preds:
            loss = float(_as_numpy(pred).sum())
            self.sum_metric += loss
            self.num_inst += _as_numpy(pred).size


class Caffe(Loss):
    pass


class Torch(Loss):
    pass


class CustomMetric(EvalMetric):
    def __init__(self, feval, name="custom", allow_extra_outputs=False,
                 **kwargs):
        super().__init__("custom(%s)" % name, **kwargs)
        self._feval = feval
        self._allow_extra_outputs = allow_extra_outputs

    def update(self, labels, preds):
        for label, pred in zip(labels, preds):
            reval = self._feval(_as_numpy(label), _as_numpy(pred))
            if isinstance(reval, tuple):
                sum_metric, num_inst = reval
                self.sum_metric += sum_metric
                self.num_inst += num_inst
            else:
                self.sum_metric += reval
                self.num_inst += 1


def np(numpy_feval, name="custom", allow_extra_outputs=False):
    def feval(label, pred):
        return numpy_feval(label, pred)
    feval.__name__ = name
    return CustomMetric(feval, name, allow_extra_outputs)
