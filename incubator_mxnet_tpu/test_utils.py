"""Test utilities (ref: python/mxnet/test_utils.py).

The cornerstone of the test strategy (SURVEY §4): numeric-gradient
checking against numpy references, cross-backend consistency, random
array/shape generators, tolerance maps.
"""
from __future__ import annotations

import os

import numpy as _np

from .base import MXNetError, dtype_np
from .context import Context, cpu, current_context
from .ndarray.ndarray import NDArray
from . import ndarray as nd

__all__ = ["default_context", "set_default_context", "assert_almost_equal",
           "almost_equal", "same", "rand_ndarray", "rand_shape_nd",
           "rand_shape_2d", "rand_shape_3d", "random_arrays",
           "check_numeric_gradient", "numeric_grad", "check_consistency",
           "effective_dtype", "environment", "assert_exception"]

_DEFAULT_RTOL = {
    _np.dtype(_np.float16): 1e-2,
    _np.dtype(_np.float32): 1e-4,
    _np.dtype(_np.float64): 1e-5,
}
_DEFAULT_ATOL = {
    _np.dtype(_np.float16): 1e-3,
    _np.dtype(_np.float32): 1e-5,
    _np.dtype(_np.float64): 1e-7,
}


def default_context() -> Context:
    """ref: test_utils.default_context — env-overridable test context."""
    dev = os.environ.get("MXNET_TEST_DEVICE", "")
    if dev.startswith("tpu") or dev.startswith("gpu"):
        from .context import tpu
        return tpu(int(dev.split(":")[-1]) if ":" in dev else 0)
    return current_context()


def set_default_context(ctx: Context):
    Context._default.stack = [ctx]


def _as_np(x):
    if isinstance(x, NDArray):
        return x.asnumpy()
    return _np.asarray(x)


def same(a, b):
    return _np.array_equal(_as_np(a), _as_np(b))


def almost_equal(a, b, rtol=None, atol=None, equal_nan=False):
    a, b = _as_np(a), _as_np(b)
    rtol = rtol if rtol is not None else \
        _DEFAULT_RTOL.get(a.dtype, 1e-5)
    atol = atol if atol is not None else \
        _DEFAULT_ATOL.get(a.dtype, 1e-7)
    return _np.allclose(a, b, rtol=rtol, atol=atol, equal_nan=equal_nan)


def assert_almost_equal(a, b, rtol=None, atol=None, names=("a", "b"),
                        equal_nan=False):
    a_np, b_np = _as_np(a).astype(_np.float64), _as_np(b).astype(_np.float64)
    rtol = rtol if rtol is not None else \
        _DEFAULT_RTOL.get(_as_np(a).dtype, 1e-4)
    atol = atol if atol is not None else \
        _DEFAULT_ATOL.get(_as_np(a).dtype, 1e-5)
    if not _np.allclose(a_np, b_np, rtol=rtol, atol=atol,
                        equal_nan=equal_nan):
        err = _np.abs(a_np - b_np)
        rel = err / (_np.abs(b_np) + atol)
        raise AssertionError(
            "%s and %s differ: max abs err %g, max rel err %g "
            "(rtol=%g atol=%g)\n%r\nvs\n%r"
            % (names[0], names[1], err.max(), rel.max(), rtol, atol,
               a_np.ravel()[:8], b_np.ravel()[:8]))


def rand_shape_nd(ndim, dim=10, allow_zero_size=False):
    low = 0 if allow_zero_size else 1
    return tuple(_np.random.randint(low, dim + 1, size=ndim).tolist())


def rand_shape_2d(dim0=10, dim1=10):
    return (_np.random.randint(1, dim0 + 1), _np.random.randint(1, dim1 + 1))


def rand_shape_3d(dim0=10, dim1=10, dim2=10):
    return (_np.random.randint(1, dim0 + 1), _np.random.randint(1, dim1 + 1),
            _np.random.randint(1, dim2 + 1))


def random_arrays(*shapes):
    arrays = [_np.random.randn(*s).astype(_np.float32) if s else
              _np.asarray(_np.random.randn(), dtype=_np.float32)
              for s in shapes]
    if len(arrays) == 1:
        return arrays[0]
    return arrays


def rand_ndarray(shape, stype="default", density=None, dtype="float32",
                 ctx=None):
    """Dense or sparse random array (ref: rand_ndarray incl. densities)."""
    ctx = ctx or default_context()
    a = _np.random.uniform(-1, 1, size=shape).astype(dtype_np(dtype))
    if stype == "default":
        return nd.array(a, ctx=ctx)
    density = 0.5 if density is None else density
    mask = _np.random.rand(*shape) < density
    a = a * mask
    from .ndarray.sparse import cast_storage
    return cast_storage(nd.array(a, ctx=ctx), stype)


def numeric_grad(f, x, eps=1e-4):
    """Central-difference gradient of scalar-valued f at numpy x."""
    x = x.astype(_np.float64)
    grad = _np.zeros_like(x)
    it = _np.nditer(x, flags=["multi_index"])
    while not it.finished:
        idx = it.multi_index
        orig = x[idx]
        x[idx] = orig + eps
        fp = f(x)
        x[idx] = orig - eps
        fm = f(x)
        x[idx] = orig
        grad[idx] = (fp - fm) / (2 * eps)
        it.iternext()
    return grad


def check_numeric_gradient(fn, inputs, rtol=1e-2, atol=1e-3, eps=1e-3,
                           argnums=None):
    """Compare autograd gradients of `fn` (NDArray→NDArray, scalar-summed)
    against central differences (ref: check_numeric_gradient).

    `fn` takes NDArrays, returns an NDArray (any shape — summed to scalar).
    """
    from . import autograd as ag
    nds = [nd.array(x.astype(_np.float64).astype(_np.float32))
           for x in inputs]
    check = range(len(nds)) if argnums is None else argnums
    for i in check:
        nds[i].attach_grad()
    with ag.record():
        out = fn(*nds)
        loss = out.sum()
    loss.backward()

    for i in check:
        def scalar_f(x_np, i=i):
            args = [n.asnumpy().astype(_np.float64) for n in nds]
            args[i] = x_np
            vals = [nd.array(a.astype(_np.float32)) for a in args]
            return float(fn(*vals).sum().asscalar())
        num = numeric_grad(scalar_f, inputs[i].astype(_np.float64), eps)
        sym = nds[i].grad.asnumpy()
        assert_almost_equal(sym, num, rtol=rtol, atol=atol,
                            names=("autograd", "numeric"))


def check_consistency(fn, inputs, ctx_list=None, rtol=1e-4, atol=1e-5):
    """Run `fn` on multiple contexts and compare outputs (ref:
    check_consistency cpu/gpu/cudnn cross-check; here cpu vs tpu)."""
    ctx_list = ctx_list or [cpu()]
    outs = []
    for ctx in ctx_list:
        args = [nd.array(x, ctx=ctx) for x in inputs]
        outs.append(_as_np(fn(*args)))
    for o in outs[1:]:
        assert_almost_equal(outs[0], o, rtol=rtol, atol=atol)


def effective_dtype(dtype):
    return dtype_np(dtype)


class environment:
    """ref: test_utils.environment — temporary env var scope."""

    def __init__(self, *args):
        if len(args) == 2:
            self._kwargs = {args[0]: args[1]}
        else:
            self._kwargs = args[0]
        self._saved = {}

    def __enter__(self):
        for k, v in self._kwargs.items():
            self._saved[k] = os.environ.get(k)
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = str(v)
        return self

    def __exit__(self, *exc):
        for k, v in self._saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def assert_exception(f, exception_type, *args, **kwargs):
    try:
        f(*args, **kwargs)
    except exception_type:
        return
    raise AssertionError("did not raise %s" % exception_type)
