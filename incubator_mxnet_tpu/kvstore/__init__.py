"""mx.kvstore namespace (ref: python/mxnet/kvstore/)."""
from .kvstore import KVStore, create

__all__ = ["KVStore", "create"]
