"""mx.kvstore namespace (ref: python/mxnet/kvstore/)."""
from .kvstore import KVStore, StaleMembership, create

__all__ = ["KVStore", "StaleMembership", "create"]
