"""KVStore — key/value parameter store facade.

TPU-native re-design of the reference KVStore
(ref: include/mxnet/kvstore.h, src/kvstore/kvstore_local.h /
kvstore_nccl.h / kvstore_dist.h).

Semantics preserved: Init/Push/Pull/PushPull/Broadcast, optional
server-side optimizer (`set_optimizer` → update runs "in the store"),
`row_sparse_pull`, gradient-compression config.  Realisation differs by
design (SURVEY §5.8): on TPU the reduce is an XLA collective (or a local
add when arrays live on one chip), not NCCL rings or ps-lite RPC —
`gluon.Trainer` code is unchanged.

Types accepted for `create(name)`:
  local/device/nccl — in-process reduction over per-device copies; on a
      multi-chip mesh the reduce lowers to an ICI all-reduce.
  dist_sync/dist_async/dist_sync_device — multi-host (jax.distributed)
      data-parallel: DistKVStore below; workers join the coordination
      service from the DMLC_* env (base.ensure_jax_distributed), the
      aggregate is a cross-process sum, optional 2-bit compression with
      error feedback rides the wire payload.  Single-process runs behave
      as `local` with num_workers=1 (honest fallback).  Multi-node is
      faked as multi-process-on-localhost in tests, the reference's own
      strategy (tests/nightly/dist_sync_kvstore.py).
"""
from __future__ import annotations

import pickle
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp

from ..base import MXNetError
from ..ndarray.ndarray import NDArray
from ..optimizer import Optimizer, get_updater

__all__ = ["KVStore", "DistKVStore", "StaleMembership", "create"]


class StaleMembership(MXNetError):
    """A rank presented a membership generation older than the store's
    current one — it belongs to a PREVIOUS mesh (it was declared down
    and the survivors re-formed without it).  A stale rank must NOT be
    allowed into a barrier/collective of the new generation: its
    arrival would unbalance the collective and corrupt or deadlock the
    reformed mesh.  The rank should exit and rejoin through the
    elastic re-admission path (`parallel.elastic`), which hands it the
    current generation."""


def _is_list(x):
    return isinstance(x, (list, tuple))


class KVStore:
    """ref: kvstore.py KVStore (python facade over the C KVStore)."""

    def _span(self, op):
        """A telemetry span for one store operation, tagged with the
        membership generation + this rank (ISSUE 11: kvstore traffic
        is the fleet's shared wire, so barrier/push/pull intervals
        must be attributable to a generation and a rank on the merged
        timeline).  One bool read when telemetry is disabled."""
        from ..telemetry import spans as _tele
        if not _tele.enabled():
            return _tele.span(op)       # the shared no-op
        return _tele.span("kv." + op, gen=self._generation,
                          rank=self.rank)

    def __init__(self, kv_type: str = "local"):
        self.type = kv_type
        self._store: Dict = {}
        self._updater = None
        self._optimizer = None
        self._compression = {}
        # membership epoch (elastic mesh): bumped on every mesh
        # shrink/grow so a rank from a previous mesh generation can be
        # rejected at the barrier instead of corrupting a collective
        self._generation = 0

    # -- membership epochs (elastic mesh) -------------------------------
    @property
    def generation(self) -> int:
        """Current membership generation.  Ranks tag their barrier
        entries (and heartbeats) with the generation they joined under;
        a mismatch means the mesh re-formed without them."""
        return self._generation

    def advance_generation(self, reason: str = "membership-change") -> int:
        """Bump the membership epoch (elastic shrink/grow).  Every
        in-flight credential from the previous generation — barrier
        entries, heartbeats — becomes invalid atomically."""
        self._generation += 1
        from ..monitor import events
        events.incr("kvstore.generation_advanced")
        try:
            from ..telemetry import flightrec as _bb
            _bb.record("mesh", "generation", gen=self._generation,
                       reason=reason)
        except Exception:           # noqa: BLE001 — forensics must not
            pass                    # change membership semantics
        return self._generation

    def check_generation(self, generation) -> None:
        """Validate a rank's membership generation (None = unchecked,
        the pre-elastic callers).  Raises `StaleMembership` on
        mismatch and counts it (`kvstore.stale_rank`)."""
        if generation is None:
            return
        if int(generation) != self._generation:
            from ..monitor import events
            events.incr("kvstore.stale_rank")
            raise StaleMembership(
                "rank presented membership generation %d but the "
                "store is at generation %d — this rank belongs to a "
                "previous mesh; exit and rejoin via elastic "
                "re-admission" % (int(generation), self._generation))

    # ------------------------------------------------------------------
    def _is_dist(self):
        return self.type.startswith("dist") or self.type == "p3store_dist"

    @property
    def rank(self) -> int:
        return jax.process_index() if self._is_dist() else 0

    @property
    def num_workers(self) -> int:
        return jax.process_count() if self._is_dist() else 1

    # ------------------------------------------------------------------
    def init(self, key, value):
        keys, values = self._normalize(key, value)
        for k, v in zip(keys, values):
            if k in self._store:
                continue
            vv = v[0] if _is_list(v) else v
            self._store[k] = vv.copy() if isinstance(vv, NDArray) else \
                NDArray(vv)

    broadcast = init

    def push(self, key, value, priority=0):
        keys, values = self._normalize(key, value)
        with self._span("push"):
            self._push_body(keys, values)

    def _push_body(self, keys, values):
        for k, v in zip(keys, values):
            if k not in self._store:
                raise MXNetError("key %r not initialised" % (k,))
            agg = self._reduce(v)
            from ..ndarray.sparse import RowSparseNDArray
            if self._updater is not None:
                # server-side optimizer (ref: kvstore_dist_server.h
                # DataHandleEx → updater(key, grad, weight)); row_sparse
                # grads dispatch to the optimizer's FComputeEx-style path
                self._updater(self._int_key(k), agg, self._store[k])
            elif isinstance(agg, RowSparseNDArray):
                # sparse push without updater: the pushed rows replace the
                # stored rows (ref: kvstore row_sparse aggregation)
                rows = agg.indices._data.astype(jnp.int32)
                dst = self._store[k]
                dst._data = dst._data.at[rows].set(
                    agg.data._data.astype(dst._data.dtype))
            else:
                # reference semantics: push REPLACES the stored value with
                # the aggregate (init 2, push 8 → pull 8), it does not
                # accumulate into it.  Cast to the stored dtype and force a
                # copy: storing the caller's buffer verbatim would alias it
                # (fatal if the caller's buffer is later donated) and drift
                # the store's dtype to the pushed dtype.
                self._store[k]._data = jax.device_put(
                    jnp.array(agg._data, dtype=self._store[k]._data.dtype,
                              copy=True),
                    self._store[k].context.jax_device)

    @staticmethod
    def _write_out(dst, src):
        """Write an aggregate (NDArray or RowSparseNDArray) into `dst`,
        converting storage types as needed."""
        from ..ndarray.sparse import RowSparseNDArray, cast_storage
        if isinstance(src, RowSparseNDArray):
            if isinstance(dst, RowSparseNDArray):
                dst.indices = src.indices.copy()
                dst.data = src.data.copy()
                dst._shape = src.shape
                return
            KVStore._copy_into(dst, src.tostype("default")._data)
            return
        if isinstance(dst, RowSparseNDArray):
            rsp = cast_storage(src, "row_sparse")
            dst.indices = rsp.indices
            dst.data = rsp.data
            dst._shape = rsp.shape
            return
        KVStore._copy_into(dst, src._data)

    @staticmethod
    def _copy_into(dst, src_data):
        """Write `src_data` into `dst` as a FRESH buffer in dst's dtype.
        Handing out an aliased buffer is fatal once the other alias is
        donated (e.g. the in-store updater donates the stored weight on
        the next push; same class of bug as push() storing the caller's
        grad buffer)."""
        dst._data = jax.device_put(
            jnp.array(src_data, dtype=dst._data.dtype, copy=True),
            dst.context.jax_device)

    def pull(self, key, out=None, priority=0, ignore_sparse=True):
        keys, outs = self._normalize(key, out)
        with self._span("pull"):
            for k, o in zip(keys, outs):
                if k not in self._store:
                    raise MXNetError("key %r not initialised" % (k,))
                src = self._store[k]
                for dst in (o if _is_list(o) else [o]):
                    self._write_out(dst, src)

    def pushpull(self, key, value, out=None, priority=0):
        """Fused allreduce (ref: KVStoreNCCL::PushPull — grouped
        ncclAllReduce ≙ one XLA all-reduce / local tree add)."""
        keys, values = self._normalize(key, value)
        if out is None:
            out = value
        _, outs = self._normalize(key, out)
        with self._span("pushpull"):
            for k, v, o in zip(keys, values, outs):
                agg = self._reduce(v)
                for dst in (o if _is_list(o) else [o]):
                    self._write_out(dst, agg)

    def row_sparse_pull(self, key, out=None, priority=0, row_ids=None):
        """Pull only the rows in `row_ids` (ref: sparse kvstore pull for
        row_sparse embeddings)."""
        keys, outs = self._normalize(key, out)
        _, rids = self._normalize(key, row_ids)
        for k, o, r in zip(keys, outs, rids):
            src = self._store[k]
            rows = (r if not _is_list(r) else r[0])._data.astype(jnp.int32)
            vals = jnp.take(src._data, rows, axis=0)
            for dst in (o if _is_list(o) else [o]):
                dst._data = jax.device_put(
                    jnp.zeros(src.shape, src._data.dtype)
                    .at[rows].set(vals), dst.context.jax_device)

    # ------------------------------------------------------------------
    def set_optimizer(self, optimizer: Optimizer):
        self._optimizer = optimizer
        self._updater = get_updater(optimizer)

    def set_gradient_compression(self, compression_params):
        """ref: gradient_compression.h 2-bit quantisation.  Only the
        dist kvstores transfer payloads over a wire, so only they can
        compress — matching the reference, which ties compression to the
        ps-lite push path.  No silent no-op: the local store refuses."""
        raise MXNetError(
            "gradient compression requires a dist kvstore "
            "(create('dist_sync')); %r is in-process and transfers "
            "nothing to compress" % self.type)

    def save_optimizer_states(self, fname, dump_optimizer=False):
        """Atomic write (temp file + os.replace): a crash mid-write can
        never leave a truncated states file where the old one was."""
        if self._updater is None:
            raise MXNetError("optimizer not set on kvstore")
        import os
        payload = self._updater.get_states(dump_optimizer)
        tmp = "%s.tmp.%d" % (fname, os.getpid())
        try:
            with open(tmp, "wb") as f:
                f.write(payload)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, fname)
        finally:
            if os.path.exists(tmp):
                os.remove(tmp)

    def load_optimizer_states(self, fname):
        if self._updater is None:
            raise MXNetError("optimizer not set on kvstore")
        with open(fname, "rb") as f:
            self._updater.set_states(f.read())

    def _barrier(self, timeout=None, generation=None):
        # in-process store: nothing to wait on, but membership is still
        # enforced — a stale rank must not believe it passed a barrier
        with self._span("barrier"):
            self.check_generation(generation)

    def _set_updater(self, updater):
        self._updater = updater

    # ------------------------------------------------------------------
    @staticmethod
    def _normalize(key, value):
        if _is_list(key):
            return list(key), list(value)
        return [key], [value]

    @staticmethod
    def _int_key(k):
        try:
            return int(k)
        except (TypeError, ValueError):
            return k

    @staticmethod
    def _reduce(v) -> NDArray:
        """Sum a list of per-device arrays.  Single-host: adds go through
        XLA on whichever chip holds the first copy; multi-chip meshes use
        in-executable psum via the parallel/ module instead."""
        if not _is_list(v):
            return v
        if len(v) == 1:
            return v[0]
        from ..ndarray.sparse import RowSparseNDArray, add as sparse_add
        if any(isinstance(x, RowSparseNDArray) for x in v):
            acc = v[0]
            for x in v[1:]:
                acc = sparse_add(acc, x)
            return acc
        dev = v[0]._data.sharding.device_set if hasattr(
            v[0]._data, "sharding") else None
        acc = v[0]._data
        for x in v[1:]:
            xd = x._data
            if dev is not None and hasattr(xd, "sharding") and \
                    xd.sharding.device_set != dev:
                xd = jax.device_put(xd, list(dev)[0])
            acc = acc + xd
        return NDArray(acc, ctx=v[0].context)


# ---------------------------------------------------------------------------
# multi-process (DCN) kvstore
# ---------------------------------------------------------------------------


from ..base import ensure_jax_distributed as _ensure_jax_distributed


def _quantize_2bit(g, residual, threshold):
    """ref: gradient_compression.cu Quantize2BitKernel — map each grad
    element (+ carried residual) to {-threshold, 0, +threshold}; the
    quantisation error stays in `residual` (error feedback)."""
    x = g + residual
    q = jnp.where(x >= threshold, threshold,
                  jnp.where(x <= -threshold, -threshold, 0.0)) \
        .astype(g.dtype)
    return q, x - q


class DistKVStore(KVStore):
    """Multi-host data-parallel store: every worker pushes its local
    gradient, the aggregate is the sum over ALL workers (allreduce over
    DCN via the jax coordination/collective layer), every worker pulls
    the same value (ref: kvstore_dist.h + kvstore_dist_server.h
    sync aggregation counting num_workers pushes)."""

    def __init__(self, kv_type="dist_sync"):
        super().__init__(kv_type)
        _ensure_jax_distributed()
        self._residuals: Dict = {}

    # -- cross-process primitives --------------------------------------
    def _retry(self, fn, what):
        """Retry around the wire aggregate.  Scoped to INJECTED
        transients only: a real partial collective failure must not be
        retried per-rank — peers that succeeded have moved on, and an
        uncoordinated re-entry would mismatch collectives across the
        job (deadlock or wrong sums).  Real failures propagate so the
        worker fails fast and the scheduler restarts it."""
        from ..parallel.resilience import retry_transient
        from .. import fault as _fault

        def attempt():
            _fault.maybe_raise("kvstore.collective")
            return fn()
        return retry_transient(attempt, what=what,
                               retryable=(_fault.TransientFault,))

    def _allreduce_sum(self, data):
        if self.num_workers == 1:
            return data
        from jax.experimental import multihost_utils
        import numpy as _np

        def run():
            gathered = multihost_utils.process_allgather(_np.asarray(data))
            return jnp.asarray(
                _np.sum(gathered, axis=0, dtype=_np.float64)
                .astype(_np.asarray(data).dtype))
        return self._retry(run, "kvstore allreduce (rank %d)" % self.rank)

    def _bcast_from_root(self, data):
        if self.num_workers == 1:
            return data
        from jax.experimental import multihost_utils
        import numpy as _np

        def run():
            return jnp.asarray(multihost_utils.broadcast_one_to_all(
                _np.asarray(data)))
        return self._retry(run, "kvstore broadcast (rank %d)" % self.rank)

    def _barrier(self, timeout=None, generation=None):
        """Barrier with a deadline: a worker that never arrives (hung
        host, dead process) turns into a clear rank-tagged error on the
        waiting workers instead of an indefinite hang.  `timeout` in
        seconds (default MXNET_KVSTORE_BARRIER_TIMEOUT; 0 = wait
        forever, the reference behaviour).  `generation` is the
        caller's membership epoch: a rank from a previous mesh
        generation (declared down, mesh re-formed without it) is
        rejected with `StaleMembership` BEFORE it can enter — an
        unbalanced barrier entry would wedge or corrupt the reformed
        collective.

        On timeout the waiter thread is abandoned mid-collective, so
        the process must be treated as wedged: the error is terminal —
        exit and let the scheduler restart the worker; do not issue
        further kvstore ops from this process."""
        from .. import config, fault as _fault
        with self._span("barrier"):
            return self._barrier_body(timeout, generation, config,
                                      _fault)

    def _barrier_body(self, timeout, generation, config, _fault):
        self.check_generation(generation)
        if timeout is None:
            timeout = float(config.get("MXNET_KVSTORE_BARRIER_TIMEOUT"))
        hang = _fault.should_fire("kvstore.barrier_hang")
        if self.num_workers <= 1 and not hang:
            return

        def wait():
            if hang:
                # injected stuck-peer: stall just long enough to trip
                # the deadline (bounded, so the abandoned daemon thread
                # doesn't linger for hours in long test processes)
                import time
                time.sleep(max(timeout, 0.1) + 5)
                return
            from jax.experimental import multihost_utils
            multihost_utils.sync_global_devices("kvstore_barrier")

        if timeout <= 0:
            return wait()
        import threading
        err = []

        def body():
            try:
                wait()
            except Exception as e:        # surfaced after join
                err.append(e)
        t = threading.Thread(target=body, daemon=True,
                             name="kvstore_barrier")
        t.start()
        t.join(timeout)
        if t.is_alive():
            from ..monitor import events
            events.incr("kvstore.barrier_timeout")
            raise MXNetError(
                "kvstore barrier timed out after %.1fs on worker rank "
                "%d/%d — a peer is hung or dead; exit and let the "
                "scheduler restart this worker (raise "
                "MXNET_KVSTORE_BARRIER_TIMEOUT if the pod is just slow)"
                % (timeout, self.rank, self.num_workers))
        if err:
            raise err[0]

    # -- overridden API -------------------------------------------------
    def init(self, key, value):
        """Worker 0's value wins (ref: dist server stores the first
        init; others are ignored) and is broadcast to every worker."""
        keys, values = self._normalize(key, value)
        for k, v in zip(keys, values):
            if k in self._store:
                continue
            vv = v[0] if _is_list(v) else v
            data = self._bcast_from_root(vv._data)
            out = vv.copy() if isinstance(vv, NDArray) else NDArray(vv)
            out._data = jax.device_put(data, out.context.jax_device)
            self._store[k] = out

    broadcast = init

    def _maybe_compress(self, k, payload):
        """2-bit quantise the wire payload with per-key error-feedback
        residual (ref: GradientCompression::Quantize before ZPush)."""
        if self._compression.get("type") == "2bit":
            thr = float(self._compression.get("threshold", 0.5))
            res = self._residuals.get(k)
            if res is None:
                res = jnp.zeros_like(payload)
            payload, res = _quantize_2bit(payload, res, thr)
            self._residuals[k] = res
        return payload

    def _dist_aggregate(self, k, local):
        """local (NDArray or RowSparseNDArray) → cross-worker aggregate.
        RowSparse payloads densify for the wire (variable-nnz allgather
        is a follow-up); single-worker runs skip the wire entirely."""
        from ..ndarray.sparse import RowSparseNDArray
        if isinstance(local, RowSparseNDArray):
            if self.num_workers == 1:
                return local
            local = local.tostype("default")
        if self.num_workers == 1:
            return NDArray(self._maybe_compress(k, local._data),
                           ctx=local.context)
        if self._compression.get("type") == "2bit":
            thr = float(self._compression.get("threshold", 0.5))
            agg = self._allreduce_2bit(k, local._data, thr)
        else:
            agg = self._allreduce_sum(local._data)
        return NDArray(agg, ctx=local.context)

    def _allreduce_2bit(self, k, payload, thr):
        """Quantise to {-thr, 0, +thr}, PACK to 2-bit codes (4 elements
        per byte), allgather the packed bytes, decode+sum — the wire
        carries 1/16 of the f32 payload (ref: gradient_compression.cc
        packing into uint32 words)."""
        import numpy as _np
        from jax.experimental import multihost_utils
        q = self._maybe_compress(k, payload)            # {-thr, 0, thr}
        codes = (_np.sign(_np.asarray(q)) + 1).astype(_np.uint8)  # {0,1,2}
        n = codes.size
        pad = (-n) % 4
        codes = _np.concatenate([codes.ravel(),
                                 _np.ones(pad, _np.uint8)])  # 1 == zero
        packed = (codes[0::4] | (codes[1::4] << 2) |
                  (codes[2::4] << 4) | (codes[3::4] << 6))
        gathered = multihost_utils.process_allgather(packed)
        total = _np.zeros(n + pad, _np.float32)
        for row in gathered.reshape(self.num_workers, -1):
            u = _np.stack([row & 3, (row >> 2) & 3,
                           (row >> 4) & 3, (row >> 6) & 3], axis=1).ravel()
            total += (u.astype(_np.float32) - 1.0) * thr
        return jnp.asarray(total[:n].reshape(payload.shape)
                           .astype(_np.asarray(payload).dtype))

    def push(self, key, value, priority=0):
        keys, values = self._normalize(key, value)
        with self._span("push"):
            for k, v in zip(keys, values):
                if k not in self._store:
                    raise MXNetError("key %r not initialised" % (k,))
                agg = self._dist_aggregate(k, self._reduce(v))
                from ..ndarray.sparse import RowSparseNDArray
                if self._updater is not None:
                    self._updater(self._int_key(k), agg, self._store[k])
                elif isinstance(agg, RowSparseNDArray):
                    rows = agg.indices._data.astype(jnp.int32)
                    dst = self._store[k]
                    dst._data = dst._data.at[rows].set(
                        agg.data._data.astype(dst._data.dtype))
                else:
                    self._store[k]._data = jax.device_put(
                        jnp.array(agg._data,
                                  dtype=self._store[k]._data.dtype,
                                  copy=True),
                        self._store[k].context.jax_device)

    def pushpull(self, key, value, out=None, priority=0):
        """Fused allreduce across workers: local reduce → DCN sum →
        write-out (ref: KVStoreDist push+pull pair in Trainer.step)."""
        keys, values = self._normalize(key, value)
        if out is None:
            out = value
        _, outs = self._normalize(key, out)
        with self._span("pushpull"):
            for k, v, o in zip(keys, values, outs):
                agg = self._dist_aggregate(k, self._reduce(v))
                for dst in (o if _is_list(o) else [o]):
                    self._write_out(dst, agg)

    def set_gradient_compression(self, compression_params):
        params = dict(compression_params)
        ctype = params.get("type", "2bit")
        if ctype != "2bit":
            raise MXNetError("unsupported compression type %r" % ctype)
        self._compression = params


_TYPES = ("local", "device", "nccl", "dist_sync", "dist_async",
          "dist_sync_device", "dist_async_device", "horovod", "p3store_dist")


def create(name: str = "local") -> KVStore:
    """ref: KVStore::Create."""
    if name not in _TYPES:
        raise MXNetError("unknown kvstore type %r" % name)
    if name.startswith("dist") or name == "p3store_dist":
        return DistKVStore(name)
    return KVStore(name)
