"""KVStore — key/value parameter store facade.

TPU-native re-design of the reference KVStore
(ref: include/mxnet/kvstore.h, src/kvstore/kvstore_local.h /
kvstore_nccl.h / kvstore_dist.h).

Semantics preserved: Init/Push/Pull/PushPull/Broadcast, optional
server-side optimizer (`set_optimizer` → update runs "in the store"),
`row_sparse_pull`, gradient-compression config.  Realisation differs by
design (SURVEY §5.8): on TPU the reduce is an XLA collective (or a local
add when arrays live on one chip), not NCCL rings or ps-lite RPC —
`gluon.Trainer` code is unchanged.

Types accepted for `create(name)`:
  local/device/nccl — in-process reduction over per-device copies; on a
      multi-chip mesh the reduce lowers to an ICI all-reduce.
  dist_sync/dist_async/dist_sync_device — multi-host (jax.distributed)
      data-parallel; in a single-process run they behave as `local` with
      num_workers=1 (the multi-process path arrives with the DCN slice).
"""
from __future__ import annotations

import pickle
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp

from ..base import MXNetError
from ..ndarray.ndarray import NDArray
from ..optimizer import Optimizer, get_updater

__all__ = ["KVStore", "create"]


def _is_list(x):
    return isinstance(x, (list, tuple))


class KVStore:
    """ref: kvstore.py KVStore (python facade over the C KVStore)."""

    def __init__(self, kv_type: str = "local"):
        self.type = kv_type
        self._store: Dict = {}
        self._updater = None
        self._optimizer = None
        self._compression = {}

    # ------------------------------------------------------------------
    @property
    def rank(self) -> int:
        return jax.process_index() if self.type.startswith("dist") else 0

    @property
    def num_workers(self) -> int:
        return jax.process_count() if self.type.startswith("dist") else 1

    # ------------------------------------------------------------------
    def init(self, key, value):
        keys, values = self._normalize(key, value)
        for k, v in zip(keys, values):
            if k in self._store:
                continue
            vv = v[0] if _is_list(v) else v
            self._store[k] = vv.copy() if isinstance(vv, NDArray) else \
                NDArray(vv)

    broadcast = init

    def push(self, key, value, priority=0):
        keys, values = self._normalize(key, value)
        for k, v in zip(keys, values):
            if k not in self._store:
                raise MXNetError("key %r not initialised" % (k,))
            agg = self._reduce(v)
            if self._updater is not None:
                # server-side optimizer (ref: kvstore_dist_server.h
                # DataHandleEx → updater(key, grad, weight))
                self._updater(self._int_key(k), agg, self._store[k])
            else:
                # reference semantics: push REPLACES the stored value with
                # the aggregate (init 2, push 8 → pull 8), it does not
                # accumulate into it.  Cast to the stored dtype and force a
                # copy: storing the caller's buffer verbatim would alias it
                # (fatal if the caller's buffer is later donated) and drift
                # the store's dtype to the pushed dtype.
                self._store[k]._data = jax.device_put(
                    jnp.array(agg._data, dtype=self._store[k]._data.dtype,
                              copy=True),
                    self._store[k].context.jax_device)

    @staticmethod
    def _copy_into(dst, src_data):
        """Write `src_data` into `dst` as a FRESH buffer in dst's dtype.
        Handing out an aliased buffer is fatal once the other alias is
        donated (e.g. the in-store updater donates the stored weight on
        the next push; same class of bug as push() storing the caller's
        grad buffer)."""
        dst._data = jax.device_put(
            jnp.array(src_data, dtype=dst._data.dtype, copy=True),
            dst.context.jax_device)

    def pull(self, key, out=None, priority=0, ignore_sparse=True):
        keys, outs = self._normalize(key, out)
        for k, o in zip(keys, outs):
            if k not in self._store:
                raise MXNetError("key %r not initialised" % (k,))
            src = self._store[k]
            for dst in (o if _is_list(o) else [o]):
                self._copy_into(dst, src._data)

    def pushpull(self, key, value, out=None, priority=0):
        """Fused allreduce (ref: KVStoreNCCL::PushPull — grouped
        ncclAllReduce ≙ one XLA all-reduce / local tree add)."""
        keys, values = self._normalize(key, value)
        if out is None:
            out = value
        _, outs = self._normalize(key, out)
        for k, v, o in zip(keys, values, outs):
            agg = self._reduce(v)
            for dst in (o if _is_list(o) else [o]):
                self._copy_into(dst, agg._data)

    def row_sparse_pull(self, key, out=None, priority=0, row_ids=None):
        """Pull only the rows in `row_ids` (ref: sparse kvstore pull for
        row_sparse embeddings)."""
        keys, outs = self._normalize(key, out)
        _, rids = self._normalize(key, row_ids)
        for k, o, r in zip(keys, outs, rids):
            src = self._store[k]
            rows = (r if not _is_list(r) else r[0])._data.astype(jnp.int32)
            vals = jnp.take(src._data, rows, axis=0)
            for dst in (o if _is_list(o) else [o]):
                dst._data = jax.device_put(
                    jnp.zeros(src.shape, src._data.dtype)
                    .at[rows].set(vals), dst.context.jax_device)

    # ------------------------------------------------------------------
    def set_optimizer(self, optimizer: Optimizer):
        self._optimizer = optimizer
        self._updater = get_updater(optimizer)

    def set_gradient_compression(self, compression_params):
        """ref: gradient_compression.h 2-bit quantisation. Recorded; the
        DCN payload-compression path lands with multi-host support."""
        self._compression = dict(compression_params)

    def save_optimizer_states(self, fname, dump_optimizer=False):
        if self._updater is None:
            raise MXNetError("optimizer not set on kvstore")
        with open(fname, "wb") as f:
            f.write(self._updater.get_states(dump_optimizer))

    def load_optimizer_states(self, fname):
        if self._updater is None:
            raise MXNetError("optimizer not set on kvstore")
        with open(fname, "rb") as f:
            self._updater.set_states(f.read())

    def _barrier(self):
        pass

    def _set_updater(self, updater):
        self._updater = updater

    # ------------------------------------------------------------------
    @staticmethod
    def _normalize(key, value):
        if _is_list(key):
            return list(key), list(value)
        return [key], [value]

    @staticmethod
    def _int_key(k):
        try:
            return int(k)
        except (TypeError, ValueError):
            return k

    @staticmethod
    def _reduce(v) -> NDArray:
        """Sum a list of per-device arrays.  Single-host: adds go through
        XLA on whichever chip holds the first copy; multi-chip meshes use
        in-executable psum via the parallel/ module instead."""
        if not _is_list(v):
            return v
        if len(v) == 1:
            return v[0]
        dev = v[0]._data.sharding.device_set if hasattr(
            v[0]._data, "sharding") else None
        acc = v[0]._data
        for x in v[1:]:
            xd = x._data
            if dev is not None and hasattr(xd, "sharding") and \
                    xd.sharding.device_set != dev:
                xd = jax.device_put(xd, list(dev)[0])
            acc = acc + xd
        return NDArray(acc, ctx=v[0].context)


_TYPES = ("local", "device", "nccl", "dist_sync", "dist_async",
          "dist_sync_device", "dist_async_device", "horovod", "p3store_dist")


def create(name: str = "local") -> KVStore:
    """ref: KVStore::Create."""
    if name not in _TYPES:
        raise MXNetError("unknown kvstore type %r" % name)
    return KVStore(name)
