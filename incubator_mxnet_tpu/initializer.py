"""Weight initializers (ref: python/mxnet/initializer.py).

Same registry + `InitDesc`-by-name dispatch as the reference; bodies use
the framework's stateful RNG facade so `mx.random.seed` reproduces runs.
"""
from __future__ import annotations

import json
import math
import re

import numpy as _np

from .base import MXNetError

__all__ = ["Initializer", "Zero", "One", "Constant", "Uniform", "Normal",
           "Orthogonal", "Xavier", "MSRAPrelu", "Bilinear", "LSTMBias",
           "Mixed", "register", "create", "InitDesc"]

_REGISTRY = {}


def register(klass):
    _REGISTRY[klass.__name__.lower()] = klass
    return klass


def create(name, **kwargs):
    if isinstance(name, Initializer):
        return name
    if name is None:
        return Uniform()
    if callable(name):
        return name
    key = name.lower()
    aliases = {"zeros": "zero", "ones": "one", "gaussian": "normal",
               "msraprelu": "msraprelu"}
    key = aliases.get(key, key)
    if key not in _REGISTRY:
        raise MXNetError("unknown initializer %r" % name)
    return _REGISTRY[key](**kwargs)


class InitDesc(str):
    """Parameter name + attrs hint (ref: initializer.py InitDesc)."""

    def __new__(cls, name, attrs=None, global_init=None):
        obj = super().__new__(cls, name)
        obj.attrs = attrs or {}
        obj.global_init = global_init
        return obj


class Initializer:
    """Base initializer; `__call__(name, arr)` fills `arr` in place
    (rebinding the buffer, as all mutation does here)."""

    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def __call__(self, name, arr):
        if not isinstance(name, str):
            name = ""
        self.init_weight(name, arr)

    def init_weight(self, name, arr):
        if name.endswith("bias"):
            self._init_zero(arr)
        elif name.endswith("gamma"):
            self._init_one(arr)
        elif name.endswith("beta"):
            self._init_zero(arr)
        elif name.endswith("running_mean") or name.endswith("moving_mean"):
            self._init_zero(arr)
        elif name.endswith("running_var") or name.endswith("moving_var"):
            self._init_one(arr)
        else:
            self._init_weight(name, arr)

    # -- default fills ----------------------------------------------------
    def _init_zero(self, arr):
        self._fill(arr, _np.zeros(arr.shape, dtype=arr.dtype))

    def _init_one(self, arr):
        self._fill(arr, _np.ones(arr.shape, dtype=arr.dtype))

    def _init_weight(self, name, arr):
        raise NotImplementedError

    @staticmethod
    def _fill(arr, value):
        from .ndarray import NDArray
        import jax
        arr._data = jax.device_put(
            _np.asarray(value, dtype=arr.dtype), arr.context.jax_device)

    @staticmethod
    def _cpu_key(ctx):
        """Derive a fresh init key ENTIRELY on the host + local cpu
        backend: init-time randomness runs there (threefry is
        backend-deterministic), so a fresh process pays zero remote
        device compiles for its ~hundreds of per-shape init programs
        (measured: 38-117 s of BERT startup on the tunnel-attached
        chip was param-init compiles — including the device-side
        threefry seed/fold/split chain `split_key` would run)."""
        from . import random as rnd
        import jax
        try:
            # process-LOCAL cpu device: jax.devices("cpu")[0] is rank
            # 0's under multi-controller — non-addressable elsewhere
            cpu = jax.local_devices(backend="cpu")[0]
            bits = rnd.next_key_bits(ctx)      # host-only derivation
            with jax.default_device(cpu):
                return jax.random.wrap_key_data(bits), True
        except Exception:
            return rnd.split_key(ctx), False

    @staticmethod
    def _rand_normal(arr, scale):
        import jax
        key, on_cpu = Initializer._cpu_key(arr.context)
        if on_cpu:
            with jax.default_device(jax.local_devices(
                    backend="cpu")[0]):
                vals = jax.random.normal(key, arr.shape)
        else:
            vals = jax.random.normal(key, arr.shape)
        Initializer._fill(arr, _np.asarray(vals) * scale)

    @staticmethod
    def _rand_uniform(arr, low, high):
        import jax
        key, on_cpu = Initializer._cpu_key(arr.context)
        if on_cpu:
            with jax.default_device(jax.local_devices(
                    backend="cpu")[0]):
                vals = jax.random.uniform(key, arr.shape, minval=low,
                                          maxval=high)
        else:
            vals = jax.random.uniform(key, arr.shape, minval=low,
                                      maxval=high)
        Initializer._fill(arr, _np.asarray(vals))

    def dumps(self):
        return json.dumps([self.__class__.__name__.lower(), self._kwargs])

    def __repr__(self):
        return self.__class__.__name__


@register
class Zero(Initializer):
    def _init_weight(self, name, arr):
        self._fill(arr, _np.zeros(arr.shape, dtype=arr.dtype))


@register
class One(Initializer):
    def _init_weight(self, name, arr):
        self._fill(arr, _np.ones(arr.shape, dtype=arr.dtype))


@register
class Constant(Initializer):
    def __init__(self, value=0.0):
        super().__init__(value=value)
        self.value = value

    def _init_weight(self, name, arr):
        self._fill(arr, _np.full(arr.shape, self.value, dtype=arr.dtype))


@register
class Uniform(Initializer):
    def __init__(self, scale=0.07):
        super().__init__(scale=scale)
        self.scale = scale

    def _init_weight(self, name, arr):
        self._rand_uniform(arr, -self.scale, self.scale)


@register
class Normal(Initializer):
    def __init__(self, sigma=0.01):
        super().__init__(sigma=sigma)
        self.sigma = sigma

    def _init_weight(self, name, arr):
        self._rand_normal(arr, self.sigma)


@register
class Orthogonal(Initializer):
    def __init__(self, scale=1.414, rand_type="uniform"):
        super().__init__(scale=scale, rand_type=rand_type)
        self.scale = scale
        self.rand_type = rand_type

    def _init_weight(self, name, arr):
        from . import random as rnd
        import jax
        key = rnd.split_key(arr.context)
        nout = arr.shape[0]
        nin = int(_np.prod(arr.shape[1:]))
        if self.rand_type == "uniform":
            tmp = _np.asarray(jax.random.uniform(
                key, (nout, nin), minval=-1.0, maxval=1.0))
        else:
            tmp = _np.asarray(jax.random.normal(key, (nout, nin)))
        u, _, v = _np.linalg.svd(tmp, full_matrices=False)
        q = u if u.shape == tmp.shape else v
        self._fill(arr, self.scale * q.reshape(arr.shape))


@register
class Xavier(Initializer):
    """ref: initializer.py Xavier (gaussian/uniform × avg/in/out)."""

    def __init__(self, rnd_type="uniform", factor_type="avg", magnitude=3):
        super().__init__(rnd_type=rnd_type, factor_type=factor_type,
                         magnitude=magnitude)
        self.rnd_type = rnd_type
        self.factor_type = factor_type
        self.magnitude = float(magnitude)

    def _init_weight(self, name, arr):
        shape = arr.shape
        hw_scale = 1.0
        if len(shape) < 2:
            raise MXNetError("Xavier requires ndim >= 2 (got %r)" % (shape,))
        if len(shape) > 2:
            hw_scale = _np.prod(shape[2:])
        fan_in = shape[1] * hw_scale
        fan_out = shape[0] * hw_scale
        factor = {"avg": (fan_in + fan_out) / 2.0,
                  "in": fan_in, "out": fan_out}[self.factor_type]
        scale = math.sqrt(self.magnitude / factor)
        if self.rnd_type == "uniform":
            self._rand_uniform(arr, -scale, scale)
        else:
            self._rand_normal(arr, scale)


@register
class MSRAPrelu(Xavier):
    def __init__(self, factor_type="avg", slope=0.25):
        magnitude = 2.0 / (1 + slope ** 2)
        super().__init__("gaussian", factor_type, magnitude)
        self._kwargs = {"factor_type": factor_type, "slope": slope}


@register
class Bilinear(Initializer):
    def _init_weight(self, name, arr):
        shape = arr.shape
        weight = _np.zeros(int(_np.prod(shape)), dtype="float32")
        f = _np.ceil(shape[3] / 2.0)
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        for i in range(weight.size):
            x = i % shape[3]
            y = (i // shape[3]) % shape[2]
            weight[i] = (1 - abs(x / f - c)) * (1 - abs(y / f - c))
        self._fill(arr, weight.reshape(shape))


@register
class LSTMBias(Initializer):
    def __init__(self, forget_bias=1.0):
        super().__init__(forget_bias=forget_bias)
        self.forget_bias = forget_bias

    def _init_weight(self, name, arr):
        b = _np.zeros(arr.shape, dtype="float32")
        num_hidden = arr.shape[0] // 4
        b[num_hidden:2 * num_hidden] = self.forget_bias
        self._fill(arr, b)


class Mixed:
    """ref: initializer.Mixed — regex-pattern dispatch."""

    def __init__(self, patterns, initializers):
        self.map = list(zip([re.compile(p) for p in patterns], initializers))

    def __call__(self, name, arr):
        for prog, init in self.map:
            if prog.match(name):
                init(name, arr)
                return
        raise MXNetError("no initializer pattern matches %r" % name)
