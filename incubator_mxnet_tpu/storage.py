"""Storage manager facade (ref: include/mxnet/storage.h,
src/storage/pooled_storage_manager.h — GPUPooledStorageManager's
size-bucketed free lists, MXNET_GPU_MEM_POOL_* knobs).

Deliberate TPU re-design: device memory pooling is the PJRT/XLA
allocator's job (a BFC arena owns HBM; XLA's buffer assignment reuses
and donates buffers inside executables), so there is no hand-written
pool here to configure.  What this module preserves from the reference
surface:

- `Storage.get()` singleton with `alloc`/`free` bookkeeping hooks — the
  imperative NDArray path doesn't call it (jax.Array owns its buffer),
  but custom native extensions can use it for host scratch;
- per-device memory introspection (`memory_info`) mapping
  `mx.context.gpu_memory_info` onto PJRT's memory stats;
- the MXNET_GPU_MEM_POOL_* env knobs are registered in `config` and
  accepted (recorded, no-op) so reference launch scripts run unchanged.
"""
from __future__ import annotations

import threading

__all__ = ["Storage", "memory_info", "memory_events",
           "live_arrays_events"]


def memory_info(device=None):
    """(bytes_in_use, bytes_limit) for a device (ref:
    mx.context.gpu_memory_info; backed by PJRT memory_stats)."""
    import jax
    if device is None:
        device = jax.devices()[0]
    elif isinstance(device, int):
        device = jax.devices()[device]
    elif hasattr(device, "jax_device"):
        device = device.jax_device
    stats = getattr(device, "memory_stats", lambda: None)()
    if not stats:
        return (0, 0)
    return (stats.get("bytes_in_use", 0),
            stats.get("bytes_limit", stats.get("bytes_reservable_limit",
                                               0)))


def memory_events(devices=None, counters=None):
    """Sample per-device HBM used/peak onto `monitor.events` as `mem.*`
    observed series (ISSUE 5): `mem.bytes_in_use` / `mem.peak_bytes`
    samples whose p50/p99 render through the MetricsExporter like any
    latency series.  Returns one dict per device that HAS stats.

    Degrades cleanly on backends whose PJRT `memory_stats` returns
    None or raises (the axon plugin, ndarray.py:77): that device
    contributes NO event and NO crash — the return is simply shorter
    (empty on a statless backend, e.g. CPU jax)."""
    import jax
    if counters is None:
        from .monitor import events as counters
    out = []
    for d in (devices if devices is not None else jax.devices()):
        d = getattr(d, "jax_device", d)
        try:
            stats = getattr(d, "memory_stats", lambda: None)()
        except Exception:           # noqa: BLE001 — introspection must
            stats = None            # never take the run down
        if not stats:
            continue
        used = int(stats.get("bytes_in_use", 0))
        peak = int(stats.get("peak_bytes_in_use", used))
        limit = int(stats.get("bytes_limit",
                              stats.get("bytes_reservable_limit", 0)))
        counters.observe("mem.bytes_in_use", used)
        counters.observe("mem.peak_bytes", max(peak, used))
        out.append({"device": "%s:%d" % (getattr(d, "platform", "dev"),
                                         getattr(d, "id", 0)),
                    "bytes_in_use": used,
                    "peak_bytes": max(peak, used),
                    "bytes_limit": limit})
    return out


def live_arrays_events(devices=None, counters=None):
    """`memory_events`-shaped rows computed from `jax.live_arrays()`
    — the measured-bytes fallback for backends whose PJRT
    ``memory_stats`` reports nothing (CPU jax, the axon plugin).
    Each row carries ``source="live_arrays"``; the per-device sum
    counts every addressable shard on the device that holds it, so
    replicated and sharded arrays both attribute where their bytes
    actually live.  There is no allocator here, so ``peak_bytes`` ==
    ``bytes_in_use`` and ``bytes_limit`` is 0 (unreported)."""
    import jax
    if counters is None:
        from .monitor import events as counters
    per_dev = {}
    for arr in jax.live_arrays():
        try:
            shards = arr.addressable_shards
        except Exception:           # noqa: BLE001 — a deleted array
            shards = None           # must not kill the probe
        if shards:
            for sh in shards:
                d = sh.device
                key = "%s:%d" % (getattr(d, "platform", "dev"),
                                 getattr(d, "id", 0))
                per_dev[key] = per_dev.get(key, 0) \
                    + int(sh.data.nbytes)
            continue
        try:
            nb = int(arr.nbytes)
            devs = list(arr.devices())
        except Exception:           # noqa: BLE001
            continue
        for d in devs:
            key = "%s:%d" % (getattr(d, "platform", "dev"),
                             getattr(d, "id", 0))
            per_dev[key] = per_dev.get(key, 0) + nb // max(1,
                                                          len(devs))
    want = None
    if devices is not None:
        want = set()
        for d in devices:
            d = getattr(d, "jax_device", d)
            want.add("%s:%d" % (getattr(d, "platform", "dev"),
                                getattr(d, "id", 0)))
    out = []
    for key in sorted(per_dev):
        if want is not None and key not in want:
            continue
        used = per_dev[key]
        counters.observe("mem.bytes_in_use", used)
        counters.observe("mem.peak_bytes", used)
        out.append({"device": key, "bytes_in_use": used,
                    "peak_bytes": used, "bytes_limit": 0,
                    "source": "live_arrays"})
    return out


class Storage:
    """Host-scratch allocator facade (singleton, ref: Storage::Get).

    Tracks outstanding allocations for leak diagnostics; allocation
    itself is plain bytearray (aligned host memory — device memory is
    always XLA's)."""

    _instance = None
    _lock = threading.Lock()

    @classmethod
    def get(cls):
        with cls._lock:
            if cls._instance is None:
                cls._instance = cls()
            return cls._instance

    def __init__(self):
        self._outstanding = {}
        self._next = 0
        self._mu = threading.Lock()

    def alloc(self, size):
        """Returns (handle_id, buffer)."""
        buf = bytearray(size)
        with self._mu:
            hid = self._next
            self._next += 1
            self._outstanding[hid] = size
        return hid, buf

    def free(self, handle_id):
        with self._mu:
            self._outstanding.pop(handle_id, None)

    def direct_free(self, handle_id):
        self.free(handle_id)

    @property
    def outstanding_bytes(self):
        with self._mu:
            return sum(self._outstanding.values())

    @property
    def outstanding_count(self):
        with self._mu:
            return len(self._outstanding)
