"""Stateful RNG facade over JAX threefry keys.

ref: python/mxnet/random.py (mx.random.seed) + per-device PRNG Resource
(src/common/random_generator.h).  Each Context holds a key; every sampling
op splits it (so results differ call-to-call) while `seed(n)` restores the
reference's reproducibility contract.  Op bodies stay pure functions of an
explicit key — the stateful part lives only here, outside any jit.
"""
from __future__ import annotations

import threading
from typing import Dict

import jax

from .context import Context, current_context

__all__ = ["seed", "split_key", "current_key"]

_LOCK = threading.Lock()
_KEYS: Dict[Context, "jax.Array"] = {}
_BASE_SEED = 0


def seed(seed_state: int, ctx="all"):
    """mx.random.seed — reseed one context or all (ref semantics)."""
    global _BASE_SEED
    with _LOCK:
        if ctx == "all":
            _BASE_SEED = int(seed_state)
            _KEYS.clear()
        else:
            _KEYS[ctx] = jax.random.key(int(seed_state))


def _ctx_key(ctx: Context):
    if ctx not in _KEYS:
        # derive deterministic per-context key from base seed + device id
        _KEYS[ctx] = jax.random.fold_in(
            jax.random.key(_BASE_SEED), hash((ctx.device_type,
                                              ctx.device_id)) & 0x7FFFFFFF)
    return _KEYS[ctx]


class _TraceRng(threading.local):
    """While a hybridized block is being traced, sampling ops must draw
    from a *traced* key input (a host-side key would bake the random bits
    into the executable as constants). The cached-op machinery pushes a
    key holder here for the duration of the trace."""

    def __init__(self):
        self.stack = []


_TRACE_STATE = _TraceRng()


class KeyHolder:
    __slots__ = ("key",)

    def __init__(self, key):
        self.key = key

    def next(self):
        self.key, sub = jax.random.split(self.key)
        return sub


def push_trace_key(holder: KeyHolder):
    _TRACE_STATE.stack.append(holder)


def pop_trace_key():
    return _TRACE_STATE.stack.pop()


def split_key(ctx: Context = None):
    """Split the context's key; returns a fresh subkey for one op call."""
    if _TRACE_STATE.stack:
        return _TRACE_STATE.stack[-1].next()
    ctx = ctx or current_context()
    with _LOCK:
        key = _ctx_key(ctx)
        new, sub = jax.random.split(key)
        _KEYS[ctx] = new
        return sub


def current_key(ctx: Context = None):
    ctx = ctx or current_context()
    with _LOCK:
        return _ctx_key(ctx)
