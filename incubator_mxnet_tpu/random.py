"""Stateful RNG facade over JAX threefry keys.

ref: python/mxnet/random.py (mx.random.seed) + per-device PRNG Resource
(src/common/random_generator.h).  Each Context holds a key; every sampling
op splits it (so results differ call-to-call) while `seed(n)` restores the
reference's reproducibility contract.  Op bodies stay pure functions of an
explicit key — the stateful part lives only here, outside any jit.
"""
from __future__ import annotations

import threading
from typing import Dict

import jax

from .context import Context, current_context

__all__ = ["seed", "split_key", "current_key"]

_LOCK = threading.Lock()
_KEYS: Dict[Context, "jax.Array"] = {}
_BASE_SEED = 0


def seed(seed_state: int, ctx="all"):
    """mx.random.seed — reseed one context or all (ref semantics)."""
    global _BASE_SEED
    with _LOCK:
        if ctx == "all":
            _BASE_SEED = int(seed_state)
            _KEYS.clear()
            _BITS_COUNTER.clear()
            _CTX_SEED.clear()
        else:
            _KEYS[ctx] = jax.random.key(int(seed_state))
            _BITS_COUNTER.pop(ctx, None)
            _CTX_SEED[ctx] = int(seed_state)


def _ctx_key(ctx: Context):
    if ctx not in _KEYS:
        # derive deterministic per-context key from base seed + device id.
        # crc32, NOT Python hash(): string hashing is salted per process,
        # which would give dist workers different streams for the same
        # seed (breaking same-init invariants; see next_key_bits).
        import zlib
        mix = zlib.crc32(repr((ctx.device_type,
                               ctx.device_id)).encode()) & 0x7FFFFFFF
        _KEYS[ctx] = jax.random.fold_in(jax.random.key(_BASE_SEED), mix)
    return _KEYS[ctx]


class _TraceRng(threading.local):
    """While a hybridized block is being traced, sampling ops must draw
    from a *traced* key input (a host-side key would bake the random bits
    into the executable as constants). The cached-op machinery pushes a
    key holder here for the duration of the trace."""

    def __init__(self):
        self.stack = []


_TRACE_STATE = _TraceRng()


class KeyHolder:
    __slots__ = ("key",)

    def __init__(self, key):
        self.key = key

    def next(self):
        self.key, sub = jax.random.split(self.key)
        return sub


def push_trace_key(holder: KeyHolder):
    _TRACE_STATE.stack.append(holder)


def pop_trace_key():
    return _TRACE_STATE.stack.pop()


_BITS_COUNTER = {}  # ctx -> monotone draw counter (host-side)
_CTX_SEED = {}      # ctx -> per-context seed override (seed(n, ctx=...))


def next_key_bits(ctx: Context = None):
    """Fresh threefry KEY DATA derived entirely on the host — zero device
    ops.  A threefry key is 2×uint32 of arbitrary bits; (seed-mix, call
    counter) gives each call an independent stream.  Used by hot paths
    (cached-op executables) that feed the bits in as a jit input;
    mx.random.seed resets the counter for reproducibility.

    The mix uses crc32, not Python hash() — string hashing is salted
    per process and would break cross-run reproducibility."""
    import numpy as _np
    import zlib
    ctx = ctx or current_context()
    with _LOCK:
        c = _BITS_COUNTER.get(ctx, 0)
        _BITS_COUNTER[ctx] = c + 1
        seed_val = _CTX_SEED.get(ctx, _BASE_SEED)
    mix = zlib.crc32(repr((ctx.device_type, ctx.device_id,
                           seed_val)).encode()) & 0xFFFFFFFF
    return _np.array([mix ^ ((c >> 32) & 0xFFFFFFFF), c & 0xFFFFFFFF],
                     dtype=_np.uint32)


def split_key(ctx: Context = None):
    """Split the context's key; returns a fresh subkey for one op call.
    (Hot paths avoid this device op entirely via `next_key_bits`.)"""
    if _TRACE_STATE.stack:
        return _TRACE_STATE.stack[-1].next()
    ctx = ctx or current_context()
    with _LOCK:
        key = _ctx_key(ctx)
        new, sub = jax.random.split(key)
        _KEYS[ctx] = new
        return sub


def current_key(ctx: Context = None):
    ctx = ctx or current_context()
    with _LOCK:
        return _ctx_key(ctx)
