"""Executor — bound symbolic graph.

TPU-native re-design of ref: src/executor/graph_executor.{h,cc} +
python/mxnet/executor.py.

`GraphExecutor::Init`'s pass pipeline (InferShape → InferType →
PlanMemory → AttachOpExecs → bulking) collapses into two jitted XLA
executables: forward, and forward+vjp for backward.  The shared-memory
rebind trick BucketingModule relied on (`shared_buffer`) is subsumed by
the jit cache keyed on input shapes — each bucket shape compiles once and
XLA's buffer assignment shares what it can.
"""
from __future__ import annotations

from typing import Dict, List, Optional

import jax

from .base import MXNetError
from .ndarray.ndarray import NDArray
from . import ndarray as nd

__all__ = ["Executor"]


class Executor:
    def __init__(self, symbol, ctx, args, args_grad=None, grad_req="write"):
        self._symbol = symbol
        self._ctx = ctx
        self.arg_names = symbol.list_arguments()
        if isinstance(args, dict):
            self.arg_dict = dict(args)
        else:
            self.arg_dict = dict(zip(self.arg_names, args))
        missing = set(self.arg_names) - set(self.arg_dict)
        if missing:
            raise MXNetError("executor missing args: %s" % missing)
        if args_grad is None:
            self.grad_dict = {}
        elif isinstance(args_grad, dict):
            self.grad_dict = dict(args_grad)
        else:
            self.grad_dict = dict(zip(self.arg_names, args_grad))
        self.grad_req = grad_req if isinstance(grad_req, dict) else \
            {n: grad_req for n in self.arg_names}
        self.outputs: List[NDArray] = []
        self.aux_dict = {}
        self._fwd_jit = None
        self._vjp_fn = None

    # ------------------------------------------------------------------
    def _build_fwd(self):
        symbol = self._symbol
        names = self.arg_names

        def f(*arrs):
            from .symbol.symbol import _eval_symbol
            feed = dict(zip(names, arrs))
            out = _eval_symbol(symbol, feed, raw=True)
            if isinstance(out, (list, tuple)):
                return tuple(out)
            return (out,)
        return jax.jit(f)

    def forward(self, is_train=False, **kwargs):
        for k, v in kwargs.items():
            if k not in self.arg_dict:
                raise MXNetError("unknown input %r" % k)
            self.arg_dict[k]._data = v._data if isinstance(v, NDArray) \
                else nd.array(v)._data
        if self._fwd_jit is None:
            self._fwd_jit = self._build_fwd()
        arrs = [self.arg_dict[n]._data for n in self.arg_names]
        if is_train:
            outs, self._vjp_fn = jax.vjp(
                lambda *a: self._fwd_jit(*a), *arrs)
        else:
            outs = self._fwd_jit(*arrs)
            self._vjp_fn = None
        self.outputs = [NDArray(o, ctx=self._ctx) for o in outs]
        return self.outputs

    def backward(self, out_grads=None):
        if self._vjp_fn is None:
            raise MXNetError("backward called without forward(is_train=True)")
        import jax.numpy as jnp
        if out_grads is None:
            cots = tuple(jnp.ones(o.shape, o._data.dtype)
                         for o in self.outputs)
        else:
            if isinstance(out_grads, NDArray):
                out_grads = [out_grads]
            cots = tuple(g._data for g in out_grads)
        in_cots = self._vjp_fn(cots)
        for name, g in zip(self.arg_names, in_cots):
            req = self.grad_req.get(name, "null")
            if req == "null" or name not in self.grad_dict:
                continue
            tgt = self.grad_dict[name]
            if req == "add":
                tgt._data = tgt._data + g
            else:
                tgt._data = g

    def copy_params_from(self, arg_params, aux_params=None,
                         allow_extra_params=False):
        for name, arr in arg_params.items():
            if name in self.arg_dict:
                self.arg_dict[name]._data = arr._data
            elif not allow_extra_params:
                raise MXNetError("unknown param %r" % name)

    @property
    def output_dict(self):
        return dict(zip(self._symbol.list_outputs(), self.outputs))
