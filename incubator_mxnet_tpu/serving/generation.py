"""Generation serving: KV-cached decode with continuous batching
(ISSUE 14 tentpole).

The serving stack so far (engine → lanes → registry → breaker) is
one-shot: submit a tensor, get a tensor.  Autoregressive generation —
*the* million-user workload — only existed as `contrib/text/decode`'s
host loop, which re-runs the whole network per emitted token (O(n²)
compute, no KV cache).  `GenerationEngine` makes generation a
first-class serving workload under the repo's compile-time-
specialization doctrine: the executable set is CLOSED and warmed ahead
of traffic, and every piece of dynamic behavior — who is in the batch,
at what length, with what prompt — is expressed as DATA flowing
through fixed-shape executables, never as shapes that would retrace.

**Executables** (all AOT-warmed through `aot_cache.aot_jit`, recompiles
metered on `serve.traces` exactly like the one-shot engine):

1. ``prefill`` — one signature per power-of-two PROMPT bucket
   (`MXNET_GEN_BUCKETS`): encode the padded prompt, produce one slot's
   decode cache.  Exactness under padding is the model's contract
   (`init_cache`): variable-length RNN state freezing + attention
   masks whose pad weights underflow to exactly 0, so a bucketed
   prompt decodes token-identically to the unpadded forward (the
   greedy-parity oracle in tests).
2. ``decode_step`` — ONE executable specialized to the engine's
   (slot-count bucket, max_len bucket): a fixed (S, …) batch advances
   every slot one token.  Its KV/state buffers are DONATED between
   steps (`donate_argnums` + the PR 10 `expect_donated` audit at
   build, plus a runtime no-copy probe on the first steps — a backend
   that silently copies warns with the executable label and counts
   ``gen.donation_copy``).  Per-sequence state (cur position, last
   token, emitted tokens) lives in device arrays indexed by slot
   INSIDE the donated cache.
3. ``join`` — admit one prefilled request into a free slot: a one-hot
   masked update on every cache leaf (cache donated).  Joins and
   retires never reshape anything.

**Continuous batching.**  The decode loop advances the fixed-slot
batch step by step.  A sequence that finishes (EOS / token budget /
deadline) frees its slot at the step boundary, and queued requests
join immediately — no drain barrier.  Admission order is the PR 8
`_LaneQueue`: strict priority across lanes, EDF within one, per-lane
occupancy quotas and per-tenant quotas shed excess work with the
existing typed errors (`Shed`/`QueueFull`/`DeadlineExceeded`); a
born-expired or infeasible-deadline request (prefill EWMA says it
cannot emit a first token in time) is shed before touching the
device.  ``continuous=False`` degrades to drain batching (a new batch
only forms when every slot is free) — the A/B baseline
`bench.py generate` and `tools/check_decode.py` measure TTFT against.

**Streaming.**  `submit()` returns a `GenerationStream`: iterate it
for tokens as they are emitted (time-to-first-token and inter-token
latency land in the labeled percentile rings `gen.ttft_us` /
`gen.intertoken_us` split by lane), or call `.result()` for the final
token array.  `drain()`/`close()` resolve every stream exactly once.

**Observability.**  Spans `serve.prefill` / `serve.decode_step`,
`gen.*` counters, a slot-occupancy gauge (`gen.slots_live` ring +
flight-recorder events on every join/retire), and per-lane TTFT SLO
targets (`slo_targets()`) that `telemetry/slo.py`'s default generation
rules alert on.

Model contract (``models/seq2seq.py``, ``models/transformer.py``):

- ``init_cache(src, src_valid_len, max_len=, mem_len=)`` → dict of
  NDArray leaves, ALL slot-major (axis 0 = request), shapes a pure
  function of (prompt bucket, max_len, mem_len).
- ``decode_step(tok, pos, cache)`` → (next-token logits (B, V),
  updated cache).  One token per slot per call; position is data.
"""
from __future__ import annotations

import queue
import threading
import time
import weakref
from concurrent.futures import Future

import numpy as _np

from .. import config as _cfg
from .. import fault
from ..context import Context, current_context
from ..monitor import events
from ..telemetry import flightrec as _bb
from ..telemetry import reqtrace as _reqtrace
from ..telemetry import spans as _tele
from .engine import (DeadlineExceeded, EngineClosed, QueueFull, Shed,
                     _LaneQueue, _OverQuota, _parse_lane_quotas,
                     _parse_lanes)

__all__ = ["GenerationEngine", "GenerationStream",
           "project_generation_footprint"]

_END = object()          # stream sentinel: normal end


def _parse_prompt_buckets(spec, max_len):
    """Power-of-two prompt-length buckets (`MXNET_GEN_BUCKETS`): the
    closed signature set prefill is warmed over.  Empty = 8, 16, …
    up to max_len (always at least one bucket)."""
    if spec and isinstance(spec, (list, tuple, set, frozenset)):
        bs = sorted({int(s) for s in spec})
    elif spec:
        bs = sorted({int(s) for s in str(spec).split(",") if s.strip()})
    else:
        bs, b = [], 8
        while b < int(max_len):
            bs.append(b)
            b *= 2
        bs.append(int(max_len))
        bs = sorted(set(bs))
    if not bs or bs[0] < 1:
        raise ValueError("generation prompt buckets must be positive "
                         "ints, got %r" % (spec,))
    return tuple(bs)


def _pure_method(block, method, training=False):
    """`parallel.functional.functionalize` for an arbitrary block
    METHOD over pytree inputs: returns
    ``pure(params_dict, *ivals) -> jax pytree`` where every jax-array
    leaf of ``ivals`` crosses the seam wrapped as NDArray and every
    NDArray leaf of the result is unwrapped.  The param swap /
    autograd / RNG discipline is the same as `functionalize` — this is
    the seam `init_cache`/`decode_step` trace through."""
    import jax
    from .. import autograd as _ag
    from .. import random as _rnd
    from ..gluon.block import _STATE
    from ..ndarray.ndarray import NDArray
    pd = block.collect_params()
    params = list(pd.values())

    def _wrap(v):
        # jax leaves (incl. tracers) cross wrapped; python scalars
        # (max_len/mem_len attrs) pass through untouched
        return NDArray(v) if isinstance(v, jax.Array) else v

    def _unwrap(v):
        return v._data if isinstance(v, NDArray) else v

    def pure(pvals, *ivals):
        saved = []
        for p in params:
            ctx0 = next(iter(p._data))
            saved.append((p, ctx0, p._data[ctx0]))
            p._data[ctx0] = NDArray(pvals[p.name], ctx=ctx0)
        states = []
        prev_state, _STATE.active = _STATE.active, states
        prev_rec = _ag.set_recording(False)
        prev_train = _ag.set_training(training)
        # trace-local RNG: needs_rng ops (the fused RNN) split a key at
        # trace time; without a pushed holder that split leaks a tracer
        # into the global key state.  Inference is deterministic (no
        # dropout), so a constant key is correct — and constant-folds.
        holder = _rnd.KeyHolder(jax.random.PRNGKey(0))
        _rnd.push_trace_key(holder)
        try:
            nd_in = jax.tree_util.tree_map(_wrap, ivals)
            out = getattr(block, method)(*nd_in)
        finally:
            _rnd.pop_trace_key()
            _ag.set_training(prev_train)
            _ag.set_recording(prev_rec)
            _STATE.active = prev_state
            for p, ctx0, orig in saved:
                p._data[ctx0] = orig
        return jax.tree_util.tree_map(
            _unwrap, out, is_leaf=lambda v: isinstance(v, NDArray))

    return pure


def project_generation_footprint(block, slots, max_len, buckets,
                                 vocab_hint=None, temp_factor=None):
    """Projected per-device HBM bytes for GENERATION serving: param
    bytes + ``slots × kv_bytes_per_slot`` (the term one-shot admission
    has no analogue for — HBM now scales with CONCURRENT SEQUENCES,
    not just model size) + a temp-factor margin over the decode-step
    activations.  KV bytes come from `jax.eval_shape` over the
    model's own ``init_cache`` — a trace, never a compile.  Returns
    (total_bytes, detail) with the KV term broken out so an
    `AdmissionDenied` can NAME it."""
    import jax
    from .registry import _param_bytes
    if temp_factor is None:
        temp_factor = float(_cfg.get("MXNET_SERVE_HBM_TEMP_FACTOR"))
    pb = _param_bytes(block)
    mem_len = int(max(buckets))
    pure = _pure_method(block, "init_cache")
    pvals = {p.name: jax.ShapeDtypeStruct(v.shape, v.dtype)
             for p, v in ((p, p.data()._data)
                          for p in block.collect_params().values())}
    src = jax.ShapeDtypeStruct((1, mem_len), _np.int32)
    vl = jax.ShapeDtypeStruct((1,), _np.int32)
    cache = jax.eval_shape(lambda pv, s, v: pure(
        pv, s, v, int(max_len), mem_len), pvals, src, vl)
    kv_slot = sum(int(_np.prod(a.shape[1:]))
                  * _np.dtype(a.dtype).itemsize
                  for a in jax.tree_util.tree_leaves(cache))
    kv_total = int(slots) * kv_slot
    # decode activations are O(slots × vocab) for the logits row plus
    # the per-layer working set the temp factor covers.  The vocab is
    # DERIVED from the model's own decode_step output aval (another
    # eval_shape — still a trace) unless hinted; without it the
    # margin would be vacuously zero and admission would only learn
    # the working set at warmup-reconcile time, after the OOM-prone
    # first compile
    vocab = int(vocab_hint or 0)
    if not vocab:
        try:
            step = _pure_method(block, "decode_step")
            tok = jax.ShapeDtypeStruct((1,), _np.int32)
            logits, _ = jax.eval_shape(step, pvals, tok, vl, cache)
            vocab = int(logits.shape[-1])
        except Exception:       # noqa: BLE001 — degrade to KV-only
            pass
    act = int(slots) * max(vocab, 1) * 4
    total = int(pb + kv_total + temp_factor * act)
    return total, {"param_bytes": int(pb),
                   "kv_bytes_per_slot": int(kv_slot),
                   "slots": int(slots),
                   "kv_bytes": int(kv_total),
                   "max_len": int(max_len),
                   "mem_len": mem_len,
                   "temp_factor": float(temp_factor)}


class GenerationStream:
    """Streaming handle for one generation request.

    - Iterate for tokens as they are emitted (``for tok in stream``).
    - ``result(timeout)`` blocks for the FULL sequence (np.int32
      array) or raises the terminal error (DeadlineExceeded /
      EngineClosed / Shed).
    - ``future`` is the underlying `concurrent.futures.Future`
      (resolved exactly once by the engine's drain/close contract).
    """

    def __init__(self, lane, tenant):
        self.lane = lane
        self.tenant = tenant
        self.future = Future()
        self._q = queue.Queue()
        self._tokens = []
        self._t_first = None

    # -- engine side ---------------------------------------------------
    def _push(self, tok):
        self._tokens.append(int(tok))
        self._q.put(int(tok))

    def _finish(self, exc=None):
        """Resolve exactly once (idempotent — the close() flush may
        race a retire)."""
        if self.future.done():
            return False
        try:
            if exc is not None:
                self.future.set_exception(exc)
            else:
                self.future.set_result(
                    _np.asarray(self._tokens, _np.int32))
        except Exception:       # noqa: BLE001 — cancelled by caller
            events.incr("gen.cancelled")
        self._q.put(exc if exc is not None else _END)
        return True

    # -- caller side ---------------------------------------------------
    def __iter__(self):
        return self

    def __next__(self):
        item = self._q.get()
        if item is _END:
            raise StopIteration
        if isinstance(item, BaseException):
            raise item
        return item

    def tokens(self):
        """Tokens emitted so far (list copy, non-blocking)."""
        return list(self._tokens)

    def result(self, timeout=None):
        return self.future.result(timeout)

    def done(self):
        return self.future.done()


class _GenRequest:
    __slots__ = ("prompt", "max_new", "deadline", "lane", "tenant",
                 "stream", "t_enq", "tele", "future", "n", "acct",
                 "rec")

    def __init__(self, prompt, max_new, deadline, lane, tenant):
        self.prompt = prompt
        self.max_new = max_new
        self.t_enq = time.monotonic()
        self.deadline = None if deadline is None \
            else self.t_enq + float(deadline)
        self.lane = lane
        self.tenant = tenant
        self.stream = GenerationStream(lane, tenant)
        self.future = self.stream.future    # _LaneQueue/engine duck type
        self.n = 1
        self.acct = False       # queue/tenant accounting released once
        self.tele = _tele.current()
        self.rec = None         # reqtrace.Record (journal lifecycle)


class _Slot:
    __slots__ = ("req", "emitted", "t_join", "t_last")

    def __init__(self, req):
        self.req = req
        self.emitted = 0
        self.t_join = time.monotonic()
        self.t_last = None      # last token wall (inter-token meter)


class GenerationEngine:
    """KV-cached autoregressive decode with continuous batching over a
    fixed slot set.

    block: a model implementing ``init_cache``/``decode_step`` (the
        explicit-cache contract — `models.Seq2Seq`,
        `models.TransformerNMT`).  Parameters must be initialized.
    bos / eos: special token ids (decode starts from bos; an emitted
        eos retires the sequence).
    slots / max_len: the (slot-count bucket, max_len bucket) the ONE
        decode executable is specialized to (`MXNET_GEN_SLOTS`,
        `MXNET_GEN_MAX_LEN`).  max_len bounds prompt length AND
        emitted tokens per request.
    prompt_buckets: closed prompt-length bucket set
        (`MXNET_GEN_BUCKETS`; empty = powers of two up to max_len).
    continuous: True = continuous batching (join at step boundaries);
        False = drain batching (the measured baseline).

    Lifecycle: construct → ``warmup()`` → ``submit()`` traffic →
    ``drain()`` / ``close()``.
    """

    def __init__(self, block, bos, eos, ctx=None, slots=None,
                 max_len=None, prompt_buckets=None, queue_cap=None,
                 lanes=None, lane_quotas=None, tenant_quota=None,
                 continuous=True, cost_label=None, max_new_default=None):
        self._block = block
        for m in ("init_cache", "decode_step"):
            if not callable(getattr(block, m, None)):
                raise TypeError(
                    "generation needs a model with the explicit-cache "
                    "decode contract (missing %r) — see "
                    "models/seq2seq.py / models/transformer.py" % m)
        self._bos, self._eos = int(bos), int(eos)
        self._ctx = ctx if isinstance(ctx, Context) else (
            Context(*ctx) if ctx is not None else current_context())
        self._S = int(slots if slots is not None
                      else _cfg.get("MXNET_GEN_SLOTS"))
        self._L = int(max_len if max_len is not None
                      else _cfg.get("MXNET_GEN_MAX_LEN"))
        if self._S < 1 or self._L < 2:
            raise ValueError("need slots >= 1 and max_len >= 2")
        blk_max = getattr(block, "_max_length", None)
        if blk_max is not None and self._L > int(blk_max):
            raise ValueError(
                "max_len %d exceeds the model's positional table "
                "(max_length=%d)" % (self._L, int(blk_max)))
        self._buckets = _parse_prompt_buckets(
            prompt_buckets if prompt_buckets is not None
            else _cfg.get("MXNET_GEN_BUCKETS"), self._L)
        self._mem_len = int(self._buckets[-1])
        self._max_new_default = int(max_new_default or self._L)
        self._continuous = bool(continuous)
        self._label = str(cost_label or "serve.gen")
        self._journal = _reqtrace.journal(
            "gen",
            self._label.split(":", 1)[1]
            if ":" in self._label else self._label)

        cap = max(1, int(queue_cap if queue_cap is not None
                         else _cfg.get("MXNET_SERVE_QUEUE_CAP")))
        self._lanes = _parse_lanes(
            lanes if lanes is not None
            else _cfg.get("MXNET_SERVE_LANES"))
        self._lane_caps = _parse_lane_quotas(
            lane_quotas if lane_quotas is not None
            else _cfg.get("MXNET_SERVE_LANE_QUOTAS"), self._lanes, cap)
        self._q = _LaneQueue(cap, self._lanes, self._lane_caps)
        self._tenant_quota = int(
            tenant_quota if tenant_quota is not None
            else _cfg.get("MXNET_SERVE_TENANT_QUOTA"))
        self._tenant_q = {}

        self._lock = threading.Lock()
        self._work = threading.Event()  # submit → wake the idle loop
        from collections import deque
        self._lane_deadline_s = {}      # lane -> deque of rel deadlines
        self._deque_cls = deque
        self._slots = [None] * self._S  # host mirror: _Slot | None
        self._prefill_ewma = {}         # bucket -> prefill seconds
        self._step_ewma = None          # decode-step seconds
        self._steps = 0
        self._thread = None
        self._draining = False
        self._stop = False
        self._closed = False
        self._warm = False
        self._donation_checked = False

        # deferred-shape params (the LSTM flat vector before a first
        # forward): prime with one tiny teacher-forced forward so
        # extract_params sees concrete shapes
        try:
            from ..parallel.functional import extract_params
            extract_params(block)
        except Exception:               # noqa: BLE001
            from .. import nd
            src = nd.array(_np.full((1, int(self._buckets[0])),
                                    self._bos, _np.int32))
            tgt = nd.array(_np.full((1, 1), self._bos, _np.int32))
            block(src, tgt)
        self._build_executables()
        self._cache = None              # device cache (built on warmup
                                        # or first traffic)
        _bb.install_crash_hooks()

    # -- executable construction ---------------------------------------
    def _build_executables(self):
        import jax
        import jax.numpy as jnp
        from ..aot_cache import aot_jit
        from ..parallel.functional import extract_params
        block = self._block
        S, L = self._S, self._L
        eos = self._eos
        pure_init = _pure_method(block, "init_cache")
        pure_step = _pure_method(block, "decode_step")
        mem_len = self._mem_len
        max_len = self._L

        def prefill(params, src, valid):
            # trace-time side effect only — the recompile meter the
            # zero-recompile contract is asserted on (the same
            # serve.traces the one-shot engine meters)
            events.incr("serve.traces")
            return pure_init(params, src, valid, max_len, mem_len)

        def decode_step(params, cache):
            events.incr("serve.traces")
            tok, pos = cache["tok"], cache["pos"]
            logits, new_m = pure_step(params, tok, pos, cache["m"])
            nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            # the device-resident emitted-token record (ISSUE 14
            # contract: per-sequence state lives in device arrays
            # indexed by slot).  Host streaming is authoritative
            # today; this S×L int32 row is what device-side consumers
            # (batched end-of-sequence D2H, future sampling/beam
            # state) read without a per-step host hop
            oh = jax.nn.one_hot(pos, L, dtype=jnp.int32)
            out = cache["out"] * (1 - oh) + nxt[:, None] * oh
            return nxt, {
                "m": new_m, "tok": nxt,
                # clamp keeps dead slots' one-hot writes in range; a
                # LIVE slot never reaches the clamp (the host retires
                # at max_new <= max_len)
                "pos": jnp.minimum(pos + 1, L - 1).astype(jnp.int32),
                "out": out}

        def join(cache, row, slot):
            events.incr("serve.traces")
            keep = jnp.arange(S, dtype=jnp.int32) == slot

            def upd(c, r):
                m = keep.reshape((S,) + (1,) * (c.ndim - 1))
                return jnp.where(m, r.astype(c.dtype), c)

            m = jax.tree_util.tree_map(upd, cache["m"], row)
            bos = jnp.full((S,), self._bos, jnp.int32)
            zero = jnp.zeros((S,), jnp.int32)
            return {"m": m,
                    "tok": jnp.where(keep, bos, cache["tok"]),
                    "pos": jnp.where(keep, zero, cache["pos"]),
                    "out": jnp.where(keep[:, None],
                                     jnp.full((S, L), eos, jnp.int32),
                                     cache["out"])}

        # prefill: one signature per prompt bucket, AOT-warmed; decode
        # and join donate the cache — the PR 10 audit arms the
        # donation contract at build time, the runtime probe below
        # proves no silent copy on the live path
        self._prefill = aot_jit(prefill, label=self._label + ":prefill",
                                kind="serve")
        self._decode = aot_jit(decode_step, donate_argnums=(1,),
                               label=self._label + ":decode_step",
                               kind="serve", expect_donated=(1,))
        self._join = aot_jit(join, donate_argnums=(0,),
                             label=self._label + ":join",
                             kind="serve", expect_donated=(0,))
        dev = self._ctx.jax_device
        self._params = {n: jax.device_put(v, dev)
                        for n, v in extract_params(block).items()}

    def _init_cache_arrays(self):
        """The engine's base device cache: zeros of the decode
        signature (model leaves slot-major at S, plus the per-slot
        tok/pos/out state arrays).  Also the TERMINAL-failure reset:
        a decode/join executable that died mid-donation leaves deleted
        buffers behind — rebuilding here keeps the engine serviceable
        (running sequences were already failed by the caller)."""
        import jax
        import jax.numpy as jnp
        S, L = self._S, self._L
        pure = _pure_method(self._block, "init_cache")
        pvals = {n: jax.ShapeDtypeStruct(v.shape, v.dtype)
                 for n, v in self._params.items()}
        src = jax.ShapeDtypeStruct((1, self._mem_len), _np.int32)
        vl = jax.ShapeDtypeStruct((1,), _np.int32)
        row = jax.eval_shape(lambda pv, s, v: pure(
            pv, s, v, self._L, self._mem_len), pvals, src, vl)
        dev = self._ctx.jax_device
        m = jax.tree_util.tree_map(
            lambda a: jax.device_put(
                jnp.zeros((S,) + tuple(a.shape[1:]), a.dtype), dev),
            row)
        self._cache = {
            "m": m,
            "tok": jax.device_put(
                jnp.full((S,), self._eos, jnp.int32), dev),
            "pos": jax.device_put(jnp.zeros((S,), jnp.int32), dev),
            "out": jax.device_put(
                jnp.full((S, L), self._eos, jnp.int32), dev)}

    def kv_cache_bytes(self):
        """Total device bytes held by the slot cache (the KV term of
        generation admission), and the per-slot share."""
        import jax
        if self._cache is None:
            self._init_cache_arrays()
        total = sum(int(_np.prod(a.shape))
                    * _np.dtype(a.dtype).itemsize
                    for a in jax.tree_util.tree_leaves(self._cache))
        return {"total": total, "per_slot": total // self._S,
                "slots": self._S}

    # -- warmup ---------------------------------------------------------
    def warmup(self):
        """Pre-compile (or AOT-deserialize) the WHOLE executable set:
        one prefill per prompt bucket, the join, and the (S, max_len)
        decode step — after it `serve.traces` stays flat under any mix
        of prompt lengths and batch membership (the zero-recompile
        contract).  Returns a summary dict."""
        import jax
        t0 = time.monotonic()
        per_bucket = {}
        try:
            # same deterministic OOM drill + forensic catch as the
            # one-shot engine's warmup: the KV slot cache allocated
            # here is exactly the residency an OOM dump must attribute
            fault.maybe_raise(
                "serve.oom", 0, msg="RESOURCE_EXHAUSTED: out of "
                "memory while warming %r (injected)" % self._label)
            if self._cache is None:
                self._init_cache_arrays()
            dev = self._ctx.jax_device
            for b in self._buckets:
                src = jax.device_put(
                    _np.full((1, b), self._bos, _np.int32), dev)
                vl = jax.device_put(_np.full((1,), b, _np.int32), dev)
                tb = time.monotonic()
                row = self._prefill(self._params, src, vl)
                jax.block_until_ready(
                    jax.tree_util.tree_leaves(row)[0])
                per_bucket[b] = round(time.monotonic() - tb, 4)
            self._cache = self._join(self._cache, row,
                                     jax.device_put(_np.int32(0), dev))
            nxt, self._cache = self._decode(self._params, self._cache)
            _np.asarray(nxt)            # sync
        except Exception as e:
            from ..telemetry import memwatch as _mw
            _mw.guard_oom("gen.warmup", e)
            raise
        self._warm = True
        events.incr("gen.warmups")
        # probe row from the warmup's own measured walls (ISSUE 19
        # satellite: probe writers outside bench/) — autotune evidence
        # for the prompt-bucket ladder, durable when history is on
        try:
            from ..compile import autotune as _autotune
            if per_bucket:
                _autotune.note_probe(
                    "gen_buckets", self._label,
                    ",".join(str(b) for b in self._buckets),
                    sum(per_bucket.values()) * 1e6,
                    source="gen.warmup", slots=self._S)
        except Exception:               # noqa: BLE001
            pass
        return {"prompt_buckets": list(self._buckets),
                "slots": self._S, "max_len": self._L,
                "wall_s": round(time.monotonic() - t0, 3),
                "bucket_wall_s": per_bucket,
                "kv_cache": self.kv_cache_bytes(),
                "traces": events.get("serve.traces")}

    # -- submission ------------------------------------------------------
    def _shed_mark(self, lane, tenant, reason, deadline=False):
        events.incr("gen.rejected")
        if deadline:
            events.incr("gen.deadline_expired")
        events.incr("gen.shed")
        events.incr("gen.shed", labels={"lane": lane or "-",
                                        "reason": reason})
        if tenant is not None:
            events.incr("gen.shed", labels={"tenant": tenant})

    def _shed(self, lane, tenant, reason, msg):
        self._shed_mark(lane, tenant, reason)
        raise Shed(msg)

    def submit(self, prompt, max_new_tokens=None, deadline=None,
               lane=None, tenant=None):
        """Enqueue one generation request.

        prompt: 1-D int token sequence (list/np array), length ≤ the
            largest prompt bucket.
        max_new_tokens: emitted-token budget (default: the engine's
            max_len bucket).
        deadline: seconds from now for the FULL generation; expiry —
            even mid-decode — resolves the stream with
            DeadlineExceeded and frees the slot.
        Returns a `GenerationStream`.  Raises QueueFull / Shed /
        DeadlineExceeded / EngineClosed synchronously.
        """
        if fault.should_fire("serve.enqueue"):
            events.incr("gen.rejected")
            raise QueueFull("injected enqueue fault (serve.enqueue)")
        prompt = _np.asarray(prompt, _np.int32).reshape(-1)
        if prompt.size < 1:
            raise ValueError("empty prompt")
        if prompt.size > self._buckets[-1]:
            raise ValueError(
                "prompt of %d tokens exceeds the largest prompt "
                "bucket (%d); the bucket set is closed by design "
                "(MXNET_GEN_BUCKETS)" % (prompt.size, self._buckets[-1]))
        max_new = int(max_new_tokens if max_new_tokens is not None
                      else self._max_new_default)
        if not 0 < max_new <= self._L:
            raise ValueError("max_new_tokens must be in [1, %d] (the "
                             "max_len bucket)" % self._L)
        lane = self._lanes[0] if lane is None else str(lane)
        if lane not in self._lane_caps:
            raise ValueError("unknown lane %r (engine lanes: %s)"
                             % (lane, ",".join(self._lanes)))
        tenant = str(tenant) if tenant is not None else None
        req = _GenRequest(prompt, max_new, deadline, lane, tenant)
        req.rec = self._journal.start(req.t_enq, lane, tenant)
        if req.deadline is not None and req.deadline <= req.t_enq:
            self._shed_mark(lane, tenant, "deadline", deadline=True)
            exc = DeadlineExceeded("deadline is not in the future")
            rec, req.rec = req.rec, None
            self._journal.retire(rec, exc=exc)
            raise exc
        try:
            with self._lock:
                if self._closed or self._draining:
                    events.incr("gen.rejected")
                    raise EngineClosed("engine is draining/closed")
                if tenant is not None and self._tenant_quota > 0 and \
                        self._tenant_q.get(tenant, 0) >= \
                        self._tenant_quota:
                    self._shed(
                        lane, tenant, "tenant_quota",
                        "tenant %r over quota (%d queued, cap %d)"
                        % (tenant, self._tenant_q.get(tenant, 0),
                           self._tenant_quota))
                try:
                    self._q.put_nowait(req)
                except _OverQuota as oq:
                    self._shed(
                        lane, tenant, "lane_quota",
                        "lane %r over quota (%d queued, cap %d); "
                        "excess work is shed under overload — see "
                        "MXNET_SERVE_LANE_QUOTAS"
                        % (oq.lane, oq.depth, oq.cap))
                except queue.Full:
                    events.incr("gen.rejected")
                    raise QueueFull(
                        "generation queue at capacity (%d); retry "
                        "later or raise MXNET_SERVE_QUEUE_CAP"
                        % self._q.maxsize)
                if tenant is not None:
                    self._tenant_q[tenant] = \
                        self._tenant_q.get(tenant, 0) + 1
                if deadline is not None:
                    dq = self._lane_deadline_s.get(lane)
                    if dq is None:
                        dq = self._lane_deadline_s[lane] = \
                            self._deque_cls(maxlen=256)
                    dq.append(float(deadline))
        except (Shed, QueueFull, EngineClosed) as e:
            # synchronous refusals never reach _resolve — this is
            # their journal retire point
            rec, req.rec = req.rec, None
            self._journal.retire(rec, exc=e)
            raise
        events.incr("gen.requests")
        events.incr("gen.requests", labels={"lane": lane})
        if tenant is not None:
            events.incr("gen.requests", labels={"tenant": tenant})
        self._ensure_loop()
        self._work.set()
        return req.stream

    def _ensure_loop(self):
        if self._thread is not None and self._thread.is_alive():
            return
        with self._lock:
            if self._thread is None or not self._thread.is_alive():
                self._thread = threading.Thread(
                    target=GenerationEngine._decode_loop,
                    args=(weakref.ref(self),), daemon=True,
                    name="GenDecodeLoop")
                self._thread.start()

    # -- decode loop -----------------------------------------------------
    @staticmethod
    def _decode_loop(ref):
        """Weakref-held loop (the dispatcher pattern): an engine
        dropped without close() lets this thread retire at its next
        poll instead of pinning the params + KV cache forever."""
        eng0 = ref()
        if eng0 is None:
            return
        wake = weakref.ref(eng0._work)  # the Event may outlive checks
        del eng0                        # but must not pin the engine
        while True:
            eng = ref()
            if eng is None:
                return
            try:
                state = eng._tick()
                if state == "closed":
                    # a request this thread popped/joined after
                    # close()'s own sweep must still resolve — the
                    # flush is idempotent, so both sides may run it
                    eng._flush_leftovers()
                    return
                idle = state == "idle"
            except Exception as e:      # noqa: BLE001 — the loop must
                import logging          # survive anything; slots are
                logging.getLogger(__name__).exception(
                    "generation decode loop error (recovered)")
                events.incr("gen.loop_errors")
                _bb.record("fault", "gen.loop",
                           error=type(e).__name__)
                _bb.crash_dump("gen.loop", e)
                idle = True
            finally:
                del eng
            if idle:
                # block on the submit-side event, not a poll: TTFT
                # must not pay an idle-loop sleep quantum.  The
                # strong ref lapsed above, so an abandoned engine
                # still GCs (wait() wakes on timeout and re-derefs).
                ev = wake()
                if ev is not None:
                    ev.wait(0.05)
                    ev.clear()

    def _live(self):
        return [i for i, s in enumerate(self._slots) if s is not None]

    def _free(self):
        return [i for i, s in enumerate(self._slots) if s is None]

    def _tick(self):
        """One scheduler round: admit into free slots at this step
        boundary, then advance the decode batch one token.  Returns
        'ran' | 'idle' | 'closed'.  (The loop terminates only through
        the _stop check here; close() flips it and then both sides
        run the idempotent leftover flush.)"""
        if self._stop:
            return "closed"
        self._admit()
        live = self._live()
        if not live:
            return "idle"
        self._step(live)
        return "ran"

    def _admit(self):
        """Fill free slots from the lane queue.  Continuous mode joins
        whenever a slot is free; drain mode only when EVERY slot is
        free (the baseline the TTFT comparison measures against)."""
        free = self._free()
        if not free:
            return
        if not self._continuous and len(free) != self._S:
            return
        while free:
            try:
                req = self._q.get_nowait()
            except queue.Empty:
                return
            if req.rec is not None:     # queue phase ends at the pop
                req.rec.t_collect = time.monotonic()
            slot = free.pop(0)
            if not self._admit_one(req, slot):
                free.insert(0, slot)    # shed — the slot stays free

    def _admit_one(self, req, slot):
        """Prefill + join one request into `slot`.  Returns True when
        the slot was taken.  Sheds born-expired and
        infeasible-deadline requests (prefill EWMA + one step says no
        first token can land in time) with the typed errors."""
        if self._closed or self._stop:
            # a close() raced the pop: resolve, never strand — the
            # accounting flag keeps this exactly-once against the
            # close-side flush
            self._resolve(req, exc=EngineClosed(
                "engine closed before dispatch"))
            return False
        now = time.monotonic()
        bucket = self._bucket_for(req.prompt.size)
        if req.rec is not None:
            req.rec.bucket = bucket
        if req.deadline is not None:
            est = self._prefill_ewma.get(bucket, 0.0) \
                + (self._step_ewma or 0.0)
            if now + est * 1.25 > req.deadline:
                self._shed_mark(req.lane, req.tenant, "deadline",
                                deadline=True)
                self._resolve(req, exc=DeadlineExceeded(
                    "deadline %s before the first token could land "
                    "(prefill estimate %.3fs)"
                    % ("expired" if now > req.deadline
                       else "infeasible", est)))
                return False
        if not req.stream.future.set_running_or_notify_cancel():
            events.incr("gen.cancelled")
            rec, req.rec = req.rec, None
            self._journal.retire(rec, status="cancelled",
                                 reason="cancelled while queued")
            self._retire_accounting(req)
            return False
        import jax
        dev = self._ctx.jax_device
        padded = _np.zeros((1, bucket), _np.int32)
        padded[0, :req.prompt.size] = req.prompt
        t0 = time.monotonic()
        span = _tele.span("serve.prefill", parent=req.tele)
        span.start()
        try:
            fault.maybe_raise("serve.infer", step=self._steps)
            row = self._prefill(
                self._params, jax.device_put(padded, dev),
                jax.device_put(
                    _np.array([req.prompt.size], _np.int32), dev))
        except Exception as e:          # noqa: BLE001 — prefill does
            span.stop()                 # not donate: only THIS request
            events.incr("gen.failed")   # fails, the engine survives
            self._resolve(req, exc=e)
            return False
        if self._cache is None:
            self._init_cache_arrays()
        try:
            self._cache = self._join(
                self._cache, row,
                jax.device_put(_np.int32(slot), dev))
        except Exception as e:          # noqa: BLE001 — join DONATES
            span.stop()                 # the cache: running slots lose
            events.incr("gen.failed")   # their state too — fail them,
            self._resolve(req, exc=e)   # rebuild, stay serviceable
            for i in self._live():
                self._retire(i, exc=EngineClosed(
                    "slot state lost to a failed join (%s)"
                    % type(e).__name__))
            self._init_cache_arrays()
            _bb.record("gen", "join_failed", error=type(e).__name__)
            return False
        span.stop()
        if req.rec is not None:         # prefill phase ends here
            req.rec.t_exec = time.monotonic()
        dt = time.monotonic() - t0
        prev = self._prefill_ewma.get(bucket)
        self._prefill_ewma[bucket] = dt if prev is None \
            else 0.3 * dt + 0.7 * prev
        events.observe_time("gen.prefill_us", dt)
        events.incr("gen.prefills")
        events.incr("gen.joins")
        self._slots[slot] = _Slot(req)
        self._occupancy_event("join", slot, req)
        return True

    def _bucket_for(self, n):
        for b in self._buckets:
            if b >= n:
                return b
        return self._buckets[-1]

    def _step(self, live):
        """Advance every live slot one token; stream, then retire
        finished sequences at this boundary.  A terminal decode
        failure fails every LIVE sequence (typed, exactly once) and
        rebuilds the cache — donated buffers cannot be retried."""
        import jax
        from ..parallel.resilience import retry_transient
        t0 = time.monotonic()
        with _tele.span("serve.decode_step"):
            # injected transient faults fire HOST-side (before the
            # executable), so the retry budget is donation-safe;
            # serve.decode_slow stalls a step (deadline/straggler
            # tests) without failing it
            fault.maybe_slow("serve.decode_slow", step=self._steps)
            retry_transient(
                lambda: fault.maybe_raise("serve.infer",
                                          step=self._steps),
                what="gen.decode_step", event="gen.retries")
            old_probe = None
            if not self._donation_checked:
                old_probe = jax.tree_util.tree_leaves(
                    self._cache["m"])[0]
            try:
                nxt, self._cache = self._decode(self._params,
                                                self._cache)
                toks = _np.asarray(nxt)     # (S,) host sync
            except Exception as e:          # noqa: BLE001 — terminal:
                events.incr("gen.failed")   # the donated cache may be
                for i in list(live):        # gone; fail live slots +
                    self._retire(i, exc=e)  # rebuild
                self._init_cache_arrays()
                _bb.record("gen", "step_failed",
                           error=type(e).__name__)
                return
        if old_probe is not None:
            self._donation_checked = True
            if not old_probe.is_deleted():
                # the build-time audit passed (argnums ARE donated)
                # but the backend copied anyway — say so by label
                events.incr("gen.donation_copy")
                import warnings
                warnings.warn(
                    "executable %r: donated KV cache was COPIED, not "
                    "aliased — per-step HBM traffic doubles "
                    "(backend ignores donation)"
                    % (self._label + ":decode_step"))
        dt = time.monotonic() - t0
        self._step_ewma = dt if self._step_ewma is None \
            else 0.3 * dt + 0.7 * self._step_ewma
        self._steps += 1
        events.observe_time("gen.step_us", dt)
        events.incr("gen.steps")
        events.incr("gen.tokens", len(live))
        events.observe("gen.slots_live", len(live))
        now = time.monotonic()
        for i in live:
            slot = self._slots[i]
            if slot is None:    # a racing close() swept this slot —
                continue        # its stream is already resolved
            req = slot.req
            tok = int(toks[i])
            slot.emitted += 1
            if slot.t_last is None:
                events.observe_time("gen.ttft_us", now - req.t_enq)
                events.observe("gen.ttft_us",
                               int((now - req.t_enq) * 1e6),
                               labels={"lane": req.lane})
            else:
                events.observe_time("gen.intertoken_us",
                                    now - slot.t_last)
                events.observe("gen.intertoken_us",
                               int((now - slot.t_last) * 1e6),
                               labels={"lane": req.lane})
            slot.t_last = now
            req.stream._push(tok)
            if req.deadline is not None and now > req.deadline:
                # mid-decode deadline: shed, free the slot THIS step
                self._shed_mark(req.lane, req.tenant, "deadline",
                                deadline=True)
                self._retire(i, exc=DeadlineExceeded(
                    "deadline expired after %d token(s)"
                    % slot.emitted))
            elif tok == self._eos or slot.emitted >= req.max_new:
                self._retire(i)

    def _occupancy_event(self, kind, slot, req):
        live = len(self._live())
        _bb.record("gen", kind, slot=int(slot), lane=req.lane,
                   live=live, free=self._S - live, step=self._steps)

    def _retire(self, i, exc=None):
        with self._lock:        # close()'s sweep may race this clear;
            slot = self._slots[i]   # one winner takes the request
            self._slots[i] = None
        if slot is None:
            return
        req = slot.req
        if req.rec is not None:         # decode phase ends here
            req.rec.t_fin = time.monotonic()
        self._resolve(req, exc=exc, accepted=True)
        events.incr("gen.retires")
        e2e = time.monotonic() - req.t_enq
        events.observe_time("gen.e2e_us", e2e)
        events.observe("gen.e2e_us", int(e2e * 1e6),
                       labels={"lane": req.lane})
        self._occupancy_event("retire", i, req)

    def _retire_accounting(self, req):
        """Queue-slot + tenant-hold release — exactly once per ACCEPTED
        request.  The per-request flag (flipped under the lock) makes
        the release idempotent: a close() sweeping slots can race the
        decode thread's own retire, and whoever loses must be a no-op,
        not a second task_done()."""
        with self._lock:
            if req.acct:
                return
            req.acct = True
            if req.tenant is not None:
                n = self._tenant_q.get(req.tenant, 0) - 1
                if n > 0:
                    self._tenant_q[req.tenant] = n
                else:
                    self._tenant_q.pop(req.tenant, None)
        self._q.task_done()

    def _resolve(self, req, exc=None, accepted=True):
        req.stream._finish(exc)
        rec, req.rec = req.rec, None    # single journal retire point
        if rec is not None:             # for accepted requests (swap
            self._journal.retire(rec, exc=exc)  # keeps re-runs no-op)
        if accepted:
            self._retire_accounting(req)

    # -- lifecycle -------------------------------------------------------
    def drain(self, timeout=60.0):
        """Stop intake and wait for every accepted request to resolve
        (queued requests still get generated).  True when fully
        drained in time."""
        self._draining = True
        deadline = time.monotonic() + float(timeout)
        self._ensure_loop()
        with self._q.all_tasks_done:
            while self._q.unfinished_tasks:
                rem = deadline - time.monotonic()
                if rem <= 0:
                    return False
                if self._thread is None or \
                        not self._thread.is_alive():
                    break
                self._q.all_tasks_done.wait(min(rem, 0.1))
        return self._q.unfinished_tasks == 0

    def _flush_leftovers(self):
        """Resolve everything still queued or slotted with
        EngineClosed.  Idempotent (the per-request accounting flag +
        future-done guard), and safe to run from BOTH the closing
        thread and the decode loop's exit path — a drain-timeout
        close cannot strand a request the loop popped after the
        close-side sweep, and the two sweeps cannot double-release."""
        leftovers = []
        with self._lock:
            while True:
                try:
                    leftovers.append(self._q.get_nowait())
                except queue.Empty:
                    break
            for i, s in enumerate(self._slots):
                if s is not None:
                    self._slots[i] = None
                    leftovers.append(s.req)
        for req in leftovers:
            self._resolve(req, exc=EngineClosed(
                "engine closed before completion"))

    def close(self, timeout=60.0):
        """drain() + stop the decode loop + resolve any leftover
        stream (EngineClosed) exactly once.  Idempotent."""
        t_end = time.monotonic() + float(timeout)
        self.drain(timeout)
        self._stop = True
        self._work.set()
        t = self._thread
        joined = True
        if t is not None and t.is_alive():
            t.join(max(0.1, t_end - time.monotonic()))
            joined = not t.is_alive()
        with self._lock:
            self._closed = True
        self._flush_leftovers()
        return joined

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __del__(self):
        self._draining = True
        self._stop = True
        self._closed = True

    # -- introspection ---------------------------------------------------
    def slo_targets(self):
        """{lane: tightest relative deadline seconds among recent
        ACCEPTED deadlined requests} — the TTFT p99 targets the
        default generation SLO rules derive from."""
        with self._lock:
            return {lane: min(dq)
                    for lane, dq in self._lane_deadline_s.items()
                    if dq}

    def slo_lane_quotas(self):
        cap = float(self._q.maxsize)
        return {lane: (1.0 if c is None else c / cap)
                for lane, c in self._lane_caps.items()}

    def install_slo_rules(self, **kw):
        """Register the default generation SLO rules (per-lane TTFT
        p99 vs the observed deadline targets + shed burn rates)."""
        from ..telemetry import slo as _slo
        return _slo.install_default_generation_rules(engine=self, **kw)

    def stats(self):
        with self._lock:
            tenants = dict(self._tenant_q)
        live = self._live()
        return {"counters": events.snapshot("gen."),
                "latency": events.latency_snapshot("gen."),
                "labeled": events.labeled_latency_snapshot("gen."),
                "slots": self._S, "max_len": self._L,
                "prompt_buckets": list(self._buckets),
                "slots_live": len(live),
                "queue_depth": self._q.qsize(),
                "lanes": {"order": list(self._lanes),
                          "depths": self._q.lane_depths(),
                          "caps": dict(self._lane_caps)},
                "tenants_queued": tenants,
                "continuous": self._continuous,
                "steps": self._steps,
                "warm": self._warm}
