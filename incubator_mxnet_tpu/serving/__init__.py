"""Inference serving subsystem (ISSUE 3): shape-bucketed dynamic
batching over AOT-warmed executables — the deploy-side counterpart of
the resilient trainer (PR 1) and the async device feed (PR 2).

    from incubator_mxnet_tpu import serving
    eng = net.inference_engine(ctx=mx.gpu())       # or serving.InferenceEngine(net)
    eng.warmup(example_shape=(3, 224, 224), wire_dtype="uint8")
    fut = eng.submit(img)                          # concurrent: returns a Future
    probs = fut.result()
    eng.close()

See docs/serving.md for lifecycle, knob tuning and the counter
reference.
"""
from .engine import (InferenceEngine, QueueFull, DeadlineExceeded,
                     EngineClosed, serve_counters)

__all__ = ["InferenceEngine", "QueueFull", "DeadlineExceeded",
           "EngineClosed", "serve_counters"]
