"""Inference serving subsystem (ISSUE 3 + ISSUE 8): shape-bucketed
dynamic batching over AOT-warmed executables, hardened for sustained
multi-tenant overload — the deploy-side counterpart of the resilient
trainer (PR 1) and the async device feed (PR 2).

    from incubator_mxnet_tpu import serving
    eng = net.inference_engine(ctx=mx.gpu())       # or serving.InferenceEngine(net)
    eng.warmup(example_shape=(3, 224, 224), wire_dtype="uint8")
    fut = eng.submit(img, lane="high", tenant="acme")  # concurrent Future
    probs = fut.result()
    eng.close()

Many models on one device pool go through the ModelRegistry (HBM
admission control from the cost registry, per-model circuit
breakers)::

    reg = serving.ModelRegistry(devices=[mx.gpu(0), mx.gpu(1)])
    reg.register("ranker", net, example_shape=(256,))
    reg.warmup("ranker")
    fut = reg.submit("ranker", x, lane="high", deadline=0.05)

Int8 tenants ride the same contract at ~1/4 the admission footprint
(ISSUE 15; see docs/quantization.md)::

    net, report = serving.quantize_for_serving(net, calib_batches)
    reg.register_quantized("ranker8", net2, calib_batches,
                           example_shape=(256,))

See docs/serving.md for lifecycle, admission math, the lane/shed
decision table and the counter reference.
"""
from .engine import (InferenceEngine, QueueFull, DeadlineExceeded,
                     EngineClosed, Shed, serve_counters)
from .registry import (ModelRegistry, AdmissionDenied, CircuitOpen,
                       UnknownModel, RegistrationTimeout,
                       project_footprint)
from .controlplane import FleetSupervisor
from .generation import (GenerationEngine, GenerationStream,
                         project_generation_footprint)
from .quantize import quantize_for_serving, param_bytes_by_dtype

__all__ = ["InferenceEngine", "QueueFull", "DeadlineExceeded",
           "EngineClosed", "Shed", "serve_counters",
           "ModelRegistry", "AdmissionDenied", "CircuitOpen",
           "UnknownModel", "RegistrationTimeout",
           "project_footprint", "FleetSupervisor",
           "GenerationEngine", "GenerationStream",
           "project_generation_footprint",
           "quantize_for_serving", "param_bytes_by_dtype"]
