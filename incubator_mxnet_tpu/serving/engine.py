"""Inference serving engine: shape-bucketed dynamic batching over
AOT-warmed executables (ISSUE 3 tentpole).

The ROADMAP north star is "heavy traffic from millions of users", and
the serving-side analogue of the training recompilation problem is the
RECOMPILATION CLIFF: eager `block(x)` compiles one executable per input
batch size, so organic traffic (every batch size from 1 to N) triggers
a fresh trace+compile on this backend's remote compiler — seconds to
minutes of tail latency per new shape (PROFILE.md; the hazard TVM
arxiv 1802.04799 and the XLA fusion analysis arxiv 2301.13062 both
center on).  The engine closes the executable set instead:

1. **Shape buckets.**  Requests are coalesced by a background
   dispatcher into power-of-two batch buckets (`MXNET_SERVE_BUCKETS`,
   default 1,2,4,...,`MXNET_SERVE_MAX_BATCH`) and padded up to the
   bucket size, so the set of compiled executables is CLOSED and
   known in advance.
2. **AOT warm.**  `warmup()` pre-compiles every (device, bucket)
   executable before traffic, through `aot_cache.aot_jit` — with
   `MXNET_AOT_CACHE_DIR` set, a restarted serving host deserializes
   the whole executable set from disk instead of recompiling
   (sub-second vs 75-260 s per executable on the remote-compile
   backend).  `serve.traces` counts executable traces; it stays FLAT
   after warmup under mixed-size traffic — the zero-recompile
   contract `bench.py serve` asserts.
3. **Concurrency.**  Callers `submit()` single examples (or
   `submit_batch()` small batches) and get `concurrent.futures`
   futures; a dispatcher thread coalesces, and with multiple replica
   devices each device gets its own single-thread worker so buckets
   execute concurrently across replicas (in-flight bounded at the
   replica count).  The request queue
   is BOUNDED (`MXNET_SERVE_QUEUE_CAP`): submits beyond it fail fast
   with `QueueFull` (backpressure, not unbounded memory).  Each
   request may carry a deadline; a request that expires waiting is
   resolved with `DeadlineExceeded` and never wastes device time.
4. **Robustness (PR 1 patterns).**  `drain()`/`close()` complete
   in-flight work and join the dispatcher within a timeout; every
   outstanding future is resolved.  `handle_sigterm=True` installs the
   flag-only preemption handler (resilience.py pattern): on SIGTERM
   the engine stops intake, finishes the queue, and retires.  Fault
   sites `serve.enqueue` / `serve.infer` (fault.py) inject rejection
   and transient executable failure; infer faults are retried on the
   standard `retry_transient` budget.
5. **Observability.**  `serve.*` counters on `monitor.events`
   (`queue_us`, `infer_us`, `e2e_us`, `batch_fill`, `pad_waste`,
   `rejected`, `batches`, `requests`, `traces`, ...) plus per-request
   latency samples for `events.percentiles("serve.e2e_us")` — tails,
   not means, are the serving SLO.
6. **Overload hardening (ISSUE 8).**  Requests carry a priority
   `lane` (`MXNET_SERVE_LANES`, highest first) and optionally a
   `tenant`.  The dispatcher drains lanes in strict priority order,
   earliest-deadline-first within one.  Under sustained overload the
   engine SHEDS instead of queueing toward uniform collapse: a lane
   past its quota share of the queue (`MXNET_SERVE_LANE_QUOTAS`), a
   tenant past `MXNET_SERVE_TENANT_QUOTA`, or a request whose
   deadline is already unmeetable gets the typed `Shed` /
   `DeadlineExceeded` error synchronously (`serve.shed`, labeled by
   lane/tenant/reason), and over-deadline work found at dispatch time
   is reaped without device time.  `serve.e2e_us`/`serve.requests`
   additionally split by lane and tenant through the labeled
   percentile rings (`events.labeled_latency_snapshot("serve.")`),
   so /metrics and black-box dumps answer WHOSE p99 blew out.

Multi-device replica dispatch: pass `devices=[ctx, ...]` (or build via
`ShardedTrainer.serve()` / `parallel.mesh.replica_contexts`) and the
dispatcher round-robins buckets across per-device parameter replicas.
The round-robin is HEALTH-AWARE (the serving twin of the elastic
training mesh, ISSUE 7): `MXNET_SERVE_REPLICA_FAILS` consecutive
terminal dispatch failures on one replica mark it unhealthy
(`serve.replica_unhealthy` counter + a flight-recorder event naming
the device) and traffic routes around it; after
`MXNET_SERVE_REPLICA_COOLDOWN_S` ONE probe batch is routed back —
success re-admits it (`serve.replica_recovered`), failure restarts the
cooldown.  With every replica unhealthy the engine fails OPEN (soonest
cooldown first): degraded service beats refused service.

The uint8 wire contract matches PR 2's training path: with
`HybridBlock.set_input_transform(normalize_transform(...))` installed,
clients submit raw uint8 pixels, the engine ships them as-is (4x fewer
wire bytes) and the normalize+cast is traced INTO each bucket
executable.
"""
from __future__ import annotations

import heapq
import itertools
import queue
from collections import deque
import signal
import threading
import time
import weakref
from concurrent.futures import Future

import numpy as _np

from .. import config as _cfg
from .. import fault
from ..base import MXNetError
from ..context import Context, current_context
from ..monitor import events
from ..telemetry import flightrec as _bb
from ..telemetry import reqtrace as _reqtrace
from ..telemetry import spans as _tele

__all__ = ["InferenceEngine", "QueueFull", "DeadlineExceeded",
           "EngineClosed", "Shed", "serve_counters"]


class QueueFull(MXNetError):
    """The bounded request queue is at capacity — backpressure: the
    caller should retry later or shed load upstream."""


class DeadlineExceeded(MXNetError):
    """The request's deadline expired before it reached the device."""


class EngineClosed(MXNetError):
    """submit() after drain()/close() (or during SIGTERM drain)."""


class Shed(MXNetError):
    """The request was refused by overload policy — its lane is over
    quota, its tenant is over quota, or its deadline was already
    unmeetable (ISSUE 8).  Unlike `QueueFull` (transient backpressure:
    retry soon), a shed means the engine is deliberately degrading
    low-priority intake to protect higher lanes — back off or
    re-submit on a higher lane."""


def serve_counters():
    """Snapshot of the `serve.*` counters (µs totals / counts)."""
    return events.snapshot("serve.")


class _Request:
    __slots__ = ("data", "n", "future", "t_enq", "deadline", "single",
                 "tele", "lane", "tenant", "rec")

    def __init__(self, data, n, future, deadline, single, lane=None,
                 tenant=None):
        self.data = data
        self.n = n
        self.future = future
        self.t_enq = time.monotonic()
        self.deadline = None if deadline is None \
            else self.t_enq + float(deadline)
        self.single = single
        self.lane = lane
        self.tenant = tenant
        # the submitter's span context (telemetry): the dispatcher's
        # serve.dispatch/serve.infer spans parent onto it, so a
        # request's submit→dispatch→infer chain shares one trace id
        # across the three threads it crosses
        self.tele = _tele.current()
        # lifecycle journal record (ISSUE 19): phase stamps land on it
        # as the request crosses queue→coalesce→dispatch→infer→join;
        # None when journaling is off (stamps guard on it)
        self.rec = None


class _OverQuota(Exception):
    """Internal: a put would push its lane past quota (the engine
    translates it into the public typed `Shed`)."""

    def __init__(self, lane, depth, cap):
        super().__init__(lane, depth, cap)
        self.lane, self.depth, self.cap = lane, depth, cap


class _LaneQueue:
    """Priority-lane request queue with `queue.Queue`'s accounting
    surface (the subset the engine uses: put_nowait/get/get_nowait/
    task_done/qsize/maxsize/unfinished_tasks/all_tasks_done), so the
    drain()/close() exactly-once contract carries over unchanged.

    SHARED INFRASTRUCTURE: `serving.generation.GenerationEngine`
    (ISSUE 14) admits decode-slot joins through this same queue (and
    `_parse_lanes`/`_parse_lane_quotas`/`_OverQuota`) — one admission
    policy, one set of typed errors, two engines.  Changes here have
    two consumers.

    Ordering (ISSUE 8): strict priority ACROSS lanes (the dispatcher
    never serves a lower lane while a higher one has work) and
    earliest-deadline-first WITHIN a lane (no-deadline requests keep
    FIFO order after every deadlined one — a request that asked for a
    latency bound outranks one that didn't).  Each lane may carry an
    occupancy cap (its quota share of `maxsize`): a put beyond it
    raises `_OverQuota` so over-quota low-priority work is SHED at
    submit time instead of queueing the whole engine toward uniform
    deadline collapse."""

    def __init__(self, maxsize, lanes, lane_caps):
        self.maxsize = int(maxsize)
        self._lanes = tuple(lanes)
        self._caps = dict(lane_caps)        # lane -> cap (None = none)
        self._heaps = {ln: [] for ln in self._lanes}
        self._seq = itertools.count()       # FIFO tiebreak within EDF
        self._mutex = threading.Lock()
        self._not_empty = threading.Condition(self._mutex)
        self.all_tasks_done = threading.Condition(self._mutex)
        self.unfinished_tasks = 0
        self._size = 0

    def put_nowait(self, req):
        with self._mutex:
            # lane quota BEFORE global fullness: the engine's
            # displacement path relies on queue.Full implying the
            # request's own lane still has quota headroom (so the
            # post-eviction re-put cannot fail)
            h = self._heaps[req.lane]
            cap = self._caps.get(req.lane)
            if cap is not None and len(h) >= cap:
                raise _OverQuota(req.lane, len(h), cap)
            if self._size >= self.maxsize:
                raise queue.Full
            key = (req.deadline if req.deadline is not None
                   else float("inf"), next(self._seq))
            heapq.heappush(h, (key, req))
            self._size += 1
            self.unfinished_tasks += 1
            self._not_empty.notify()

    def _pop_locked(self):
        for lane in self._lanes:            # highest priority first
            h = self._heaps[lane]
            if h:
                _, req = heapq.heappop(h)
                self._size -= 1
                return req
        raise queue.Empty

    def get_nowait(self):
        with self._mutex:
            return self._pop_locked()

    def evict_lowest(self, below):
        """Remove and return the LAST-to-run request (latest deadline,
        newest arrival) of the lowest-priority non-empty lane strictly
        below `below`, or None when every lower lane is empty.  The
        engine uses this to DISPLACE low work when a higher-lane
        submit meets a full queue — without it, lower-lane backlog
        could hold every slot and the top lane would see QueueFull
        under exactly the overload the lanes exist for.  The victim
        stays counted in unfinished_tasks: the caller sheds it through
        the normal resolve path (task_done fires there)."""
        try:
            start = self._lanes.index(below) + 1
        except ValueError:
            return None
        with self._mutex:
            for lane in reversed(self._lanes[start:]):
                h = self._heaps[lane]
                if h:
                    item = max(h)       # latest deadline, newest seq
                    h.remove(item)
                    heapq.heapify(h)
                    self._size -= 1
                    return item[1]
        return None

    def get(self, timeout=None):
        # single-consumer contract (the dispatcher): one wait then one
        # pop attempt; a timeout/spurious wakeup surfaces queue.Empty,
        # which every call site already loops on
        with self._not_empty:
            if not self._size:
                self._not_empty.wait(timeout)
            return self._pop_locked()

    def task_done(self):
        with self.all_tasks_done:
            n = self.unfinished_tasks - 1
            if n < 0:
                raise ValueError("task_done() called too many times")
            self.unfinished_tasks = n
            if n == 0:
                self.all_tasks_done.notify_all()

    def qsize(self):
        with self._mutex:
            return self._size

    def lane_depths(self):
        with self._mutex:
            return {ln: len(h) for ln, h in self._heaps.items()}


def _parse_lanes(spec):
    if spec and isinstance(spec, (list, tuple)):
        names = [str(s).strip() for s in spec if str(s).strip()]
    else:
        names = [s.strip() for s in str(spec or "").split(",")
                 if s.strip()]
    out = []
    for n in names:                         # dedupe, order-preserving
        if n not in out:
            out.append(n)
    if not out:
        raise ValueError("serve lanes spec is empty: %r" % (spec,))
    return tuple(out)


def _parse_lane_quotas(spec, lanes, cap):
    """lane -> occupancy cap (requests) from the quota-fraction spec;
    the top lane defaults to the full queue (None = no lane cap), and
    an explicit fraction >= 1 also means no extra bound.  Fraction
    parsing (incl. the auto ladder) is shared with the SLO layer's
    default shed budgets — config.serve_lane_quota_fractions — so
    what the engine enforces and what the alerts budget cannot
    drift."""
    fracs = _cfg.serve_lane_quota_fractions(spec, len(lanes))
    caps = {}
    for lane, f in zip(lanes, fracs):
        caps[lane] = None if f >= 1.0 else max(1, int(f * cap))
    return caps


def _parse_buckets(spec, max_batch):
    if spec and isinstance(spec, (list, tuple, set, frozenset)):
        bs = sorted({int(s) for s in spec})
    elif spec:
        bs = sorted({int(s) for s in str(spec).split(",") if s.strip()})
    else:
        bs, b = [], 1
        while b < max_batch:
            bs.append(b)
            b *= 2
        bs.append(int(max_batch))
        bs = sorted(set(bs))
    if not bs or bs[0] < 1:
        raise ValueError("serve buckets must be positive ints, got %r"
                         % (spec,))
    return tuple(bs)


class InferenceEngine:
    """Concurrent inference over a Block with bucketed dynamic batching.

    block: a (Hybrid)Block with initialized parameters.  Its
        `set_input_transform` (if any) is traced into every bucket
        executable — the uint8-on-wire path.
    ctx / devices: one Context, or a list for replica round-robin
        (default: the current context).
    buckets / max_batch / max_wait_us / queue_cap: see the
        MXNET_SERVE_* knobs in config.py (arguments override).
    example_shape / wire_dtype: per-example shape (no batch dim) and
        the dtype clients put on the wire; needed by `warmup()` before
        the first request has been seen.

    Lifecycle: construct → `warmup()` → submit traffic → `drain()` /
    `close()`.  The dispatcher thread starts lazily on first submit.
    """

    def __init__(self, block, ctx=None, devices=None, buckets=None,
                 max_batch=None, max_wait_us=None, queue_cap=None,
                 example_shape=None, wire_dtype=None,
                 handle_sigterm=False, lanes=None, lane_quotas=None,
                 tenant_quota=None, cost_label=None, version=None):
        from ..parallel.functional import functionalize
        if devices is None:
            devices = [ctx or current_context()]
        elif ctx is not None:
            raise ValueError("pass ctx= or devices=, not both")
        if not devices:
            raise ValueError("need at least one serving device")
        self._block = block
        self._ctxs = [d if isinstance(d, Context) else Context(*d)
                      for d in devices]
        max_batch = int(max_batch if max_batch is not None
                        else _cfg.get("MXNET_SERVE_MAX_BATCH"))
        self._buckets = _parse_buckets(
            buckets if buckets is not None
            else _cfg.get("MXNET_SERVE_BUCKETS"), max_batch)
        self._max_wait = (int(max_wait_us if max_wait_us is not None
                              else _cfg.get("MXNET_SERVE_MAX_WAIT_US"))
                          / 1e6)
        cap = max(1, int(queue_cap if queue_cap is not None
                         else _cfg.get("MXNET_SERVE_QUEUE_CAP")))
        # priority lanes (ISSUE 8): strict priority across, EDF within;
        # submits default to the TOP lane so single-lane callers keep
        # the pre-lane behavior (quota 1.0 on the top lane = the plain
        # bounded queue)
        self._lanes = _parse_lanes(
            lanes if lanes is not None else _cfg.get("MXNET_SERVE_LANES"))
        self._lane_caps = _parse_lane_quotas(
            lane_quotas if lane_quotas is not None
            else _cfg.get("MXNET_SERVE_LANE_QUOTAS"),
            self._lanes, cap)
        self._q = _LaneQueue(cap, self._lanes, self._lane_caps)
        self._tenant_quota = int(
            tenant_quota if tenant_quota is not None
            else _cfg.get("MXNET_SERVE_TENANT_QUOTA"))
        self._tenant_q = {}         # tenant -> currently-queued count
        self._cost_label = str(cost_label or "serve.infer")
        # version tag (ISSUE 16): labels the serve.requests/e2e_us/
        # shed splits so canary traffic is attributable; None = no
        # labeled children (single-version engines add no labelsets)
        self._version = str(version) if version is not None else None
        # per-request lifecycle journal (ISSUE 19): bounded ring +
        # tail-exemplar promotion; the model tag is the cost label's
        # model part (serve.infer:<model>) so exemplars join the cost
        # registry's attribution
        self._journal = _reqtrace.journal(
            "serve",
            self._cost_label.split(":", 1)[1]
            if ":" in self._cost_label else self._cost_label,
            version=self._version)
        # model.bad_version taint: >0 stalls every batch by this many
        # seconds and sign-flips outputs (deterministic degradation)
        self._degrade_s = 0.0
        self._example_shape = (tuple(example_shape)
                               if example_shape is not None else None)
        self._wire_dtype = (str(_np.dtype(wire_dtype))
                            if wire_dtype is not None else None)

        self._pure = functionalize(block, training=False)
        self._infer = self._make_infer()
        self._param_src = None      # block whose params serve (set by
                                    # refresh_params_from on promote)
        self._param_remap = None    # promoted-name -> serving-name
                                    # (auto-prefix drift)
        self._dev_params = None     # list of {name: jax.Array} per ctx
        try:
            self.refresh_params()
        except Exception:
            # deferred-shape params (model_zoo nets before a first
            # forward): resolved lazily from the first concrete batch
            # in _run (shape inference needs an input signature)
            self._dev_params = None

        self._lock = threading.Lock()       # submit/lifecycle state
        self._exec_lock = threading.Lock()  # trace/execute (warmup vs
                                            # dispatcher share the block)
        # RELATIVE deadlines recently observed per lane (bounded
        # rolling windows): the SLO targets (ISSUE 12) — what callers
        # actually asked of a lane is the honest p99 bound, not a
        # knob someone forgot to set.  A WINDOW, not an all-time min:
        # one misconfigured client's 1ms outlier must age out, not
        # poison the lane's derived p99 rule until process restart
        self._lane_deadline_s = {}  # lane -> deque of recent deadlines
        self._thread = None
        self._carry = None          # request pulled but not yet batched
        self._svc_ewma = {}         # bucket -> EWMA batch service s
                                    # (deadline feasibility at dispatch)
        self._rr = 0
        self._n_batches = 0
        self._dev_batches = [0] * len(self._ctxs)
        self._n_inflight = 0
        # replica health (round-robin routes around a failing device)
        self._max_fails = int(_cfg.get("MXNET_SERVE_REPLICA_FAILS"))
        self._cooldown = float(
            _cfg.get("MXNET_SERVE_REPLICA_COOLDOWN_S"))
        self._fail_streak = [0] * len(self._ctxs)
        self._unhealthy_until = [0.0] * len(self._ctxs)  # 0 = healthy
        if len(self._ctxs) > 1:
            # replica overlap: one single-thread worker per device so
            # device k+1 executes while device k is still busy; the
            # semaphore bounds total in-flight batches at the replica
            # count (a pool backlog would reintroduce the unbounded
            # memory the bounded queue exists to prevent)
            from concurrent.futures import ThreadPoolExecutor
            self._pools = [ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="ServeReplica%d" % i)
                for i in range(len(self._ctxs))]
            self._inflight = threading.Semaphore(len(self._ctxs))
        else:
            self._pools = None
            self._inflight = None
        self._draining = False
        self._stop = False
        self._closed = False
        self._warm = False
        self._prev_sigterm = None
        if handle_sigterm:
            self._install_sigterm()
        # a serving host is exactly the process black-box dumps exist
        # for: arm the uncaught-exception/SIGUSR2 triggers (idempotent)
        _bb.install_crash_hooks()

    # -- executable construction ---------------------------------------
    def _make_infer(self):
        from ..aot_cache import aot_jit
        from ..ndarray.ndarray import NDArray
        pure = self._pure
        block = self._block

        def infer(params, x):
            # trace-time side effect ONLY: a jit-cache hit never runs
            # this python body, so the counter is the recompile meter
            # the zero-recompile-after-warmup contract is asserted on
            events.incr("serve.traces")
            nd_in = (NDArray(x),)
            tr = getattr(block, "_apply_input_transform", None)
            if tr is not None:
                # same seam as training (PR 2): uint8 wire → on-device
                # normalize/cast, fused into this bucket's executable
                nd_in = tr(nd_in)
            out, _states = pure(params, *nd_in)
            return out

        # each (device, bucket) signature becomes one cost-registry row
        # under the engine's cost label (default serve.infer; the
        # ModelRegistry passes serve.infer:<model> so admission can
        # find THIS model's measured footprint) — the per-bucket
        # FLOPs/HBM attribution the blackbox dump reports
        return aot_jit(infer, label=self._cost_label, kind="serve")

    def refresh_params(self):
        """(Re-)replicate the block's current parameters onto every
        serving device (call after the block was retrained/updated).
        After a `refresh_params_from` promote, the promoted block is
        the parameter source — a later refresh must keep serving the
        promoted weights, not silently revert to the original's."""
        import jax
        from ..parallel.functional import extract_params
        base = extract_params(self._param_src if self._param_src
                              is not None else self._block)
        if self._param_remap:
            base = {self._param_remap.get(n, n): v
                    for n, v in base.items()}
        self._dev_params = [
            {n: jax.device_put(v, c.jax_device)
             for n, v in base.items()}
            for c in self._ctxs]

    def refresh_params_from(self, block, version=None):
        """Promote-by-weight-swap (ISSUE 16): serve `block`'s
        parameters through THIS engine's already-warmed executables.
        The parameter trees must match — same names, same shapes; or
        (gluon auto-prefixing gives separately-built copies of the
        SAME architecture fresh ``dense<N>_*`` names) same
        registration order of shapes, in which case params map
        positionally onto the serving names.  The executables were
        traced against the original signature, so an architecturally
        different version needs a fresh engine, not a swap.
        Optionally re-tags the engine's version label."""
        from ..parallel.functional import extract_params
        new = extract_params(block)
        cur = extract_params(self._param_src if self._param_src
                             is not None else self._block)
        remap = None
        if set(new) != set(cur):
            # collect_params order is registration order: identical
            # architectures enumerate identically even when the name
            # prefixes drifted
            if len(new) != len(cur):
                raise ValueError(
                    "parameter tree mismatch: promote needs an "
                    "identical tree (%d params vs %d serving) — "
                    "architecturally different versions need a fresh "
                    "engine" % (len(new), len(cur)))
            remap = dict(zip(new, cur))
            cur_by_new = {n: cur[remap[n]] for n in new}
        else:
            cur_by_new = cur
        for n in new:
            if tuple(new[n].shape) != tuple(cur_by_new[n].shape):
                raise ValueError(
                    "parameter %r shape %r != serving shape %r — the "
                    "warmed executables serve ONE signature"
                    % (n, tuple(new[n].shape),
                       tuple(cur_by_new[n].shape)))
        self._param_src = block
        self._param_remap = remap
        self.refresh_params()
        if version is not None:
            self._version = str(version)
            self._journal.version = self._version
        events.incr("serve.param_swaps")

    def set_version(self, version):
        """Re-tag the version label on this engine's serve.* splits
        (promotes re-point the primary's label at the new version)."""
        self._version = str(version) if version is not None else None
        self._journal.version = self._version

    def degrade(self, stall_s):
        """Taint this engine (model.bad_version fault site): every
        batch stalls `stall_s` seconds and outputs are sign-flipped —
        deterministic degradation the canary SLO rules must catch.
        Test/chaos hook; 0 restores healthy behavior."""
        self._degrade_s = max(0.0, float(stall_s))

    # -- signal / preemption (PR 1 pattern) ----------------------------
    def _install_sigterm(self):
        ref = weakref.ref(self)         # the process-global handler
        state = {}                      # must not pin the engine (same
                                        # GC contract as the dispatcher)

        def _on_sigterm(signum, frame):
            eng = ref()
            if eng is not None:
                # flag only (signal-safe): the dispatcher notices,
                # stops intake, completes queued work, and retires
                eng._draining = True
                events.incr("serve.preempted")
                return
            # engine collected without close(): restore the previous
            # handler and re-deliver, so the process keeps honoring
            # preemption instead of silently swallowing SIGTERM
            try:
                signal.signal(signal.SIGTERM,
                              state.get("prev") or signal.SIG_DFL)
                signal.raise_signal(signal.SIGTERM)
            except Exception:           # noqa: BLE001
                pass
        try:
            self._prev_sigterm = signal.signal(signal.SIGTERM,
                                               _on_sigterm)
            state["prev"] = self._prev_sigterm
        except ValueError:          # not the main thread
            self._prev_sigterm = None

    def uninstall_sigterm(self):
        if self._prev_sigterm is not None:
            try:
                signal.signal(signal.SIGTERM, self._prev_sigterm)
            except ValueError:
                pass
            self._prev_sigterm = None

    def request_shutdown(self):
        """Programmatic SIGTERM equivalent: stop intake, finish queued
        work in the background (pair with `close()` to join)."""
        self._draining = True
        events.incr("serve.preempted")

    # -- submission ----------------------------------------------------
    def _host_array(self, x):
        from ..ndarray.ndarray import NDArray
        if isinstance(x, NDArray):
            return x.asnumpy()
        return _np.asarray(x)

    def _check_example(self, shape, dtype):
        # shape AND wire dtype are the executable signature: accepting
        # a wrong-dtype request would silently trace a NEW executable
        # (the recompilation cliff this engine exists to close) and a
        # mixed-dtype coalesced batch would promote via np.concatenate.
        # Locked: two racing first-ever submits must agree on ONE
        # signature (the loser gets the error, not the dispatcher).
        dtype = str(_np.dtype(dtype))
        with self._lock:
            if self._example_shape is None:
                self._example_shape = tuple(shape)
                self._wire_dtype = dtype
                return
            if tuple(shape) != self._example_shape:
                raise ValueError(
                    "request example shape %r != engine example shape "
                    "%r (one executable set serves ONE signature; "
                    "build a second engine for a second signature)"
                    % (tuple(shape), self._example_shape))
            if self._wire_dtype is None:
                self._wire_dtype = dtype
            elif dtype != self._wire_dtype:
                raise ValueError(
                    "request wire dtype %s != engine wire dtype %s "
                    "(dtype is part of the warmed executable "
                    "signature; convert client-side)"
                    % (dtype, self._wire_dtype))

    def submit(self, x, deadline=None, lane=None, tenant=None):
        """Enqueue ONE example (no batch dim).  Returns a Future whose
        result is the model output for this example (batch dim
        stripped), an NDArray on the executing device.  `deadline` is
        seconds from now; expiry resolves the future with
        DeadlineExceeded.  `lane` picks the priority lane (default:
        the top lane); `tenant` tags the request for per-tenant quotas
        and the labeled serve.* splits.  Raises QueueFull / Shed /
        EngineClosed synchronously."""
        arr = self._host_array(x)
        return self._submit(arr[None], deadline, single=True,
                            lane=lane, tenant=tenant)

    def submit_batch(self, x, deadline=None, lane=None, tenant=None):
        """Enqueue a small batch (leading batch dim, size ≤ the largest
        bucket).  The batch is dispatched as one unit (never split), so
        it shares one future."""
        arr = self._host_array(x)
        if arr.ndim < 1 or arr.shape[0] < 1:
            raise ValueError("submit_batch needs a leading batch dim")
        if arr.shape[0] > self._buckets[-1]:
            raise ValueError(
                "batch of %d exceeds the largest bucket (%d); chunk it "
                "client-side (the bucket set is closed by design)"
                % (arr.shape[0], self._buckets[-1]))
        return self._submit(arr, deadline, single=False,
                            lane=lane, tenant=tenant)

    def _shed_mark(self, lane, tenant, reason, deadline=False):
        """The shed counter block — ONE definition for every shed path
        (quota sheds, born-expired, dispatch-time expiry,
        displacement), so the aggregate + lane/reason + tenant splits
        cannot drift apart."""
        events.incr("serve.rejected")
        if deadline:
            events.incr("serve.deadline_expired")
        events.incr("serve.shed")
        events.incr("serve.shed", labels={"lane": lane or "-",
                                          "reason": reason})
        if tenant is not None:
            events.incr("serve.shed", labels={"tenant": tenant})
        if self._version is not None:
            # per-version split (ISSUE 16): canary attribution — the
            # version-labeled shed burn is what the supervisor's
            # rollback rules read
            events.incr("serve.shed", labels={"version": self._version})

    def _shed(self, lane, tenant, reason, msg):
        self._shed_mark(lane, tenant, reason)
        raise Shed(msg)

    def _submit(self, arr, deadline, single, lane=None, tenant=None):
        if fault.should_fire("serve.enqueue"):
            events.incr("serve.rejected")
            raise QueueFull("injected enqueue fault (serve.enqueue)")
        self._check_example(arr.shape[1:], arr.dtype)
        lane = self._lanes[0] if lane is None else str(lane)
        if lane not in self._lane_caps:
            raise ValueError("unknown lane %r (engine lanes: %s)"
                             % (lane, ",".join(self._lanes)))
        tenant = str(tenant) if tenant is not None else None
        fut = Future()
        req = _Request(arr, arr.shape[0], fut, deadline, single,
                       lane=lane, tenant=tenant)
        req.rec = self._journal.start(req.t_enq, lane, tenant)
        if req.rec is not None:
            req.rec.n = req.n
        if req.deadline is not None and req.deadline <= req.t_enq:
            # born expired: queueing it could only burn queue slots on
            # work that is already lost — shed, deadline-typed
            self._shed_mark(lane, tenant, "deadline", deadline=True)
            exc = DeadlineExceeded("deadline is not in the future")
            self._journal.retire(req.rec, exc=exc)
            raise exc
        # closed-check + enqueue are ATOMIC against close()'s final
        # flush (which sets _closed then drains the queue under the
        # same lock): a put that wins the race lands BEFORE the flush
        # and is resolved by it — no future is ever stranded.  The
        # tenant-quota hold increments under the SAME lock, and
        # _retire's decrement is the single release point — counts
        # can't leak or double-release across the shed/expiry paths.
        try:
            self._submit_locked(req, deadline, lane, tenant)
        except MXNetError as e:
            # synchronous refusals (quota sheds / QueueFull / closed)
            # never reach _finish — this is their journal retire point
            # (terminal records always promote; the whole wall lands
            # in the queue phase, the budget phase of a refusal)
            rec, req.rec = req.rec, None
            self._journal.retire(rec, exc=e)
            raise
        self._ensure_dispatcher()
        return fut

    def _submit_locked(self, req, deadline, lane, tenant):
        with self._lock:
            if self._closed or self._draining:
                events.incr("serve.rejected")
                raise EngineClosed("engine is draining/closed")
            if tenant is not None and self._tenant_quota > 0 and \
                    self._tenant_q.get(tenant, 0) >= self._tenant_quota:
                self._shed(lane, tenant, "tenant_quota",
                           "tenant %r over quota (%d queued, cap %d); "
                           "back off or raise MXNET_SERVE_TENANT_QUOTA"
                           % (tenant, self._tenant_q.get(tenant, 0),
                              self._tenant_quota))
            victim = None
            try:
                self._q.put_nowait(req)
            except _OverQuota as oq:
                self._shed(lane, tenant, "lane_quota",
                           "lane %r over quota (%d queued, cap %d); "
                           "excess low-priority work is shed under "
                           "overload — see MXNET_SERVE_LANE_QUOTAS"
                           % (oq.lane, oq.depth, oq.cap))
            except queue.Full:
                # priority displacement: a higher-lane submit meeting
                # a full queue evicts the newest lowest-lane request
                # (which is shed, typed) instead of being rejected —
                # otherwise lower-lane backlog whose quotas sum past
                # 1.0 would hold every slot and the TOP lane would see
                # QueueFull under exactly the overload lanes exist for
                victim = self._q.evict_lowest(below=lane)
                if victim is None:
                    events.incr("serve.rejected")
                    raise QueueFull(
                        "serve queue at capacity (%d requests); retry "
                        "later or raise MXNET_SERVE_QUEUE_CAP"
                        % self._q.maxsize)
                # the eviction freed a slot and this lane was under
                # its own quota (the first put raised Full, not
                # _OverQuota), so the re-put cannot fail
                self._q.put_nowait(req)
            if tenant is not None:
                self._tenant_q[tenant] = \
                    self._tenant_q.get(tenant, 0) + 1
            if deadline is not None:
                # ACCEPTED requests only (shed paths raised above):
                # a quota-shed client's deadline never became work
                # this lane owed.  Same lock as the enqueue — one
                # deque append per deadlined submit
                dq = self._lane_deadline_s.get(lane)
                if dq is None:
                    dq = self._lane_deadline_s[lane] = \
                        deque(maxlen=256)
                dq.append(float(deadline))
        if victim is not None:          # outside the lock: _finish →
            self._shed_mark(victim.lane, victim.tenant, "displaced")
            self._finish(victim, exc=Shed(  # _retire re-takes it
                "displaced by %r-lane traffic under overload "
                "(queue full); back off or escalate lanes" % lane))

    def _ensure_dispatcher(self):
        if self._thread is not None and self._thread.is_alive():
            return
        with self._lock:
            if self._thread is None or not self._thread.is_alive():
                self._thread = threading.Thread(
                    target=InferenceEngine._dispatch_loop,
                    args=(weakref.ref(self),), daemon=True,
                    name="ServeDispatcher")
                self._thread.start()

    # -- dispatcher ----------------------------------------------------
    @staticmethod
    def _dispatch_loop(ref):
        """Holds the engine only through a WEAKREF between iterations
        (the DeviceFeed._run pattern): an engine dropped without
        close() becomes unreachable, the GC fires __del__ (stop
        flags), and this thread retires at its next poll — a
        bound-method target would pin the engine (and its per-device
        parameter replicas) for process lifetime on exactly the
        long-lived hosts that rebuild engines per model refresh."""
        while True:
            eng = ref()
            if eng is None:
                return
            try:
                reqs = eng._collect()
                if reqs is None:
                    return
                if reqs:                # [] = idle poll: release the
                    eng._execute(reqs)  # strong ref and re-resolve
            except Exception as e:      # noqa: BLE001 — the dispatcher
                # must survive ANYTHING (a dead dispatcher strands every
                # queued future); _execute resolves its own requests, so
                # whatever escaped here had none in hand
                import logging
                logging.getLogger(__name__).exception(
                    "serve dispatcher error (recovered)")
                events.incr("serve.dispatcher_errors")
                # the backstop firing means the engine survived
                # something it shouldn't have seen — leave the forensic
                # file while the evidence (ring + counters) is fresh
                _bb.record("fault", "serve.dispatcher",
                           error=type(e).__name__)
                _bb.crash_dump("serve.dispatcher", e)
                time.sleep(0.01)
            finally:
                del eng

    def _retire(self, req):
        """Return an accepted request's queue slot (task_done) and
        release its tenant-quota hold — the single decrement point,
        reached exactly once per accepted request (via _finish or the
        cancel path), so tenant counts cannot leak across shed storms
        or drain."""
        if req.tenant is not None:
            with self._lock:
                n = self._tenant_q.get(req.tenant, 0) - 1
                if n > 0:
                    self._tenant_q[req.tenant] = n
                else:
                    self._tenant_q.pop(req.tenant, None)
        self._q.task_done()

    def _finish(self, req, result=None, exc=None):
        """Resolve a request's future (result or exception) and retire
        its queue slot — tolerant of caller-side cancel()/double
        resolution (a cancelled future raises InvalidStateError on
        set_*; that must never kill the dispatcher or skew task_done
        accounting)."""
        try:
            if exc is not None:
                req.future.set_exception(exc)
            else:
                req.future.set_result(result)
        except Exception:               # noqa: BLE001 — cancelled/done
            events.incr("serve.cancelled")
        self._retire(req)
        # the single journal-retire point for every ACCEPTED request
        # (refusals retire in _submit, cancels in _execute): phase
        # math + tail-promotion happen here, off the submit path
        rec, req.rec = req.rec, None
        if rec is not None:
            self._journal.retire(rec, exc=exc)

    def _collect(self):
        """Coalesce queued requests into one bucket's worth: pull
        greedily while the queue is non-empty, wait up to max_wait for
        fill once it runs dry, stop at the largest bucket.  Returns the
        request list, or None when the dispatcher should retire."""
        max_b = self._buckets[-1]
        reqs, total = [], 0
        edl = None              # earliest deadline among collected reqs
        with self._lock:        # carry handoff races close()'s flush
            carry, self._carry = self._carry, None
        if carry is not None:
            reqs.append(carry)
            total = carry.n
            edl = carry.deadline
        t_first = time.monotonic() if reqs else None
        while total < max_b:
            if self._stop:
                break
            try:
                item = self._q.get_nowait()
            except queue.Empty:
                if reqs:
                    now = time.monotonic()
                    rem = self._max_wait - (now - t_first)
                    if edl is not None:
                        # a collected request is about to expire: stop
                        # filling and dispatch (or reap) it promptly
                        # instead of padding the wait to max_wait
                        rem = min(rem, edl - now)
                    if rem <= 0:
                        break
                    try:
                        item = self._q.get(timeout=min(rem, 0.05))
                    except queue.Empty:
                        continue
                else:
                    if self._draining:
                        return None     # intake stopped + queue empty
                    try:                # idle poll (watches stop flags)
                        item = self._q.get(timeout=0.05)
                    except queue.Empty:
                        # surface to the outer loop so the dispatcher's
                        # strong engine ref lapses between idle polls
                        # (abandonment/GC liveness)
                        return []
            if item.rec is not None:    # end of queue-wait: the
                item.rec.t_collect = time.monotonic()   # coalesce
            if item.deadline is not None and \
                    time.monotonic() > item.deadline:   # phase starts
                self._expire(item)
                continue
            if total + item.n > max_b:
                with self._lock:
                    self._carry = item  # next batch starts with it
                break
            reqs.append(item)
            total += item.n
            if item.deadline is not None:
                edl = item.deadline if edl is None \
                    else min(edl, item.deadline)
            if t_first is None:
                t_first = time.monotonic()
        return reqs if reqs else None

    def _expire(self, req):
        # over-deadline work found at dispatch time is SHED (typed
        # error, never device time) — under overload this is what keeps
        # a backed-up lane from dragging every deadline down with it
        self._shed_mark(req.lane, req.tenant, "deadline",
                        deadline=True)
        self._finish(req, exc=DeadlineExceeded(
            "request expired after %.3fs in queue"
            % (time.monotonic() - req.t_enq)))

    def _bucket_for(self, n):
        for b in self._buckets:
            if b >= n:
                return b
        return self._buckets[-1]

    #: headroom multiplier on the EWMA service estimate in the
    #: dispatch-time feasibility check: the estimate is a mean, the
    #: deadline is a bound — without margin, requests dispatched at the
    #: feasibility edge land just past their deadline whenever the
    #: actual service time comes in above the mean
    _SVC_MARGIN = 1.25

    def _svc_estimate(self, bucket):
        """EWMA batch service seconds for `bucket`.  When this bucket
        hasn't run yet, scale the NEAREST known bucket's EWMA by the
        size ratio — judging a size-1 batch by the 32-wide bucket's
        wall would spuriously shed small requests that had time to
        spare.  0 cold: feasibility shedding only engages once real
        service times exist."""
        with self._lock:
            est = self._svc_ewma.get(bucket)
            if est is None and self._svc_ewma:
                near = min(self._svc_ewma,
                           key=lambda b: abs(b - bucket))
                est = self._svc_ewma[near] * (bucket / float(near))
            return (est or 0.0) * self._SVC_MARGIN

    def _execute(self, reqs):
        # deadline-AWARE dispatch (ISSUE 8): a request is shed not only
        # when its deadline already passed, but when it CANNOT make it
        # — now + the estimated batch service time (per-bucket EWMA)
        # past the deadline means dispatching it would burn device time
        # to deliver a result the caller has already written off.
        # Two passes: reap the already-expired FIRST, then judge
        # feasibility against the service time of the batch that will
        # ACTUALLY run — 31 stale requests must not doom the 1 fresh
        # one by inflating the bucket estimate
        now = time.monotonic()
        fresh = []
        for r in reqs:
            if r.rec is not None:       # coalesce done, batch formed
                r.rec.t_exec = now
            if r.deadline is not None and now > r.deadline:
                self._expire(r)
            else:
                fresh.append(r)
        live = []
        est = self._svc_estimate(
            self._bucket_for(sum(r.n for r in fresh))) if fresh else 0.0
        for r in fresh:
            if r.deadline is not None and now + est > r.deadline:
                self._expire(r)
            elif not r.future.set_running_or_notify_cancel():
                # caller cancelled while queued: drop before burning
                # device time; the future is already CANCELLED
                events.incr("serve.cancelled")
                self._retire(r)
                rec, r.rec = r.rec, None
                self._journal.retire(rec, status="cancelled",
                                     reason="cancelled while queued")
            else:
                live.append(r)          # RUNNING: cancel() is now inert
        if not live:
            return
        total = sum(r.n for r in live)
        bucket = self._bucket_for(total)
        # queue-depth sample per dispatched batch: the black-box
        # timeline shows backlog growth leading up to a death, which
        # counters (totals) cannot reconstruct.  Stamped at the batch's
        # earliest ADMISSION, not at dispatch (ISSUE 19 satellite, the
        # emit_foreign end-stamp family): the depth belongs where the
        # oldest victim started waiting, so the dump timeline shows the
        # backlog GROWING before the slow exemplar instead of the
        # sample landing after the queue already drained
        _bb.record_at(_tele.wall_of(min(r.t_enq for r in live)),
                      "serve", "queue", depth=self._q.qsize(),
                      bucket=bucket, n=total)
        dev_i = self._pick_replica()
        if self._pools is None:
            self._run_and_fan(live, total, bucket, dev_i)
            return
        # replica overlap: hand the batch to device dev_i's worker so
        # the dispatcher can coalesce the NEXT bucket while this one
        # executes; the semaphore bounds in-flight batches at the
        # replica count (the queue cap alone can't — pool backlogs
        # would be the unbounded memory the bounded queue exists to
        # prevent)
        self._inflight.acquire()
        with self._lock:
            self._n_inflight += 1
        try:
            self._pools[dev_i].submit(self._run_and_fan, live, total,
                                      bucket, dev_i)
        except RuntimeError:            # pool shut down by a racing
            self._inflight.release()    # close(): these futures are in
            with self._lock:            # neither queue nor carry, so
                self._n_inflight -= 1   # the flush can't see them —
            for r in live:              # resolve here, never strand
                self._finish(r, exc=EngineClosed(
                    "engine closed before dispatch"))

    # -- replica health ------------------------------------------------
    def _pick_replica(self):
        """Health-aware round-robin: skip replicas inside their
        unhealthy cooldown; a replica whose cooldown expired gets ONE
        probe batch (its window re-arms immediately, so a second batch
        does not pile onto an unproven device before the probe's
        verdict).  All-unhealthy fails OPEN to the soonest-recovering
        replica — degraded service beats refused service."""
        n = len(self._ctxs)
        if n == 1:
            self._rr += 1
            return 0
        now = time.monotonic()
        with self._lock:
            for _ in range(n):
                i = self._rr % n
                self._rr += 1
                until = self._unhealthy_until[i]
                if until == 0.0:
                    return i
                if now >= until:
                    # probe: one batch back onto the cooled-down
                    # replica; success re-admits it (_replica_ok),
                    # failure restarts the cooldown (_replica_failed)
                    self._unhealthy_until[i] = now + self._cooldown
                    events.incr("serve.replica_probes")
                    return i
            i = min(range(n), key=lambda k: self._unhealthy_until[k])
        events.incr("serve.all_replicas_unhealthy")
        return i

    def _replica_failed(self, dev_i, exc):
        """A terminal dispatch failure (the retry budget is already
        spent by the time this is called) on replica `dev_i`."""
        newly = False
        with self._lock:
            self._fail_streak[dev_i] += 1
            streak = self._fail_streak[dev_i]
            if streak >= self._max_fails or \
                    self._unhealthy_until[dev_i] > 0.0:
                newly = self._unhealthy_until[dev_i] == 0.0
                self._unhealthy_until[dev_i] = \
                    time.monotonic() + self._cooldown
        if newly:
            events.incr("serve.replica_unhealthy")
            _bb.record("serve", "replica_unhealthy",
                       replica=int(dev_i),
                       device=repr(self._ctxs[dev_i]),
                       consecutive_fails=int(streak),
                       error=type(exc).__name__,
                       cooldown_s=self._cooldown)
            import logging
            logging.getLogger(__name__).warning(
                "serving replica %d (%r) marked unhealthy after %d "
                "consecutive failures (%s); routing around it for "
                "%.1fs", dev_i, self._ctxs[dev_i], streak,
                type(exc).__name__, self._cooldown)

    def _replica_ok(self, dev_i):
        """A successful dispatch: the streak resets, and an unhealthy
        replica (this was its probe) is re-admitted."""
        recovered = False
        with self._lock:
            self._fail_streak[dev_i] = 0
            if self._unhealthy_until[dev_i] > 0.0:
                self._unhealthy_until[dev_i] = 0.0
                recovered = True
        if recovered:
            events.incr("serve.replica_recovered")
            _bb.record("serve", "replica_recovered",
                       replica=int(dev_i),
                       device=repr(self._ctxs[dev_i]))

    def _run_and_fan(self, live, total, bucket, dev_i):
        """Pad→execute→fan-out for one coalesced batch — inline on a
        single-device engine, on the device's worker thread with
        replicas.  EVERY exit resolves every live future (the
        drain/close contract rides on task_done accounting)."""
        from ..parallel.resilience import retry_transient
        t0 = time.monotonic()
        for r in live:
            events.observe_time("serve.queue_us", t0 - r.t_enq)
            if r.rec is not None:       # dispatch handoff complete;
                r.rec.t_infer0 = t0     # device time starts here
                r.rec.bucket = bucket
        # the dispatch span parents onto the first request's submit-side
        # context, so the cross-thread submit→dispatch→infer chain
        # shares one trace; nested serve.infer inherits automatically
        dispatch_span = _tele.span("serve.dispatch",
                                   parent=live[0].tele)
        try:
            dispatch_span.start()
            try:
                batch = live[0].data if len(live) == 1 else \
                    _np.concatenate([r.data for r in live], axis=0)
                if bucket > total:
                    pad = _np.zeros(
                        (bucket - total,) + batch.shape[1:],
                        batch.dtype)
                    batch = _np.concatenate([batch, pad], axis=0)
                with _tele.span("serve.infer"):
                    out = retry_transient(
                        lambda: self._run(dev_i, batch),
                        what="serve.infer(bucket=%d)" % bucket,
                        event="serve.retries")
            except Exception as e:      # noqa: BLE001 — fan the failure
                events.incr("serve.failed")
                self._replica_failed(dev_i, e)
                for r in live:          # out to every caller's future
                    self._finish(r, exc=e)
                return
            self._replica_ok(dev_i)
            t1 = time.monotonic()
            dt_svc = t1 - t0
            for r in live:
                if r.rec is not None:   # device done; join/D2H next
                    r.rec.t_infer1 = t1
            with self._lock:    # feed the deadline-feasibility EWMA
                prev = self._svc_ewma.get(bucket)
                self._svc_ewma[bucket] = dt_svc if prev is None \
                    else 0.3 * dt_svc + 0.7 * prev
            events.observe_time("serve.infer_us", dt_svc)
            events.incr("serve.batches")
            events.incr("serve.batch_fill", total)
            events.incr("serve.pad_waste", bucket - total)
            events.incr("serve.requests", len(live))
            with self._lock:
                self._n_batches += 1
                self._dev_batches[dev_i] += 1
            try:
                self._fan_out(live, out, dev_i)
            except Exception as e:      # noqa: BLE001 — e.g. an output
                # leaf without a leading batch dim: the infer succeeded
                # but slicing failed; the futures must still resolve
                events.incr("serve.failed")
                for r in live:
                    if not r.future.done():
                        self._finish(r, exc=e)
        finally:
            dispatch_span.stop()
            if self._pools is not None:
                self._inflight.release()
                with self._lock:
                    self._n_inflight -= 1

    def _materialize_params(self, batch_np):
        """Resolve deferred parameter shapes from a concrete batch
        (model_zoo nets defer channel dims until a first forward),
        then replicate.  Mirrors HybridBlock.__call__'s pre-pass:
        abstract infer_shape first, one paused eager forward as the
        fallback for forwards eval_shape can't abstract."""
        from ..ndarray.ndarray import NDArray
        import jax
        blk = self._block
        x = NDArray(jax.device_put(batch_np[:1],
                                   self._ctxs[0].jax_device),
                    ctx=self._ctxs[0])
        tr = getattr(blk, "_apply_input_transform", None)
        pre = tr((x,)) if tr is not None else (x,)
        try:
            blk.infer_shape(*pre)
            for p in blk.collect_params().values():
                if p._deferred_init:
                    p._finish_deferred_init()
        except Exception:
            from .. import autograd as _ag
            from ..gluon.block import Block
            with _ag.pause():
                Block.__call__(blk, *pre)
        self.refresh_params()

    def _run(self, dev_i, batch_np):
        import jax
        fault.maybe_raise("serve.infer", step=self._n_batches)
        # benign per-batch stall (latency chaos / the controlplane
        # bench's sleep-dominated service): unlike serve.infer this
        # slows the batch instead of failing it, so capacity scales
        # with REPLICAS even on a single-core virtual-device host
        fault.maybe_slow("serve.slow", step=self._n_batches)
        if self._warm and self._dev_params is not None:
            # warmed steady state: every (device, bucket) executable
            # exists and the signature is locked, so replica workers
            # execute lock-free (jit cache hits are thread-safe) —
            # this is what lets device k+1 overlap device k
            x = jax.device_put(batch_np,
                               self._ctxs[dev_i].jax_device)
            out = self._infer(self._dev_params[dev_i], x)
            jax.block_until_ready(out)
            return self._degraded(out)
        with self._exec_lock:           # traces/materialization
            if self._dev_params is None:
                self._materialize_params(batch_np)
            x = jax.device_put(batch_np, self._ctxs[dev_i].jax_device)
            out = self._infer(self._dev_params[dev_i], x)
            jax.block_until_ready(out)
        return self._degraded(out)

    def _degraded(self, out):
        """model.bad_version taint (see `degrade`): stall + sign-flip
        — deterministic badness on latency AND correctness, so both a
        p99 rule and an output-parity check catch it."""
        if not self._degrade_s:
            return out
        import jax
        time.sleep(self._degrade_s)
        return jax.tree_util.tree_map(lambda a: -a, out)

    def _fan_out(self, reqs, out, dev_i):
        import jax
        from ..ndarray.ndarray import NDArray
        ctx = self._ctxs[dev_i]
        off = 0
        for r in reqs:
            lo, hi, single = off, off + r.n, r.single
            res = jax.tree_util.tree_map(
                lambda a: NDArray(a[lo] if single else a[lo:hi],
                                  ctx=ctx), out)
            off = hi
            if r.rec is not None:       # slice done; what remains is
                r.rec.t_fin = time.monotonic()  # future resolution
            self._finish(r, result=res)
            dt = time.monotonic() - r.t_enq
            events.observe_time("serve.e2e_us", dt)
            # tenant/lane splits of the same series (ISSUE 8): the
            # aggregate above stays authoritative, the labeled rings
            # answer "p99 for lane X / tenant Y" in /metrics + dumps
            us = int(dt * 1e6)
            # REQUEST-denominated, matching the unlabeled aggregate
            # (dispatcher: len(live)) and serve.shed (1 per shed) —
            # the SLO shed burn rules ratio shed/(requests+shed), and
            # example-denominated children would dilute that ratio by
            # the batch size for submit_batch traffic
            if r.lane is not None:
                events.observe("serve.e2e_us", us,
                               labels={"lane": r.lane})
                events.incr("serve.requests",
                            labels={"lane": r.lane})
            if r.tenant is not None:
                events.observe("serve.e2e_us", us,
                               labels={"tenant": r.tenant})
                events.incr("serve.requests",
                            labels={"tenant": r.tenant})
            if self._version is not None:
                # version split (ISSUE 16): one labelset per live
                # version (bounded by the MAX_LABELSETS fold) — the
                # percentile ring the canary p99 rule judges
                events.observe("serve.e2e_us", us,
                               labels={"version": self._version})
                events.incr("serve.requests",
                            labels={"version": self._version})

    # -- warmup --------------------------------------------------------
    def warmup(self, example_shape=None, wire_dtype=None):
        """Pre-compile (or AOT-deserialize) EVERY (device, bucket)
        executable before traffic, so no organic request ever pays a
        compile.  Needs the example signature — from the constructor,
        a prior request, or the arguments here.  Returns a summary
        dict; after it, `serve.traces` stays flat under any mix of
        request sizes ≤ the largest bucket."""
        if self._example_shape is None and example_shape is None:
            # pre-warm manifest (ISSUE 18): a previous process that
            # warmed this cost label recorded its signature — replay
            # it so a fresh serving host warms with no operator input
            # (and its bucket executables resolve straight off the
            # shared AOT disk cache, stale=0)
            hint = None
            try:
                from ..compile import prewarm as _prewarm
                hint = _prewarm.serve_hint(self._cost_label)
            except Exception:       # noqa: BLE001 — the manifest is
                hint = None         # advisory, never a blocker
            if hint and hint.get("example_shape") is not None:
                example_shape = tuple(hint["example_shape"])
                wire_dtype = wire_dtype or hint.get("wire_dtype")
                events.incr("serve.warmup_from_manifest")
                _bb.record("serve", "warmup_manifest",
                           label=self._cost_label,
                           shape=str(example_shape),
                           dtype=str(wire_dtype))
            else:
                raise ValueError(
                    "warmup() before any request needs example_shape= "
                    "(and wire_dtype=) — the executable signature "
                    "(no pre-warm manifest entry for label %r either)"
                    % self._cost_label)
        # route through the SAME signature gate as submits: a warmup
        # conflicting with an already-locked shape/dtype must raise,
        # not silently re-point the executable set away from traffic
        self._check_example(
            tuple(example_shape) if example_shape is not None
            else self._example_shape,
            wire_dtype or self._wire_dtype or "float32")
        dtype = _np.dtype(self._wire_dtype)
        t0 = time.monotonic()
        try:
            # refresh the manifest-listed blobs' LRU credit before the
            # loads below (hit semantics, ISSUE 18) — a long-lived
            # host's keep-K trim must not evict the warm set first
            from ..compile import prewarm as _prewarm
            _prewarm.replay(label_prefix=self._cost_label)
        except Exception:           # noqa: BLE001
            _prewarm = None
        per_bucket = {}
        try:
            # the deterministic OOM drill: the serve.oom fault site
            # raises a RESOURCE_EXHAUSTED-shaped failure here, through
            # the same catch the real allocator failure takes
            fault.maybe_raise(
                "serve.oom", 0, msg="RESOURCE_EXHAUSTED: out of "
                "memory while warming %r (injected)" % self._cost_label)
            for i in range(len(self._ctxs)):
                for b in self._buckets:
                    x = _np.zeros((b,) + self._example_shape, dtype)
                    tb = time.monotonic()
                    self._run(i, x)
                    per_bucket[b] = round(time.monotonic() - tb, 4)
        except Exception as e:
            # an allocator OOM while materializing the bucket ladder:
            # dump committed-vs-measured BEFORE unwinding releases the
            # buffers that prove who was resident (ISSUE 20)
            from ..telemetry import memwatch as _mw
            _mw.guard_oom("serve.warmup", e)
            raise
        self._warm = True
        events.incr("serve.warmups")
        # probe row OUTSIDE bench (ISSUE 19 satellite / ROADMAP item 2
        # follow-on): the warmup's own measured wall trains the
        # autotuner's measured tier for the serve-bucket ladder, so
        # production serving hosts contribute evidence — until now
        # only bench wrote probes and serving only consumed
        try:
            from ..compile import autotune as _autotune
            if per_bucket:
                _autotune.note_probe(
                    "serve_buckets", self._cost_label,
                    ",".join(str(b) for b in self._buckets),
                    sum(per_bucket.values()) * 1e6,
                    source="serve.warmup", devices=len(self._ctxs))
        except Exception:           # noqa: BLE001 — evidence is
            pass                    # advisory, never blocks warmup
        if _prewarm is not None:
            try:
                # durably record THIS warmup's signature so the next
                # process can warm from the manifest alone
                _prewarm.note_serve(self._cost_label,
                                    self._example_shape,
                                    self._wire_dtype, self._buckets)
            except Exception:       # noqa: BLE001
                pass
        return {"buckets": list(self._buckets),
                "devices": len(self._ctxs),
                "wall_s": round(time.monotonic() - t0, 3),
                "bucket_wall_s": per_bucket,
                "traces": events.get("serve.traces")}

    # -- lifecycle -----------------------------------------------------
    def drain(self, timeout=30.0):
        """Stop intake (submits raise EngineClosed) and wait until every
        already-accepted request is resolved.  Returns True when the
        queue fully drained within `timeout`."""
        self._draining = True
        deadline = time.monotonic() + float(timeout)
        with self._q.all_tasks_done:
            while self._q.unfinished_tasks:
                rem = deadline - time.monotonic()
                if rem <= 0:
                    return False
                if (self._thread is None or
                        not self._thread.is_alive()) and \
                        not self._n_inflight:
                    break               # nothing will drain it
                self._q.all_tasks_done.wait(min(rem, 0.1))
        return self._q.unfinished_tasks == 0

    def close(self, timeout=30.0):
        """drain() + retire the dispatcher (joined within `timeout`) +
        resolve any still-outstanding future (EngineClosed) so no
        caller blocks forever.  Idempotent.  Returns True when the
        dispatcher thread is fully joined."""
        t_end = time.monotonic() + float(timeout)
        self.drain(timeout)
        self._stop = True
        t = self._thread
        joined = True
        if t is not None and t.is_alive():
            t.join(max(0.1, t_end - time.monotonic()))
            joined = not t.is_alive()
        if self._pools is not None:     # in-flight replica batches
            for p in self._pools:       # complete (and resolve) first
                p.shutdown(wait=True)
        # anything the dispatcher never got to (drain timeout, dead
        # dispatcher, a submit that raced the shutdown): resolve, don't
        # strand.  _closed flips and the queue flushes under the SAME
        # lock _submit enqueues under, so every accepted request is
        # either flushed here or was visible to the dispatcher; the
        # carry handoff is locked against a still-alive dispatcher for
        # the same exactly-once reason.
        leftovers = []
        with self._lock:
            self._closed = True
            if self._carry is not None:
                leftovers.append(self._carry)
                self._carry = None
            while True:
                try:
                    leftovers.append(self._q.get_nowait())
                except queue.Empty:
                    break
        for r in leftovers:
            self._finish(r, exc=EngineClosed(
                "engine closed before dispatch"))
        self.uninstall_sigterm()
        return joined

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __del__(self):
        # flags only — never join a thread from a finalizer; the
        # daemon dispatcher retires at its next poll (replica pool
        # workers exit when their executors are collected with us)
        self._draining = True
        self._stop = True
        self._closed = True
        try:                            # best-effort handler restore
            self.uninstall_sigterm()    # (no-op unless installed; may
        except Exception:               # fail off the main thread —
            pass                        # the handler then chains prev)

    # -- introspection -------------------------------------------------
    def slo_targets(self):
        """{lane: tightest relative deadline seconds among the last
        256 ACCEPTED deadlined requests} — the per-lane SLO targets
        telemetry/slo.py derives its default p99-vs-deadline rules
        from (empty until deadlined traffic has been seen; an
        outlier-tight deadline ages out of the window instead of
        pinning the target forever)."""
        with self._lock:
            return {lane: min(dq)
                    for lane, dq in self._lane_deadline_s.items()
                    if dq}

    def slo_lane_quotas(self):
        """{lane: occupancy quota FRACTION this engine actually
        enforces}, reconstructed from the live caps — so the SLO
        layer's default shed budgets honor programmatic ``lanes=`` /
        ``lane_quotas=`` engines, not just the env knobs."""
        cap = float(self._q.maxsize)
        return {lane: (1.0 if c is None else c / cap)
                for lane, c in self._lane_caps.items()}

    def stats(self):
        """Engine + process-wide `serve.*` counter snapshot, including
        latency percentiles (p50/p90/p99) for the observed series."""
        now = time.monotonic()
        with self._lock:
            tenants = dict(self._tenant_q)
        return {"counters": serve_counters(),
                "latency": events.latency_snapshot("serve."),
                "labeled": events.labeled_latency_snapshot("serve."),
                "buckets": list(self._buckets),
                "devices": [repr(c) for c in self._ctxs],
                "device_batches": list(self._dev_batches),
                "replica_health": [
                    "unhealthy" if u > now else
                    ("probing" if u > 0.0 else "healthy")
                    for u in self._unhealthy_until],
                "queue_depth": self._q.qsize(),
                "lanes": {"order": list(self._lanes),
                          "depths": self._q.lane_depths(),
                          "caps": dict(self._lane_caps)},
                "tenants_queued": tenants,
                "version": self._version,
                "degraded": bool(self._degrade_s),
                "warm": self._warm}
