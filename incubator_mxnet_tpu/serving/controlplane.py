"""SLO-driven fleet control plane (ISSUE 16 tentpole).

Everything below this module can already observe and actuate: PR 12's
burn-rate rules judge the live SLO surface, PR 8's registry bin-packs
admissions against the HBM ledger, PR 7's engine routes around sick
replicas, and ``refresh_params`` swaps weights in place.  What no
in-tree component does is CLOSE THE LOOP — a human still reads the
alert and runs the resize or the rollback.  At fleet scale that human
is the outage.  `FleetSupervisor` is the missing controller: each tick
it evaluates the rules, reads the active-alert surface, the registry
ledger and the replica health, and acts —

**Autoscaling.**  `MXNET_CTL_UP_ROUNDS` consecutive ticks with a
firing shed-burn rule on a watched lane grow the model's replica set
by one (``ModelRegistry.resize`` — make-before-break admission through
the same ledger every deploy answers to).  `MXNET_CTL_DOWN_ROUNDS`
consecutive QUIET ticks shrink it back toward ``min_replicas``; HBM
pressure on the ledger (any pool device past ``MXNET_CTL_HBM_PRESSURE``
committed) halves the quiet requirement — idle capacity on a full
ledger is the first thing to give back.  Round hysteresis plus
`MXNET_CTL_COOLDOWN_S` between transitions bound the loop at <= 1
transition per direction per window: it never flaps.

**Rolling deploys.**  `deploy(block, version)` admits the version
alongside the primary (``ModelRegistry.register_version`` — own
ledger hold, own breaker, own version-labeled telemetry) and mirrors
`MXNET_CTL_CANARY_FRACTION` of traffic to it.  The fraction ramps by
`MXNET_CTL_CANARY_STEP` only after every rule for the model stays
quiet for a full observation window (`MXNET_CTL_OBSERVE_ROUNDS`
ticks); at `MXNET_CTL_CANARY_MAX` one more quiet window PROMOTES —
the primary swaps to the version's weights in place
(`refresh_params_from`) and the canary entry retires.

**Automatic rollback.**  Any firing rule ATTRIBUTABLE to the canary —
one of the version-labeled rules this supervisor installed at deploy,
or any rule whose labels carry the canary's version — triggers the
instant revert: traffic mirroring stops, the canary deregisters (its
ledger hold releases exactly once), and a proactive blackbox dump
lands with reason ``controlplane:rollback:<model>@<version>`` and a
ring event naming the breaching rule.  No operator in the loop.

The supervisor's own actions are typed ``controlplane.*`` counters,
ring events and durable history rows, and it installs watchdog rules
over itself (`telemetry.slo.default_controlplane_rules`): rollback
storms and scale oscillation page a human — the controller heals
incidents, humans heal the controller.

Typical lifecycle::

    reg = serving.ModelRegistry(devices=pool)
    reg.register("ranker", net, replicas=1, example_shape=(256,))
    reg.warmup("ranker")
    reg.install_slo_rules()
    sup = FleetSupervisor(reg, "ranker", max_replicas=len(pool))
    sup.start()                       # background tick loop
    ...
    sup.deploy(net_v2, "v2")          # canary → ramp → promote
    ...                               # (or rollback, automatically)
    sup.close()
"""
from __future__ import annotations

import threading
import time
import weakref

from .. import config as _cfg
from ..monitor import events
from ..telemetry import flightrec as _bb
from ..telemetry import slo as _slo
from .registry import AdmissionDenied, UnknownModel

__all__ = ["FleetSupervisor", "status_block"]

#: live supervisors, for the /metrics.json + blackbox "controlplane"
#: block (weak: a supervisor must die with its owner, not be pinned
#: by introspection)
_SUPERVISORS = weakref.WeakSet()


def _hist_record(action, model, value=1.0, **fields):
    try:
        from ..telemetry import history as _hist
        _hist.record("controlplane", action, float(value),
                     labels={"model": str(model)}, **fields)
    except Exception:               # noqa: BLE001 — durability is
        pass                        # best-effort, never control flow


def status_block():
    """The ``controlplane`` block for /metrics.json and blackbox
    dumps: every live supervisor's status.  Empty list = no
    supervisors (callers omit the block)."""
    out = []
    for sup in list(_SUPERVISORS):
        try:
            if not sup._closed:
                out.append(sup.status())
        except Exception:           # noqa: BLE001 — introspection
            pass                    # must never break a scrape/dump
    return out


class FleetSupervisor:
    """The rule→action controller for ONE registry model.

    registry / model: the `ModelRegistry` and the model name whose
        replica set and deploys this supervisor owns.
    lanes: lanes whose ``serve-shed-<lane>`` burn rules count as
        scale-up evidence (default: the model engine's lanes).
    watch_rules: extra rule names treated as scale evidence AND model
        noise (tests and bespoke deployments point the supervisor at
        their own rules).
    min_replicas / max_replicas: the scale envelope (max defaults to
        the registry pool size).
    evaluate: when True (default) each tick runs `slo.evaluate`
        itself — the loop is self-contained; pass False when a
        periodic exporter already evaluates at its own cadence.
    install_rules: register the supervisor watchdog rules
        (rollback-storm, scale-oscillation) at construction;
        `close()` unregisters them.

    Remaining knobs default from the ``MXNET_CTL_*`` config family
    (see docs/controlplane.md for the table); constructor arguments
    override.  `tick(now)` is manual and deterministic (tests drive
    simulated time through it); `start()` runs it on a daemon thread
    at ``tick_s`` cadence.
    """

    def __init__(self, registry, model, lanes=None, watch_rules=(),
                 min_replicas=1, max_replicas=None, tick_s=None,
                 up_rounds=None, down_rounds=None, cooldown_s=None,
                 canary_fraction=None, canary_step=None,
                 canary_max=None, observe_rounds=None,
                 hbm_pressure=None, fast_s=None, slow_s=None,
                 evaluate=True, install_rules=True):
        self._reg = registry
        self._model = str(model)
        if lanes is None:
            lanes = tuple(registry.engine(self._model)._lanes)
        self._lanes = tuple(str(l) for l in lanes)
        self._scale_rules = ({"serve-shed-%s" % l for l in self._lanes}
                             | {str(r) for r in watch_rules})
        self._noise_rules = (set(self._scale_rules)
                             | {"serve-p99-%s" % l
                                for l in self._lanes})
        self._min = max(1, int(min_replicas))
        self._max = int(max_replicas if max_replicas is not None
                        else len(registry._ctxs))
        self._tick_s = float(tick_s if tick_s is not None
                             else _cfg.get("MXNET_CTL_TICK_S"))
        self._up_rounds = int(up_rounds if up_rounds is not None
                              else _cfg.get("MXNET_CTL_UP_ROUNDS"))
        self._down_rounds = int(
            down_rounds if down_rounds is not None
            else _cfg.get("MXNET_CTL_DOWN_ROUNDS"))
        self._cooldown = float(
            cooldown_s if cooldown_s is not None
            else _cfg.get("MXNET_CTL_COOLDOWN_S"))
        self._fraction0 = float(
            canary_fraction if canary_fraction is not None
            else _cfg.get("MXNET_CTL_CANARY_FRACTION"))
        self._step = float(canary_step if canary_step is not None
                           else _cfg.get("MXNET_CTL_CANARY_STEP"))
        self._canary_max = float(
            canary_max if canary_max is not None
            else _cfg.get("MXNET_CTL_CANARY_MAX"))
        self._observe = int(
            observe_rounds if observe_rounds is not None
            else _cfg.get("MXNET_CTL_OBSERVE_ROUNDS"))
        self._pressure = float(
            hbm_pressure if hbm_pressure is not None
            else _cfg.get("MXNET_CTL_HBM_PRESSURE"))
        self._fast_s, self._slow_s = fast_s, slow_s
        self._evaluate = bool(evaluate)

        self._lock = threading.RLock()
        self._hot = 0               # consecutive ticks with scale
        self._quiet = 0             # evidence / without any
        self._cool_until = 0.0      # vs the tick's own `now`
        self._canary = None         # {"version","rules","quiet",
                                    #  "fraction"}
        self.last_rollback = None   # most recent rollback record
        self.last_scale = None      # most recent scale record
        self._thread = None
        self._stop = threading.Event()
        self._closed = False
        self._own_rules = (_slo.install_default_controlplane_rules(
            fast_s=fast_s, slow_s=slow_s) if install_rules else [])
        _SUPERVISORS.add(self)

    # -- deploys -------------------------------------------------------
    def deploy(self, block, version, fraction=None, **register_kw):
        """Ship `version` as a canary: admit it alongside the primary,
        install its version-labeled SLO rules, start mirroring
        traffic.  From here the TICK LOOP owns it — ramp while quiet,
        promote at the ceiling, roll back on any attributable alert.
        Raises (AdmissionDenied / RegistrationTimeout / ValueError)
        without supervisor state when the admit fails."""
        version = str(version)
        with self._lock:
            if self._closed:
                raise RuntimeError("supervisor is closed")
            if self._canary is not None:
                raise ValueError(
                    "model %r already has version %r in flight"
                    % (self._model, self._canary["version"]))
        rec = self._reg.register_version(
            self._model, block, version,
            fraction=fraction if fraction is not None
            else self._fraction0, **register_kw)
        rules = self._install_canary_rules(version)
        with self._lock:
            self._canary = {"version": version, "rules": rules,
                            "quiet": 0,
                            "fraction": float(rec["fraction"])}
        events.incr("controlplane.deploys")
        events.incr("controlplane.deploys",
                    labels={"model": self._model, "version": version})
        _bb.record("controlplane", "deploy", model=self._model,
                   version=version, fraction=rec["fraction"],
                   rules=list(rules))
        _hist_record("deploy", self._model, version=version,
                     fraction=rec["fraction"])
        return rec

    def _install_canary_rules(self, version):
        """The version-labeled judgement: a shed-burn rule over the
        canary's own requests, and — when the model's lanes have
        observed deadline targets — a p99 threshold on the canary's
        labeled ring at the TIGHTEST lane target (the canary must be
        good enough for the most demanding traffic it mirrors)."""
        budget = max(float(_cfg.get("MXNET_SLO_SHED_BUDGET")), 0.05)
        names = []
        r = _slo.register_rule(_slo.BurnRateRule(
            "ctl-canary-shed-%s-%s" % (self._model, version),
            bad="serve.shed",
            total=["serve.requests", "serve.shed"],
            labels={"version": version}, budget=budget,
            fast_s=self._fast_s, slow_s=self._slow_s,
            description="canary %s@%s shed fraction burns its %.0f%% "
                        "budget" % (self._model, version,
                                    budget * 100)))
        names.append(r.name)
        try:
            targets = self._reg.slo_targets()
        except Exception:           # noqa: BLE001
            targets = {}
        if targets:
            t = min(targets.values())
            r = _slo.register_rule(_slo.ThresholdRule(
                "ctl-canary-p99-%s-%s" % (self._model, version),
                metric="serve.e2e_us", pct="p99",
                labels={"version": version}, bound=float(t) * 1e6,
                description="canary %s@%s e2e p99 within the model's "
                            "tightest observed deadline (%.3fs)"
                            % (self._model, version, float(t))))
            names.append(r.name)
        return names

    def _uninstall_rules(self, names):
        for n in names:
            try:
                _slo.unregister_rule(n)
            except Exception:       # noqa: BLE001
                pass

    def rollback(self, rule=None, info=None):
        """Instant canary revert: stop the mirror, deregister the
        version (ledger hold released exactly once — registry-side
        idempotency), drop its rules, and leave the forensic trail:
        counters, ring event and a PROACTIVE blackbox dump whose
        reason names the model@version and whose ring names the
        breaching rule.  Idempotent; returns the rollback record or
        None when no version was in flight."""
        with self._lock:
            can, self._canary = self._canary, None
        if can is None:
            return None
        self._uninstall_rules(can["rules"])
        self._reg.rollback_version(self._model, reason=rule)
        events.incr("controlplane.rollbacks")
        events.incr("controlplane.rollbacks",
                    labels={"model": self._model,
                            "version": can["version"]})
        detail = {k: v for k, v in (info or {}).items()
                  if isinstance(v, (int, float, str, bool))}
        # the canary's worst promoted request exemplar (ISSUE 19)
        # rides the ring event + rollback record: the proactive dump
        # below carries the full reqtrace waterfall, this names WHICH
        # request indicted the version
        exemplar = None
        try:
            from ..telemetry import reqtrace as _rt
            for cand in _rt.exemplars(model=self._model):
                if cand.get("version") not in (None, can["version"]):
                    continue        # another version's request
                if exemplar is None or cand.get("e2e_us", 0) > \
                        exemplar.get("e2e_us", 0):
                    exemplar = cand
        except Exception:           # noqa: BLE001 — forensic garnish
            exemplar = None
        if exemplar is not None:
            detail.setdefault("exemplar_rid", exemplar.get("rid"))
            detail.setdefault("exemplar_e2e_us",
                              exemplar.get("e2e_us"))
            detail.setdefault("exemplar_phase",
                              exemplar.get("dominant"))
        _bb.record("controlplane", "rollback", model=self._model,
                   version=can["version"],
                   rule=str(rule) if rule else None,
                   fraction=can["fraction"], **detail)
        _hist_record("rollback", self._model, version=can["version"],
                     rule=str(rule) if rule else None)
        # the proactive dump: the breaching rule + version are in the
        # ring event above, the reason names the incident — blackbox's
        # suspected-cause heuristics read both
        _bb.crash_dump("controlplane:rollback:%s@%s"
                       % (self._model, can["version"]))
        rec = {"model": self._model, "version": can["version"],
               "rule": str(rule) if rule else None,
               "fraction": can["fraction"],
               "blackbox": _bb.last_dump_path()}
        if exemplar is not None:
            rec["exemplar"] = dict(exemplar)
        self.last_rollback = rec
        return rec

    def promote(self):
        """Promote the in-flight version (weight-swap onto the
        primary; canary entry retires).  Normally the tick loop calls
        this after a fully-quiet window at the fraction ceiling."""
        with self._lock:
            can = self._canary
        if can is None:
            raise ValueError("model %r has no version in flight"
                             % self._model)
        rec = self._reg.promote_version(self._model)
        with self._lock:
            self._canary = None
        self._uninstall_rules(can["rules"])
        events.incr("controlplane.promotes")
        events.incr("controlplane.promotes",
                    labels={"model": self._model,
                            "version": can["version"]})
        _bb.record("controlplane", "promote", model=self._model,
                   version=can["version"])
        _hist_record("promote", self._model, version=can["version"])
        return rec

    # -- the tick ------------------------------------------------------
    def tick(self, now=None):
        """One control round: evaluate rules, then act — canary
        first (a bad version inflates the very shed burn the scaler
        reads), then scaling, then replica health.  Deterministic
        under a caller-supplied `now` (tests drive simulated time);
        never raises — action failures are counted
        (controlplane.errors) and the loop keeps custody."""
        now = float(now if now is not None else time.time())
        with self._lock:
            if self._closed:
                return None
            events.incr("controlplane.ticks")
            if self._evaluate:
                try:
                    _slo.evaluate(now)
                except Exception:       # noqa: BLE001
                    pass
            alerts = _slo.active_alerts()
            try:
                self._tick_canary(now, alerts)
            except Exception:           # noqa: BLE001
                events.incr("controlplane.errors")
                _bb.record("controlplane", "error", model=self._model,
                           phase="canary")
            try:
                self._tick_scale(now, alerts)
            except Exception:           # noqa: BLE001
                events.incr("controlplane.errors")
                _bb.record("controlplane", "error", model=self._model,
                           phase="scale")
            try:
                self._tick_health(now)
            except Exception:           # noqa: BLE001
                events.incr("controlplane.errors")
                _bb.record("controlplane", "error", model=self._model,
                           phase="health")
            return self.status()

    def _canary_breach(self, alerts, can):
        """The firing rule attributable to the canary, or None: one
        of the rules installed for it, or any rule whose labels carry
        its version."""
        for name in can["rules"]:
            if name in alerts:
                return name
        want = str(can["version"])
        for name, info in alerts.items():
            labels = info.get("labels") or {}
            if isinstance(labels, dict) \
                    and str(labels.get("version")) == want:
                return name
        return None

    def _model_noisy(self, alerts, can):
        """True when ANY rule for the model is firing — the ramp
        gate: 'every SLO rule for the model stays quiet for a full
        observation window'."""
        watched = self._noise_rules | set(can["rules"])
        return any(name in watched for name in alerts)

    def _tick_canary(self, now, alerts):
        can = self._canary
        if can is None:
            return
        breach = self._canary_breach(alerts, can)
        if breach is not None:
            self.rollback(rule=breach, info=alerts.get(breach))
            return
        if self._model_noisy(alerts, can):
            can["quiet"] = 0        # window restarts — ramping while
            return                  # ANY model rule fires is how bad
                                    # versions reach 100%
        can["quiet"] += 1
        if can["quiet"] < self._observe:
            return
        can["quiet"] = 0
        if can["fraction"] >= self._canary_max - 1e-9:
            self.promote()
            return
        f = min(self._canary_max, can["fraction"] + self._step)
        can["fraction"] = f
        self._reg.set_canary_fraction(self._model, f)
        events.incr("controlplane.ramps")
        events.incr("controlplane.ramps",
                    labels={"model": self._model,
                            "version": can["version"]})
        _bb.record("controlplane", "ramp", model=self._model,
                   version=can["version"], fraction=f)
        _hist_record("ramp", self._model, value=f,
                     version=can["version"])

    def _replicas(self):
        try:
            return len(self._reg._entry(self._model).devices)
        except UnknownModel:
            return 0

    def _hbm_pressured(self):
        """Whether any pool device sits above the pressure fraction of
        its budget.  Upgraded by the memory observatory (ISSUE 20):
        when a FRESH measured sample exists, the MEASURED watermark
        judges pressure instead of the committed-ledger estimate —
        admission projections routinely drift from allocator reality,
        and shrinking capacity off a wrong ledger is the supervisor
        hurting the fleet.  Every pressure decision records BOTH
        values (ledger + measured, with the judging basis), so the
        forensic trail shows which number the supervisor believed."""
        pressured, decisive = False, None
        for row in self._reg.stats()["ledger"]:
            if row["budget"] <= 0:
                continue
            ledger = row["committed"]
            # stats() annotates measured_bytes from a fresh memwatch
            # sample (None on stale/absent samples) — the freshness
            # contract lives in one place
            m = row.get("measured_bytes")
            basis = "measured" if m is not None else "ledger"
            used = m if m is not None else ledger
            if used >= self._pressure * row["budget"]:
                pressured = True
                decisive = {"device": row["device"],
                            "budget": int(row["budget"]),
                            "ledger_bytes": int(ledger),
                            "measured_bytes": (int(m) if m is not None
                                               else None),
                            "basis": basis}
                break
        events.incr("controlplane.hbm_pressure_checks",
                    labels={"pressured": str(bool(pressured)).lower()})
        if pressured:
            _bb.record("controlplane", "hbm_pressure",
                       model=self._model, **decisive)
        return pressured

    def _tick_scale(self, now, alerts):
        evidence = sorted(n for n in alerts if n in self._scale_rules)
        n = self._replicas()
        if not n:
            return                  # model gone: nothing to scale
        if evidence:
            self._hot += 1
            self._quiet = 0
            if self._hot >= self._up_rounds and n < self._max \
                    and now >= self._cool_until:
                self._scale_to(n + 1, "up", evidence[0], now)
                self._hot = 0
            return
        self._hot = 0
        self._quiet += 1
        need = self._down_rounds
        if self._hbm_pressured():
            need = max(1, need // 2)    # idle capacity on a full
                                        # ledger goes back first
        if self._quiet >= need and n > self._min \
                and now >= self._cool_until:
            self._scale_to(n - 1, "down", "quiet", now)
            self._quiet = 0

    def _scale_to(self, replicas, direction, reason, now,
                  force=False):
        try:
            rec = self._reg.resize(self._model, replicas, force=force)
        except AdmissionDenied as e:
            events.incr("controlplane.scale_denied")
            events.incr("controlplane.scale_denied",
                        labels={"model": self._model})
            _bb.record("controlplane", "scale_denied",
                       model=self._model, replicas=int(replicas),
                       reason=str(e)[:300])
            # cooldown anyway: re-asking a full ledger every tick is
            # the flapping this supervisor exists to prevent
            self._cool_until = now + self._cooldown
            return None
        self._cool_until = now + self._cooldown
        events.incr("controlplane.scale_%ss" % direction)
        events.incr("controlplane.scale_%ss" % direction,
                    labels={"model": self._model})
        _bb.record("controlplane", "scale_%s" % direction,
                   model=self._model, replicas=int(replicas),
                   rule=str(reason), forced=bool(force))
        _hist_record("scale_%s" % direction, self._model,
                     value=float(replicas), rule=str(reason))
        self.last_scale = {"direction": direction,
                           "replicas": int(replicas),
                           "rule": str(reason), "at": now}
        return rec

    def _tick_health(self, now):
        try:
            health = self._reg.engine(self._model).stats().get(
                "replica_health") or []
        except (UnknownModel, Exception):   # noqa: BLE001
            return
        if not health or any(h == "healthy" for h in health):
            return
        if not all(h == "unhealthy" for h in health):
            return                  # probing replicas may recover
        events.incr("controlplane.unhealthy_fleet")
        events.incr("controlplane.unhealthy_fleet",
                    labels={"model": self._model})
        _bb.record("controlplane", "unhealthy_fleet",
                   model=self._model, replicas=len(health))
        if now < self._cool_until:
            return                  # one rebuild per cooldown window
        _bb.crash_dump("controlplane:unhealthy:%s" % self._model)
        _hist_record("rebuild", self._model, value=float(len(health)))
        # last-resort fallback: rebuild the SAME replica count on
        # fresh engines (resize force) — routing has nowhere healthy
        # left to route around
        self._scale_to(len(health), "up", "all_replicas_unhealthy",
                       now, force=True)

    # -- lifecycle / introspection -------------------------------------
    def start(self, interval=None):
        """Run `tick()` on a daemon thread every `interval` seconds
        (default MXNET_CTL_TICK_S).  Idempotent while running."""
        interval = float(interval if interval is not None
                         else self._tick_s)
        with self._lock:
            if self._closed:
                raise RuntimeError("supervisor is closed")
            if self._thread is not None and self._thread.is_alive():
                return self._thread
            self._stop.clear()

            def loop():
                while not self._stop.wait(interval):
                    try:
                        self.tick()
                    except Exception:   # noqa: BLE001 — the loop
                        events.incr("controlplane.errors")  # must
                        pass            # survive anything

            self._thread = threading.Thread(
                target=loop, daemon=True,
                name="FleetSupervisor-%s" % self._model)
            self._thread.start()
        return self._thread

    def stop(self, timeout=5.0):
        """Stop the background loop (the supervisor stays usable for
        manual ticks)."""
        self._stop.set()
        t = self._thread
        if t is not None and t.is_alive():
            t.join(timeout)
        self._thread = None

    def close(self, timeout=5.0):
        """Stop the loop and unregister every rule this supervisor
        installed (its own watchdogs + any live canary's).  The
        in-flight canary, if any, is left REGISTERED — closing the
        controller must not take a traffic decision; roll back or
        promote explicitly first.  Idempotent."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            can = self._canary
        self.stop(timeout)
        if can is not None:
            self._uninstall_rules(can["rules"])
        self._uninstall_rules(self._own_rules)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def status(self):
        """Live controller state for /metrics.json, dumps and
        tests."""
        with self._lock:
            can = dict(self._canary) if self._canary else None
        return {"model": self._model,
                "replicas": self._replicas(),
                "envelope": [self._min, self._max],
                "lanes": list(self._lanes),
                "hot_rounds": self._hot,
                "quiet_rounds": self._quiet,
                "canary": can,
                "last_scale": self.last_scale,
                "last_rollback": self.last_rollback,
                "running": bool(self._thread is not None
                                and self._thread.is_alive()),
                "closed": self._closed}
