"""Post-training quantization as a serving compile-time transform
(ISSUE 15 tentpole).

The CNN-inference-accelerator compilation flow (PAPERS.md) frames
quantization as a GRAPH TRANSFORM applied at compile time, and that is
exactly the shape of this serving stack's zero-recompile contract: the
executable set is closed at warmup, so the right place to change the
arithmetic is BEFORE the buckets are traced, not inside them.
`quantize_for_serving` is that step:

1. **Calibrate** over N batches — ``naive`` (min/max) or ``entropy``
   (KL-divergence thresholds), both from `contrib.quantization` — so
   every quantized layer carries fixed activation ranges and the
   traced executables contain no data-dependent range reductions.
2. **Rewrite** the model in place: Dense/Conv2D children become
   `QuantizedDense`/`QuantizedConv2D` whose int8 weights are
   non-trainable PARAMETERS — they flow into the bucket executables as
   arguments (replicated once per serving device, priced by admission
   at 1 byte/element), never as per-bucket baked constants.
3. **Report**: layer count, calibration mode/wall, and the weight-byte
   split before/after — the ~4x shrink is what turns one device's HBM
   budget into ~4x the admitted tenants (`ModelRegistry`), which is
   the fleet-capacity story, not just the latency one.

The returned block then goes through the SAME `InferenceEngine` /
`ModelRegistry` paths as any f32 model: `warmup()` traces/AOT-warms
the power-of-two buckets, `serve.traces` stays flat under organic
traffic, and `warmup()`→`reconcile()` swaps the int8 projection for
the measured memory-analysis rows.
"""
from __future__ import annotations

import logging
import time

from .. import config as _cfg
from ..monitor import events
from ..telemetry import flightrec as _bb

__all__ = ["quantize_for_serving", "param_bytes_by_dtype"]

log = logging.getLogger(__name__)


def param_bytes_by_dtype(block):
    """``{dtype_name: bytes}`` over the block's registered parameters —
    the admission-facing weight footprint, split so a calibration
    report (or a test) can show the f32→int8 shrink explicitly."""
    from ..parallel.functional import extract_params
    out = {}
    for v in extract_params(block).values():
        k = str(v.dtype)
        out[k] = out.get(k, 0) + int(v.size) * int(v.dtype.itemsize)
    return out


def quantize_for_serving(block, calib_data=None, calib_mode=None,
                         num_calib_batches=None, exclude_layers=None,
                         logger=None):
    """Calibrate → rewrite `block` into its int8 serving form (in
    place).  Returns ``(block, report)``.

    calib_mode: 'naive' | 'entropy' | 'none' (default:
        MXNET_QUANT_CALIB_MODE).  'none' = dynamic ranges — every
        executable recomputes min/max per batch; calibrated modes bake
        fixed ranges into the traced buckets (faster, and the form the
        compile-time-transform contract wants).
    num_calib_batches: batches consumed from `calib_data` (default:
        MXNET_QUANT_CALIB_BATCHES).
    """
    from ..contrib.quantization import (quantize_net, quantized_layers,
                                        is_quantized)
    calib_mode = str(calib_mode or _cfg.get("MXNET_QUANT_CALIB_MODE"))
    if num_calib_batches is None:
        num_calib_batches = int(
            _cfg.get("MXNET_QUANT_CALIB_BATCHES")) or None
    if is_quantized(block):
        # idempotent: quantize_for_serving(...) followed by
        # register_quantized(...) on the same block is the natural
        # call sequence — the second pass must not die on "no
        # quantizable layers found" (the layers were already swapped)
        n_layers = sum(1 for _ in quantized_layers(block))
        after = param_bytes_by_dtype(block)
        return block, {
            "quantized": True, "already_quantized": True,
            "quantized_dtype": "int8",
            "quantized_layers": int(n_layers),
            "calib_mode": calib_mode, "calib_batches": None,
            "calib_wall_s": 0.0,
            "weight_bytes_after": {k: int(v)
                                   for k, v in after.items()},
            "weight_bytes_total_after": int(sum(after.values())),
        }
    before = param_bytes_by_dtype(block)
    t0 = time.perf_counter()
    quantize_net(block,
                 calib_data=calib_data if calib_mode != "none" else None,
                 calib_mode=calib_mode,
                 num_calib_batches=num_calib_batches,
                 exclude_layers=exclude_layers, logger=logger)
    wall = time.perf_counter() - t0
    after = param_bytes_by_dtype(block)
    n_layers = sum(1 for _ in quantized_layers(block))
    report = {
        "quantized": True,
        "quantized_dtype": "int8",
        "quantized_layers": int(n_layers),
        "calib_mode": calib_mode,
        "calib_batches": (int(num_calib_batches)
                          if num_calib_batches else None),
        "calib_wall_s": round(wall, 3),
        "weight_bytes_before": {k: int(v) for k, v in before.items()},
        "weight_bytes_after": {k: int(v) for k, v in after.items()},
        "weight_bytes_total_before": int(sum(before.values())),
        "weight_bytes_total_after": int(sum(after.values())),
    }
    events.incr("quant.models")
    events.incr("quant.layers", n_layers)
    events.observe_time("quant.calib_us", wall)
    _bb.record("quant", "calibrated", layers=int(n_layers),
               mode=calib_mode,
               weight_bytes_before=report["weight_bytes_total_before"],
               weight_bytes_after=report["weight_bytes_total_after"])
    return block, report
