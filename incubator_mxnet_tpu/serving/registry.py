"""Multi-model serving registry with HBM admission control and
per-model circuit breakers (ISSUE 8 tentpole).

The PR 3 engine serves ONE model; production traffic is many models on
a fixed device pool, and nothing stopped a second model from being
loaded past HBM capacity — the failure mode is an allocator OOM (or a
wedged device) at TRAFFIC time, long after the deploy decision that
caused it.  `ModelRegistry` closes the loop the PR 5 cost registry
opened: XLA's memory_analysis already tells us every serving
executable's argument/output/temp bytes, so admission becomes a ledger
check instead of a production incident.

**Admission control.**  Each registry device carries a budget
(`MXNET_SERVE_HBM_BUDGET`, else the device's PJRT ``bytes_limit``
where the backend reports one) and a committed-bytes ledger.  A model
asks for `replicas` devices; admission judges a fresh **projection**
of the block in hand — parameter bytes (one full replica per device)
+ `MXNET_SERVE_HBM_TEMP_FACTOR` × the largest bucket's input+output
activation bytes (outputs via ``jax.eval_shape`` — a trace, never a
compile).  **Measured** reality flows in through
``warmup()``→``reconcile()``: once this engine's executables exist,
their memory-analysis rows (label ``serve.infer:<name>``; max bucket
argument + output + temp bytes) replace the projection in the
ledger.  Register never trusts pre-existing rows — the cost registry
is process-wide, and a re-registered name must not inherit its
previous incarnation's footprint (unregister drops the rows).

Placement is best-fit decreasing: the `replicas` devices with the most
free budget take the model.  If the k-th best device cannot fit it,
registration fails with the typed `AdmissionDenied`, a
``serve.admission_rejected`` counter, and a flight-recorder event
naming the model and the bin-packing decision (per-device free bytes
vs the footprint) — the refusal is forensically visible, not a silent
stack trace.  ``warmup(name)`` re-reconciles the ledger against the
measured rows once the executables exist.

**Circuit breaker.**  The PR 7 replica-health probe generalized to
whole-model backends: `MXNET_SERVE_BREAKER_FAILS` consecutive terminal
request failures (infrastructure errors — flow-control sheds and
deadline expiries are neutral) OPEN the model's breaker, and further
submits fail fast with `CircuitOpen` instead of queueing onto a dead
backend.  After `MXNET_SERVE_BREAKER_COOLDOWN_S` ONE probe request is
let through (half-open); success re-closes the breaker
(``serve.breaker_closed``), failure restarts the cooldown.  Every
transition lands in the flight-recorder ring naming the model.

Typical lifecycle::

    reg = serving.ModelRegistry(devices=[mx.gpu(i) for i in range(4)])
    reg.register("ranker", ranker_net, replicas=2,
                 example_shape=(256,), wire_dtype="float32")
    reg.warmup("ranker")                      # compile + reconcile
    fut = reg.submit("ranker", x, lane="high", tenant="acme",
                     deadline=0.05)
    ...
    reg.unregister("ranker")                  # close + release budget
"""
from __future__ import annotations

import threading
import time
import weakref

import numpy as _np

from .. import config as _cfg
from .. import fault
from ..base import MXNetError
from ..context import Context, current_context
from ..monitor import events
from ..telemetry import costs as _costs
from ..telemetry import flightrec as _bb
from ..telemetry import memwatch as _mw
from .engine import (InferenceEngine, QueueFull, DeadlineExceeded,
                     EngineClosed, Shed)

__all__ = ["ModelRegistry", "AdmissionDenied", "CircuitOpen",
           "UnknownModel", "RegistrationTimeout", "project_footprint",
           "live_registries"]

#: every live registry, weakly — the memwatch attribution join and
#: the mem-drift reconcile walk these (the controlplane's
#: _SUPERVISORS pattern)
_REGISTRIES = weakref.WeakSet()


def live_registries():
    """The live ModelRegistry instances (weak — closed/collected
    registries drop out)."""
    return [r for r in list(_REGISTRIES) if not r._closed]


class AdmissionDenied(MXNetError):
    """The model's projected HBM footprint does not fit the remaining
    per-device budget on enough devices — refused at REGISTRATION time
    (a ledger check), not discovered as an allocator OOM at traffic
    time."""


class RegistrationTimeout(MXNetError):
    """The engine build (param replication + functionalization) did
    not complete within the bounded build timeout
    (MXNET_SERVE_BUILD_TIMEOUT_S / ``build_timeout=``): the ledger
    hold was rolled back and the name released, so the deploy path is
    free to retry — a wedged compile must not hold it hostage.  If
    the abandoned build eventually completes, its engine is closed in
    the background (never leaked)."""


class CircuitOpen(MXNetError):
    """The model's backend circuit breaker is open: its recent
    dispatches failed terminally, so submits fail fast instead of
    queueing onto a dead backend.  Retry after the cooldown (a probe
    re-closes the breaker once the backend recovers)."""


class UnknownModel(MXNetError):
    """submit()/warmup()/unregister() for a name that was never
    registered (or was already unregistered)."""


#: flow-control errors are NEUTRAL for the breaker: they mean the
#: engine is protecting itself, not that the backend is broken
_FLOW_ERRORS = (Shed, QueueFull, DeadlineExceeded, EngineClosed,
                CircuitOpen)


def _param_bytes(block):
    """Total parameter bytes of an initialized block (one full replica
    per serving device).  Deferred-init params (model_zoo nets before a
    first forward) are materialized the same way the engine's
    extract_params would."""
    from ..parallel.functional import extract_params
    return sum(int(_np.prod(v.shape)) * _np.dtype(v.dtype).itemsize
               for v in extract_params(block).values())


def project_footprint(block, buckets, example_shape, wire_dtype,
                      temp_factor=None):
    """Projected per-device HBM bytes for serving `block` with the
    given bucket set: parameter bytes + temp_factor × (input + output
    bytes of the largest bucket).  Outputs come from `jax.eval_shape`
    over the functionalized block — a trace, never a compile, so
    admission stays cheap.  Returns (bytes, detail dict)."""
    import jax
    from ..parallel.functional import functionalize
    from ..ndarray.ndarray import NDArray
    if temp_factor is None:
        temp_factor = float(_cfg.get("MXNET_SERVE_HBM_TEMP_FACTOR"))
    pb = _param_bytes(block)
    largest = int(max(buckets))
    dt = _np.dtype(wire_dtype or "float32")
    in_bytes = largest * int(_np.prod(example_shape)) * dt.itemsize
    out_bytes = 0
    try:
        from ..parallel.functional import extract_params
        pure = functionalize(block, training=False)
        pvals = {n: jax.ShapeDtypeStruct(v.shape, v.dtype)
                 for n, v in extract_params(block).items()}

        def fwd(params, x):
            nd_in = (NDArray(x),)
            tr = getattr(block, "_apply_input_transform", None)
            if tr is not None:
                nd_in = tr(nd_in)
            out, _ = pure(params, *nd_in)
            return out

        x = jax.ShapeDtypeStruct((largest,) + tuple(example_shape),
                                 dt)
        out = jax.eval_shape(fwd, pvals, x)
        out_bytes = sum(
            int(_np.prod(a.shape)) * _np.dtype(a.dtype).itemsize
            for a in jax.tree_util.tree_leaves(out))
    except Exception:           # noqa: BLE001 — projection degrades to
        pass                    # the input-side estimate, never raises
    total = int(pb + temp_factor * (in_bytes + out_bytes))
    return total, {"param_bytes": int(pb), "input_bytes": int(in_bytes),
                   "output_bytes": int(out_bytes),
                   "temp_factor": float(temp_factor),
                   "bucket": largest}


class _Breaker:
    """Whole-model circuit breaker (closed → open → half-open).  State
    transitions are lock-guarded; `allow()` is the submit-time gate."""

    def __init__(self, model, max_fails, cooldown_s):
        self.model = model
        self.max_fails = int(max_fails)
        self.cooldown = float(cooldown_s)
        self._lock = threading.Lock()
        self.state = "closed"
        self.streak = 0
        self.open_until = 0.0

    def allow(self):
        """True when a submit may proceed.  An open breaker whose
        cooldown elapsed admits exactly ONE probe (the window re-arms
        immediately, so a burst cannot pile onto an unproven
        backend)."""
        with self._lock:
            if self.state == "closed":
                return True
            now = time.monotonic()
            if now < self.open_until:
                return False
            # half-open: one probe through, window re-armed
            self.open_until = now + self.cooldown
            events.incr("serve.breaker_probes")
            return True

    def ok(self):
        with self._lock:
            self.streak = 0
            reopened = self.state != "closed"
            self.state = "closed"
            self.open_until = 0.0
        if reopened:
            events.incr("serve.breaker_closed")
            _bb.record("serve", "breaker_closed", model=self.model)

    def fail(self, exc=None):
        with self._lock:
            self.streak += 1
            tripped = (self.streak >= self.max_fails
                       or self.state == "open")
            newly = tripped and self.state == "closed"
            if tripped:
                self.state = "open"
                self.open_until = time.monotonic() + self.cooldown
            streak = self.streak
        if newly:
            events.incr("serve.breaker_opened")
            _bb.record("serve", "breaker_open", model=self.model,
                       consecutive_fails=int(streak),
                       error=type(exc).__name__ if exc else None,
                       cooldown_s=self.cooldown)
            import logging
            logging.getLogger(__name__).warning(
                "serving backend %r circuit OPEN after %d consecutive "
                "failures (%s); failing fast for %.1fs", self.model,
                streak, type(exc).__name__ if exc else "?",
                self.cooldown)


class _Entry:
    __slots__ = ("name", "engine", "breaker", "footprint", "basis",
                 "devices", "detail", "cost_labels", "version",
                 "canary", "spawn")

    def __init__(self, name, engine, breaker, footprint, basis,
                 devices, detail, cost_labels=None, version=None,
                 spawn=None):
        self.name = name
        self.engine = engine
        self.breaker = breaker
        self.footprint = footprint
        self.basis = basis          # "measured" | "projected"
        self.devices = devices      # indices into the registry pool
        self.detail = detail
        # cost-registry label families this entry's measured footprint
        # is read from (one for one-shot engines; prefill/decode_step/
        # join for generation engines)
        self.cost_labels = cost_labels or ["serve.infer:%s" % name]
        self.version = version      # serving version tag (ISSUE 16)
        # in-flight canary route: {"name", "version", "fraction",
        # "acc"} — the deterministic traffic-mirroring state
        self.canary = None
        # registration kwargs, so resize / register_version can
        # rebuild an engine with the same signature without the
        # caller re-supplying it
        self.spawn = spawn or {}


class ModelRegistry:
    """N `InferenceEngine`s behind one admission-controlled surface.

    devices: the serving pool (Contexts; default: the current
        context).  Every model replica occupies one pool device and
        commits its footprint to that device's ledger.
    hbm_budget: per-device budget in bytes (default
        MXNET_SERVE_HBM_BUDGET; 0 = the device's reported bytes_limit,
        else unbudgeted — admission always runs, the ledger is always
        kept, but nothing is refused without a budget to refuse
        against).
    """

    def __init__(self, devices=None, hbm_budget=None):
        if devices is None:
            devices = [current_context()]
        self._ctxs = [d if isinstance(d, Context) else Context(*d)
                      for d in devices]
        budget = int(hbm_budget if hbm_budget is not None
                     else _cfg.get("MXNET_SERVE_HBM_BUDGET"))
        self._budgets = [self._device_budget(c, budget)
                         for c in self._ctxs]
        self._committed = [0] * len(self._ctxs)
        self._lock = threading.Lock()
        self._models = {}           # name -> _Entry
        self._closed = False
        _bb.install_crash_hooks()
        _REGISTRIES.add(self)

    @staticmethod
    def _device_budget(ctx, budget):
        if budget > 0:
            return budget
        try:
            from ..storage import memory_info
            _, limit = memory_info(ctx)
            return int(limit or 0)  # 0 = backend reports no limit
        except Exception:           # noqa: BLE001
            return 0

    # -- admission -----------------------------------------------------
    def _place(self, name, footprint, replicas, kv_detail=None):
        """Best-fit decreasing bin-pack: the `replicas` pool devices
        with the most free budget take the model.  Returns the chosen
        indices, or raises AdmissionDenied with the full decision.
        `kv_detail` (generation admission) breaks the footprint's
        slots×kv term out so the refusal NAMES it — KV cache is the
        part that scales with concurrency, not the deploy.  Caller
        holds self._lock."""
        free = [(self._budgets[i] - self._committed[i]
                 if self._budgets[i] > 0 else float("inf"), i)
                for i in range(len(self._ctxs))]
        free.sort(key=lambda t: (-t[0], t[1]))
        if replicas > len(self._ctxs):
            raise AdmissionDenied(
                "model %r wants %d replicas but the pool has %d "
                "devices" % (name, replicas, len(self._ctxs)))
        chosen = free[:replicas]
        worst_free, _ = chosen[-1]
        if worst_free < footprint:
            decision = [
                {"device": repr(self._ctxs[i]),
                 "budget": self._budgets[i],
                 "committed": self._committed[i],
                 "free": (self._budgets[i] - self._committed[i]
                          if self._budgets[i] > 0 else None)}
                for i in range(len(self._ctxs))]
            events.incr("serve.admission_rejected")
            events.incr("serve.admission_rejected",
                        labels={"model": name})
            # the refusal is a flight-recorder event NAMING the model
            # and the bin-packing decision (the acceptance contract) —
            # a later blackbox dump explains why the deploy bounced
            _bb.record("serve", "admission_rejected", model=name,
                       projected_bytes=int(footprint),
                       replicas=int(replicas),
                       kv_detail=kv_detail,
                       decision=decision)
            kv_term = ""
            if kv_detail:
                kv_term = (" — of which KV cache %d bytes (%d slots x "
                           "%d bytes/slot; fewer slots or a smaller "
                           "max_len shrink the KV term, the model "
                           "itself is only %d bytes)"
                           % (kv_detail.get("kv_bytes", 0),
                              kv_detail.get("slots", 0),
                              kv_detail.get("kv_bytes_per_slot", 0),
                              kv_detail.get("param_bytes", 0)))
            raise AdmissionDenied(
                "model %r projected footprint %d bytes does not fit "
                "the remaining budget on %d device(s): %s%s"
                % (name, footprint, replicas,
                   ", ".join("%s free=%s" % (d["device"], d["free"])
                             for d in decision), kv_term))
        return [i for _, i in chosen]

    def _build_engine(self, name, ctor, build_timeout):
        """Run the engine constructor in a worker bounded by
        `build_timeout` seconds (MXNET_SERVE_BUILD_TIMEOUT_S when
        None; <= 0 = unbounded).  A build that wedges (hung compile,
        stalled param replication) raises the typed
        `RegistrationTimeout` instead of holding the deploy path
        hostage; the abandoned worker closes its engine if it ever
        finishes, so nothing leaks.  The `serve.build` fault site
        stalls inside the worker — the deterministic wedge the
        regression test arms."""
        if build_timeout is None:
            build_timeout = float(
                _cfg.get("MXNET_SERVE_BUILD_TIMEOUT_S"))
        if build_timeout <= 0:
            fault.maybe_slow("serve.build")
            try:
                return ctor()
            except Exception as e:
                # an allocator OOM during the build IS the forensic
                # moment: dump who was resident before unwinding
                _mw.guard_oom("serve.build", e)
                raise
        box = {"engine": None, "exc": None, "abandoned": False}
        done = threading.Event()
        claim = threading.Lock()

        def build():
            try:
                fault.maybe_slow("serve.build")
                eng = ctor()
            except BaseException as e:      # noqa: BLE001 — reraised
                box["exc"] = e              # on the caller's thread
            else:
                with claim:                 # exactly one side owns the
                    orphan = box["abandoned"]   # engine: the caller
                    if not orphan:          # (returned) or the builder
                        box["engine"] = eng     # (closes the orphan)
                if orphan:
                    try:                    # too late: caller already
                        eng.close(1.0)      # rolled the ledger back
                    except Exception:       # noqa: BLE001
                        pass
            done.set()

        t = threading.Thread(target=build, daemon=True,
                             name="ServeBuild-%s" % name)
        t.start()
        if not done.wait(build_timeout):
            with claim:
                timed_out = box["engine"] is None
                box["abandoned"] = timed_out
            if timed_out:
                events.incr("serve.registration_timeout")
                events.incr("serve.registration_timeout",
                            labels={"model": name})
                _bb.record("serve", "registration_timeout",
                           model=name, timeout_s=float(build_timeout))
                raise RegistrationTimeout(
                    "engine build for model %r did not complete "
                    "within %.1fs (MXNET_SERVE_BUILD_TIMEOUT_S / "
                    "build_timeout=); ledger hold rolled back — "
                    "retry or raise the bound" % (name, build_timeout))
        if box["exc"] is not None:
            _mw.guard_oom("serve.build", box["exc"])
            raise box["exc"]
        return box["engine"]

    def register(self, name, block, replicas=1, example_shape=None,
                 wire_dtype=None, buckets=None, max_batch=None,
                 build_timeout=None, **engine_kw):
        """Admit `block` as model `name` on `replicas` pool devices.

        The per-device footprint comes from the cost registry when
        measured rows exist for this model (a known re-deploy), else
        from `project_footprint` — both checked against the device
        budgets BEFORE any executable is built.  Raises AdmissionDenied
        (with a flight-recorder event) on refusal; returns the
        admission record on success."""
        name = str(name)
        max_batch = int(max_batch if max_batch is not None
                        else _cfg.get("MXNET_SERVE_MAX_BATCH"))
        from .engine import _parse_buckets
        bset = _parse_buckets(
            buckets if buckets is not None
            else _cfg.get("MXNET_SERVE_BUCKETS"), max_batch)
        label = "serve.infer:%s" % name
        # admission always starts from a fresh PROJECTION of the block
        # in hand: the cost registry is process-wide and keeps rows
        # across unregister, so trusting a pre-existing
        # 'serve.infer:<name>' row here would admit a RE-registered
        # name at its previous incarnation's footprint.  Measured
        # reality flows into the ledger through warmup()→reconcile(),
        # which reads the rows THIS engine's executables just filed.
        if example_shape is not None:
            footprint, detail = project_footprint(
                block, bset, example_shape, wire_dtype)
            basis = "projected"
        else:
            # no signature yet (deferred first-request engines): only
            # the parameter side is projectable
            try:
                footprint = _param_bytes(block)
            except Exception:       # noqa: BLE001 — deferred params
                footprint = 0
            basis, detail = "projected", {"source": "params_only"}
        with self._lock:
            if self._closed:
                raise EngineClosed("registry is closed")
            if name in self._models:
                raise ValueError("model %r already registered "
                                 "(unregister it first)" % name)
            idxs = self._place(name, footprint, int(replicas))
            for i in idxs:
                self._committed[i] += footprint
            # hold the name while the engine builds OUTSIDE the lock
            # (construction replicates params onto devices — slow)
            self._models[name] = None
        try:
            # deploys watermark under their own memwatch phase: param
            # replication is the residency step change the steady
            # envelope must not absorb
            with _mw.phase("deploy"):
                engine = self._build_engine(
                    name,
                    lambda: InferenceEngine(
                        block, devices=[self._ctxs[i] for i in idxs],
                        buckets=bset, max_batch=max_batch,
                        example_shape=example_shape,
                        wire_dtype=wire_dtype,
                        cost_label=label, **engine_kw),
                    build_timeout)
        except Exception:
            with self._lock:    # roll the admission back — a failed
                for i in idxs:  # (or timed-out) build must not leak
                    self._committed[i] = max(    # committed budget
                        0, self._committed[i] - footprint)
                self._models.pop(name, None)
            raise
        entry = _Entry(
            name, engine,
            _Breaker(name, _cfg.get("MXNET_SERVE_BREAKER_FAILS"),
                     _cfg.get("MXNET_SERVE_BREAKER_COOLDOWN_S")),
            footprint, basis, idxs, detail,
            version=engine_kw.get("version"),
            spawn=dict(engine_kw, replicas=int(replicas),
                       example_shape=example_shape,
                       wire_dtype=wire_dtype, buckets=list(bset),
                       max_batch=max_batch))
        with self._lock:
            if self._closed:
                closed = True       # a close() raced the engine build:
            else:                   # don't resurrect a closed registry
                closed = False
                self._models[name] = entry
        if closed:
            engine.close()
            raise EngineClosed("registry closed during registration "
                               "of model %r" % name)
        events.incr("serve.models_admitted")
        _bb.record("serve", "admitted", model=name,
                   footprint_bytes=int(footprint), basis=basis,
                   devices=[repr(self._ctxs[i]) for i in idxs])
        return {"model": name, "footprint_bytes": int(footprint),
                "basis": basis, "detail": detail,
                "devices": [repr(self._ctxs[i]) for i in idxs]}

    def register_quantized(self, name, block, calib_data=None,
                           calib_mode=None, num_calib_batches=None,
                           exclude_layers=None, **register_kw):
        """Post-training-quantize `block` (in place: calibrate over
        `calib_data`, rewrite Dense/Conv2D into their int8 forms —
        `serving.quantize.quantize_for_serving`), then admit it like
        any other model.

        The int8 weights are non-trainable Parameters, so the SAME
        ledger that prices an f32 tenant prices this one at ~1/4 the
        parameter bytes — the admission record and any refusal carry
        the quantization detail (layers, calibration mode, weight-byte
        split), and ``warmup(name)``→``reconcile()`` replaces the int8
        projection with the measured rows exactly as for f32 models.
        Returns the admission record with the calibration report
        merged into its ``detail``."""
        from .quantize import quantize_for_serving
        _, qreport = quantize_for_serving(
            block, calib_data, calib_mode=calib_mode,
            num_calib_batches=num_calib_batches,
            exclude_layers=exclude_layers)
        try:
            rec = self.register(name, block, **register_kw)
        except AdmissionDenied:
            # the refusal event already fired in _place; a second one
            # here names the quantization detail so the forensic trail
            # shows the ~1/4 footprint was already applied when the
            # deploy bounced (fewer replicas or a bigger budget is the
            # next lever, not a smaller dtype)
            _bb.record("serve", "quantized_rejected", model=str(name),
                       **{k: qreport[k] for k in
                          ("quantized_layers", "calib_mode",
                           "weight_bytes_total_after")})
            raise
        entry = self._entry(name)
        entry.detail.update(qreport)
        rec["detail"] = dict(entry.detail)
        rec["quantized"] = True
        _bb.record("serve", "quantized_admitted", model=entry.name,
                   footprint_bytes=int(entry.footprint),
                   layers=qreport["quantized_layers"],
                   calib_mode=qreport["calib_mode"],
                   weight_bytes_after=qreport[
                       "weight_bytes_total_after"])
        return rec

    def register_generator(self, name, block, bos, eos, slots=None,
                           max_len=None, prompt_buckets=None,
                           **engine_kw):
        """Admit `block` as GENERATION model `name` on one pool device
        (a `serving.generation.GenerationEngine`).

        Admission accounts what one-shot serving has no analogue for:
        the KV term — ``slots × kv_bytes_per_slot`` from
        `project_generation_footprint` (HBM scales with CONCURRENT
        SEQUENCES, not just params).  A refusal names that term in
        both the AdmissionDenied message and the flight-recorder
        ledger.  ``warmup(name)`` reconciles the projection against
        the measured ``decode_step`` cost-registry row (whose argument
        bytes ARE params + the full slot cache)."""
        from .generation import (GenerationEngine,
                                 project_generation_footprint,
                                 _parse_prompt_buckets)
        name = str(name)
        slots = int(slots if slots is not None
                    else _cfg.get("MXNET_GEN_SLOTS"))
        max_len = int(max_len if max_len is not None
                      else _cfg.get("MXNET_GEN_MAX_LEN"))
        bset = _parse_prompt_buckets(
            prompt_buckets if prompt_buckets is not None
            else _cfg.get("MXNET_GEN_BUCKETS"), max_len)
        label = "serve.infer:%s" % name
        footprint, detail = project_generation_footprint(
            block, slots, max_len, bset)
        with self._lock:
            if self._closed:
                raise EngineClosed("registry is closed")
            if name in self._models:
                raise ValueError("model %r already registered "
                                 "(unregister it first)" % name)
            idxs = self._place(name, footprint, 1, kv_detail=detail)
            for i in idxs:
                self._committed[i] += footprint
            self._models[name] = None       # hold the name (build
        try:                                # outside the lock)
            engine = GenerationEngine(
                block, bos, eos, ctx=self._ctxs[idxs[0]], slots=slots,
                max_len=max_len, prompt_buckets=bset,
                cost_label=label, **engine_kw)
        except Exception:
            with self._lock:
                for i in idxs:
                    self._committed[i] = max(
                        0, self._committed[i] - footprint)
                self._models.pop(name, None)
            raise
        entry = _Entry(
            name, engine,
            _Breaker(name, _cfg.get("MXNET_SERVE_BREAKER_FAILS"),
                     _cfg.get("MXNET_SERVE_BREAKER_COOLDOWN_S")),
            footprint, "projected", idxs, detail,
            cost_labels=[label + ":prefill", label + ":decode_step",
                         label + ":join"])
        with self._lock:
            if self._closed:
                closed = True
            else:
                closed = False
                self._models[name] = entry
        if closed:
            engine.close()
            raise EngineClosed("registry closed during registration "
                               "of model %r" % name)
        events.incr("serve.models_admitted")
        _bb.record("serve", "admitted", model=name,
                   footprint_bytes=int(footprint), basis="projected",
                   kv_detail=detail,
                   devices=[repr(self._ctxs[i]) for i in idxs])
        return {"model": name, "footprint_bytes": int(footprint),
                "basis": "projected", "detail": detail,
                "devices": [repr(self._ctxs[i]) for i in idxs]}

    def generate(self, name, prompt, max_new_tokens=None,
                 deadline=None, lane=None, tenant=None):
        """Route one generation request through model `name`'s
        circuit breaker (the same `_route` triage as one-shot
        submits).  Returns the `GenerationStream`; terminal
        infrastructure failures on its future feed the breaker."""
        entry = self._entry(name)
        return self._route(entry, entry.engine.submit, prompt,
                           max_new_tokens=max_new_tokens,
                           deadline=deadline, lane=lane,
                           tenant=tenant)

    def unregister(self, name, timeout=30.0):
        """Close the model's engine (drain + resolve every future) and
        release its committed budget."""
        with self._lock:
            entry = self._models.get(str(name))
            if entry is None:           # absent or mid-register
                raise UnknownModel("model %r is not registered"
                                   % (name,))
            del self._models[str(name)]
            for i in entry.devices:
                self._committed[i] = max(
                    0, self._committed[i] - entry.footprint)
            # instant traffic revert: any primary mirroring traffic to
            # this name stops NOW, not at its next rollback bookkeeping
            for e in self._models.values():
                if e is not None and e.canary \
                        and e.canary.get("name") == str(name):
                    e.canary = None
        entry.engine.close(timeout)
        # drop the model's cost rows with it: a later re-registration
        # under the same name must not read THIS incarnation's
        # footprint (register projects fresh; warmup re-measures)
        for fam in entry.cost_labels:
            _costs.drop_rows(fam, kind="serve")
        events.incr("serve.models_evicted")
        _bb.record("serve", "evicted", model=entry.name,
                   released_bytes=int(entry.footprint))

    # -- elastic resize (ISSUE 16) -------------------------------------
    def resize(self, name, replicas, force=False, timeout=30.0,
               build_timeout=None):
        """Grow/shrink model `name` to `replicas` pool devices —
        make-before-break: the NEW replica set is admitted (bin-packed
        + committed) while the old one still serves, the new engine is
        built and warmed, traffic swaps atomically, and only then is
        the old engine closed and its commitment released.  The
        temporary double-count is the safe direction — admission may
        transiently refuse OTHER deploys, never oversubscribe HBM.
        `force=True` rebuilds even at the same replica count (the
        supervisor's all-replicas-unhealthy fallback).  Raises
        AdmissionDenied when the new set does not fit; the old engine
        keeps serving untouched."""
        entry = self._entry(name)
        if not isinstance(entry.engine, InferenceEngine):
            raise ValueError(
                "resize() supports one-shot InferenceEngine models "
                "only (generation engines are single-device)")
        replicas = int(replicas)
        if replicas < 1:
            raise ValueError("replicas must be >= 1, got %d"
                             % replicas)
        if replicas == len(entry.devices) and not force:
            return {"model": entry.name, "replicas": replicas,
                    "resized": False}
        with self._lock:
            if self._closed:
                raise EngineClosed("registry is closed")
            idxs = self._place(entry.name, entry.footprint, replicas)
            for i in idxs:
                self._committed[i] += entry.footprint
        old_engine = entry.engine
        spawn = {k: v for k, v in entry.spawn.items()
                 if k not in ("replicas", "version")}
        example_shape = spawn.pop("example_shape", None)
        wire_dtype = spawn.pop("wire_dtype", None)
        bset = spawn.pop("buckets", None)
        max_batch = spawn.pop("max_batch", None)
        label = "serve.infer:%s" % entry.name

        def ctor():
            eng = InferenceEngine(
                old_engine._block,
                devices=[self._ctxs[i] for i in idxs],
                buckets=bset, max_batch=max_batch,
                example_shape=example_shape, wire_dtype=wire_dtype,
                cost_label=label, version=entry.version, **spawn)
            if old_engine._param_src is not None:
                # the primary was promoted since registration: new
                # replicas must serve the promoted weights, not the
                # original block's
                eng.refresh_params_from(old_engine._param_src)
            return eng

        engine = None
        try:
            engine = self._build_engine(entry.name, ctor,
                                        build_timeout)
            if example_shape is not None:
                engine.warmup()     # new replicas compile BEFORE the
                                    # swap — traffic never pays it
        except Exception:
            with self._lock:        # release the NEW commitment; the
                for i in idxs:      # old set never stopped serving
                    self._committed[i] = max(
                        0, self._committed[i] - entry.footprint)
            if engine is not None:
                try:
                    engine.close(1.0)
                except Exception:   # noqa: BLE001
                    pass
            raise
        with self._lock:
            old_devices, entry.devices = entry.devices, idxs
            entry.engine = engine
            for i in old_devices:
                self._committed[i] = max(
                    0, self._committed[i] - entry.footprint)
        old_engine.close(timeout)
        events.incr("serve.resized")
        events.incr("serve.resized", labels={"model": entry.name})
        _bb.record("serve", "resized", model=entry.name,
                   replicas=replicas, from_replicas=len(old_devices),
                   forced=bool(force),
                   devices=[repr(self._ctxs[i]) for i in idxs])
        return {"model": entry.name, "replicas": replicas,
                "resized": True,
                "devices": [repr(self._ctxs[i]) for i in idxs]}

    # -- versioned deploys (ISSUE 16) ----------------------------------
    def register_version(self, name, block, version, fraction=None,
                         warmup=True, **register_kw):
        """Admit `block` as version `version` of model `name`
        ALONGSIDE the serving one, under the same admission ledger
        (entry name ``<name>@<version>``, own engine/breaker/ledger
        hold), and start mirroring a deterministic `fraction` of the
        primary's traffic to it (default
        MXNET_CTL_CANARY_FRACTION).  Engine signature defaults come
        from the primary's registration, so the canary serves the
        same wire contract without re-specifying it.  The
        `model.bad_version` fault site taints the version admitted
        while armed (engine.degrade) — after warmup, so the taint
        degrades traffic, not compilation.  Promote with
        `promote_version`, abort with `rollback_version`."""
        base = self._entry(name)
        if not isinstance(base.engine, InferenceEngine):
            raise ValueError("register_version() supports one-shot "
                             "InferenceEngine models only")
        version = str(version)
        cname = "%s@%s" % (name, version)
        with self._lock:
            if base.canary is not None:
                raise ValueError(
                    "model %r already has version %r in flight "
                    "(promote or roll it back first)"
                    % (name, base.canary["version"]))
        tainted = fault.should_fire("model.bad_version")
        spawn = {k: v for k, v in base.spawn.items()
                 if k not in ("replicas", "version")}
        spawn.update(register_kw)
        replicas = int(spawn.pop("replicas", 1))
        rec = self.register(cname, block, replicas=replicas,
                            version=version, **spawn)
        try:
            centry = self._entry(cname)
            if warmup and centry.engine._example_shape is not None:
                self.warmup(cname)
            if tainted:
                stall = float(_cfg.get("MXNET_CTL_DEGRADE_S"))
                centry.engine.degrade(stall)
                _bb.record("serve", "bad_version", model=str(name),
                           version=version, stall_s=stall)
            fraction = float(
                fraction if fraction is not None
                else _cfg.get("MXNET_CTL_CANARY_FRACTION"))
            if not (0.0 <= fraction <= 1.0):
                raise ValueError("canary fraction must be in [0, 1], "
                                 "got %r" % (fraction,))
            with self._lock:
                cur = self._models.get(str(name))
                if cur is None or cur is not base:
                    raise UnknownModel(
                        "model %r was unregistered while version %r "
                        "built" % (name, version))
                base.canary = {"name": cname, "version": version,
                               "fraction": fraction, "acc": 0.0}
        except Exception:
            # the canary's ledger hold releases on EVERY exit path —
            # a failed warmup/validation must not strand it
            try:
                self.unregister(cname, timeout=5.0)
            except UnknownModel:
                pass
            raise
        events.incr("serve.versions_admitted")
        events.incr("serve.versions_admitted",
                    labels={"model": str(name), "version": version})
        _bb.record("serve", "version_admitted", model=str(name),
                   version=version, fraction=fraction,
                   tainted=bool(tainted))
        rec.update(version=version, fraction=fraction,
                   tainted=bool(tainted))
        return rec

    def canary(self, name):
        """The in-flight canary route for model `name` ({name,
        version, fraction, acc}) or None."""
        with self._lock:
            entry = self._models.get(str(name))
            if entry is None:
                raise UnknownModel("model %r is not registered"
                                   % (name,))
            return dict(entry.canary) if entry.canary else None

    def set_canary_fraction(self, name, fraction):
        """Re-point the mirrored traffic fraction of model `name`'s
        in-flight version (the supervisor's ramp actuator)."""
        fraction = float(fraction)
        if not (0.0 <= fraction <= 1.0):
            raise ValueError("canary fraction must be in [0, 1], "
                             "got %r" % (fraction,))
        with self._lock:
            entry = self._models.get(str(name))
            if entry is None:
                raise UnknownModel("model %r is not registered"
                                   % (name,))
            if entry.canary is None:
                raise ValueError("model %r has no version in flight"
                                 % (name,))
            entry.canary["fraction"] = fraction
            version = entry.canary["version"]
        _bb.record("serve", "canary_fraction", model=str(name),
                   version=version, fraction=fraction)
        return fraction

    def promote_version(self, name, timeout=30.0):
        """Promote model `name`'s in-flight version: the primary
        engine swaps to the version's weights in place
        (`refresh_params_from` — the already-warmed executables keep
        serving, zero downtime), re-tags its version label, and the
        canary entry is unregistered (its ledger hold released
        exactly once).  A failed swap (parameter-tree mismatch)
        restores the canary route so `rollback_version` can still
        clean up."""
        name = str(name)
        with self._lock:
            entry = self._models.get(name)
            if entry is None:
                raise UnknownModel("model %r is not registered"
                                   % (name,))
            can, entry.canary = entry.canary, None
        if can is None:
            raise ValueError("model %r has no version in flight to "
                             "promote" % (name,))
        try:
            centry = self._entry(can["name"])
            src = (centry.engine._param_src
                   if centry.engine._param_src is not None
                   else centry.engine._block)
            entry.engine.refresh_params_from(src,
                                             version=can["version"])
        except Exception:
            with self._lock:        # keep the canary rollbackable —
                cur = self._models.get(name)    # its ledger hold must
                if cur is not None and cur.canary is None:  # still
                    cur.canary = can            # release exactly once
            raise
        entry.version = can["version"]
        try:
            self.unregister(can["name"], timeout)
        except UnknownModel:
            pass
        events.incr("serve.versions_promoted")
        events.incr("serve.versions_promoted",
                    labels={"model": name, "version": can["version"]})
        _bb.record("serve", "version_promoted", model=name,
                   version=can["version"])
        return {"model": name, "version": can["version"]}

    def rollback_version(self, name, reason=None, timeout=30.0):
        """Revert model `name`'s in-flight version: traffic mirroring
        stops immediately (the route is cleared under the lock before
        anything slow), the canary entry is unregistered and its
        ledger hold released.  Idempotent — a second rollback (or a
        rollback racing a promote) returns None and touches nothing,
        so the release happens exactly once.  Returns the rolled-back
        route dict."""
        name = str(name)
        with self._lock:
            entry = self._models.get(name)
            can = entry.canary if entry is not None else None
            if entry is not None:
                entry.canary = None
        if can is None:
            return None
        try:
            self.unregister(can["name"], timeout)
        except UnknownModel:
            pass
        events.incr("serve.versions_rolled_back")
        events.incr("serve.versions_rolled_back",
                    labels={"model": name, "version": can["version"]})
        _bb.record("serve", "version_rolled_back", model=name,
                   version=can["version"],
                   reason=str(reason) if reason else None)
        return dict(can)

    # -- traffic -------------------------------------------------------
    def _entry(self, name):
        with self._lock:
            entry = self._models.get(str(name))
        if entry is None:   # absent OR still mid-register (placeholder)
            raise UnknownModel("model %r is not registered" % (name,))
        return entry

    def engine(self, name):
        """The model's underlying InferenceEngine (escape hatch)."""
        return self._entry(name).engine

    def _observed(self, breaker):
        """Future callback: success (or a flow-control rejection)
        feeds the breaker's verdict; infrastructure failures trip
        it."""
        def cb(fut):
            if fut.cancelled():
                return
            exc = fut.exception()
            if exc is None:
                breaker.ok()
            elif not isinstance(exc, _FLOW_ERRORS):
                breaker.fail(exc)
        return cb

    def _route(self, entry, submit, *args, **kw):
        """ONE breaker triage for every submit shape: one-shot submits
        return a Future, generation submits a GenerationStream whose
        `.future` carries the verdict — the done-callback lands on
        whichever exists."""
        if not entry.breaker.allow():
            events.incr("serve.breaker_rejected")
            events.incr("serve.breaker_rejected",
                        labels={"model": entry.name})
            raise CircuitOpen(
                "model %r backend circuit is open (cooldown %.1fs); "
                "recent dispatches failed terminally"
                % (entry.name, entry.breaker.cooldown))
        try:
            res = submit(*args, **kw)
        except _FLOW_ERRORS:
            raise                   # engine self-protection: neutral
        except (ValueError, TypeError):
            raise                   # CLIENT error (bad shape/dtype/
                                    # lane): a misconfigured caller
                                    # must not open the breaker on a
                                    # healthy backend for everyone
        except Exception as e:      # noqa: BLE001 — submit-side infra
            entry.breaker.fail(e)   # failure counts against the model
            raise
        fut = getattr(res, "future", res)
        fut.add_done_callback(self._observed(entry.breaker))
        return res

    def _traffic_entry(self, entry):
        """Canary mirroring (ISSUE 16): a deterministic fraction
        ACCUMULATOR (not a RNG) routes exactly `fraction` of the
        primary's submits to the in-flight version — reproducible
        splits, no sampling noise in the canary's labeled series.
        The canary rides its own entry: own breaker, own engine, own
        version-labeled telemetry."""
        if entry.canary is None:
            return entry
        with self._lock:
            can = entry.canary
            if can is None or can["fraction"] <= 0.0:
                return entry
            can["acc"] += can["fraction"]
            if can["acc"] < 1.0 - 1e-9:
                return entry
            can["acc"] -= 1.0
            target = self._models.get(can["name"])
        return target if target is not None else entry

    def submit(self, name, x, deadline=None, lane=None, tenant=None):
        """Route one example to model `name` through its circuit
        breaker.  Raises UnknownModel / CircuitOpen synchronously on
        top of the engine's QueueFull / Shed / EngineClosed.  With a
        version in flight, a deterministic fraction of submits mirrors
        to the canary entry instead."""
        entry = self._traffic_entry(self._entry(name))
        return self._route(entry, entry.engine.submit, x,
                           deadline=deadline, lane=lane, tenant=tenant)

    def submit_batch(self, name, x, deadline=None, lane=None,
                     tenant=None):
        entry = self._traffic_entry(self._entry(name))
        return self._route(entry, entry.engine.submit_batch, x,
                           deadline=deadline, lane=lane, tenant=tenant)

    # -- warmup / reconcile --------------------------------------------
    def warmup(self, name=None, **kw):
        """`engine.warmup()` for one model (or all), then reconcile the
        admission ledger against the MEASURED cost-registry rows the
        warmup just created — the projection admitted the model, the
        measurement keeps the ledger honest."""
        if name is not None:
            names = [str(name)]
        else:
            with self._lock:
                names = [n for n, e in self._models.items()
                         if e is not None]
        out = {}
        for n in names:
            entry = self._entry(n)
            # warmup residency is a phase of its own in the memory
            # observatory: the compile/replication spike watermarks
            # under "warmup", never inflating the steady envelope
            with _mw.phase("warmup"):
                out[n] = entry.engine.warmup(**kw)
            self.reconcile(n)
        return out if name is None else out[str(name)]

    def reconcile(self, name):
        """Swap a model's projected footprint for the measured one
        (cost-registry memory-analysis rows) when available; adjusts
        the committed ledger by the delta and records the correction.
        Generation entries read the max across their prefill/
        decode_step/join families — decode_step's argument bytes ARE
        params + the full slot cache, the honest concurrent working
        set.  Returns the measured bytes (0 = nothing measured
        yet)."""
        entry = self._entry(name)
        measured = max(_costs.footprint_bytes(fam, kind="serve")
                       for fam in entry.cost_labels)
        if measured <= 0 or measured == entry.footprint:
            return measured
        with self._lock:
            prior = entry.footprint
            delta = measured - prior
            for i in entry.devices:
                self._committed[i] = max(0, self._committed[i] + delta)
            entry.footprint, entry.basis = measured, "measured"
        pct = (delta / prior) if prior > 0 else 1.0
        _bb.record("serve", "footprint_reconciled", model=entry.name,
                   measured_bytes=int(measured), delta_bytes=int(delta),
                   pct_moved=round(pct, 4))
        if abs(pct) > 0.10:
            # a reconcile that MOVES the row >10% means the projection
            # (or a prior measurement) was materially wrong — its own
            # event + counter so drift trends are countable without
            # parsing every reconcile (ISSUE 20 satellite)
            events.incr("serve.footprint_reconcile_large")
            events.incr("serve.footprint_reconcile_large",
                        labels={"model": entry.name})
            _bb.record("serve", "footprint_reconcile_large",
                       model=entry.name, prior_bytes=int(prior),
                       measured_bytes=int(measured),
                       pct_moved=round(pct, 4))
        return measured

    # -- introspection / lifecycle -------------------------------------
    def slo_targets(self):
        """{lane: tightest relative deadline seconds observed across
        every hosted model's engine} — the registry-level SLO targets
        (ISSUE 12).  A lane's target is the MOST demanding deadline
        any tenant asked of it; lanes that never saw a deadlined
        request contribute nothing."""
        with self._lock:
            entries = [e for e in self._models.values()
                       if e is not None]
        out = {}
        for e in entries:
            for lane, t in e.engine.slo_targets().items():
                cur = out.get(lane)
                if cur is None or t < cur:
                    out[lane] = t
        return out

    def slo_lane_quotas(self):
        """{lane: most restrictive occupancy quota fraction enforced
        by any hosted engine} — the budgets the default shed burn
        rules derive from (see `InferenceEngine.slo_lane_quotas`)."""
        with self._lock:
            entries = [e for e in self._models.values()
                       if e is not None]
        out = {}
        for e in entries:
            for lane, f in e.engine.slo_lane_quotas().items():
                cur = out.get(lane)
                out[lane] = f if cur is None else min(cur, f)
        return out

    def install_slo_rules(self, **kw):
        """Build + register the default serving SLO rules
        (telemetry/slo.py) with this registry's observed per-lane
        deadline targets: per-lane shed burn-rate + p99-vs-deadline.
        Returns the registered rule names; call again after traffic
        has established deadlines to pick up tighter targets."""
        from ..telemetry import slo as _slo
        return _slo.install_default_serving_rules(registry=self, **kw)

    def slow_requests(self, name=None, lane=None):
        """The promoted slow-request exemplars (ISSUE 19) of one
        hosted model's engine — or of every hosted model when ``name``
        is None — newest last.  The per-request autopsy surface:
        each row carries the full phase waterfall, terminal status
        and dominant phase (`tools/blackbox.py autopsy` renders the
        same rows from a dump)."""
        if name is not None:
            names = [str(name)]
        else:
            with self._lock:
                names = [n for n, e in self._models.items()
                         if e is not None]
        out = []
        for n in names:
            j = getattr(self._entry(n).engine, "_journal", None)
            if j is None:
                continue
            for ex in j.exemplars():
                if lane is None or ex.get("lane") == lane:
                    out.append(ex)
        out.sort(key=lambda e: e.get("ts", 0))
        return out

    def stats(self):
        with self._lock:
            models = {}
            for n, e in self._models.items():
                if e is None:
                    continue
                j = getattr(e.engine, "_journal", None)
                models[n] = {
                    "footprint_bytes": e.footprint, "basis": e.basis,
                    "devices": [repr(self._ctxs[i])
                                for i in e.devices],
                    "replicas": len(e.devices),
                    "version": e.version,
                    "canary": dict(e.canary) if e.canary else None,
                    "breaker": e.breaker.state,
                    "reqtrace": None if j is None else
                    {"records": j.records, "promoted": j.promoted}}
            # measured columns (ISSUE 20 satellite): a FRESH memwatch
            # sample annotates each ledger row with the allocator's
            # view and the drift ratio; stale/absent samples leave
            # None — the reader always knows whether it is looking at
            # measurement or just the ledger again
            measured = None
            try:
                measured = _mw.fresh_device_bytes()
            except Exception:       # noqa: BLE001
                measured = None
            ledger = []
            for c, b, u in zip(self._ctxs, self._budgets,
                               self._committed):
                m = None if measured is None else \
                    measured.get(_mw.device_key(c))
                ledger.append(
                    {"device": repr(c), "budget": b, "committed": u,
                     "free": (b - u) if b > 0 else None,
                     "measured_bytes": m,
                     "drift": (round(m / u, 4)
                               if m is not None and u > 0 else None)})
        return {"models": models, "ledger": ledger}

    def drain_all(self, timeout=30.0):
        ok = True
        with self._lock:
            entries = [e for e in self._models.values()
                       if e is not None]
        for e in entries:
            ok = e.engine.drain(timeout) and ok
        return ok

    def close(self, timeout=30.0):
        """Close every engine (resolving every outstanding future) and
        release the whole ledger.  Idempotent."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            entries, self._models = [
                e for e in self._models.values() if e is not None], {}
            self._committed = [0] * len(self._ctxs)
        for e in entries:
            e.engine.close(timeout)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
