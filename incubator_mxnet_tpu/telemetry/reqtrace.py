"""Per-request lifecycle journal with tail-based exemplar retention
(ISSUE 19 tentpole).

Every aggregate surface so far — percentile rings, burn rates, cost
tables — answers "how slow", never "WHICH request and WHERE did its
time go".  When ``serve-p99-high`` fires, the on-call needs the
autopsy, not the gauge.  This module keeps it:

- **A compact record per request.**  Engines allocate one pre-sized
  `Record` (``__slots__`` struct) at submit and fill its phase stamps
  from timestamps they ALREADY compute — no extra clock reads on the
  hot path beyond the stamps the engine takes anyway.  The serve
  ladder is queue-wait → coalesce → dispatch → device-infer →
  join/D2H → future-resolution; generation maps queue → prefill →
  decode → resolution onto the same slots.  Sheds and deadline kills
  record their termination reason and which phase ate the budget (the
  first phase whose end stamp never landed).
- **A bounded per-engine ring** (`MXNET_REQTRACE_RING`) of retired
  records — the recent-request journal `Journal.snapshot()` /
  teletop render.
- **Tail-based exemplar promotion**, decided OFF the hot path at
  retire time: a request whose e2e lands above its lane's rolling p99
  (window `MXNET_REQTRACE_WINDOW`; pin the threshold with
  `MXNET_REQTRACE_PIN_P99_US` for deterministic tests), and every
  terminal failure (shed / deadline / error), is promoted to an
  **exemplar**: the full phase waterfall goes to the flight-recorder
  ring (stamped at ADMISSION time — the same end-vs-delivery
  discipline as `spans.emit_foreign`), to a durable ``reqtrace``
  history row, and into the bounded process-wide exemplar set that
  SLO alerts attach the worst match from (`worst_exemplar`).

Surfaces: `block()` feeds `dump_blackbox()` / ``/metrics.json`` /
teletop; ``python -m incubator_mxnet_tpu.tools.blackbox autopsy``
renders the waterfall + phase-dominance verdict; `telemetry/slo.py`
attaches the worst matching exemplar to every firing serving /
generation rule.

Overhead contract: `tools/check_overhead.py` holds the serving loop
with journaling on vs off to <2% — records are pre-sized structs, the
submit path pays one allocation + plain attribute writes, and ALL
classification (phase math, p99 compare, promotion) happens at retire
time.  ``MXNET_REQTRACE=0`` (or `enable(False)`) makes `start()`
return None and every stamp a no-op.
"""
from __future__ import annotations

import itertools
import threading
import time
import weakref
from collections import deque

from .. import config as _cfg
from ..monitor import events
from . import flightrec as _bb
from . import spans as _sp

__all__ = ["Record", "Journal", "journal", "enabled", "enable",
           "exemplars", "worst_exemplar", "block", "reset", "PHASES"]

#: per-engine-kind phase ladders: (phase, end-stamp slot) pairs walked
#: in order from ``t_enq``.  A record terminated before a stamp landed
#: charges the remaining wall to that phase — "which phase ate the
#: budget" for sheds and deadline kills.
PHASES = {
    "serve": (("queue", "t_collect"), ("coalesce", "t_exec"),
              ("dispatch", "t_infer0"), ("infer", "t_infer1"),
              ("join", "t_fin"), ("resolve", "t_done")),
    "gen": (("queue", "t_collect"), ("prefill", "t_exec"),
            ("decode", "t_fin"), ("resolve", "t_done")),
}

#: rolling-p99 promotion needs this many completed requests in the
#: lane window first — without the floor, the first request after
#: start would always out-tail an empty window
MIN_WINDOW = 20

#: retire-time p99 cache: re-sort the lane window only every N
#: retires (the tail moves slowly; an exact per-retire sort would be
#: the kind of hidden O(n log n) the overhead gate exists to catch)
_P99_EVERY = 32

# None = follow the MXNET_REQTRACE knob; enable() installs an explicit
# process-local override (the flightrec/spans pattern — what the
# overhead gate's on/off trial flips)
_enabled = None


def enabled() -> bool:
    """Whether the request journal is armed for this process."""
    if _enabled is not None:
        return _enabled
    return bool(_cfg.get("MXNET_REQTRACE"))


def enable(flag=True):
    """Flip journaling on/off (None = revert to the MXNET_REQTRACE
    knob); returns the previous effective state."""
    global _enabled
    prev = enabled()
    _enabled = None if flag is None else bool(flag)
    return prev


_rids = itertools.count(1)      # CPython-atomic next(); no lock


class Record:
    """One request's lifecycle struct — pre-sized slots, filled by
    plain attribute writes from stamps the engine already takes.
    Monotonic seconds throughout; phase math happens once, at retire
    or render time, never on the submit path."""

    __slots__ = ("rid", "lane", "tenant", "bucket", "n",
                 "t_enq", "t_collect", "t_exec", "t_infer0",
                 "t_infer1", "t_fin", "t_done",
                 "status", "reason", "e2e_us")

    def __init__(self, t_enq, lane, tenant):
        self.rid = next(_rids)
        self.lane = lane
        self.tenant = tenant
        self.bucket = None
        self.n = 1
        self.t_enq = t_enq
        self.t_collect = None
        self.t_exec = None
        self.t_infer0 = None
        self.t_infer1 = None
        self.t_fin = None
        self.t_done = None
        self.status = None
        self.reason = None
        self.e2e_us = None


def _status_of(exc):
    """(status, reason) from the engine's terminal exception — typed
    errors map onto stable status strings the autopsy families key
    on."""
    if exc is None:
        return "ok", None
    name = type(exc).__name__
    msg = str(exc)
    if len(msg) > 120:
        msg = msg[:117] + "..."
    if name == "Shed":
        return "shed", msg
    if name == "DeadlineExceeded":
        return "deadline", msg
    if name == "QueueFull":
        return "queue_full", msg
    if name == "EngineClosed":
        return "closed", msg
    return "error", "%s: %s" % (name, msg)


def _phases(rec, kind):
    """(phase µs dict, budget phase) for one retired record: an exact
    partition of [t_enq, t_done] along the kind's ladder.  A missing
    stamp means the request terminated INSIDE that phase — it is
    charged the remaining wall and named the budget phase; a complete
    record's budget phase is its dominant one."""
    ladder = PHASES.get(kind, PHASES["serve"])
    phases, cur, budget = {}, rec.t_enq, None
    for name, attr in ladder:
        t = getattr(rec, attr)
        if t is None:
            phases[name] = max(0.0, (rec.t_done - cur) * 1e6)
            budget = name
            break
        phases[name] = max(0.0, (t - cur) * 1e6)
        cur = t
    else:
        budget = max(phases, key=phases.get) if phases else None
    return phases, budget


def record_summary(rec, kind):
    """A retired record as a plain dict (ring snapshots / teletop)."""
    phases, budget = _phases(rec, kind)
    return {"rid": rec.rid, "lane": rec.lane or "-",
            "tenant": rec.tenant, "bucket": rec.bucket, "n": rec.n,
            "status": rec.status, "reason": rec.reason,
            "e2e_us": round(rec.e2e_us or 0.0, 1),
            "phases": {k: round(v, 1) for k, v in phases.items()},
            "dominant": max(phases, key=phases.get) if phases
            else None,
            "budget_phase": budget}


class Journal:
    """One engine's bounded request journal + per-lane tail tracker.

    Engines call `start()` at submit (None when disabled — every
    later stamp guards on the record), fill stamps as the request
    crosses phases, and `retire()` exactly once at resolution.
    Everything that costs more than an attribute write — phase math,
    the p99 compare, exemplar promotion — happens inside `retire()`,
    off the submit path."""

    def __init__(self, kind, model, version=None, ring=None,
                 window=None, keep=None):
        self.kind = str(kind)
        self.model = str(model)
        self.version = version
        self._ring = deque(maxlen=int(
            ring if ring is not None
            else _cfg.get("MXNET_REQTRACE_RING")))
        self._window = int(window if window is not None
                           else _cfg.get("MXNET_REQTRACE_WINDOW"))
        self._ex = deque(maxlen=int(
            keep if keep is not None
            else _cfg.get("MXNET_REQTRACE_EXEMPLARS")))
        self._lane_e2e = {}         # lane -> deque of completed e2e µs
        self._lane_p99 = {}         # lane -> [cached p99, age]
        self._lock = threading.Lock()
        self.records = 0
        self.promoted = 0

    # -- hot path ------------------------------------------------------
    def start(self, t_enq, lane, tenant=None):
        """A fresh record for an admitted request (None when the
        journal is disabled — stamps and retire() no-op on None)."""
        if not enabled():
            return None
        return Record(t_enq, lane, tenant)

    # -- retire path (off the submit path) -----------------------------
    def retire(self, rec, exc=None, status=None, reason=None,
               t_done=None):
        """Classify one finished record: status from the terminal
        exception (or explicit ``status=``), e2e, ring append, lane
        tail update, and the promotion decision.  Idempotence is the
        CALLER's contract (engines null the request's rec reference
        before calling)."""
        if rec is None:
            return None
        rec.t_done = float(t_done) if t_done is not None \
            else time.monotonic()
        if status is not None:
            rec.status, rec.reason = str(status), reason
        else:
            rec.status, rec.reason = _status_of(exc)
        rec.e2e_us = (rec.t_done - rec.t_enq) * 1e6
        lane = rec.lane or "-"
        promote = rec.status != "ok"
        with self._lock:
            self._ring.append(rec)
            self.records += 1
            if rec.status == "ok":
                dq = self._lane_e2e.get(lane)
                if dq is None:
                    dq = self._lane_e2e[lane] = \
                        deque(maxlen=self._window)
                dq.append(rec.e2e_us)
                promote = rec.e2e_us > self._p99_locked(lane, dq)
        events.incr("reqtrace.records")
        if promote:
            self._promote(rec)
        return rec

    def _p99_locked(self, lane, dq):
        """The lane's promotion threshold: the pinned value when
        `MXNET_REQTRACE_PIN_P99_US` > 0 (deterministic tests), else
        the rolling window's p99, re-sorted every `_P99_EVERY`
        retires.  Infinite until the window has MIN_WINDOW samples."""
        pin = float(_cfg.get("MXNET_REQTRACE_PIN_P99_US") or 0.0)
        if pin > 0.0:
            return pin
        if len(dq) < MIN_WINDOW:
            return float("inf")
        cached = self._lane_p99.get(lane)
        if cached is not None and cached[1] < _P99_EVERY:
            cached[1] += 1
            return cached[0]
        xs = sorted(dq)
        p = xs[min(len(xs) - 1, int(0.99 * len(xs)))]
        self._lane_p99[lane] = [p, 0]
        return p

    def _promote(self, rec):
        """Exemplar promotion: full waterfall into the flight-recorder
        ring (admission-stamped), a durable history row, and the
        bounded exemplar sets alerts/dumps read."""
        phases, budget = _phases(rec, self.kind)
        dominant = max(phases, key=phases.get) if phases else None
        wall0 = _sp.wall_of(rec.t_enq)
        ex = {"rid": rec.rid, "engine": self.kind, "model": self.model,
              "lane": rec.lane or "-", "tenant": rec.tenant,
              "bucket": rec.bucket, "n": rec.n,
              "status": rec.status, "reason": rec.reason,
              "e2e_us": round(rec.e2e_us, 1),
              "phases": {k: round(v, 1) for k, v in phases.items()},
              "dominant": dominant, "budget_phase": budget,
              "ts": wall0}
        if self.version is not None:
            ex["version"] = str(self.version)
        with self._lock:
            self._ex.append(ex)
            self.promoted += 1
        with _GLOCK:
            _EXEMPLARS.append(ex)
        events.incr("reqtrace.exemplars")
        events.incr("reqtrace.exemplars", labels={"lane": ex["lane"]})
        # ring event stamped at ADMISSION (the emit_foreign end-stamp
        # discipline, satellite 3): the dump timeline shows the
        # exemplar where its wait BEGAN, so queue growth and the
        # victim line up instead of the exemplar appearing after the
        # backlog already drained
        _bb.record_at(wall0, "reqtrace", "exemplar", rid=rec.rid,
                      engine=self.kind, model=self.model,
                      lane=ex["lane"], status=rec.status,
                      e2e_us=int(rec.e2e_us), dominant=str(dominant),
                      **{"%s_us" % k: int(v)
                         for k, v in phases.items()})
        try:
            from . import history as _hist
            _hist.record("reqtrace", "exemplar", rec.e2e_us,
                         labels={"engine": self.kind,
                                 "lane": ex["lane"],
                                 "model": self.model},
                         rid=rec.rid, status=rec.status,
                         reason=rec.reason, dominant=dominant,
                         phases=ex["phases"])
        except Exception:           # noqa: BLE001 — durability is
            pass                    # best-effort, never the request

    # -- introspection -------------------------------------------------
    def exemplars(self):
        with self._lock:
            return [dict(e) for e in self._ex]

    def snapshot(self):
        """The journal's block for dumps / /metrics.json / teletop:
        counts, per-lane window p99 + slowest recent request (with its
        waterfall), and the retained exemplars."""
        with self._lock:
            recs = list(self._ring)
            windows = {ln: (len(dq), list(dq))
                       for ln, dq in self._lane_e2e.items()}
            exs = [dict(e) for e in self._ex]
        slow = {}
        for rec in recs:
            ln = rec.lane or "-"
            cur = slow.get(ln)
            if rec.e2e_us is not None and \
                    (cur is None or rec.e2e_us > cur.e2e_us):
                slow[ln] = rec
        lanes = {}
        for ln in set(windows) | set(slow):
            n, vals = windows.get(ln, (0, []))
            entry = {"window_n": n}
            if vals:
                xs = sorted(vals)
                entry["p99_us"] = round(
                    xs[min(len(xs) - 1, int(0.99 * len(xs)))], 1)
            if ln in slow:
                entry["slowest"] = record_summary(slow[ln], self.kind)
            lanes[ln] = entry
        out = {"engine": self.kind, "model": self.model,
               "records": self.records, "promoted": self.promoted,
               "ring": len(recs), "lanes": lanes, "exemplars": exs}
        if self.version is not None:
            out["version"] = str(self.version)
        return out


# -- process-wide registry (dumps, alerts, teletop) --------------------
_GLOCK = threading.Lock()
_JOURNALS = []                  # weakrefs — journals die with engines
_EXEMPLARS = deque(maxlen=64)   # newest promotions across all engines


def journal(kind, model, version=None, **kw) -> Journal:
    """Create + register one engine's journal.  Held by WEAKREF here:
    a journal lives exactly as long as its engine, and a torn-down
    engine's journal must not pin its ring in every later dump."""
    j = Journal(kind, model, version=version, **kw)
    with _GLOCK:
        _JOURNALS[:] = [r for r in _JOURNALS if r() is not None]
        _JOURNALS.append(weakref.ref(j))
    return j


def _live_journals():
    with _GLOCK:
        refs = list(_JOURNALS)
    return [j for j in (r() for r in refs) if j is not None]


def exemplars(lane=None, engine=None, model=None):
    """Recent promoted exemplars across every engine, oldest first,
    optionally filtered by lane / engine kind / model."""
    with _GLOCK:
        out = list(_EXEMPLARS)
    if lane is not None:
        out = [e for e in out if e.get("lane") == str(lane)]
    if engine is not None:
        out = [e for e in out if e.get("engine") == str(engine)]
    if model is not None:
        out = [e for e in out if e.get("model") == str(model)]
    return out


def worst_exemplar(lane=None, engine=None, model=None):
    """The retained exemplar with the largest e2e matching the
    filters (None when nothing matches) — what a firing SLO rule
    attaches as its autopsy."""
    best = None
    for ex in exemplars(lane=lane, engine=engine, model=model):
        if best is None or ex.get("e2e_us", 0) > best.get("e2e_us", 0):
            best = ex
    return best


def block() -> dict:
    """The ``reqtrace`` block for dumps / /metrics.json / teletop:
    every live journal's snapshot + the newest cross-engine
    exemplars.  Empty dict when nothing was journaled."""
    js = [j.snapshot() for j in _live_journals()]
    js = [s for s in js if s["records"]]
    with _GLOCK:
        exs = list(_EXEMPLARS)
    if not js and not exs:
        return {}
    return {"journals": js, "exemplars": exs[-16:]}


def reset():
    """Tests: drop every registered journal and retained exemplar."""
    global _enabled
    with _GLOCK:
        del _JOURNALS[:]
        _EXEMPLARS.clear()
    _enabled = None
