"""Flight recorder: always-on black-box forensics (ISSUE 5 tentpole
part 1).

PR 4's telemetry is pull-based: a dashboard someone is watching.  When
a run DIES — NaN rollback, preemption, a serving dispatcher backstop,
an uncaught exception on a feed thread — nothing durable survives to
explain it.  This module is the black box: a lock-guarded bounded ring
of structured events that every subsystem appends to unconditionally
(step records, span completions, counter-delta samples, checkpoint /
rollback / fault / preemption markers, feed stalls, serving
queue-depth samples, HBM watermarks), plus an atomic JSON dump that
turns the ring + the counter ledger + the executable cost table
(costs.py) + the config-knob snapshot into ONE self-contained forensic
file a dead run leaves behind.

Cost model — the recorder is ON BY DEFAULT, so it must be nearly free:
`record()` is one enabled-check, one tuple build and one deque append
under a lock; no string formatting, no serialization, nothing until
dump time.  `MXNET_BLACKBOX=0` reduces every hook to a single bool
read.

Dump triggers (all end in `dump_blackbox()`):

- NaN-rollback and preemption in `ResilientTrainer`
- a mesh shrink in `parallel/elastic.py` (a replica died: the dump
  names it and carries the health timeline that condemned it)
- the serving dispatcher's error backstop (`serving/engine.py`)
- uncaught exceptions: `sys.excepthook` + `threading.excepthook`
  (a raising feed/dispatcher worker leaves a dump, not silence)
- `SIGUSR2` — a live-run snapshot without stopping anything
- an explicit `telemetry.dump_blackbox()`

`install_crash_hooks()` is idempotent and chains the previous hooks;
`ResilientTrainer`, `InferenceEngine` and `telemetry.start()` install
them on construction.  `python -m incubator_mxnet_tpu.tools.blackbox
<dump>` summarizes a dump (timeline tail, counters, cost table, a
one-line suspected-cause heuristic).
"""
from __future__ import annotations

import itertools
import json
import os
import signal
import sys
import threading
import time
from collections import deque

from .. import config as _cfg
from ..monitor import events

__all__ = ["enabled", "enable", "record", "record_at", "record_mesh",
           "ring_snapshot",
           "clear", "configure", "hbm_sample", "hbm_peaks",
           "sample_counters", "dump_blackbox", "crash_dump",
           "install_crash_hooks", "uninstall_crash_hooks",
           "last_dump_path", "set_fleet_provider", "fleet_block"]

SCHEMA = "mxtpu-blackbox/1"

_LOCK = threading.Lock()
_RING = None                    # deque of (ts, tid, kind, name, data)
_SEQ = itertools.count(1)       # CPython-atomic; dump filename ordinal
_HBM_PEAK = {}                  # device label -> peak bytes_in_use seen
_LAST_COUNTS = {}               # sample_counters baseline
_LAST = {"path": None}          # newest dump path (tests / CLI)
_CRASH_SEEN = {}                # reason -> last crash_dump wall time
#: min seconds between crash dumps for the SAME reason — a persistent
#: dispatcher error loops every ~10ms, and each dump is a full file;
#: without a throttle a degraded host fills its disk with forensics
CRASH_DUMP_MIN_GAP_S = 10.0

# None = follow the MXNET_BLACKBOX knob; enable() installs an explicit
# process-local override (the spans.py pattern)
_enabled = None

# fleet-view provider (ISSUE 11): telemetry/fleet.py registers a
# zero-arg callable returning the merged per-replica telemetry block;
# every dump embeds its result so a forensic file answers "which
# replica was slow" without a live process to ask
_FLEET = {"provider": None}


def set_fleet_provider(fn):
    """Register the callable whose result becomes the `fleet` block of
    every black-box dump (None unregisters).  Best-effort at dump
    time: a raising provider yields no block, never a failed dump."""
    _FLEET["provider"] = fn


def fleet_block():
    """The registered fleet provider's current block (None when no
    provider is set, the provider raised, or its supervisor is gone)."""
    fn = _FLEET["provider"]
    if fn is None:
        return None
    try:
        return fn()
    except Exception:               # noqa: BLE001 — the fleet view is
        return None                 # forensic garnish, never a blocker


def enabled() -> bool:
    """Whether the flight recorder is on (default: yes — it exists for
    the runs nobody instrumented in advance)."""
    if _enabled is not None:
        return _enabled
    return bool(_cfg.get("MXNET_BLACKBOX"))


def enable(flag=True):
    """Flip the recorder on/off (None = revert to the MXNET_BLACKBOX
    knob); returns the previous effective state."""
    global _enabled
    prev = enabled()
    _enabled = None if flag is None else bool(flag)
    return prev


def _ring():
    global _RING
    r = _RING
    if r is None:
        with _LOCK:
            if _RING is None:
                _RING = deque(maxlen=max(
                    16, int(_cfg.get("MXNET_BLACKBOX_RING"))))
            r = _RING
    return r


def configure(maxlen=None):
    """(Re)size the ring (drops retained events).  Tests use this; the
    default comes from MXNET_BLACKBOX_RING at first use."""
    global _RING
    with _LOCK:
        _RING = deque(maxlen=max(16, int(
            maxlen if maxlen is not None
            else _cfg.get("MXNET_BLACKBOX_RING"))))


def record(kind: str, name: str, **data):
    """Append one structured event to the ring.  The HOT path: one
    bool read disabled (checked HERE, before the clock read and the
    delegate call — the MXNET_BLACKBOX=0 contract); enabled, one
    tuple + one locked deque append — no formatting, no serialization
    until dump time."""
    if not enabled():
        return
    record_at(time.time(), kind, name, **data)


def record_at(ts: float, kind: str, name: str, **data):
    """`record()` with an explicit wall-clock stamp: a FOREIGN span
    (telemetry.emit_foreign) describes an interval that ended in
    another process BEFORE the message delivering it arrived — a
    prefetched decode batch can sit in the queue for hundreds of ms,
    and stamping delivery time would shift the slice right by the
    whole queue wait in the dump's chrome view."""
    if not enabled():
        return
    ev = (float(ts), threading.get_ident(), kind, name, data or None)
    _ring()                         # ensure it exists (locks itself)
    with _LOCK:
        # re-read under the lock: a concurrent configure() swaps the
        # ring, and appending to the discarded deque loses the event
        _RING.append(ev)


def record_mesh(phase: str, **data):
    """Mesh-transition marker (the elastic trainer's forensic trail):
    one ring event under kind ``mesh`` — ``replica_down`` /
    ``replica_slow`` / ``shrink`` / ``grow`` / ``generation`` — with
    the replica ids, device labels and step in `data`.  A mesh-shrink
    black-box dump is read by exactly these events: the dump NAMES the
    lost replica because this marker landed in the ring before
    `crash_dump("mesh.shrink")` snapshotted it."""
    record("mesh", phase, **data)


def clear():
    with _LOCK:
        if _RING is not None:
            _RING.clear()
        _LAST_COUNTS.clear()
        _HBM_PEAK.clear()
        _CRASH_SEEN.clear()
        _LAST["path"] = None


def ring_snapshot(last=None):
    """The retained events, oldest first, as dicts (`last` keeps only
    the newest N)."""
    with _LOCK:
        evs = list(_RING) if _RING is not None else []
    if last is not None:
        evs = evs[-int(last):]
    out = []
    for ts, tid, kind, name, data in evs:
        d = {"ts": ts, "tid": tid % 100000, "kind": kind, "name": name}
        if data:
            d.update(data)
        out.append(d)
    return out


# -- HBM watermarks ----------------------------------------------------
def hbm_sample(tag="sample", force=False):
    """Sample per-device HBM via `storage.memory_events` (which posts
    the `mem.*` series on monitor.events), update the per-device peak
    watermarks, and append one ring event per device.  Backends whose
    PJRT `memory_stats` returns None — CPU jax, the axon plugin
    (ndarray.py:77) — used to silently no-op here; they now fall back
    to the `jax.live_arrays()` per-device byte sum
    (`storage.live_arrays_events`), each event tagged
    ``source="live_arrays"`` so a dump never mistakes the committed-
    buffer sum for an allocator report.  Gated on `enabled()` (the
    MXNET_BLACKBOX=0 contract is a single bool read per hook);
    `force=True` is the dump path, which samples even when an explicit
    dump was requested on a disarmed recorder."""
    if not (enabled() or force):
        return []
    try:
        from ..storage import live_arrays_events, memory_events
        stats = memory_events()
        if not stats:
            stats = live_arrays_events()
    except Exception:               # noqa: BLE001 — forensics must
        return []                   # never take the run down
    for s in stats:
        dev = s["device"]
        with _LOCK:
            peak = max(_HBM_PEAK.get(dev, 0),
                       s.get("peak_bytes", 0), s["bytes_in_use"])
            _HBM_PEAK[dev] = peak
        record("hbm", dev, tag=tag, bytes_in_use=s["bytes_in_use"],
               peak_bytes=peak, bytes_limit=s.get("bytes_limit", 0),
               **({"source": s["source"]} if "source" in s else {}))
    return stats


def hbm_peaks() -> dict:
    """{device: peak bytes_in_use observed by hbm_sample}."""
    with _LOCK:
        return dict(_HBM_PEAK)


# -- counter-delta samples ---------------------------------------------
def sample_counters(prefixes=None):
    """Record the nonzero counter DELTAS since the last sample as one
    ring event (the periodic exporter calls this every tick, so the
    timeline shows counter flow between dumps, not just the final
    totals).  Returns the delta dict.  Baseline updates are locked —
    the exporter worker and a checkpointing training thread sample
    concurrently, and a racy read-modify-write would double-count or
    drop deltas in the forensic timeline."""
    if not enabled():
        return {}
    snap = events.snapshot()
    if prefixes:
        snap = {k: v for k, v in snap.items()
                if any(k.startswith(p) for p in prefixes)}
    delta = {}
    with _LOCK:
        for k, v in snap.items():
            d = v - _LAST_COUNTS.get(k, 0)
            if d:
                delta[k] = d
            _LAST_COUNTS[k] = v
    if delta:                       # record() takes _LOCK itself —
        record("counters", "delta", **delta)    # append outside it
    return delta


# -- dump --------------------------------------------------------------
def _exc_block(exc):
    if exc is None:
        return None
    import traceback
    try:
        tb = "".join(traceback.format_exception(
            type(exc), exc, getattr(exc, "__traceback__", None)))
    except Exception:               # noqa: BLE001
        tb = ""
    return {"type": type(exc).__name__,
            "message": str(exc)[:500],
            "traceback": tb[-8000:]}


def _config_snapshot():
    out = {}
    for name in _cfg.list_vars():
        try:
            v = _cfg.get(name)
            out[name] = v if isinstance(
                v, (bool, int, float, str, type(None))) else str(v)
        except Exception:           # noqa: BLE001
            out[name] = "<unreadable>"
    return out


def _chrome_view(evs):
    """The event timeline as chrome://tracing JSON: span events render
    as complete ('X') slices, everything else as instants.  An event
    carrying an explicit `pid` (a foreign span emitted on behalf of a
    decode worker — telemetry.emit_foreign) keeps that pid, so the
    trace shows the worker's interval in its own process row."""
    out = []
    for e in evs:
        base = {"name": "%s:%s" % (e["kind"], e["name"]),
                "cat": e["kind"], "pid": e.get("pid") or os.getpid(),
                "tid": e["tid"]}
        dur = e.get("dur_us")
        if dur is not None:
            base.update(ph="X", ts=(e["ts"] * 1e6) - dur, dur=dur)
        else:
            base.update(ph="i", ts=e["ts"] * 1e6, s="t")
        args = {k: v for k, v in e.items()
                if k not in ("ts", "tid", "kind", "name")}
        if args:
            base["args"] = args
        out.append(base)
    return out


def _slug(s):
    return "".join(c if c.isalnum() or c in "-_." else "-"
                   for c in str(s))[:48] or "dump"


def _resolve_path(path, reason):
    if path:
        path = str(path)
        if not os.path.isdir(path):
            return path             # explicit file
        d = path
    else:
        # default to scratch, never the checkout: crash hooks armed
        # OUTSIDE bench/conftest (which set MXNET_BLACKBOX_DIR) used
        # to drop excepthook dumps into whatever directory the process
        # happened to be launched from — typically the repo root
        import tempfile
        d = _cfg.get("MXNET_BLACKBOX_DIR") or tempfile.gettempdir()
        os.makedirs(d, exist_ok=True)
    name = "blackbox-%s-p%d-%03d-%s.json" % (
        time.strftime("%Y%m%dT%H%M%S"), os.getpid(), next(_SEQ),
        _slug(reason))
    return os.path.join(d, name)


def dump_blackbox(path=None, reason="manual", exc=None, last=None):
    """Write the black box: config-knob snapshot, counter ledger +
    percentiles, executable cost table, HBM watermarks, the last-N
    event timeline, and a chrome-trace view of it — one atomic JSON
    file (tmp + os.replace).  `path` may be a file, a directory, or
    None (MXNET_BLACKBOX_DIR, else the system temp dir; auto-named).
    Returns the written path."""
    # order matters: snapshot the ledger FIRST, then sample (the
    # sample's own events land in the timeline of the NEXT dump, and
    # cost resolution must not skew the counters this dump reports)
    counters = events.snapshot()
    pcts = events.latency_snapshot()
    # tenant/lane splits (ISSUE 8): the labeled rings ride along so an
    # overload dump can say WHOSE p99 blew out, not just that one did
    labeled = {"counters": events.labeled_snapshot(),
               "percentiles": events.labeled_latency_snapshot()}
    hbm_sample(tag="dump", force=True)
    from . import costs as _costs
    try:
        cost_block = _costs.snapshot()
    except Exception:               # noqa: BLE001 — cost attribution
        cost_block = {"rows": [], "totals": {}}  # is best-effort
    fleet = fleet_block()
    # the SLO rule/alert state (ISSUE 12): a dump triggered BY an
    # alert (reason "slo:<rule>") carries the firing evidence; any
    # other dump still answers "was anything firing when this died"
    try:
        from . import slo as _slo
        slo_block = _slo.block() or None
    except Exception:               # noqa: BLE001 — forensic garnish
        slo_block = None
    # the control-plane state (ISSUE 16): guarded on the module being
    # ALREADY imported — a training-only dump must not pull the whole
    # serving stack in just to say "no supervisors"
    ctl_block = None
    try:
        ctl_mod = sys.modules.get(
            "incubator_mxnet_tpu.serving.controlplane")
        if ctl_mod is not None:
            ctl_block = ctl_mod.status_block() or None
    except Exception:               # noqa: BLE001
        ctl_block = None
    # the compile-loop decisions (ISSUE 18): same already-imported
    # guard — a run that never tuned must not pull the compile
    # subsystem in just to say "no decisions"
    tune_block = None
    try:
        tune_mod = sys.modules.get(
            "incubator_mxnet_tpu.compile.autotune")
        if tune_mod is not None:
            tune_block = tune_mod.block() or None
    except Exception:               # noqa: BLE001
        tune_block = None
    # the request journals + promoted exemplars (ISSUE 19): same
    # already-imported guard — a dump from a process that never ran an
    # engine must not import the tracing layer to say "no requests"
    rt_block = None
    try:
        rt_mod = sys.modules.get(
            "incubator_mxnet_tpu.telemetry.reqtrace")
        if rt_mod is not None:
            rt_block = rt_mod.block() or None
    except Exception:               # noqa: BLE001
        rt_block = None
    # the memory observatory (ISSUE 20): same already-imported guard;
    # the dump takes one sample first (when armed) so the block shows
    # the corpse's residency, not a stale tick — the OOM path already
    # forced its own sample before reaching here
    mw_block = None
    try:
        mw_mod = sys.modules.get(
            "incubator_mxnet_tpu.telemetry.memwatch")
        if mw_mod is not None:
            mw_mod.sample(tag="dump")
            mw_block = mw_mod.block() or None
    except Exception:               # noqa: BLE001
        mw_block = None
    evs = ring_snapshot(last=last)
    doc = {
        "schema": SCHEMA,
        "ts": time.time(),
        "pid": os.getpid(),
        "reason": str(reason),
        "exception": _exc_block(exc),
        "config": _config_snapshot(),
        "counters": counters,
        "percentiles": pcts,
        "labeled": labeled,
        "costs": cost_block,
        "fleet": fleet,
        "slo": slo_block,
        "controlplane": ctl_block,
        "autotune": tune_block,
        "reqtrace": rt_block,
        "memwatch": mw_block,
        "hbm": {"peaks": hbm_peaks()},
        "events": evs,
        "trace": {"traceEvents": _chrome_view(evs),
                  "displayTimeUnit": "ms"},
    }
    path = _resolve_path(path, reason)
    tmp = "%s.tmp.%d.%d" % (path, os.getpid(), threading.get_ident())
    with open(tmp, "w") as f:
        json.dump(doc, f, default=str)
    os.replace(tmp, path)
    _LAST["path"] = path
    events.incr("blackbox.dumps")
    record("dump", str(reason), path=path)
    return path


def last_dump_path():
    """The newest dump this process wrote (None before the first)."""
    return _LAST["path"]


def crash_dump(reason, exc=None):
    """`dump_blackbox` for crash paths: never raises (a failing dump
    in an excepthook / signal handler / dispatcher backstop must not
    mask the original failure), and throttled per reason
    (CRASH_DUMP_MIN_GAP_S) — a persistently-failing dispatcher loop
    must not fill the disk with one dump per poll.  Returns the path,
    or None (disabled / throttled / failed)."""
    if not enabled():
        return None
    now = time.monotonic()
    with _LOCK:
        last = _CRASH_SEEN.get(reason)
        if last is not None and now - last < CRASH_DUMP_MIN_GAP_S:
            return None
        _CRASH_SEEN[reason] = now
    try:
        return dump_blackbox(reason=reason, exc=exc)
    except Exception:               # noqa: BLE001
        return None


# -- crash hooks -------------------------------------------------------
_HOOKS = {"installed": False, "prev_sys": None, "prev_thread": None,
          "prev_sig": None, "sig_installed": False}


def install_crash_hooks(sigusr2=True):
    """Install the black-box triggers: `sys.excepthook` +
    `threading.excepthook` (CHAINED — the previous hooks still run
    after the dump) and, on the main thread, a SIGUSR2 handler (which
    REPLACES any previous one; `uninstall_crash_hooks` restores it).
    Idempotent, and each trigger arms independently: a first call off
    the main thread installs the excepthooks only, and a later
    main-thread call still arms SIGUSR2.  No-op (returns False) when
    the recorder is disabled."""
    if not enabled():
        return False
    did = False
    if not _HOOKS["installed"]:
        prev_sys = sys.excepthook
        prev_thread = threading.excepthook

        def _sys_hook(tp, val, tb):
            if not (tp is SystemExit or tp is KeyboardInterrupt):
                record("fault", "uncaught", where="main",
                       type=getattr(tp, "__name__", str(tp)))
                crash_dump("excepthook", val)
            (prev_sys or sys.__excepthook__)(tp, val, tb)

        def _thread_hook(args):
            if args.exc_type is not SystemExit:
                record("fault", "uncaught",
                       where=getattr(args.thread, "name", "?"),
                       type=getattr(args.exc_type, "__name__", "?"))
                crash_dump("threading.excepthook", args.exc_value)
            prev_thread(args)

        sys.excepthook = _sys_hook
        threading.excepthook = _thread_hook
        _HOOKS.update(prev_sys=prev_sys, prev_thread=prev_thread,
                      installed=True)
        did = True
    if sigusr2 and not _HOOKS["sig_installed"] \
            and hasattr(signal, "SIGUSR2"):
        def _usr2_work():
            record("marker", "sigusr2")
            crash_dump("sigusr2")

        def _on_usr2(signum, frame):
            # the handler interrupts the main thread BETWEEN bytecodes
            # — it may hold the ring lock mid-record(), so taking it
            # here would self-deadlock; hand the dump to a thread
            threading.Thread(target=_usr2_work, daemon=True,
                             name="BlackboxUSR2").start()
        try:
            _HOOKS["prev_sig"] = signal.signal(signal.SIGUSR2, _on_usr2)
            _HOOKS["sig_installed"] = True
            did = True
        except (ValueError, OSError):   # not the main thread: a later
            _HOOKS["prev_sig"] = None   # main-thread call retries
    return did


def uninstall_crash_hooks():
    """Restore the chained hooks (tests; idempotent)."""
    if not _HOOKS["installed"]:
        return
    sys.excepthook = _HOOKS["prev_sys"] or sys.__excepthook__
    threading.excepthook = _HOOKS["prev_thread"] or \
        threading.__excepthook__
    if _HOOKS["sig_installed"]:
        try:
            signal.signal(signal.SIGUSR2,
                          _HOOKS["prev_sig"] or signal.SIG_DFL)
        except (ValueError, OSError):
            pass
        _HOOKS["sig_installed"] = False
    _HOOKS.update(installed=False, prev_sys=None, prev_thread=None,
                  prev_sig=None)
