"""Durable on-disk metrics history (ISSUE 12 tentpole part 1).

Every telemetry surface so far — counters, percentile rings, the cost
registry, the fleet view — evaporates at process exit; the only
history the repo keeps is whatever a bench run chose to embed in a
BENCH_*.json blob, and the flight recorder only dumps AFTER something
died.  This module is the durable substrate: an append-only, bounded,
on-disk time series every run contributes to and every later run (or
tool) can query.

Model:

- **One shard file per process** under ``MXNET_HISTORY_DIR``:
  ``history-<runid>.jsonl`` where ``runid = <ts>-p<pid>`` — no
  cross-process file locking, ever; concurrent runs write disjoint
  shards and `query()` reads across all of them.
- **Fixed-schema rows**, one JSON object per line.  Every row carries
  ``ts`` (epoch seconds), ``run``, ``kind``, ``name``, ``v`` (the
  scalar a trend plots) and optionally ``labels``; kinds add their own
  fields:

  =========  ==========================================================
  kind       rows written per exporter tick (`tick()`)
  =========  ==========================================================
  counter    per-name DELTA since the last tick (``v``) + the
             cumulative ``total`` — labeled splits ride as their own
             rows with ``labels``
  pct        percentile summary of each sampled series:
             ``p50``/``p90``/``p99``/``n`` with ``v`` = p99 (tails are
             what SLOs are defined on)
  cost       one row per cost-registry executable whose invocation
             count moved: ``flops``/``bytes_accessed``/``invocations``
             /``compile_wall_s`` (+ memory-analysis bytes when
             present), ``v`` = invocations.  These rows — including
             the ``aot.*`` compile/load walls riding the counter rows
             — are the persisted measured-cost substrate the ROADMAP
             item 2 autotuner trains on.
  fleet      one row per replica from the rank-0 FleetView merge
             (``labels={"replica": rid}``, the FIELDS vector inlined,
             ``v`` = step_us) — written by `record_fleet()` at the
             fleet PUBLISH cadence, not per tick: the merge owner
             stamps each round exactly once
  marker     durable run markers (checkpoint / rollback / preemption /
             mesh transitions), ``v`` = 1
  slo        alert transitions (telemetry/slo.py), ``v`` = 1 fired /
             0 cleared — firing serving/generation transitions also
             carry the scalar ``exemplar_*`` fields of the attached
             slow-request exemplar
  reqtrace   one row per PROMOTED request exemplar (telemetry/
             reqtrace.py): ``v`` = e2e µs, ``labels`` =
             {engine, lane, model}, the per-phase waterfall inlined
             under ``phases`` — written at retire time, tail/failure
             requests only, so slow-request autopsies survive the
             process and query across runs
  memwatch   per-device peak-watermark rows (telemetry/memwatch.py):
             ``v`` = peak used bytes, ``labels`` = {device, phase,
             source} — written only when a watermark RISES, so the
             cross-run memory envelope queries by run id
  =========  ==========================================================

- **Bounded**: a shard past ``MXNET_HISTORY_SHARD_KB`` is COMPACTED in
  place (atomic rewrite): the newest half of the rows survive intact,
  the older half is downsampled 2:1 (every other row), repeated until
  the shard fits in ~3/4 of the cap — old history loses resolution,
  never its envelope, and the newest rows are never dropped.  The
  writer is thread-safe (exporter worker + fleet supervisor + explicit
  callers share one lock).

Hot-path contract: NOTHING here runs per training step or per serving
request.  Rows are written at exporter-tick cadence (`tick()` from
`MetricsExporter`'s periodic worker), at fleet-publish cadence, and at
marker events (checkpoint/rollback) that are already off the critical
path — `tools/check_overhead.py` stays green with history enabled
because the step loop never touches this module.

Query:

    from incubator_mxnet_tpu.telemetry import history
    rows = history.query("serve.infer", kind="cost")      # across runs
    rows = history.query("train.step_us", since=t0, run="...-p123")

`python -m incubator_mxnet_tpu.tools.blackbox history` renders the
cross-run trend tables (and ``--diff`` two runs) from the same rows.
"""
from __future__ import annotations

import json
import os
import threading
import time

from .. import config as _cfg
from ..monitor import events

__all__ = ["HistoryWriter", "enabled", "history_dir", "get_writer",
           "record", "note_event", "record_fleet", "tick", "query",
           "runs", "flush", "reset"]

SCHEMA = "mxtpu-history/1"

#: rows the compaction floor never drops below (a shard with a handful
#: of giant rows must converge, not loop)
MIN_ROWS = 16


def history_dir() -> str:
    """The shard directory (MXNET_HISTORY_DIR; empty = disabled)."""
    return str(_cfg.get("MXNET_HISTORY_DIR") or "")


def enabled() -> bool:
    return bool(history_dir())


def _new_run_id() -> str:
    return "%s-p%d" % (time.strftime("%Y%m%dT%H%M%S"), os.getpid())


class HistoryWriter:
    """One process's append-only shard with size-capped compaction.

    Thread-safe; every public method is a no-op returning 0/None when
    the directory is unset.  `tick()` is the batch entry point the
    periodic exporter drives; `append()` is the single-row primitive
    markers and alerts use."""

    def __init__(self, directory=None, run=None, shard_kb=None):
        self._dir = directory if directory is not None \
            else history_dir()
        self.run = str(run) if run else _new_run_id()
        self._cap = int(shard_kb if shard_kb is not None
                        else _cfg.get("MXNET_HISTORY_SHARD_KB")) * 1024
        self._lock = threading.Lock()
        # serializes whole tick() bodies (the exporter worker and a
        # checkpointing training thread both tick): the delta
        # baselines below are read-modify-write state, and racing
        # them would write the same counter delta twice.  Separate
        # from _lock because tick() ends in append_rows (which takes
        # _lock itself)
        self._tick_lock = threading.Lock()
        self._bytes = None          # lazily sized from the file
        self._last_counts = {}      # tick counter-delta baseline
        self._last_lcounts = {}     # labeled-counter baseline
        self._last_invocations = {} # cost-row key -> invocations
        self.rows_written = 0

    @property
    def path(self):
        if not self._dir:
            return None
        return os.path.join(self._dir, "history-%s.jsonl" % self.run)

    # -- writing -------------------------------------------------------
    def append(self, kind, name, value, labels=None, ts=None, **fields):
        """Write ONE row (no-op when disabled).  Returns 1 if a row was
        written."""
        if not self._dir:
            return 0
        row = {"ts": float(ts if ts is not None else time.time()),
               "run": self.run, "kind": str(kind), "name": str(name),
               "v": float(value)}
        if labels:
            row["labels"] = {str(k): str(v) for k, v in labels.items()}
        if fields:
            row.update(fields)
        return self.append_rows([row])

    def append_rows(self, rows):
        """Write a batch of pre-built rows under one lock (one open +
        one flush per tick, not per row).  Returns the count."""
        if not self._dir or not rows:
            return 0
        body = "".join(json.dumps(r, sort_keys=True, default=str) + "\n"
                       for r in rows)
        data = body.encode()
        with self._lock:
            os.makedirs(self._dir, exist_ok=True)
            path = self.path
            if self._bytes is None:
                try:
                    self._bytes = os.path.getsize(path)
                except OSError:
                    self._bytes = 0
            with open(path, "a") as f:
                f.write(body)
            self._bytes += len(data)
            self.rows_written += len(rows)
            if self._bytes > self._cap:
                self._compact_locked()
        events.incr("history.rows", len(rows))
        return len(rows)

    def _compact_locked(self):
        """Rewrite the shard under the size cap: newest half kept
        intact, older half downsampled 2:1, repeated until the shard
        fits in ~3/4 of the cap (headroom so the next append doesn't
        immediately re-compact).  Atomic (tmp + os.replace); caller
        holds the lock."""
        path = self.path
        try:
            with open(path) as f:
                lines = [ln for ln in f.read().splitlines() if ln]
        except OSError:
            self._bytes = 0
            return
        target = max(1024, int(self._cap * 0.75))
        dropped = 0

        def size_of(ls):
            return sum(len(ln) + 1 for ln in ls)

        while size_of(lines) > target and len(lines) > MIN_ROWS:
            half = len(lines) // 2
            old, new = lines[:half], lines[half:]
            kept_old = old[1::2]        # downsample 2:1, newest-biased
            dropped += len(old) - len(kept_old)
            lines = kept_old + new
            if not kept_old and size_of(lines) > target:
                # pathological giant rows: shed oldest outright
                dropped += 1
                lines = lines[1:]
        tmp = "%s.tmp.%d.%d" % (path, os.getpid(),
                                threading.get_ident())
        body = "\n".join(lines) + ("\n" if lines else "")
        with open(tmp, "w") as f:
            f.write(body)
        os.replace(tmp, path)
        self._bytes = len(body.encode())
        events.incr("history.compactions")
        if dropped:
            events.incr("history.rows_downsampled", dropped)

    #: counter families the tick never writes: the history layer's own
    #: bookkeeping counters move BECAUSE a tick wrote rows, so
    #: including them would make every tick write at least one row
    #: forever — an idle process must quiesce
    SELF_PREFIXES = ("history.",)

    # -- the exporter-tick batch ---------------------------------------
    def tick(self, now=None):
        """Write one tick's fixed-schema batch: counter deltas (plain
        + labeled), percentile summaries, and cost-registry rows that
        moved.  (Per-replica fleet rows are written by
        `record_fleet()` at the rank-0 publish cadence — the merge
        owner stamps them once; re-reading the fleet block here would
        duplicate stale copies every tick.)  Returns the number of
        rows written.  Whole-tick bodies are serialized: the periodic
        exporter worker and a checkpointing training thread both call
        this, and the delta baselines are read-modify-write state."""
        if not self._dir:
            return 0
        with self._tick_lock:
            return self._tick_locked(
                float(now if now is not None else time.time()))

    def _tick_locked(self, now):
        rows = []
        step = None
        try:
            from . import spans as _sp
            step = _sp.get_global_step()
        except Exception:           # noqa: BLE001
            pass

        def row(kind, name, v, labels=None, **fields):
            r = {"ts": now, "run": self.run, "kind": kind,
                 "name": name, "v": float(v)}
            if step is not None:
                r["step"] = int(step)
            if labels:
                r["labels"] = {str(k): str(v_) for k, v_ in
                               labels.items()}
            r.update(fields)
            rows.append(r)

        # counters: deltas since the last tick (rates belong to the
        # reader; the cumulative rides along for exactness).  The
        # delta maps double as the movement gate for the pct rows
        # below, so they must be collected before baselines update
        snap = events.snapshot()
        deltas, ldeltas = {}, {}
        for name in sorted(snap):
            d = snap[name] - self._last_counts.get(name, 0)
            if d:
                deltas[name] = d
                if not name.startswith(self.SELF_PREFIXES):
                    row("counter", name, d, total=snap[name])
            self._last_counts[name] = snap[name]
        for name, lrows in events.labeled_snapshot().items():
            for lr in lrows:
                key = (name,) + tuple(sorted(lr["labels"].items()))
                d = lr["value"] - self._last_lcounts.get(key, 0)
                if d:
                    ldeltas[key] = d
                    if not name.startswith(self.SELF_PREFIXES):
                        row("counter", name, d, labels=lr["labels"],
                            total=lr["value"])
                self._last_lcounts[key] = lr["value"]

        # percentile summaries of the ring's CURRENT window — only
        # for series that SAW samples this tick (the companion
        # '<name>.n' counter moved): an idle process must quiesce,
        # not append identical windows forever (which would also
        # flood anomaly baselines with duplicates, driving MAD to 0)
        for name, p in events.latency_snapshot(pcts=(50, 90, 99)) \
                .items():
            if p and deltas.get(name + ".n"):
                row("pct", name, p.get("p99", 0), p50=p.get("p50"),
                    p90=p.get("p90"), p99=p.get("p99"), n=p.get("n"))
        for name, lrows in events.labeled_latency_snapshot(
                pcts=(50, 90, 99)).items():
            for lr in lrows:
                key = (name + ".n",) + tuple(sorted(
                    lr["labels"].items()))
                if not ldeltas.get(key):
                    continue
                row("pct", name, lr.get("p99", 0),
                    labels=lr["labels"], p50=lr.get("p50"),
                    p90=lr.get("p90"), p99=lr.get("p99"),
                    n=lr.get("n"))

        # cost rows that moved since the last tick: the persisted
        # measured-cost substrate (ROADMAP item 2's autotuner input)
        try:
            from . import costs as _costs
            for r in _costs.table():
                key = r["key"]
                if self._last_invocations.get(key) == r["invocations"] \
                        and key in self._last_invocations:
                    continue
                self._last_invocations[key] = r["invocations"]
                extra = {f: r[f] for f in
                         ("argument_bytes", "output_bytes",
                          "temp_bytes", "donated_bytes") if f in r}
                row("cost", r["label"], r["invocations"],
                    labels={"kind": r["kind"]},
                    flops=r["flops"],
                    bytes_accessed=r["bytes_accessed"],
                    invocations=r["invocations"],
                    compile_wall_s=r["compile_wall_s"],
                    analyzed=bool(r.get("analyzed")), **extra)
        except Exception:           # noqa: BLE001 — cost attribution
            pass                    # is best-effort, never a blocker
        return self.append_rows(rows)

    def flush(self):
        """Durability point (trainers call this at checkpoint
        boundaries): appends already hit the OS on write; this exists
        so callers have an explicit barrier to order against."""
        return self.path


# -- module-level singleton --------------------------------------------
_WRITER = None
_WLOCK = threading.Lock()


def get_writer() -> HistoryWriter:
    """The process-wide writer (created on first use; its run id is
    fixed for the process lifetime)."""
    global _WRITER
    w = _WRITER
    if w is None:
        with _WLOCK:
            if _WRITER is None:
                _WRITER = HistoryWriter()
            w = _WRITER
    return w


def record(kind, name, value, labels=None, **fields):
    """One row through the process writer (no-op when disabled)."""
    if not enabled():
        return 0
    return get_writer().append(kind, name, value, labels=labels,
                               **fields)


def note_event(name, **fields):
    """Durable run marker (checkpoint / rollback / preemption / mesh
    transition): survives the process where the flight-recorder ring
    does not.  No-op when disabled."""
    if not enabled():
        return 0
    return get_writer().append("marker", name, 1.0, **fields)


def record_fleet(replicas, step=None, stragglers=()):
    """Per-replica fleet rows from the rank-0 merge (FleetTelemetry
    calls this at publish cadence).  No-op when disabled."""
    if not enabled() or not replicas:
        return 0
    w = get_writer()
    slow = {str(s) for s in (stragglers or ())}
    rows = []
    now = time.time()
    for rid, fr in replicas.items():
        # FIELDS starts with the replica's own (possibly lagging)
        # "step" — inline it FIRST under its own name, then stamp the
        # row keys: "step" is the rank-0 MERGE round, so one round's
        # rows across replicas share it and can be joined
        r = dict(fr, replica_step=fr.get("step"))
        r.update(ts=now, run=w.run, kind="fleet", name="replica",
                 v=float(fr.get("step_us", 0)),
                 labels={"replica": str(rid)},
                 straggler=str(rid) in slow)
        if step is not None:
            r["step"] = int(step)
        rows.append(r)
    return w.append_rows(rows)


def tick(now=None):
    """One exporter tick's history batch (no-op when disabled)."""
    if not enabled():
        return 0
    return get_writer().tick(now=now)


def flush():
    if _WRITER is not None:
        return _WRITER.flush()
    return None


def reset():
    """Drop the process writer (tests: a new MXNET_HISTORY_DIR or run
    id takes effect on next use)."""
    global _WRITER
    with _WLOCK:
        _WRITER = None


# -- reading -----------------------------------------------------------
def _shards(directory):
    try:
        names = sorted(n for n in os.listdir(directory)
                       if n.startswith("history-")
                       and n.endswith(".jsonl"))
    except OSError:
        return []
    return [os.path.join(directory, n) for n in names]


def runs(directory=None):
    """Run ids with shards in the directory, oldest first: by the
    second-resolution start timestamp the name embeds, ties (two
    processes started in the same second — the pid suffix encodes no
    order) broken by the shard's mtime, so the most recently WRITING
    run sorts newest for `blackbox history --diff`'s default pair."""
    d = directory if directory is not None else history_dir()
    entries = []
    for p in _shards(d):
        rid = os.path.basename(p)[len("history-"):-len(".jsonl")]
        try:
            mt = os.stat(p).st_mtime
        except OSError:
            mt = 0.0
        entries.append((rid.split("-p")[0], mt, rid))
    entries.sort()
    return [rid for _, _, rid in entries]


def query(name=None, labels=None, since=None, run=None, kind=None,
          directory=None, limit=None):
    """Read matching rows across every shard (i.e. across runs) in the
    history directory, oldest first.

    name:   row-name PREFIX (``"serve.infer"`` matches the per-bucket
            ``serve.infer:demo[0]`` cost rows; None = all)
    labels: subset match — a row matches when it carries AT LEAST
            these label pairs
    since:  minimum ``ts`` (epoch seconds)
    run:    restrict to one run id (default: all runs)
    kind:   restrict to one row kind ("counter"/"pct"/"cost"/...)
    limit:  keep only the NEWEST N matches

    Malformed lines (a run killed mid-write) are skipped, never
    raised."""
    d = directory if directory is not None else history_dir()
    if not d:
        return []
    want = {str(k): str(v) for k, v in (labels or {}).items()}
    out = []
    for path in _shards(d):
        if run is not None and ("history-%s.jsonl" % run) != \
                os.path.basename(path):
            continue
        try:
            with open(path) as f:
                lines = f.read().splitlines()
        except OSError:
            continue
        for ln in lines:
            if not ln:
                continue
            try:
                row = json.loads(ln)
            except ValueError:
                continue            # torn tail line of a killed run
            if kind is not None and row.get("kind") != kind:
                continue
            if name is not None and \
                    not str(row.get("name", "")).startswith(str(name)):
                continue
            if since is not None and row.get("ts", 0) < float(since):
                continue
            if want:
                have = row.get("labels") or {}
                if any(have.get(k) != v for k, v in want.items()):
                    continue
            out.append(row)
    out.sort(key=lambda r: (r.get("ts", 0), r.get("run", "")))
    if limit is not None:
        out = out[-int(limit):]
    return out
