"""Declarative SLO / alert rules over the telemetry ledger (ISSUE 12
tentpole part 2).

Everything below this module observes; nothing JUDGES.  The serving
stack implicitly promises per-lane deadlines (PR 8) and the trainer
promises forward progress, but no in-tree component turns the
counters into a verdict while the run is still alive — the flight
recorder only dumps after the corpse.  This module closes that loop:
rules are evaluated each exporter tick against live snapshots (and,
for anomaly rules, the on-disk history baselines), and a FIRING rule
is a typed, multi-surface event:

- ``slo.fired`` / ``slo.cleared`` counters, labeled ``{rule=}``
- a flight-recorder ring event (kind ``slo``) naming the rule
- the ``mxnet_alert_active{rule="..."}`` Prometheus gauge (1 while
  firing, 0 while clear — `MetricsExporter` renders every registered
  rule)
- a PROACTIVE black-box dump, reason ``slo:<rule>`` — the recorder
  finally triggers BEFORE the crash, with the rule's evidence in the
  ring (throttled by flightrec's per-reason crash-dump gap)
- a durable ``slo`` history row (telemetry/history.py)
- an optional registered action hook (page, shed, scale …)

Three rule kinds:

- **`ThresholdRule`** — a live counter or percentile vs a static
  bound (``serve.e2e_us{lane=high} p99 <= deadline``).
- **`BurnRateRule`** — multi-window error-budget burn (the SRE
  pattern): over a FAST and a SLOW window, ``burn = (bad/total) /
  budget``; the rule fires when BOTH windows burn at >= 1x (the fast
  window reacts, the slow window de-flakes a blip) and clears when
  the fast window recovers.  Windows are sampled from the cumulative
  counters at each evaluation, so the rule needs no per-request hook.
- **`AnomalyRule`** — the live windowed tail vs a robust history
  baseline: fires when the current value exceeds
  ``median + max(sigma·1.4826·MAD, floor·median)`` over the baseline
  rows — the same leave-nothing-to-variance math the PR 11 straggler
  detector uses (`fleet.robust_threshold`), pointed at time instead
  of replicas.

**Default serving rules** derive from the PR 8 knobs so a serving
process gets SLOs without writing any: per lane (MXNET_SERVE_LANES),
a shed-rate burn rule whose error budget follows the lane-quota
ladder (the top lane gets MXNET_SLO_SHED_BUDGET, lower lanes are
designed to shed and get ``1 - quota``), and — when the engine has
observed per-lane request deadlines — a p99-vs-deadline threshold
rule per lane (`ModelRegistry.slo_targets()` /
`InferenceEngine.slo_targets()`).

Evaluation cost: nothing here runs on a request or step path — the
periodic exporter worker calls `evaluate()` at tick cadence, and each
rule reads a few counters under the ledger lock.
"""
from __future__ import annotations

import threading
import time
from collections import deque

from .. import config as _cfg
from ..monitor import events
from . import flightrec as _bb

__all__ = ["Rule", "ThresholdRule", "BurnRateRule", "AnomalyRule",
           "CostDriftRule", "MemDriftRule",
           "register_rule", "unregister_rule", "clear_rules", "rules",
           "active_alerts", "evaluate", "block", "register_action",
           "default_serving_rules", "install_default_serving_rules",
           "default_generation_rules",
           "install_default_generation_rules",
           "default_controlplane_rules",
           "install_default_controlplane_rules",
           "default_cost_drift_rules", "install_cost_drift_rules",
           "default_memwatch_rules", "install_memwatch_rules"]


# -- metric readers ----------------------------------------------------
def counter_value(name, labels=None) -> float:
    """Cumulative counter value; with ``labels`` the SUM over every
    labelset carrying at least those pairs (``serve.shed{lane=low}``
    sums across its per-reason splits)."""
    if not labels:
        return float(events.get(name))
    want = {str(k): str(v) for k, v in labels.items()}
    total = 0.0
    for row in events.labeled_snapshot().get(name, ()):
        have = row["labels"]
        if all(have.get(k) == v for k, v in want.items()):
            total += row["value"]
    return total


def percentile_value(name, p="p99", labels=None):
    """The live ring's percentile for a series (labeled: the FIRST
    labelset carrying at least the given pairs).  None when nothing
    was observed."""
    pcts = (50, 90, 99)
    if not labels:
        d = events.percentiles(name, pcts)
        return d.get(p) if d else None
    want = {str(k): str(v) for k, v in labels.items()}
    for row in events.labeled_percentiles(name, pcts):
        have = row["labels"]
        if all(have.get(k) == v for k, v in want.items()):
            return row.get(p)
    return None


class Rule:
    """Base: a named predicate over the ledger.  Subclasses implement
    ``check(now) -> (firing, info)`` where ``firing`` may be None
    (not judgeable yet — no samples, cold windows); `evaluate()` owns
    the alert lifecycle around it."""

    kind = "rule"

    def __init__(self, name, description=""):
        self.name = str(name)
        self.description = str(description)

    def check(self, now):           # pragma: no cover — abstract
        raise NotImplementedError

    def describe(self) -> dict:
        return {"rule": self.name, "kind": self.kind,
                "description": self.description}


class ThresholdRule(Rule):
    """Static bound on a live counter or percentile.

    metric: counter/series name.  With ``pct`` ("p50"/"p90"/"p99")
    the value is the live ring's percentile, else the cumulative
    counter.  ``op``: "<=" means the SLO is ``value <= bound`` (the
    rule FIRES on violation); ">=" the reverse (e.g. a liveness
    floor)."""

    kind = "threshold"

    def __init__(self, name, metric, bound, pct=None, labels=None,
                 op="<=", description=""):
        super().__init__(name, description)
        self.metric = str(metric)
        self.bound = float(bound)
        self.pct = pct
        self.labels = dict(labels) if labels else None
        if op not in ("<=", ">="):
            raise ValueError("op must be '<=' or '>=', got %r" % (op,))
        self.op = op

    def check(self, now):
        if self.pct:
            v = percentile_value(self.metric, self.pct,
                                 labels=self.labels)
            if v is None:
                return None, {}
        else:
            v = counter_value(self.metric, labels=self.labels)
        bad = v > self.bound if self.op == "<=" else v < self.bound
        return bool(bad), {"value": float(v), "bound": self.bound,
                           "op": self.op, "metric": self.metric,
                           "pct": self.pct, "labels": self.labels}


class BurnRateRule(Rule):
    """Multi-window error-budget burn over cumulative counters.

    bad:    counter name (or list of names) counting SLO violations —
            summed, labeled reads sum label-subset matches
    total:  counter name (or list) for the DENOMINATOR — pass
            ``["serve.requests", "serve.shed"]`` when the bad events
            are not included in the good counter
    budget: the allowed bad/total ratio (the error budget)
    fast_s / slow_s: the two windows; the rule fires when the burn
            rate ``(bad/total)/budget`` is >= 1 over BOTH, clears
            when the fast window drops back under 1.

    A window without a sample old enough is measured from the oldest
    retained sample (standard cold-start behavior: a fresh process
    under immediate overload should page, not wait an hour)."""

    kind = "burn_rate"

    def __init__(self, name, bad, total, budget, fast_s=None,
                 slow_s=None, labels=None, min_total=1.0,
                 description=""):
        super().__init__(name, description)
        self.bad = [bad] if isinstance(bad, str) else list(bad)
        self.total = [total] if isinstance(total, str) else list(total)
        self.budget = float(budget)
        if not (0.0 < self.budget <= 1.0):
            raise ValueError("budget must be a ratio in (0, 1], got %r"
                             % (budget,))
        self.fast_s = float(fast_s if fast_s is not None
                            else _cfg.get("MXNET_SLO_FAST_S"))
        self.slow_s = float(slow_s if slow_s is not None
                            else _cfg.get("MXNET_SLO_SLOW_S"))
        self.labels = dict(labels) if labels else None
        self.min_total = float(min_total)
        # (ts, bad_cum, total_cum) samples spanning >= the slow window
        self._samples = deque()
        # latched while firing: clearing is judged on the FAST window
        # alone (the slow window jittering across 1.0 under sustained
        # marginal burn must not flap one continuous incident into
        # repeated fired/cleared pairs)
        self._latched = False

    def _read(self, names):
        return sum(counter_value(n, labels=self.labels) for n in names)

    def _window(self, now, window_s):
        """(Δbad, Δtotal) over the trailing window (oldest retained
        sample when the window isn't covered yet)."""
        base = self._samples[0]
        for s in self._samples:
            if s[0] >= now - window_s:
                break
            base = s
        cur = self._samples[-1]
        return cur[1] - base[1], cur[2] - base[2]

    def check(self, now):
        bad, total = self._read(self.bad), self._read(self.total)
        self._samples.append((now, bad, total))
        horizon = now - self.slow_s * 1.5
        while len(self._samples) > 2 and self._samples[1][0] < horizon:
            self._samples.popleft()
        if len(self._samples) < 2:
            return None, {}
        burns = {}
        for tag, win in (("fast", self.fast_s), ("slow", self.slow_s)):
            db, dt = self._window(now, win)
            if dt < self.min_total:
                burns[tag] = 0.0
                continue
            burns[tag] = (db / dt) / self.budget
        # fire on BOTH windows (the slow window de-flakes a blip);
        # once latched, stay firing until the FAST window recovers
        firing = burns["fast"] >= 1.0 and \
            (burns["slow"] >= 1.0 or self._latched)
        self._latched = firing
        return firing, {"burn_fast": round(burns["fast"], 3),
                        "burn_slow": round(burns["slow"], 3),
                        "budget": self.budget,
                        "fast_s": self.fast_s, "slow_s": self.slow_s,
                        "bad": self.bad, "total": self.total,
                        "labels": self.labels}


class AnomalyRule(Rule):
    """Live value vs a robust baseline from the on-disk history
    (telemetry/history.py): fires when the current windowed value
    exceeds ``median + max(sigma·1.4826·MAD, floor·median)`` over the
    baseline rows — the PR 11 straggler math
    (`fleet.robust_threshold`) pointed at this run's past instead of
    at other replicas.

    series: a sampled series; the LIVE value is its current ring
        percentile (``pct``, default p99); the BASELINE values are
        the history ``pct`` rows of the same name — and the same
        ``labels``, so a per-lane rule judges a lane against ITS OWN
        history, not a mix of every lane — over the trailing
        ``baseline_s`` seconds across OTHER runs.  Self-exclusion is
        load-bearing here exactly as it is for the straggler
        detector: the current run writes its own (possibly degraded)
        values into history every tick, so including them would let
        a sustained degradation normalize its own baseline until the
        rule can never fire.  ``include_self=True`` opts back in
        (single-long-run deployments with no prior history)."""

    kind = "anomaly"

    def __init__(self, name, series, sigma=None, baseline_s=3600.0,
                 pct="p99", labels=None, min_baseline=8,
                 rel_floor=0.5, include_self=False, description=""):
        super().__init__(name, description)
        self.series = str(series)
        self.sigma = float(sigma if sigma is not None
                           else _cfg.get("MXNET_STRAGGLER_SIGMA"))
        self.baseline_s = float(baseline_s)
        self.pct = str(pct)
        self.labels = dict(labels) if labels else None
        self.min_baseline = int(min_baseline)
        self.rel_floor = float(rel_floor)
        self.include_self = bool(include_self)
        self._cache_key = None      # shard (path, mtime, size) stats
        self._cache_rows = None

    def _baseline_rows(self, now):
        """The matching history rows, cached on the shard files'
        (path, mtime, size) stats: evaluation runs every exporter
        tick, and re-parsing every shard in the directory per tick
        per rule is the dominant cost — but other runs' shards are
        immutable once those runs end, so a cheap stat sweep usually
        answers 'nothing changed'.  The time filter applies to the
        cached rows, never busts the cache."""
        import os
        from . import history as _hist
        d = _hist.history_dir()
        me = _hist.get_writer().run if (_hist.enabled()
                                        and not self.include_self) \
            else None
        key = []
        for p in _hist._shards(d):
            if me is not None and os.path.basename(p) == \
                    "history-%s.jsonl" % me:
                continue            # own shard is excluded anyway —
                                    # its every-tick growth must not
                                    # bust the cache
            try:
                st = os.stat(p)
                key.append((p, st.st_mtime_ns, st.st_size))
            except OSError:
                continue
        key = tuple(key)
        if key != self._cache_key:
            rows = _hist.query(self.series, kind="pct",
                               labels=self.labels, directory=d)
            if me is not None:
                rows = [r for r in rows if r.get("run") != me]
            if self.labels is None:
                # an unlabeled rule baselines against the unlabeled
                # aggregate only (labeled children are different
                # series)
                rows = [r for r in rows if not r.get("labels")]
            self._cache_key, self._cache_rows = key, rows
        return [r for r in self._cache_rows
                if r.get("ts", 0) >= now - self.baseline_s]

    def check(self, now):
        from .fleet import robust_threshold
        cur = percentile_value(self.series, self.pct,
                               labels=self.labels)
        if cur is None:
            return None, {}
        rows = self._baseline_rows(now)
        base = [float(r.get(self.pct, r.get("v", 0))) for r in rows
                if r.get(self.pct) is not None or "v" in r]
        if len(base) < self.min_baseline:
            return None, {"baseline_n": len(base)}
        thresh = robust_threshold(base, self.sigma,
                                  rel_floor=self.rel_floor)
        return bool(cur > thresh), {
            "value": float(cur), "threshold": round(float(thresh), 1),
            "baseline_n": len(base), "sigma": self.sigma,
            "series": self.series, "pct": self.pct}


class CostDriftRule(Rule):
    """Cost-model regression: this run's measured ``kind="cost"`` /
    probe evidence contradicts the evidence a PRIOR run's autotune
    decision was based on (compile/autotune.py records the basis —
    ``best_us`` or ``basis_bytes`` — on every decision row).

    Judged entirely from durable history via
    ``autotune.drift_evidence(knob, label)``: unjudgeable (None) until
    both a prior decision with a recorded basis and fresh current-run
    measurements exist, firing when they disagree beyond
    ``autotune.DRIFT_FACTOR`` in either direction.  Firing also calls
    ``autotune.invalidate(knob, label)`` so the next ``suggest_*`` for
    the key ignores stale cross-run evidence and re-resolves from this
    run's rows — recording a ``drift_refresh`` decision event, which
    makes the rule unjudgeable again (the contradiction is resolved)
    and clears the alert after the debounce rounds."""

    kind = "cost_drift"

    def __init__(self, name, knob, label, description=""):
        super().__init__(
            name, description or
            "measured cost for %s[%s] vs prior-run decision evidence"
            % (knob, label))
        self.knob = str(knob)
        self.label = str(label or "")

    def check(self, now):
        try:
            from ..compile import autotune as _at
        except Exception:           # noqa: BLE001
            return None, {}
        ev = _at.drift_evidence(self.knob, self.label)
        if ev is None:
            return None, {}
        firing = bool(ev.get("drift"))
        if firing:
            _at.invalidate(self.knob, self.label)
        return firing, {
            "prior": round(float(ev["prior"]), 3),
            "current": round(float(ev["current"]), 3),
            "ratio": round(float(ev["ratio"]), 3),
            "basis": str(ev["basis"]),
            "chosen": str(ev.get("chosen")),
            "prior_run": str(ev.get("prior_run")),
            "factor": float(_at.DRIFT_FACTOR),
            "labels": {"knob": self.knob, "label": self.label}}


class MemDriftRule(Rule):
    """Ledger-vs-allocator memory drift (ISSUE 20): the memwatch
    attribution join apportions each device's MEASURED resident bytes
    (PJRT ``memory_stats``, live-arrays fallback) to the tenants that
    COMMITTED bytes for it; this rule fires when the worst tenant's
    measured/committed ratio contradicts its commitment by more than
    ``MXNET_MEMWATCH_DRIFT_FACTOR`` in either direction — a model
    resident far above its admission footprint is eating someone
    else's budget, one far below is hoarding ledger nobody can use.

    The CostDriftRule lifecycle, applied to bytes: unjudgeable (None)
    until a FRESH sample exists (MXNET_MEMWATCH_FRESH_S), and firing
    also re-reconciles the drifting tenant's ledger row
    (`memwatch.reconcile_tenant` → `ModelRegistry.reconcile`), so the
    contradiction resolves and the alert clears on the next judged
    round.  The firing info carries the top-N consumers table
    (``info["top"]`` — rides into active alerts and dumps) plus the
    scalar evidence that survives the ring/history filters.

    ``rows_fn`` / ``reconcile_fn`` inject the attribution and the
    reconcile side-effect for deterministic tests (the fire →
    reconcile → clear drill runs off a hand-built ledger)."""

    kind = "mem_drift"

    def __init__(self, name="mem-drift", factor=None, top=None,
                 rows_fn=None, reconcile_fn=None, description=""):
        super().__init__(
            name, description or
            "measured resident bytes vs ledger commitment per tenant "
            "(memwatch attribution join)")
        self.factor = factor
        self.top = top
        self.rows_fn = rows_fn
        self.reconcile_fn = reconcile_fn

    def check(self, now):
        from . import memwatch as _mw
        if self.rows_fn is not None:
            rows = self.rows_fn()
        elif _mw.fresh_sample() is None:
            return None, {}
        else:
            rows = _mw.attribution()
        if not rows:
            return None, {}
        factor = float(self.factor if self.factor is not None
                       else _cfg.get("MXNET_MEMWATCH_DRIFT_FACTOR"))
        worst, worst_score = None, 0.0
        for r in rows:
            c = int(r.get("committed_bytes", 0))
            if c <= 0:
                continue            # nothing promised, nothing to
            m = int(r.get("measured_bytes", 0))     # contradict
            score = (m / c) if m >= c else \
                (float("inf") if m <= 0 else c / m)
            if score > worst_score:
                worst, worst_score = r, score
        if worst is None:
            return None, {}
        firing = bool(worst_score > factor)
        top_n = int(self.top if self.top is not None
                    else _cfg.get("MXNET_MEMWATCH_TOP"))
        top = {}
        for r in sorted(rows,
                        key=lambda x: -x.get("measured_bytes", 0)
                        )[:max(1, top_n)]:
            top["%s@%s" % (r.get("tenant"), r.get("device"))] = \
                int(r.get("measured_bytes", 0))
        info = {
            "tenant": str(worst.get("tenant")),
            "device": str(worst.get("device")),
            "committed_bytes": int(worst.get("committed_bytes", 0)),
            "measured_bytes": int(worst.get("measured_bytes", 0)),
            "ratio": round(float(worst_score), 3),
            "factor": factor,
            "source": str(worst.get("source", "?")),
            "top": top,
            "labels": {"tenant": str(worst.get("tenant"))}}
        if firing:
            rec = self.reconcile_fn if self.reconcile_fn is not None \
                else _mw.reconcile_tenant
            try:
                info["reconciled"] = bool(rec(worst.get("tenant")))
            except Exception:       # noqa: BLE001 — the side-effect
                info["reconciled"] = False      # is best-effort
        return firing, info


# -- registry + alert lifecycle ----------------------------------------
_LOCK = threading.Lock()
_RULES = {}                 # name -> Rule
_ACTIVE = {}                # name -> info dict while firing
_ACTIONS = []               # callables (rule_name, firing, info)
_UNJUDGED = {}              # name -> consecutive unjudgeable rounds
#: consecutive unjudgeable rounds before an ACTIVE alert is cleared:
#: a firing rule REPLACED mid-incident (install_slo_rules re-run) is
#: unjudgeable for exactly one round while its windows warm — that
#: blip must not emit a cleared+fired pair for one continuous
#: incident, while genuinely evaporated evidence (an aged-out
#: baseline) stays unjudgeable round after round and does clear
UNJUDGED_CLEAR_ROUNDS = 2


def register_rule(rule: Rule) -> Rule:
    """Add (or replace) a rule; it is evaluated from the next tick.
    Replacing a rule whose alert is FIRING keeps the alert active —
    the next evaluation under the new definition either continues the
    incident (no double `slo.fired`) or emits the `cleared`
    transition, so fired/cleared rows always pair up."""
    with _LOCK:
        _RULES[rule.name] = rule
    return rule


def unregister_rule(name) -> None:
    """Remove a rule.  If its alert is FIRING, the cleared transition
    is emitted first — fired/cleared counters, ring events and
    history rows must always pair up, and the gauge's final scrape
    must read 0, not vanish at 1 until Prometheus staleness."""
    with _LOCK:
        _RULES.pop(str(name), None)
        _UNJUDGED.pop(str(name), None)
        prev = _ACTIVE.pop(str(name), None)
    if prev is not None:
        _transition(str(name), False, dict(prev, unregistered=True))


def clear_rules() -> None:
    """Drop every rule (and action hook).  Firing alerts clear with
    paired transitions first (see `unregister_rule`)."""
    with _LOCK:
        active = {k: dict(v) for k, v in _ACTIVE.items()}
        _RULES.clear()
        _ACTIVE.clear()
        _UNJUDGED.clear()
    for name in sorted(active):
        _transition(name, False, dict(active[name],
                                      unregistered=True))
    with _LOCK:
        del _ACTIONS[:]


def rules() -> dict:
    """{name: Rule} snapshot of the registered rules."""
    with _LOCK:
        return dict(_RULES)


def active_alerts() -> dict:
    """{rule name: info} for the rules currently firing — the state
    behind the ``mxnet_alert_active`` gauge."""
    with _LOCK:
        return {k: dict(v) for k, v in _ACTIVE.items()}


def register_action(fn) -> None:
    """Register a hook called as ``fn(rule_name, firing, info)`` on
    every alert TRANSITION (fired and cleared).  Hooks are
    best-effort: a raising hook is counted (slo.action_errors), never
    propagated into the exporter tick."""
    with _LOCK:
        _ACTIONS.append(fn)


def _attach_exemplar(name, info):
    """Attach the worst matching promoted request exemplar (ISSUE 19)
    to a firing serving/generation rule's info, IN PLACE: the full
    waterfall under ``info["exemplar"]`` (rides into the active-alerts
    block, /metrics.json and the proactive dump), plus scalar
    ``exemplar_*`` fields that survive the ring event's and history
    row's scalar filters — the on-call sees the autopsy, not just the
    gauge."""
    if name.startswith("serve-"):
        engine = "serve"
    elif name.startswith("gen-"):
        engine = "gen"
    else:
        return                      # only request-path rules carry one
    try:
        from . import reqtrace as _rt
        ex = _rt.worst_exemplar(
            lane=(info.get("labels") or {}).get("lane"),
            engine=engine)
    except Exception:               # noqa: BLE001 — attachment is
        return                      # garnish, never breaks the alert
    if not ex:
        return
    info["exemplar"] = dict(ex)
    info["exemplar_rid"] = ex.get("rid")
    info["exemplar_e2e_us"] = ex.get("e2e_us")
    info["exemplar_status"] = ex.get("status")
    info["exemplar_phase"] = ex.get("dominant")


def _transition(name, firing, info):
    events.incr("slo.fired" if firing else "slo.cleared")
    events.incr("slo.fired" if firing else "slo.cleared",
                labels={"rule": name})
    _bb.record("slo", "fired" if firing else "cleared", rule=name,
               **{k: v for k, v in info.items()
                  if isinstance(v, (int, float, str, bool))})
    try:
        from . import history as _hist
        _hist.record("slo", name, 1.0 if firing else 0.0,
                     labels={"rule": name},
                     event="fired" if firing else "cleared",
                     **{k: v for k, v in info.items()
                        if k.startswith("exemplar_")})
    except Exception:               # noqa: BLE001
        pass
    if firing:
        # the proactive dump: the black box triggers while the run is
        # still alive, reason names the rule (per-reason throttled)
        _bb.crash_dump("slo:%s" % name)
    with _LOCK:
        hooks = list(_ACTIONS)
    for fn in hooks:
        try:
            fn(name, firing, dict(info))
        except Exception:           # noqa: BLE001 — an alert hook
            events.incr("slo.action_errors")    # must not kill the
                                                # evaluator


def evaluate(now=None) -> list:
    """Evaluate every registered rule (the periodic exporter calls
    this each tick).  Handles fired/cleared transitions; returns the
    sorted names of the rules currently firing.  Never raises — a
    broken rule is counted on ``slo.rule_errors`` and skipped."""
    now = float(now if now is not None else time.time())
    with _LOCK:
        todo = list(_RULES.items())
    fired_now = []
    for name, rule in todo:
        try:
            firing, info = rule.check(now)
        except Exception:           # noqa: BLE001
            events.incr("slo.rule_errors")
            continue
        if firing is None:
            # not judgeable (cold windows, empty ring, baseline aged
            # out).  A rule that STAYS unjudgeable while firing must
            # clear — the evidence evaporated, and an alert nothing
            # can ever re-judge would latch active forever, gauge
            # stuck at 1 with no paired cleared transition.
            # Debounced (UNJUDGED_CLEAR_ROUNDS): a firing rule
            # replaced mid-incident warms up over one round, which
            # must not flap cleared+fired
            with _LOCK:
                active = name in _ACTIVE
                n = _UNJUDGED[name] = _UNJUDGED.get(name, 0) + 1
                prev = _ACTIVE.pop(name, None) \
                    if active and n >= UNJUDGED_CLEAR_ROUNDS else None
            if prev is not None:
                _transition(name, False,
                            dict(prev, unjudgeable=True))
            continue
        _UNJUDGED.pop(name, None)
        if firing:
            _attach_exemplar(name, info)
        with _LOCK:
            was = name in _ACTIVE
            if firing:
                _ACTIVE[name] = dict(info, since=_ACTIVE.get(
                    name, {}).get("since", now))
            else:
                _ACTIVE.pop(name, None)
        if firing and not was:
            _transition(name, True, info)
        elif was and not firing:
            _transition(name, False, info)
        if firing:
            fired_now.append(name)
    return sorted(fired_now)


def block() -> dict:
    """The ``slo`` block for /metrics.json, dumps and teletop: the
    registered rules and the currently-active alerts."""
    with _LOCK:
        if not _RULES and not _ACTIVE:
            return {}
        return {"rules": [r.describe() for _, r in
                          sorted(_RULES.items())],
                "active": {k: dict(v) for k, v in _ACTIVE.items()}}


# -- default serving rules (derived from the PR 8 knobs) ---------------
def _lanes_and_quotas():
    """(lanes, {lane: quota fraction}) from MXNET_SERVE_LANES /
    MXNET_SERVE_LANE_QUOTAS — the fraction ladder is the SHARED
    `config.serve_lane_quota_fractions` the engine's enforcement
    also parses through (importing the engine itself would pull
    jax)."""
    lanes = [s.strip() for s in
             str(_cfg.get("MXNET_SERVE_LANES") or "").split(",")
             if s.strip()] or ["high"]
    fracs = _cfg.serve_lane_quota_fractions(
        _cfg.get("MXNET_SERVE_LANE_QUOTAS") or "", len(lanes))
    return lanes, dict(zip(lanes, fracs))


def default_serving_rules(targets=None, shed_budget=None, fast_s=None,
                          slow_s=None, lanes=None,
                          quotas=None) -> list:
    """The serving SLO set PR 8 implicitly promised, as rules:

    - per lane, a shed-rate **burn** rule: bad = that lane's sheds,
      total = its requests + sheds; the error budget follows the
      lane-quota ladder — the TOP lane budgets ``shed_budget``
      (MXNET_SLO_SHED_BUDGET), lower lanes are DESIGNED to shed under
      overload and budget ``max(shed_budget, 1 - quota)``
    - per lane with an observed deadline (``targets``: {lane:
      seconds}, from `InferenceEngine.slo_targets()` /
      `ModelRegistry.slo_targets()`), a p99-vs-deadline **threshold**
      rule on the labeled ``serve.e2e_us`` ring

    ``lanes``/``quotas`` override the env knobs — a live engine's
    ``slo_lane_quotas()`` supplies what it actually enforces (a
    programmatic ``lane_quotas=`` engine must not be budgeted off
    the env ladder it isn't using).  Returns the rule list (callers
    register what they keep)."""
    if shed_budget is None:
        shed_budget = float(_cfg.get("MXNET_SLO_SHED_BUDGET"))
    if lanes is None and quotas is not None:
        lanes = list(quotas)        # dict order = priority order
    if lanes is None or quotas is None:
        env_lanes, env_quotas = _lanes_and_quotas()
        lanes = list(lanes) if lanes is not None else env_lanes
        quotas = dict(quotas) if quotas is not None else env_quotas
    out = []
    for lane in lanes:
        budget = max(shed_budget, 1.0 - quotas.get(lane, 1.0))
        out.append(BurnRateRule(
            "serve-shed-%s" % lane,
            bad="serve.shed", total=["serve.requests", "serve.shed"],
            budget=budget, fast_s=fast_s, slow_s=slow_s,
            labels={"lane": lane},
            description="lane %r shed fraction burns its %.0f%% error "
                        "budget over both windows" % (lane,
                                                      budget * 100)))
        t = (targets or {}).get(lane)
        if t:
            out.append(ThresholdRule(
                "serve-p99-%s" % lane,
                metric="serve.e2e_us", pct="p99",
                labels={"lane": lane}, bound=float(t) * 1e6,
                description="lane %r e2e p99 within its observed "
                            "%.3fs deadline" % (lane, float(t))))
    return out


def default_generation_rules(targets=None, shed_budget=None,
                             fast_s=None, slow_s=None, lanes=None,
                             quotas=None) -> list:
    """The generation-serving SLO set (ISSUE 14): same lane-ladder
    discipline as `default_serving_rules`, pointed at the
    `GenerationEngine`'s own series —

    - per lane, a shed-rate burn rule over ``gen.shed`` /
      (``gen.requests`` + ``gen.shed``) with the lane-quota error
      budget;
    - per lane with an observed deadline target (``targets``: {lane:
      seconds}, from `GenerationEngine.slo_targets()`), a
      **TTFT p99** threshold rule on the labeled ``gen.ttft_us`` ring
      — time-to-first-token is the generation tail users feel; a
      request that will finish in time but starts late is already a
      violation.
    """
    if shed_budget is None:
        shed_budget = float(_cfg.get("MXNET_SLO_SHED_BUDGET"))
    if lanes is None and quotas is not None:
        lanes = list(quotas)
    if lanes is None or quotas is None:
        env_lanes, env_quotas = _lanes_and_quotas()
        lanes = list(lanes) if lanes is not None else env_lanes
        quotas = dict(quotas) if quotas is not None else env_quotas
    out = []
    for lane in lanes:
        budget = max(shed_budget, 1.0 - quotas.get(lane, 1.0))
        out.append(BurnRateRule(
            "gen-shed-%s" % lane,
            bad="gen.shed", total=["gen.requests", "gen.shed"],
            budget=budget, fast_s=fast_s, slow_s=slow_s,
            labels={"lane": lane},
            description="lane %r generation shed fraction burns its "
                        "%.0f%% error budget over both windows"
                        % (lane, budget * 100)))
        t = (targets or {}).get(lane)
        if t:
            out.append(ThresholdRule(
                "gen-ttft-p99-%s" % lane,
                metric="gen.ttft_us", pct="p99",
                labels={"lane": lane}, bound=float(t) * 1e6,
                description="lane %r time-to-first-token p99 within "
                            "its observed %.3fs deadline"
                            % (lane, float(t))))
    return out


def install_default_generation_rules(engine=None, registry=None,
                                     **kw) -> list:
    """Build + register the default generation rules; ``engine`` (a
    GenerationEngine) or ``registry`` supplies the observed per-lane
    deadline targets and enforced quotas.  Returns rule names."""
    targets = kw.pop("targets", None)
    src = engine if engine is not None else registry
    if src is not None:
        if targets is None:
            targets = src.slo_targets()
        if "quotas" not in kw:
            q = src.slo_lane_quotas()
            if q:
                kw["quotas"] = q
    installed = [register_rule(r) for r in
                 default_generation_rules(targets=targets, **kw)]
    return [r.name for r in installed]


def default_controlplane_rules(fast_s=None, slow_s=None) -> list:
    """Watchdogs over the WATCHER (ISSUE 16): the FleetSupervisor's
    own actions are counters, so its pathologies are burn rules like
    everyone else's —

    - **rollback storm**: rollbacks burning against deploys past 50%
      means versions are being shipped that the canary gate keeps
      rejecting (or the gate itself is broken) — either way a human
      should look before the loop masks a systemic problem;
    - **scale oscillation**: scale transitions burning against ticks
      past 25% means the hysteresis/cooldown envelope is mis-tuned
      for the load pattern and the supervisor is flapping capacity.
    """
    return [
        BurnRateRule(
            "ctl-rollback-storm",
            bad="controlplane.rollbacks",
            total=["controlplane.deploys"],
            budget=0.5, min_total=2.0, fast_s=fast_s, slow_s=slow_s,
            description="canary rollbacks burn >50% of deploys over "
                        "both windows — bad versions keep shipping "
                        "(or the canary gate is broken)"),
        BurnRateRule(
            "ctl-scale-oscillation",
            bad=["controlplane.scale_ups", "controlplane.scale_downs"],
            total=["controlplane.ticks"],
            budget=0.25, min_total=8.0, fast_s=fast_s, slow_s=slow_s,
            description="scale transitions on >25% of supervisor "
                        "ticks over both windows — the hysteresis/"
                        "cooldown envelope is flapping capacity"),
    ]


def install_default_controlplane_rules(**kw) -> list:
    """Build + register the supervisor watchdog rules (the
    FleetSupervisor installs these at construction).  Returns the
    registered rule names."""
    installed = [register_rule(r)
                 for r in default_controlplane_rules(**kw)]
    return [r.name for r in installed]


def install_default_serving_rules(registry=None, engine=None,
                                  **kw) -> list:
    """Build + register the default serving rules; ``registry`` /
    ``engine`` supply the per-lane deadline targets AND the enforced
    lane quotas (so programmatic lane configs get budgets matching
    their actual enforcement).  Returns the registered rule names."""
    targets = kw.pop("targets", None)
    src = registry if registry is not None else engine
    if src is not None:
        if targets is None:
            targets = src.slo_targets()
        if "quotas" not in kw:
            q = src.slo_lane_quotas()
            if q:
                kw["quotas"] = q
    installed = [register_rule(r) for r in
                 default_serving_rules(targets=targets, **kw)]
    return [r.name for r in installed]


def default_cost_drift_rules(keys=None) -> list:
    """One ``CostDriftRule`` per autotune key that has EVIDENCE to
    contradict: ``keys`` is an iterable of ``(knob, label)`` pairs,
    or None to discover them from the durable decision rows that
    recorded a basis (``best_us`` / ``basis_bytes``).  No history, no
    prior evidence → no rules — a fresh deployment has nothing to
    drift from."""
    if keys is None:
        keys, seen = [], set()
        try:
            from . import history as _hist
            if not _hist.enabled():
                return []
            for r in _hist.query(name="decision", kind="autotune"):
                if "best_us" not in r and "basis_bytes" not in r:
                    continue
                lb = r.get("labels") or {}
                k = (lb.get("knob"), lb.get("label") or "")
                if k[0] and k not in seen:
                    seen.add(k)
                    keys.append(k)
        except Exception:           # noqa: BLE001
            return []
    return [CostDriftRule("autotune-cost-drift-%s-%s"
                          % (knob, label or "any"), knob, label)
            for knob, label in keys]


def install_cost_drift_rules(keys=None) -> list:
    """Build + register the autotune cost-drift rules (ISSUE 19
    satellite: decisions carried across runs get re-litigated when
    this run's measurements contradict their recorded evidence).
    Returns the registered rule names."""
    installed = [register_rule(r)
                 for r in default_cost_drift_rules(keys=keys)]
    return [r.name for r in installed]


def default_memwatch_rules(**kw) -> list:
    """The memory-drift watchdog (ISSUE 20): one `MemDriftRule`
    judging the whole attribution join — it fires naming the WORST
    drifting tenant, so one rule covers every tenant the ledgers
    know about (new deploys included, no re-install needed)."""
    return [MemDriftRule(**kw)]


def install_memwatch_rules(**kw) -> list:
    """Build + register the memwatch drift rule.  Returns the
    registered rule names."""
    installed = [register_rule(r) for r in default_memwatch_rules(**kw)]
    return [r.name for r in installed]
