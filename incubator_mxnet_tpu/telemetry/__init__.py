"""Unified telemetry backbone (ISSUE 4): spans, metrics export, and
per-step training telemetry over the `monitor.events` ledger.

Three layers, one ledger:

- `telemetry.span(name, parent=ctx)` — thread-safe spans with explicit
  cross-thread parent propagation, emitted into the profiler's
  chrome-trace sink (spans.py).
- `telemetry.MetricsExporter` — `monitor.events` counters + latency
  percentiles rendered as Prometheus text / JSON, with periodic file
  export and an optional `/metrics` + `/healthz` HTTP thread
  (export.py).
- `telemetry.StepTelemetry` — per-step `train.*` counters/samples,
  wired into `ResilientTrainer` / `ShardedTrainer` (stepstats.py).

Switch: `MXNET_TELEMETRY=1` or `telemetry.enable()`.  Disabled, every
hot-path hook is a single bool read.  `telemetry.start()` boots the
process-wide exporter off the MXNET_TELEMETRY_* knobs;
`python -m incubator_mxnet_tpu.tools.teletop` renders a live or
file-snapshot table.  See docs/observability.md.

ISSUE 5 adds the push-based layer the pull-based surfaces above can't
replace when a run dies:

- `telemetry.flightrec` — the ALWAYS-ON flight recorder: a bounded
  ring of structured events (steps, spans, markers, stalls, HBM
  watermarks) dumped atomically as a self-contained forensic JSON on
  rollback/preemption/uncaught exceptions/SIGUSR2 or an explicit
  `telemetry.dump_blackbox()` (`MXNET_BLACKBOX=0` disarms).
- `telemetry.costs` — the per-executable FLOPs/HBM cost registry every
  jitted executable (aot_cache, fused imperative step, trainer steps,
  serving buckets) reports into.

`python -m incubator_mxnet_tpu.tools.blackbox <dump>` summarizes a
dump.

ISSUE 12 makes the telemetry DURABLE and JUDGED:

- `telemetry.history` — an append-only, bounded on-disk time series
  (MXNET_HISTORY_DIR): the periodic exporter tick writes counter
  deltas, percentile summaries, cost-registry rows and per-replica
  fleet rows to per-process shard files, queryable across runs
  (`history.query`; `blackbox history` renders the trends).
- `telemetry.slo` — declarative SLO/alert rules (static thresholds,
  multi-window burn-rate over an error budget, MAD anomaly vs
  history baselines) evaluated each exporter tick; a firing rule is
  a typed event: `slo.*` counters, a ring event, the
  `mxnet_alert_active{rule=}` gauge, and a PROACTIVE black-box dump
  naming the rule.
"""
from __future__ import annotations

from .spans import (SpanContext, TraceContext, current, emit_foreign,
                    enable, enabled, get_global_step, propagate,
                    recording, set_global_step, span)
from .export import MetricsExporter
from .stepstats import StepTelemetry
from . import costs
from . import flightrec
from . import fleet
from . import history
from . import memwatch
from . import slo
from .fleet import (FleetReporter, FleetTelemetry, FleetView,
                    StragglerDetector)
from .flightrec import dump_blackbox, install_crash_hooks
from .slo import (AnomalyRule, BurnRateRule, ThresholdRule,
                  register_rule)

__all__ = ["SpanContext", "TraceContext", "span", "current", "enable",
           "enabled", "recording", "propagate", "set_global_step",
           "get_global_step", "emit_foreign", "MetricsExporter",
           "StepTelemetry", "start", "stop", "get_exporter",
           "snapshot_dict", "costs", "flightrec", "fleet", "history",
           "memwatch", "slo",
           "FleetReporter", "FleetView", "FleetTelemetry",
           "StragglerDetector", "ThresholdRule", "BurnRateRule",
           "AnomalyRule", "register_rule", "dump_blackbox",
           "install_crash_hooks"]

#: counter families the condensed snapshot (bench.py JSON) carries
SNAPSHOT_PREFIXES = ("serve.", "feed.", "train.", "aot.",
                     "resilience.", "mem.", "fault.", "blackbox.",
                     "mesh.", "fleet.", "slo.", "history.",
                     "memwatch.")

_exporter = None


def start(port=None, path=None, period_s=None) -> MetricsExporter:
    """Boot (or return) the process-wide exporter: HTTP endpoint when
    `port`/MXNET_TELEMETRY_PORT is nonzero, periodic file export when
    `path`/MXNET_TELEMETRY_EXPORT_PATH is set.  Also flips
    `telemetry.enable()` on — starting an export surface means the
    operator wants the instrumentation feeding it."""
    from .. import config as _cfg
    global _exporter
    enable()
    # a started export surface implies a production run — arm the
    # black-box crash hooks too (idempotent; MXNET_BLACKBOX=0 disarms)
    flightrec.install_crash_hooks()
    if _exporter is None:
        _exporter = MetricsExporter()
    if port is not None:
        # explicit port starts the endpoint (0 = ephemeral bind)
        _exporter.serve_http(port)
    elif int(_cfg.get("MXNET_TELEMETRY_PORT")):
        # knob semantics: 0 means "no endpoint"
        _exporter.serve_http()
    if path or _cfg.get("MXNET_TELEMETRY_EXPORT_PATH"):
        _exporter.start(path=path, period_s=period_s)
    return _exporter


def get_exporter():
    """The process-wide exporter (None until `start()`)."""
    return _exporter


def stop():
    """Flag-drain the process-wide exporter (idempotent)."""
    global _exporter
    exp, _exporter = _exporter, None
    if exp is not None:
        exp.close()


def snapshot_dict(prefixes=SNAPSHOT_PREFIXES, pcts=(50, 99)) -> dict:
    """Condensed counter + percentile snapshot of the telemetry
    families, sized for embedding in a one-line JSON record (bench.py's
    BENCH_r*/BENCH_serve schema)."""
    from ..monitor import events
    keep = lambda k: any(k.startswith(p) for p in prefixes)
    out = {"counters": {k: v for k, v in events.snapshot().items()
                        if keep(k)},
           "percentiles": {k: v for k, v in
                           events.latency_snapshot(pcts=pcts).items()
                           if keep(k)}}
    try:
        t = costs.totals()
        if t.get("executables"):
            # cost-table totals ride in the same one-line record
            # (flops / bytes / hbm peak — the bench.py contract)
            out["costs"] = t
    except Exception:               # noqa: BLE001 — attribution is
        pass                        # best-effort in a snapshot
    return out
