"""Fleet telemetry: kvstore-aggregated per-replica snapshots and
telemetry-driven straggler detection (ISSUE 11 tentpole parts 2+3).

Every telemetry surface below this module is strictly per-process:
`monitor.events`, `StepTelemetry` and the flight recorder each see ONE
process, so a blackbox dump from rank 0 cannot say *which replica*
made a step slow, and `ElasticTrainer`'s "slow (observed)" replica
state had no telemetry feeding it — an alive-but-slow replica was
invisible until its heartbeats staled out.  This module closes both
gaps with three pieces that ride the infrastructure the fleet already
shares, the kvstore:

- **`FleetReporter`** — one replica's side: every
  ``MXNET_FLEET_PUBLISH_STEPS`` steps it pushes a compact fixed-schema
  float64 vector (step id, step/dispatch/collective/data-wait µs, HBM
  watermark, aot hit/miss/stale, skipped steps) to
  ``__mesh__/telemetry/<rid>`` — the same channel and pattern as the
  elastic heartbeats, a dozen floats per publish, AFTER the step's
  async dispatch returns.  Cost, measured on the 2-core dev box:
  ~0.65 ms per replica-publish (the kvstore's device_put round
  trip), so ~5 ms/step for the 8-replica single-controller
  simulation and one sub-ms push per step for a real one-replica-
  per-process fleet; the full round is metered on
  ``fleet.publish_us`` so the overhead is itself observable, and the
  cadence knob is the lever when steps are micro-benchmark short.
  (`tools/check_overhead.py` gates the always-on recorder hooks on a
  plain trainer; fleet publishing exists only under an
  `ElasticTrainer` supervisor and is judged by its own counter.)
- **`FleetView`** — rank 0's side: pull every replica's vector and
  merge them into one ``{rid: {field: value}}`` view, surfaced as
  replica-labeled children in `MetricsExporter`
  (``mxnet_fleet_step_us{replica="3",quantile="0.99"}``), a ``fleet``
  block in every black-box dump (`flightrec.set_fleet_provider`), and
  per-replica columns in ``teletop``.
- **`StragglerDetector`** — the actionable part: a rolling per-replica
  median over ``MXNET_STRAGGLER_WINDOW`` published step times,
  compared against the fleet median + ``MXNET_STRAGGLER_SIGMA`` robust
  sigmas (1.4826·MAD, floored at +50% so a uniform fleet never flags
  micro-skew).  A replica over the line is a straggler:
  ``mesh.straggler`` counter (labeled by replica) + a ring event
  naming it, and — through `ElasticTrainer` — the replica enters the
  existing "slow (observed)" health state, detected from its
  *published step times* while its heartbeats are still fresh.

`FleetTelemetry` bundles the three for the supervisor
(`ElasticTrainer` owns one): in a single-controller virtual mesh it
publishes every replica's vector itself; in a multi-controller fleet
each process owns the `FleetReporter` for its rid and rank 0 owns the
`FleetView` — the wire format is the same either way.
"""
from __future__ import annotations

import statistics
import time
import weakref
from collections import deque

import numpy as _np

from ..monitor import events
from . import flightrec as _bb
from . import spans as _tele

__all__ = ["FIELDS", "FleetReporter", "FleetView", "StragglerDetector",
           "FleetTelemetry", "telemetry_key", "robust_threshold"]


def robust_threshold(values, sigma, rel_floor=0.5):
    """``median + max(sigma·1.4826·MAD, rel_floor·median)`` over
    `values` — the outlier line the straggler detector judges replicas
    against, factored out so the SLO anomaly rules (telemetry/slo.py)
    can point the SAME math at history baselines instead of at other
    replicas.  The MAD term adapts to a naturally-noisy population;
    the relative floor keeps a uniform one (MAD ≈ 0) from flagging
    micro-skew."""
    vals = [float(v) for v in values]
    med = statistics.median(vals)
    mad = statistics.median(abs(x - med) for x in vals)
    return med + max(float(sigma) * 1.4826 * mad,
                     float(rel_floor) * med)

#: the fixed wire schema: one float64 per field, in this order.  A
#: fixed schema (not pickles) keeps the payload a dozen numbers, makes
#: it language/version-agnostic, and lets the kvstore treat it as any
#: other array key.
FIELDS = ("step", "step_us", "dispatch_us", "collective_us",
          "data_wait_us", "hbm_peak_bytes", "aot_hit", "aot_miss",
          "aot_stale", "steps_skipped", "feed_stall_us",
          "decode_batches")

_KEY = "__mesh__/telemetry/%d"


def telemetry_key(rid: int) -> str:
    """The kvstore key replica `rid` publishes under."""
    return _KEY % int(rid)


def _counter_sample():
    """The process-level counter fields of a snapshot (cumulative
    totals; per-step rates are the VIEW's job, division belongs where
    the denominators are known)."""
    return {
        "hbm_peak_bytes": max(_bb.hbm_peaks().values(), default=0),
        "aot_hit": events.get("aot.hit"),
        "aot_miss": events.get("aot.miss"),
        "aot_stale": events.get("aot.stale"),
        "steps_skipped": events.get("train.steps_skipped"),
        "feed_stall_us": events.get("feed.stall_us"),
        "decode_batches": events.get("io.decode.batches"),
    }


class FleetReporter:
    """Publishes ONE replica's compact snapshot vector through the
    kvstore (`telemetry_key(rid)`).  The push is span-wrapped
    (``kv.telemetry`` tagged with generation + rank) so the publish
    itself is visible on the cross-process timeline."""

    def __init__(self, kv, rid: int):
        self.kv = kv
        self.rid = int(rid)
        self._init = False

    def publish(self, sample: dict) -> None:
        """Push one snapshot (`FIELDS` subset; missing fields are 0)."""
        from ..ndarray.ndarray import NDArray
        vec = _np.asarray([float(sample.get(f, 0) or 0)
                           for f in FIELDS], _np.float64)
        key = telemetry_key(self.rid)
        arr = NDArray(vec)
        if not self._init:
            self.kv.init(key, arr)
            self._init = True
        with _tele.span("kv.telemetry", rank=self.rid,
                        gen=int(getattr(self.kv, "generation", 0))):
            self.kv.push(key, arr)


class FleetView:
    """Rank 0's merged per-replica view: pull every published vector
    and decode it back into ``{rid: {field: value}}``."""

    def __init__(self, kv):
        self.kv = kv
        self._last = {}

    def refresh(self, rids) -> dict:
        """Pull the listed replicas' vectors (a replica that never
        published simply contributes no row).  Returns and retains the
        merged view."""
        from ..base import MXNetError
        from ..ndarray.ndarray import NDArray
        out = {}
        for rid in rids:
            buf = NDArray(_np.zeros(len(FIELDS), _np.float64))
            try:
                with _tele.span("kv.telemetry_pull", rank=int(rid),
                                gen=int(getattr(self.kv, "generation",
                                                0))):
                    self.kv.pull(telemetry_key(int(rid)), out=buf)
            except MXNetError:
                continue            # never published under this store
            vals = buf.asnumpy()
            row = dict(zip(FIELDS, (float(v) for v in vals)))
            if row.get("step", 0) < 0:
                continue            # initialized but never pushed
            out[int(rid)] = row
        self._last = out
        return out

    @property
    def last(self) -> dict:
        return self._last


class StragglerDetector:
    """Rolling per-replica step-time skew detector.

    Per replica: the median over its last `window` published step
    times (robust to one blip).  Across replicas: each candidate is
    judged against the LEAVE-ONE-OUT baseline — the median of the
    OTHER replicas' medians, and the MAD around that.  A replica is a
    straggler when its median exceeds

        med(others) + max(sigma * 1.4826 * MAD(others),
                          0.5 * med(others))

    Self-exclusion matters on small fleets: with 2-4 replicas an
    outlier included in its own baseline inflates both the median and
    the MAD until nothing can ever cross the line (a 2-replica MAD is
    half the outlier's own excess).  The MAD term adapts to a
    naturally-noisy fleet; the +50% floor keeps a uniform fleet
    (MAD ≈ 0) from flagging scheduler jitter; and a replica must be
    over the line for ``CONFIRM_ROUNDS`` CONSECUTIVE rounds before it
    is flagged — a genuinely slow replica stays over for its whole
    degradation, while a one-round median crossing (a compile or GC
    blip transiting the window) resets and never fires.  Transitions
    (not steady states) are counted and ring-recorded:
    ``mesh.straggler`` / ``mesh.straggler_recovered``, labeled and
    named by replica."""

    #: minimum relative excess over the fleet median (a 1.0x-uniform
    #: fleet has MAD ~ 0; without a floor any micro-skew would flag)
    REL_FLOOR = 0.5
    #: consecutive over-the-line rounds before a replica is flagged
    #: (debounce: one transient window crossing must not page anyone)
    CONFIRM_ROUNDS = 2

    def __init__(self, window=None, sigma=None):
        from .. import config as _cfg
        # floor 2: the median needs >= 2 samples, and the clamp lives
        # HERE so the observe() staleness check (`dq.maxlen !=
        # self.window`) compares against the effective value — a
        # window knob of 1 must not rebuild every deque on every call
        self.window = max(2, int(window if window is not None
                                 else _cfg.get(
                                     "MXNET_STRAGGLER_WINDOW")))
        self.sigma = float(sigma if sigma is not None
                           else _cfg.get("MXNET_STRAGGLER_SIGMA"))
        self._win = {}              # rid -> deque of recent step_us
        self._over = {}             # rid -> consecutive rounds over
        self.flagged = set()        # rids currently flagged

    def observe(self, step: int, per_replica_us: dict) -> list:
        """Feed one round of published per-replica step times; returns
        the rids CURRENTLY judged stragglers (transition events fire
        inside).  Needs >= 2 replicas with >= 2 samples each before it
        judges — one sample is noise, one replica has no fleet."""
        for rid, us in per_replica_us.items():
            dq = self._win.get(rid)
            if dq is None or dq.maxlen != self.window:
                dq = self._win[rid] = deque(dq or (),
                                            maxlen=self.window)
            dq.append(float(us))
        stats = {rid: statistics.median(dq)
                 for rid, dq in self._win.items() if len(dq) >= 2}
        if len(stats) < 2:
            return sorted(self.flagged)
        now, baseline = set(), {}
        for rid, v in stats.items():
            others = [x for r, x in stats.items() if r != rid]
            med = statistics.median(others)
            thresh = robust_threshold(others, self.sigma,
                                      rel_floor=self.REL_FLOOR)
            baseline[rid] = (med, thresh)
            if v > thresh:
                self._over[rid] = self._over.get(rid, 0) + 1
                # already-flagged replicas stay flagged while over;
                # new ones must confirm for CONFIRM_ROUNDS rounds
                if rid in self.flagged or \
                        self._over[rid] >= self.CONFIRM_ROUNDS:
                    now.add(rid)
            else:
                self._over.pop(rid, None)
        for rid in sorted(now - self.flagged):
            med, thresh = baseline[rid]
            events.incr("mesh.straggler")
            events.incr("mesh.straggler",
                        labels={"replica": str(rid)})
            _bb.record_mesh("straggler", replica=int(rid),
                            step=int(step),
                            step_us=int(stats[rid]),
                            fleet_median_us=int(med),
                            threshold_us=int(thresh))
        for rid in sorted(self.flagged - now):
            events.incr("mesh.straggler_recovered")
            _bb.record_mesh("straggler_recovered", replica=int(rid),
                            step=int(step),
                            step_us=int(stats.get(rid, 0)))
        self.flagged = now
        return sorted(now)

    def forget(self, rid: int) -> None:
        """Drop a replica's window (it left the mesh)."""
        self._win.pop(int(rid), None)
        self._over.pop(int(rid), None)
        self.flagged.discard(int(rid))


class FleetTelemetry:
    """The supervisor-side bundle: reporters for the replicas this
    process speaks for, the rank-0 merged view, the straggler
    detector, and the dump/export surfaces.

    ``update(step, per_replica_step_us)`` is the one call a supervisor
    makes per step: publish (at the MXNET_FLEET_PUBLISH_STEPS
    cadence), refresh the view, feed the replica-labeled
    ``fleet.step_us`` summary rings (the Prometheus children), run the
    detector, and return the straggler rids.  Publishing happens after
    the step's async dispatch has returned — the device is already
    busy; the host-side cost is a dozen-float kvstore push per
    replica."""

    def __init__(self, kv, n_replicas: int, window=None, sigma=None,
                 publish_steps=None, rank0: bool = True):
        from .. import config as _cfg
        self.kv = kv
        self.n = int(n_replicas)
        self.publish_steps = int(
            publish_steps if publish_steps is not None
            else _cfg.get("MXNET_FLEET_PUBLISH_STEPS"))
        self.reporters = {}         # rid -> FleetReporter (lazy)
        self.view = FleetView(kv) if rank0 else None
        self.detector = StragglerDetector(window=window, sigma=sigma)
        self._last_counts = {}      # publish-delta baselines
        self._last_step = None
        # the newest dump should answer "which replica" even after
        # this object is gone mid-crash — but a dead supervisor must
        # not pin itself through the module hook: weakref provider
        ref = weakref.ref(self)

        def _provider():
            ft = ref()
            return None if ft is None else ft.block()
        _bb.set_fleet_provider(_provider)

    # -- publish -------------------------------------------------------
    def _reporter(self, rid: int) -> FleetReporter:
        rep = self.reporters.get(int(rid))
        if rep is None:
            rep = self.reporters[int(rid)] = FleetReporter(self.kv, rid)
        return rep

    def _step_deltas(self, step: int) -> dict:
        """Per-step averages of the process-level train.* wall
        counters since the last publish (the StepTelemetry deltas the
        snapshot carries)."""
        names = ("train.dispatch_us", "train.collective_us",
                 "train.data_wait_us")
        now = {n: events.get(n) for n in names}
        steps = 1 if self._last_step is None \
            else max(1, step - self._last_step)
        out = {n.split(".", 1)[1]:
               (now[n] - self._last_counts.get(n, 0)) / steps
               for n in names}
        self._last_counts = now
        self._last_step = step
        return out

    def update(self, step: int, per_replica_step_us: dict) -> list:
        """One supervised step's fleet round (see class docstring).
        `per_replica_step_us`: {rid: measured step wall in µs} for the
        replicas this process speaks for.  Returns the straggler rids
        (empty when publishing is disabled or off-cadence)."""
        if self.publish_steps <= 0 or not per_replica_step_us:
            return []
        if step % self.publish_steps != 0:
            return sorted(self.detector.flagged)
        t0 = time.perf_counter()
        base = _counter_sample()
        base.update(self._step_deltas(step))
        for rid, us in per_replica_step_us.items():
            sample = dict(base, step=step, step_us=float(us))
            self._reporter(rid).publish(sample)
        if self.view is None:
            events.observe_time("fleet.publish_us",
                                time.perf_counter() - t0)
            return []
        merged = self.view.refresh(sorted(per_replica_step_us))
        per_us = {}
        for rid, row in merged.items():
            us = row.get("step_us", 0.0)
            per_us[rid] = us
            # the replica-labeled Prometheus children: summary rings
            # keyed {replica=}, rendered by MetricsExporter for free
            events.observe("fleet.step_us", us,
                           labels={"replica": str(rid)})
        out = self.detector.observe(step, per_us)
        # the rank-0 merge is also the durable per-replica record
        # (ISSUE 12): one history row per replica at publish cadence —
        # already off the step critical path, and a no-op when
        # MXNET_HISTORY_DIR is unset
        try:
            from . import history as _hist
            _hist.record_fleet(merged, step=step, stragglers=out)
        except Exception:           # noqa: BLE001 — durability is
            pass                    # best-effort, never a step cost
        # the fleet layer meters ITSELF: publish+refresh+detect wall
        # per round, so "what does fleet telemetry cost" is a counter
        # you read, not a claim you trust
        events.observe_time("fleet.publish_us",
                            time.perf_counter() - t0)
        return out

    # -- surfaces ------------------------------------------------------
    def block(self) -> dict:
        """The `fleet` block for dumps / bench JSON / teletop: the
        merged per-replica view plus the detector's verdicts."""
        merged = self.view.last if self.view is not None else {}
        return {
            "ts": time.time(),
            "replicas": {str(rid): {k: (int(v) if float(v).is_integer()
                                        else round(float(v), 1))
                                    for k, v in row.items()}
                         for rid, row in sorted(merged.items())},
            "stragglers": sorted(int(r) for r in
                                 self.detector.flagged),
            "straggler_window": self.detector.window,
            "straggler_sigma": self.detector.sigma,
        }
