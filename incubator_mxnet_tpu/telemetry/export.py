"""Metrics export surface (ISSUE 4 tentpole part 2).

`monitor.events` already holds every survival/feed/serving counter and
latency sample ring in the process — but only in memory.
`MetricsExporter` renders that ledger two ways:

- **Prometheus text format** (`prometheus_text()` / `GET /metrics`):
  every counter as a `counter` metric, every observed sample series
  (the `observe()`/`observe_time()` names, conventionally `*_us`) as a
  `summary` with p50/p90/p99 quantiles, `_sum` (the companion
  monotonic counter, when one exists) and `_count`.
- **JSON** (`json_dict()` / `GET /metrics.json` / the periodic file):
  `{"ts": ..., "counters": {...}, "percentiles": {...}}` — the
  round-trippable snapshot `tools/teletop.py` and bench.py embed.

Serving modes:

- `export_file(path)` — one atomic snapshot (`.prom`/`.txt` → text
  format, anything else → JSON).
- `start(path, period_s)` — background periodic file export.  The
  worker holds the exporter only through a weakref (the DeviceFeed
  pattern): an abandoned exporter is GC'd and its thread retires.
- `serve_http(port)` — stdlib `ThreadingHTTPServer` thread answering
  `/metrics`, `/metrics.json` and `/healthz` (port 0 picks a free one;
  default `MXNET_TELEMETRY_PORT`).  `close()` is flag-drain like the
  serving engine: intake flips to draining (healthz reports it, new
  scrapes get 503), the server shuts down, threads join.
"""
from __future__ import annotations

import json
import os
import re
import threading
import time
import weakref

from .. import config as _cfg
from ..monitor import events

__all__ = ["MetricsExporter"]

_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")


def _metric_name(prefix, name):
    return _NAME_RE.sub("_", prefix + name)


def _fmt(v):
    if isinstance(v, bool):
        return "1" if v else "0"
    if isinstance(v, int):
        return str(v)
    f = float(v)
    return str(int(f)) if f.is_integer() else repr(f)


class MetricsExporter:
    """Render an `EventCounters` ledger (default: the process-wide
    `monitor.events`) as Prometheus text / JSON, with optional periodic
    file export and an HTTP endpoint thread."""

    def __init__(self, counters=None, prefix="mxnet_",
                 pcts=(50, 90, 99)):
        self._c = counters if counters is not None else events
        self._prefix = prefix
        self._pcts = tuple(pcts)
        self._t0 = time.time()
        self._stop = threading.Event()
        self._draining = False
        self._thread = None
        self._path = None
        self._httpd = None
        self._http_thread = None
        self.http_port = None

    # -- rendering -----------------------------------------------------
    def _snapshot(self):
        return self._c.snapshot(), self._c.latency_snapshot(
            pcts=self._pcts)

    @staticmethod
    def _escape_label(v):
        """Prometheus exposition label-value escaping: backslash,
        double quote and newline (an unescaped one invalidates the
        WHOLE scrape, not just the line)."""
        return (str(v).replace("\\", r"\\").replace('"', r"\"")
                .replace("\n", r"\n"))

    @classmethod
    def _labelstr(cls, labels, extra=None):
        """`{k="v",...}` for a labels dict (+ optional extra pairs),
        deterministically ordered; empty string for no labels."""
        items = sorted((labels or {}).items())
        if extra:
            items += list(extra.items())
        if not items:
            return ""
        return "{%s}" % ",".join(
            '%s="%s"' % (cls._escape_label(k), cls._escape_label(v))
            for k, v in items)

    @staticmethod
    def _cost_lines(prefix):
        """The executable cost registry (telemetry.costs) as labeled
        gauge families — flops / bytes-accessed / invocations /
        compile wall per registered executable (ISSUE 5)."""
        from . import costs as _costs
        rows = _costs.table()
        if not rows:
            return []
        lines = []
        fams = (("executable_flops", "flops"),
                ("executable_bytes_accessed", "bytes_accessed"),
                ("executable_invocations", "invocations"),
                ("executable_compile_seconds", "compile_wall_s"))
        for fam, field in fams:
            m = _metric_name(prefix, fam)
            lines.append("# TYPE %s gauge" % m)
            for r in rows:
                # the registry key makes the labelset unique: two
                # trainers/engines in one process produce rows with
                # identical kind+label, and duplicate series make the
                # whole scrape unparseable to Prometheus
                lines.append('%s{kind="%s",label="%s",key="%d"} %s'
                             % (m,
                                MetricsExporter._escape_label(r["kind"]),
                                MetricsExporter._escape_label(r["label"]),
                                r["key"], _fmt(r[field])))
        return lines

    @staticmethod
    def _slo_lines(prefix):
        """The SLO alert gauge (ISSUE 12): one ``<prefix>alert_active``
        child per REGISTERED rule — 1 while firing, 0 while clear, so
        a scrape sees alerts clear (an active-only family would just
        go stale)."""
        from . import slo as _slo
        names = set(_slo.rules())
        active = set(_slo.active_alerts())
        if not names and not active:
            return []
        m = _metric_name(prefix, "alert_active")
        lines = ["# TYPE %s gauge" % m]
        for name in sorted(names | active):
            lines.append('%s{rule="%s"} %d'
                         % (m, MetricsExporter._escape_label(name),
                            1 if name in active else 0))
        return lines

    @staticmethod
    def _reqtrace_lines(prefix):
        """Exemplar annotations for the labeled latency summaries
        (ISSUE 19): per (engine, lane), the WORST promoted request
        exemplar rides the scrape as a gauge family whose labels name
        the request — rid, terminal status, dominant phase — so the
        dashboard showing a lane's p99 can link straight to the
        autopsy instead of a faceless quantile.  Guarded on reqtrace
        being ALREADY imported: a scrape never pulls the tracing
        layer in just to say 'no requests'."""
        import sys as _sys
        rt = _sys.modules.get("incubator_mxnet_tpu.telemetry.reqtrace")
        if rt is None:
            return []
        worst = {}                  # (engine, lane) -> exemplar
        for ex in rt.exemplars():
            key = (ex.get("engine"), ex.get("lane"))
            if key not in worst or \
                    ex.get("e2e_us", 0) > worst[key].get("e2e_us", 0):
                worst[key] = ex
        if not worst:
            return []
        esc = MetricsExporter._escape_label
        m = _metric_name(prefix, "request_exemplar_e2e_us")
        mp = _metric_name(prefix, "request_exemplar_phase_us")
        lines = ["# TYPE %s gauge" % m]
        phase_lines = ["# TYPE %s gauge" % mp]
        for (engine, lane), ex in sorted(
                worst.items(), key=lambda kv: (str(kv[0][0]),
                                               str(kv[0][1]))):
            base = 'engine="%s",lane="%s"' % (esc(engine), esc(lane))
            lines.append(
                '%s{%s,rid="%s",status="%s",phase="%s"} %s'
                % (m, base, ex.get("rid"), esc(ex.get("status")),
                   esc(ex.get("dominant")), _fmt(ex.get("e2e_us", 0))))
            for ph, us in sorted((ex.get("phases") or {}).items()):
                phase_lines.append(
                    '%s{%s,rid="%s",phase="%s"} %s'
                    % (mp, base, ex.get("rid"), esc(ph), _fmt(us)))
        return lines + (phase_lines if len(phase_lines) > 1 else [])

    @staticmethod
    def _memwatch_lines(prefix):
        """The memory observatory (ISSUE 20) as gauge families:
        ``<prefix>hbm_used_bytes{device=,source=}`` from the newest
        sample, ``<prefix>hbm_peak_bytes{device=,phase=}`` from the
        per-phase watermarks, and
        ``<prefix>hbm_committed_bytes{device=,tenant=}`` from the
        attribution join — so a dashboard plots committed vs measured
        vs peak on one axis.  Guarded on memwatch being ALREADY
        imported: a scrape never pulls the observatory in just to say
        'no samples'."""
        import sys as _sys
        mw = _sys.modules.get("incubator_mxnet_tpu.telemetry.memwatch")
        if mw is None:
            return []
        smp = mw.last_sample()
        if smp is None:
            return []
        esc = MetricsExporter._escape_label
        lines = []
        m = _metric_name(prefix, "hbm_used_bytes")
        lines.append("# TYPE %s gauge" % m)
        for dev, d in sorted(smp.get("devices", {}).items()):
            lines.append('%s{device="%s",source="%s"} %s'
                         % (m, esc(dev), esc(d.get("source", "?")),
                            _fmt(d.get("used_bytes", 0))))
        marks = mw.watermarks()
        if marks:
            mp = _metric_name(prefix, "hbm_peak_bytes")
            lines.append("# TYPE %s gauge" % mp)
            for ph in sorted(marks):
                for dev, b in sorted(marks[ph].items()):
                    lines.append('%s{device="%s",phase="%s"} %s'
                                 % (mp, esc(dev), esc(ph), _fmt(b)))
        rows = mw.attribution()
        if rows:
            mc = _metric_name(prefix, "hbm_committed_bytes")
            lines.append("# TYPE %s gauge" % mc)
            for r in rows:
                lines.append('%s{device="%s",tenant="%s"} %s'
                             % (mc, esc(r.get("device")),
                                esc(r.get("tenant")),
                                _fmt(r.get("committed_bytes", 0))))
        return lines

    def prometheus_text(self) -> str:
        """Prometheus exposition text (version 0.0.4): counters +
        quantile summaries for every observed sample series (labeled
        tenant/lane splits render as labeled children of the same
        family — ISSUE 8), plus the per-executable cost families."""
        counts, lats = self._snapshot()
        lcounts = self._c.labeled_snapshot()
        llats = self._c.labeled_latency_snapshot(pcts=self._pcts)
        # an empty percentile dict (a reset() racing this scrape
        # between the snapshot's name collection and the per-name
        # percentiles) renders as a plain counter path, never KeyError
        sampled = {n for n, p in lats.items() if p}
        sampled |= {n for n, rows in llats.items() if rows}
        # sampled series render as summaries; their companion counters
        # (the same name = total µs, '<name>.n' = total observations)
        # fold into _sum/_count instead of repeating as bare counters
        folded = sampled | {n + ".n" for n in sampled}
        lines = []
        for name in sorted(set(counts) | sampled | set(lcounts)):
            if name in sampled:
                m = _metric_name(self._prefix, name)
                p = lats.get(name) or {}
                lines.append("# TYPE %s summary" % m)
                for pct in self._pcts:
                    if p:
                        lines.append('%s{quantile="%s"} %s'
                                     % (m, _fmt(pct / 100.0),
                                        _fmt(p["p%g" % pct])))
                    for row in llats.get(name, ()):
                        lines.append("%s%s %s" % (
                            m, self._labelstr(
                                row["labels"],
                                {"quantile": _fmt(pct / 100.0)}),
                            _fmt(row["p%g" % pct])))
                if name in counts:      # observe_time keeps the total
                    lines.append("%s_sum %s" % (m, _fmt(counts[name])))
                if p:
                    lines.append("%s_count %s"
                                 % (m, _fmt(counts.get(name + ".n",
                                                       p["n"]))))
                # labeled _count comes from the CUMULATIVE '<name>.n'
                # labelset counters, not the bounded ring window — a
                # window-size count plateaus at MAX_SAMPLES and reads
                # as rate()==0 to Prometheus while traffic flows
                lcum = {tuple(sorted(r["labels"].items())): r["value"]
                        for r in lcounts.get(name + ".n", ())}
                for row in llats.get(name, ()):
                    key = tuple(sorted(row["labels"].items()))
                    lines.append("%s_count%s %s"
                                 % (m, self._labelstr(row["labels"]),
                                    _fmt(lcum.get(key, row["n"]))))
            elif name not in folded:
                m = _metric_name(self._prefix, name)
                lines.append("# TYPE %s counter" % m)
                if name in counts:
                    lines.append("%s %s" % (m, _fmt(counts[name])))
                for row in lcounts.get(name, ()):
                    lines.append("%s%s %s"
                                 % (m, self._labelstr(row["labels"]),
                                    _fmt(row["value"])))
        if self._c is events:
            # the cost registry is process-wide state: it accompanies
            # the process ledger only — an exporter over a custom
            # EventCounters renders exactly those counters
            try:
                lines += self._cost_lines(self._prefix)
            except Exception:       # noqa: BLE001 — cost attribution
                pass                # must never break a scrape
            try:
                lines += self._slo_lines(self._prefix)
            except Exception:       # noqa: BLE001 — alerting must
                pass                # never break a scrape either
            try:
                lines += self._reqtrace_lines(self._prefix)
            except Exception:       # noqa: BLE001 — exemplars must
                pass                # never break a scrape either
            try:
                lines += self._memwatch_lines(self._prefix)
            except Exception:       # noqa: BLE001 — the memory
                pass                # observatory must not either
        return "\n".join(lines) + "\n"

    def json_dict(self) -> dict:
        counts, lats = self._snapshot()
        out = {"ts": time.time(),
               "uptime_s": round(time.time() - self._t0, 3),
               "counters": counts,
               "percentiles": lats}
        lcounts = self._c.labeled_snapshot()
        llats = self._c.labeled_latency_snapshot(pcts=self._pcts)
        if lcounts or llats:
            out["labeled"] = {"counters": lcounts,
                              "percentiles": llats}
        if self._c is events:
            try:
                from . import costs as _costs
                block = _costs.snapshot()
                if block["rows"]:
                    out["costs"] = block
            except Exception:       # noqa: BLE001
                pass
            # the merged per-replica fleet view (ISSUE 11), when a
            # supervisor registered one — teletop renders it as
            # per-replica columns
            try:
                from . import flightrec as _bb
                fleet = _bb.fleet_block()
                if fleet and fleet.get("replicas"):
                    out["fleet"] = fleet
            except Exception:       # noqa: BLE001
                pass
            # the SLO rule/alert state (ISSUE 12): teletop renders the
            # alert rows, and a scraped snapshot answers "is anything
            # firing" without the Prometheus surface
            try:
                from . import slo as _slo
                sblock = _slo.block()
                if sblock:
                    out["slo"] = sblock
            except Exception:       # noqa: BLE001
                pass
            # supervisor state (ISSUE 16) — only when the control
            # plane is already imported (same guard as the blackbox:
            # a scrape must not import the serving stack)
            try:
                import sys as _sys
                ctl = _sys.modules.get(
                    "incubator_mxnet_tpu.serving.controlplane")
                if ctl is not None:
                    cblock = ctl.status_block()
                    if cblock:
                        out["controlplane"] = cblock
            except Exception:       # noqa: BLE001
                pass
            # the request journals + promoted slow-request exemplars
            # (ISSUE 19) — same already-imported guard
            try:
                import sys as _sys
                rt = _sys.modules.get(
                    "incubator_mxnet_tpu.telemetry.reqtrace")
                if rt is not None:
                    rblock = rt.block()
                    if rblock:
                        out["reqtrace"] = rblock
            except Exception:       # noqa: BLE001
                pass
            # the memory observatory (ISSUE 20) — same guard; teletop
            # renders the memory pane from this block
            try:
                import sys as _sys
                mw = _sys.modules.get(
                    "incubator_mxnet_tpu.telemetry.memwatch")
                if mw is not None:
                    mblock = mw.block()
                    if mblock:
                        out["memwatch"] = mblock
            except Exception:       # noqa: BLE001
                pass
        return out

    def json_text(self) -> str:
        return json.dumps(self.json_dict(), sort_keys=True)

    # -- file export ---------------------------------------------------
    def export_file(self, path=None) -> str:
        """Write one snapshot atomically (tmp + os.replace).  `.prom` /
        `.txt` suffix → Prometheus text, anything else → JSON.
        Default path: MXNET_TELEMETRY_EXPORT_PATH."""
        path = path or self._path or _cfg.get("MXNET_TELEMETRY_EXPORT_PATH")
        if not path:
            raise ValueError("no export path (argument, start(), or "
                             "MXNET_TELEMETRY_EXPORT_PATH)")
        body = self.prometheus_text() \
            if path.endswith((".prom", ".txt")) else self.json_text()
        # pid+tid: the periodic worker and a manual/close-time export
        # in the same process must not interleave on one temp file
        tmp = "%s.tmp.%d.%d" % (path, os.getpid(),
                                threading.get_ident())
        with open(tmp, "w") as f:
            f.write(body)
        os.replace(tmp, path)
        return path

    @staticmethod
    def _export_loop(ref, stop, period):
        while not stop.wait(period):
            exp = ref()
            if exp is None:
                return
            try:
                exp.export_file()
            except Exception:           # noqa: BLE001 — periodic export
                pass                    # is best-effort, never fatal
            try:
                # each export tick also lands a counter-delta sample in
                # the flight-recorder ring, so a later black-box dump
                # shows counter FLOW over time, not just final totals
                from . import flightrec as _bb
                _bb.sample_counters()
                _bb.hbm_sample(tag="export")
            except Exception:           # noqa: BLE001
                pass
            try:
                # the memory observatory samples at exactly this
                # cadence (ISSUE 20) — tick time is its ONLY periodic
                # hook, so MXNET_MEMWATCH never touches a request or
                # step path
                from . import memwatch as _mw
                _mw.sample(tag="export")
            except Exception:           # noqa: BLE001
                pass
            # the durable layer rides the same cadence (ISSUE 12):
            # one history batch per tick, then the SLO rules judged
            # against the snapshots the batch just captured — both
            # off every hot path by construction.  SEPARATE guards:
            # a full/unwritable history disk raising every tick must
            # not also silence alerting — disk trouble is exactly
            # when the alerts are needed
            try:
                from . import history as _hist
                _hist.tick()
            except Exception:           # noqa: BLE001 — durability is
                pass                    # best-effort
            try:
                from . import slo as _slo
                _slo.evaluate()
            except Exception:           # noqa: BLE001 — and a broken
                pass                    # rule set must not kill export
            del exp

    def start(self, path=None, period_s=None):
        """Begin periodic file export every `period_s` seconds (default
        MXNET_TELEMETRY_EXPORT_S) to `path` (default
        MXNET_TELEMETRY_EXPORT_PATH).  Returns self (chainable)."""
        self._path = path or _cfg.get("MXNET_TELEMETRY_EXPORT_PATH")
        if not self._path:
            raise ValueError("periodic export needs a path (argument "
                             "or MXNET_TELEMETRY_EXPORT_PATH)")
        if period_s is None:
            period_s = float(_cfg.get("MXNET_TELEMETRY_EXPORT_S"))
        # (re)configure: retire any live worker (its Event flips, it
        # exits without a straggler export) and hand the NEW worker a
        # fresh Event with the new period — a second start() must
        # honor new args, and a start() after close() must not inherit
        # the already-set stop Event (the thread would exit on its
        # first wait without ever exporting)
        if (self._thread is not None and self._thread.is_alive()) \
                or self._stop.is_set():
            self._stop.set()
            self._stop = threading.Event()
            self._draining = False
        self._thread = threading.Thread(
            target=MetricsExporter._export_loop,
            args=(weakref.ref(self), self._stop, float(period_s)),
            daemon=True, name="TelemetryExport")
        self._thread.start()
        return self

    # -- HTTP endpoint -------------------------------------------------
    def serve_http(self, port=None, host="127.0.0.1") -> int:
        """Start the `/metrics` + `/healthz` endpoint thread.  `port`
        defaults to MXNET_TELEMETRY_PORT; 0 binds an ephemeral port.
        Binds loopback by default — counters and loss samples are
        process internals; exposing them fleet-wide is an explicit
        `host="0.0.0.0"` opt-in.  Returns the bound port (also on
        `self.http_port`)."""
        from http.server import BaseHTTPRequestHandler, \
            ThreadingHTTPServer
        if self._httpd is not None:
            if port is not None and int(port) not in (0, self.http_port):
                raise ValueError(
                    "metrics endpoint already bound on port %d; "
                    "close() it before rebinding to %d"
                    % (self.http_port, int(port)))
            return self.http_port
        if port is None:
            port = int(_cfg.get("MXNET_TELEMETRY_PORT"))
        ref = weakref.ref(self)         # the handler must not pin the
                                        # exporter (GC liveness — the
                                        # DeviceFeed/engine contract)

        class _Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # noqa: N802 — stdlib name
                pass                    # scrapes must not spam stderr

            def _send(self, code, ctype, body):
                data = body.encode()
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):           # noqa: N802 — stdlib name
                exp = ref()
                if exp is None or exp._draining:
                    self._send(503, "application/json",
                               '{"status": "draining"}')
                    return
                path = self.path.split("?")[0].rstrip("/") or "/"
                if path == "/metrics":
                    self._send(200,
                               "text/plain; version=0.0.4",
                               exp.prometheus_text())
                elif path in ("/metrics.json", "/json"):
                    self._send(200, "application/json",
                               exp.json_text())
                elif path == "/healthz":
                    self._send(200, "application/json", json.dumps(
                        {"status": "ok",
                         "uptime_s": round(time.time() - exp._t0, 3),
                         "counters": len(exp._c.snapshot())}))
                else:
                    self._send(404, "application/json",
                               '{"error": "not found"}')

        # binding a fresh endpoint un-drains (symmetric with start():
        # a serve_http() after close() must serve, not 503 forever)
        self._draining = False
        self._httpd = ThreadingHTTPServer((host, int(port)), _Handler)
        self.http_port = self._httpd.server_address[1]
        self._http_thread = threading.Thread(
            target=self._httpd.serve_forever, kwargs={"poll_interval": 0.1},
            daemon=True, name="TelemetryHTTP")
        self._http_thread.start()
        return self.http_port

    # -- lifecycle -----------------------------------------------------
    def close(self, timeout=5.0):
        """Flag-drain shutdown: scrapes start getting 503, the export
        thread retires (after one final file snapshot when a path is
        configured), the HTTP server joins.  Idempotent."""
        self._draining = True
        self._stop.set()
        t = self._thread
        if t is not None and t.is_alive():
            t.join(timeout)
        self._thread = None
        if self._path:
            try:
                self.export_file()      # final state on disk
            except Exception:           # noqa: BLE001
                pass
        httpd, self._httpd = self._httpd, None
        if httpd is not None:
            try:
                httpd.shutdown()
                httpd.server_close()
            except Exception:           # noqa: BLE001
                pass
        ht = self._http_thread
        if ht is not None and ht.is_alive():
            ht.join(timeout)
        self._http_thread = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __del__(self):
        # flags only — never join threads from a finalizer; the daemon
        # workers see the stop flag / dead weakref and retire
        self._draining = True
        self._stop.set()
        httpd = self._httpd
        if httpd is not None:
            try:
                httpd.shutdown()
            except Exception:           # noqa: BLE001
                pass
