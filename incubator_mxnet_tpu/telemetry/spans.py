"""Cross-thread spans on the profiler's chrome-trace timeline (ISSUE 4
tentpole part 1).

The op-dispatch profiler (profiler.py) sees imperative dispatches; the
async layers — DeviceFeed's transfer worker, the serving dispatcher and
its replica workers, checkpoint writes — are invisible to it because
their work happens on framework threads, between dispatches.  A span
names one such interval:

    with telemetry.span("serve.dispatch"):
        ...

Spans carry a trace id (one per causal chain) and a span id, with
EXPLICIT cross-thread parent propagation — thread-locals cannot follow
a request from the submitting thread onto the dispatcher:

    ctx = telemetry.current()           # producer thread
    ...
    with telemetry.span("feed.transfer", parent=ctx):   # worker thread
        ...

Completed spans are appended to the SAME chrome-trace sink profiler.py
dumps (`profiler.add_trace_event`), so `profiler.dump()` renders feed
transfers, dispatch→infer chains and checkpoint writes on one timeline
with the op events; trace/span/parent ids ride in each event's `args`.

Cost model (revised in ISSUE 5): span OBJECTS exist whenever telemetry
is enabled (`telemetry.enable()` / `MXNET_TELEMETRY=1`); with
telemetry off, `span()` returns a shared no-op — one bool read, no
allocation.  A completed span lands in TWO sinks with independent
gates:

- the profiler's chrome-trace sink, ONLY while the profiler is
  collecting (`set_state("run")`, not paused — the sink is unbounded,
  `recording()` reports this gate);
- the flight-recorder ring (flightrec.py), whenever the recorder is
  armed — the ring is bounded, so span completions survive into
  black-box dumps even on runs nobody is tracing.
"""
from __future__ import annotations

import itertools
import threading
import time

from .. import config as _cfg
from .. import profiler as _prof
from . import flightrec as _bb

__all__ = ["SpanContext", "enabled", "enable", "span", "current",
           "recording"]

_ids = itertools.count(1)       # CPython-atomic next(); no lock needed
_tls = threading.local()

# None = follow the MXNET_TELEMETRY knob live (config.set / env work
# like every other registered knob); enable() installs an explicit
# process-local override
_enabled = None


def enabled() -> bool:
    """Whether telemetry instrumentation (spans + per-step training
    counters) is switched on for this process."""
    if _enabled is not None:
        return _enabled
    return bool(_cfg.get("MXNET_TELEMETRY"))


def enable(flag=True):
    """Flip telemetry instrumentation on/off (None = revert to the
    MXNET_TELEMETRY knob); returns the previous effective state (so
    tests can restore it)."""
    global _enabled
    prev = enabled()
    _enabled = None if flag is None else bool(flag)
    return prev


def recording() -> bool:
    """Whether a span completed now would reach the CHROME-TRACE sink:
    telemetry enabled AND the profiler collecting.  (Ring recording
    into the flight recorder needs only `enabled()` — see the module
    docstring.)"""
    return (enabled() and _prof._STATE["running"]
            and not _prof._STATE["paused"])


class SpanContext:
    """Immutable (trace_id, span_id) handle for cross-thread parenting.
    Hand it to a worker thread and open child spans with
    ``span(name, parent=ctx)``."""

    __slots__ = ("trace_id", "span_id")

    def __init__(self, trace_id: str, span_id: str):
        self.trace_id = trace_id
        self.span_id = span_id

    def __repr__(self):
        return "SpanContext(trace=%s, span=%s)" % (self.trace_id,
                                                   self.span_id)


def _stack():
    st = getattr(_tls, "stack", None)
    if st is None:
        st = _tls.stack = []
    return st


def current():
    """The innermost open span's context on THIS thread (None outside
    any span, or when telemetry is disabled).  Capture it before
    handing work to another thread — that thread's spans pass it as
    `parent=` to join the same trace."""
    if not enabled():
        return None
    st = getattr(_tls, "stack", None)
    return st[-1] if st else None


class _NullSpan:
    """Shared no-op for the disabled path — `with` works, nothing is
    recorded, nothing is allocated per call."""

    __slots__ = ()

    def __enter__(self):
        return None

    def __exit__(self, *exc):
        return False

    def start(self):
        return self

    def stop(self):
        pass


_NULL = _NullSpan()


class _Span:
    __slots__ = ("name", "ctx", "parent_id", "_t0")

    def __init__(self, name, parent):
        if parent is None:
            parent = current()
        if parent is not None:
            trace = parent.trace_id
            self.parent_id = parent.span_id
        else:
            trace = "t%08x" % next(_ids)
            self.parent_id = None
        self.ctx = SpanContext(trace, "s%08x" % next(_ids))
        self.name = name
        self._t0 = None

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False

    def start(self):
        self._t0 = time.perf_counter()
        _stack().append(self.ctx)
        return self

    def stop(self):
        if self._t0 is None:
            return
        t0, self._t0 = self._t0, None
        st = _stack()
        if st and st[-1] is self.ctx:
            st.pop()
        elif self.ctx in st:        # mispaired stop(): drop ours only
            st.remove(self.ctx)
        dur = time.perf_counter() - t0
        args = {"trace_id": self.ctx.trace_id,
                "span_id": self.ctx.span_id}
        if self.parent_id is not None:
            args["parent_id"] = self.parent_id
        # chrome sink: add_trace_event self-gates on the profiler state
        # (a span that STARTED while collecting must not grow the sink
        # after set_state('stop'))
        _prof.add_trace_event(self.name, "span", t0, dur, args=args)
        # flight-recorder ring: bounded, so span completions survive
        # into black-box dumps with NO profiler running (ISSUE 5) —
        # record() is one bool read when the recorder is disarmed
        _bb.record("span", self.name, dur_us=int(dur * 1e6),
                   trace=self.ctx.trace_id, span=self.ctx.span_id,
                   parent=self.parent_id)


def span(name: str, parent: SpanContext = None):
    """Open a span (use as a context manager, or `.start()`/`.stop()`).
    `parent` joins an existing trace across threads; by default the
    innermost open span on this thread is the parent.  Returns a shared
    no-op when telemetry is disabled; enabled, the completion reaches
    the chrome sink and/or the flight-recorder ring per their own
    gates (see module docstring)."""
    if not enabled():
        return _NULL
    return _Span(name, parent)
