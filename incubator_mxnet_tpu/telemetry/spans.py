"""Cross-thread spans on the profiler's chrome-trace timeline (ISSUE 4
tentpole part 1).

The op-dispatch profiler (profiler.py) sees imperative dispatches; the
async layers — DeviceFeed's transfer worker, the serving dispatcher and
its replica workers, checkpoint writes — are invisible to it because
their work happens on framework threads, between dispatches.  A span
names one such interval:

    with telemetry.span("serve.dispatch"):
        ...

Spans carry a trace id (one per causal chain) and a span id, with
EXPLICIT cross-thread parent propagation — thread-locals cannot follow
a request from the submitting thread onto the dispatcher:

    ctx = telemetry.current()           # producer thread
    ...
    with telemetry.span("feed.transfer", parent=ctx):   # worker thread
        ...

Completed spans are appended to the SAME chrome-trace sink profiler.py
dumps (`profiler.add_trace_event`), so `profiler.dump()` renders feed
transfers, dispatch→infer chains and checkpoint writes on one timeline
with the op events; trace/span/parent ids ride in each event's `args`.

Cost model (revised in ISSUE 5): span OBJECTS exist whenever telemetry
is enabled (`telemetry.enable()` / `MXNET_TELEMETRY=1`); with
telemetry off, `span()` returns a shared no-op — one bool read, no
allocation.  A completed span lands in TWO sinks with independent
gates:

- the profiler's chrome-trace sink, ONLY while the profiler is
  collecting (`set_state("run")`, not paused — the sink is unbounded,
  `recording()` reports this gate);
- the flight-recorder ring (flightrec.py), whenever the recorder is
  armed — the ring is bounded, so span completions survive into
  black-box dumps even on runs nobody is tracing.

**Cross-process propagation (ISSUE 11).**  A fleet is many processes:
decode workers, serving hosts, per-replica trainers.  Three additions
make one request/step traceable across all of them:

- `TraceContext` — the SERIALIZABLE form of a span context
  (trace_id, parent span_id, global step): `propagate()` captures the
  innermost open span + current global step as a plain tuple that
  crosses any wire (a queue message, a kvstore key, an env var);
  `TraceContext.from_wire()` rebuilds it on the far side, ready to be
  passed as `parent=`.
- `set_global_step(step)` — every span completed while a global step
  is set carries `step` in its args/ring record, so traces from
  DIFFERENT processes (trainer rank 0, a decode worker, a serving
  host) correlate on the same step id even when their trace ids never
  meet.  Trainers stamp it each step.
- `emit_foreign(...)` — record a completed span ON BEHALF of another
  process (a jax-free decode worker reports wall-clock timing in its
  batch message; the consumer emits the span with the WORKER's pid,
  re-parented under the consumer's current span).  The chrome view
  then renders the worker's decode interval in its own process row of
  the same timeline.

Spans also take free-form tags: ``span("kv.push", gen=3, rank=0)`` —
tags land in the chrome event args and the ring record.
"""
from __future__ import annotations

import itertools
import os
import threading
import time

from .. import config as _cfg
from .. import profiler as _prof
from . import flightrec as _bb

__all__ = ["SpanContext", "TraceContext", "enabled", "enable", "span",
           "current", "recording", "propagate", "set_global_step",
           "get_global_step", "emit_foreign", "wall_of"]


def wall_of(t_mono):
    """The `time.time()` epoch stamp corresponding to a
    `time.monotonic()` reading taken earlier in THIS process.

    Interval stamps on the hot path are monotonic (immune to clock
    steps), but the flight-recorder ring and `record_at` speak epoch
    time.  Both clocks advance at wall rate, so the reading was
    (monotonic-now − t_mono) seconds ago.  This is the conversion the
    admission-time stamping discipline rides on (ISSUE 19 satellite —
    same family as `emit_foreign`'s end-stamping): convert the
    ORIGINAL stamp at emit time rather than stamping delivery time."""
    return time.time() - (time.monotonic() - float(t_mono))

_ids = itertools.count(1)       # CPython-atomic next(); no lock needed
_tls = threading.local()

# per-process id salt: trace/span ids must be unique ACROSS processes
# (ISSUE 11 — `blackbox merge` joins timelines on trace_id equality,
# and a bare counter starting at 1 would collide between any two
# processes, fabricating cross-process correlations).  pid + a time
# component survives pid recycling within one merge's inputs.
_PROC = "%08x" % ((os.getpid() << 12 ^ time.time_ns()) & 0xffffffff)


def _new_id(prefix):
    return "%s%s-%06x" % (prefix, _PROC, next(_ids))

# None = follow the MXNET_TELEMETRY knob live (config.set / env work
# like every other registered knob); enable() installs an explicit
# process-local override
_enabled = None


def enabled() -> bool:
    """Whether telemetry instrumentation (spans + per-step training
    counters) is switched on for this process."""
    if _enabled is not None:
        return _enabled
    return bool(_cfg.get("MXNET_TELEMETRY"))


def enable(flag=True):
    """Flip telemetry instrumentation on/off (None = revert to the
    MXNET_TELEMETRY knob); returns the previous effective state (so
    tests can restore it)."""
    global _enabled
    prev = enabled()
    _enabled = None if flag is None else bool(flag)
    return prev


def recording() -> bool:
    """Whether a span completed now would reach the CHROME-TRACE sink:
    telemetry enabled AND the profiler collecting.  (Ring recording
    into the flight recorder needs only `enabled()` — see the module
    docstring.)"""
    return (enabled() and _prof._STATE["running"]
            and not _prof._STATE["paused"])


class SpanContext:
    """Immutable (trace_id, span_id) handle for cross-thread parenting.
    Hand it to a worker thread and open child spans with
    ``span(name, parent=ctx)``."""

    __slots__ = ("trace_id", "span_id")

    def __init__(self, trace_id: str, span_id: str):
        self.trace_id = trace_id
        self.span_id = span_id

    def __repr__(self):
        return "SpanContext(trace=%s, span=%s)" % (self.trace_id,
                                                   self.span_id)


class TraceContext(SpanContext):
    """The SERIALIZABLE span context for crossing a process boundary:
    (trace_id, parent span_id, global step).  `to_wire()` is a plain
    tuple of primitives — safe in a multiprocessing queue message,
    a kvstore payload, or JSON; `from_wire()` rebuilds it on the far
    side, and the result is a valid `parent=` for `span()` /
    `emit_foreign()` (it IS a SpanContext).  `step` rides along so the
    receiver can adopt the sender's global step (`set_global_step`)
    and its spans correlate on the same step id."""

    __slots__ = ("step",)

    def __init__(self, trace_id: str, span_id: str, step=None):
        super().__init__(trace_id, span_id)
        self.step = None if step is None else int(step)

    def to_wire(self):
        """(trace_id, span_id, step) — primitives only."""
        return (self.trace_id, self.span_id, self.step)

    @classmethod
    def from_wire(cls, wire):
        """Rebuild from `to_wire()` output (or any 2/3-tuple of
        primitives).  None in, None out."""
        if wire is None:
            return None
        t = tuple(wire)
        return cls(str(t[0]), str(t[1]),
                   t[2] if len(t) > 2 else None)

    def __repr__(self):
        return "TraceContext(trace=%s, span=%s, step=%s)" % (
            self.trace_id, self.span_id, self.step)


def propagate():
    """The current position in the trace as a serializable
    `TraceContext` (innermost open span on this thread + the global
    step), for handing to ANOTHER PROCESS.  None when telemetry is
    disabled or no span is open AND no global step is set — a bare
    step still propagates (trace ids are minted lazily on the far
    side)."""
    ctx = current()
    step = get_global_step()
    if ctx is None and step is None:
        return None
    if ctx is None:
        # no open span: mint a trace so the far side still correlates
        return TraceContext(_new_id("t"),
                            _new_id("s"), step)
    return TraceContext(ctx.trace_id, ctx.span_id, step)


# global step id (process-wide): trainers stamp it every step; every
# span completed while it is set carries `step` in its args/ring
# record, which is what lets traces from DIFFERENT processes correlate
# on one step even when their trace ids never meet.  A plain attribute
# write/read — torn reads are impossible for a python int slot, so no
# lock on the hot path.
_GSTEP = {"step": None}


def set_global_step(step):
    """Stamp the process's current global step id onto every span
    completed from now on (None clears it).  Returns the previous
    value so scoped users can restore.

    Lifecycle contract: trainers stamp it each step and
    `ShardedTrainer.release()` clears it — a stamp that outlives its
    run would mark unrelated later spans (serving, checkpoint
    verifies) with a dead step id and fabricate cross-process
    correlations in `blackbox merge`.  Ad-hoc users (bench proofs,
    tests) clear it themselves."""
    prev = _GSTEP["step"]
    _GSTEP["step"] = None if step is None else int(step)
    return prev


def get_global_step():
    """The current global step id (None when unset)."""
    return _GSTEP["step"]


def _stack():
    st = getattr(_tls, "stack", None)
    if st is None:
        st = _tls.stack = []
    return st


def current():
    """The innermost open span's context on THIS thread (None outside
    any span, or when telemetry is disabled).  Capture it before
    handing work to another thread — that thread's spans pass it as
    `parent=` to join the same trace."""
    if not enabled():
        return None
    st = getattr(_tls, "stack", None)
    return st[-1] if st else None


class _NullSpan:
    """Shared no-op for the disabled path — `with` works, nothing is
    recorded, nothing is allocated per call."""

    __slots__ = ()

    def __enter__(self):
        return None

    def __exit__(self, *exc):
        return False

    def start(self):
        return self

    def stop(self):
        pass


_NULL = _NullSpan()


class _Span:
    __slots__ = ("name", "ctx", "parent_id", "tags", "_t0")

    def __init__(self, name, parent, tags=None):
        if parent is None:
            parent = current()
        if parent is not None:
            trace = parent.trace_id
            self.parent_id = parent.span_id
        else:
            trace = _new_id("t")
            self.parent_id = None
        self.ctx = SpanContext(trace, _new_id("s"))
        self.name = name
        self.tags = tags
        self._t0 = None

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False

    def start(self):
        self._t0 = time.perf_counter()
        _stack().append(self.ctx)
        return self

    def stop(self):
        if self._t0 is None:
            return
        t0, self._t0 = self._t0, None
        st = _stack()
        if st and st[-1] is self.ctx:
            st.pop()
        elif self.ctx in st:        # mispaired stop(): drop ours only
            st.remove(self.ctx)
        dur = time.perf_counter() - t0
        args = {"trace_id": self.ctx.trace_id,
                "span_id": self.ctx.span_id}
        if self.parent_id is not None:
            args["parent_id"] = self.parent_id
        step = _GSTEP["step"]
        if step is not None:
            args["step"] = step
        if self.tags:
            args.update(self.tags)
        # chrome sink: add_trace_event self-gates on the profiler state
        # (a span that STARTED while collecting must not grow the sink
        # after set_state('stop'))
        _prof.add_trace_event(self.name, "span", t0, dur, args=args)
        # flight-recorder ring: bounded, so span completions survive
        # into black-box dumps with NO profiler running (ISSUE 5) —
        # record() is one bool read when the recorder is disarmed
        extra = dict(self.tags) if self.tags else {}
        if step is not None:
            extra["step"] = step
        _bb.record("span", self.name, dur_us=int(dur * 1e6),
                   trace=self.ctx.trace_id, span=self.ctx.span_id,
                   parent=self.parent_id, **extra)


def span(name: str, parent: SpanContext = None, **tags):
    """Open a span (use as a context manager, or `.start()`/`.stop()`).
    `parent` joins an existing trace across threads/processes (a
    `SpanContext` or a deserialized `TraceContext`); by default the
    innermost open span on this thread is the parent.  Free-form
    `tags` (e.g. ``gen=3, rank=0``) ride in the completion's args and
    ring record.  Returns a shared no-op when telemetry is disabled;
    enabled, the completion reaches the chrome sink and/or the
    flight-recorder ring per their own gates (see module docstring)."""
    if not enabled():
        return _NULL
    return _Span(name, parent, tags or None)


def emit_foreign(name, t0_wall, dur_s, parent=None, pid=None, tid=None,
                 **tags):
    """Record a COMPLETED span on behalf of another process.

    The fleet's jax-free workers (decode processes) cannot import the
    telemetry stack; they report wall-clock timing in their messages
    and the consumer calls this on delivery — the span lands in the
    chrome sink / flight-recorder ring with the WORKER's `pid` (its
    own process row in the merged timeline), re-parented under
    `parent` (default: the consumer's innermost open span), and
    stamped with the current global step.

    `t0_wall` is a `time.time()` epoch stamp from the foreign process
    (epoch time IS comparable across processes on one host, unlike
    `perf_counter`); `dur_s` seconds.  Returns the new span's
    `SpanContext` (None when telemetry is disabled)."""
    if not enabled():
        return None
    if parent is None:
        parent = current()
    if parent is not None:
        trace = parent.trace_id
        parent_id = parent.span_id
    else:
        trace = _new_id("t")
        parent_id = None
    ctx = SpanContext(trace, _new_id("s"))
    args = {"trace_id": trace, "span_id": ctx.span_id}
    if parent_id is not None:
        args["parent_id"] = parent_id
    step = _GSTEP["step"]
    if step is not None:
        args["step"] = step
    if tags:
        args.update(tags)
    # map the foreign epoch stamp onto this process's perf_counter
    # origin (the chrome sink's timebase): both clocks advance at
    # wall rate, so the offset is (now_wall - t0_wall) ago
    t0_perf = time.perf_counter() - max(0.0, time.time() - t0_wall)
    _prof.add_trace_event(name, "span", t0_perf, dur_s, args=args,
                          pid=pid, tid=tid)
    extra = dict(tags) if tags else {}
    if step is not None:
        extra["step"] = step
    if pid is not None:
        extra["pid"] = int(pid)
    # stamp the ring event at the interval's true END (the foreign
    # process's clock), not at delivery — a prefetched batch's decode
    # slice must not shift right by its queue wait in the dump view
    _bb.record_at(t0_wall + dur_s, "span", name,
                  dur_us=int(dur_s * 1e6), trace=trace,
                  span=ctx.span_id, parent=parent_id, **extra)
    return ctx
