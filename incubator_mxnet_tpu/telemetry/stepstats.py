"""Per-step training telemetry (ISSUE 4 tentpole part 3).

`StepTelemetry` turns one train step into the `train.*` ledger on
`monitor.events`:

    train.steps             steps recorded
    train.step_us           step wall (counter total + p50/p99 samples)
    train.data_wait_us      batch placement / feed wait inside the step
    train.compute_us        dispatch→host-sync wall (guarded steps)
    train.dispatch_us       async dispatch wall (ShardedTrainer steps —
                            loss stays on device, so compute wall is
                            not observable without forfeiting overlap)
    train.dispatch_replica_us  per-replica batch-shard upload wall,
                            labeled {replica=<i>} (the DispatchPool
                            fan-out — ISSUE 10); aggregate + labeled
                            percentile rings
    train.collective_us     attributed collective wall per step where
                            a caller can measure it (the bench's
                            weak-scaling breakdown derives it from a
                            collective-free compiled baseline; inside
                            ONE fused executable it is not separately
                            observable)
    train.loss              loss samples (percentiles; no counter)
    train.steps_skipped     guarded steps whose update was not applied
    train.steps_compiling   steps that traced a new executable
                            (`train.traces` moved — the recompile
                            smoke alarm, PROFILE.md's dominant tail)
    train.checkpoint_us     checkpoint write wall

`ResilientTrainer` / `ShardedTrainer` instantiate one lazily when
`telemetry.enabled()` — the disabled hot path pays a single bool read.
The trace counter `train.traces` itself is incremented inside the
jitted step bodies (trace-time python side effect, the serving
`serve.traces` pattern): zero cost in the executable, and a cache hit
never touches it.
"""
from __future__ import annotations

import math

from ..monitor import events

__all__ = ["StepTelemetry"]


class StepTelemetry:
    """Records per-step training telemetry onto an `EventCounters`
    ledger (default: the process-wide `monitor.events`)."""

    def __init__(self, counters=None, own_traces=0):
        self._c = counters if counters is not None else events
        # compile-delta baselines taken NOW: `own_traces` is the owning
        # trainer's trace count at creation (nonzero when telemetry is
        # enabled mid-run — those earlier compiles must not fire the
        # alarm on the first recorded step), the global counter
        # baselines itself the same way
        self._last_own = int(own_traces)
        self._last_global = self._c.get("train.traces")

    def record_step(self, loss=None, ok=True, wall_s=None,
                    data_wait_s=None, compute_s=None,
                    dispatch_s=None, collective_s=None, traces=None):
        """One step's telemetry.  Durations in seconds (None = not
        measured); `loss` a host float (NaN/None skipped as a sample);
        `ok` False counts the step as skipped (guarded-step contract);
        `traces` the OWNING trainer's executable-trace count (falls
        back to the process-global `train.traces` — which misattributes
        another trainer's compile in multi-trainer processes, so
        trainers pass their own)."""
        c = self._c
        c.incr("train.steps")
        if wall_s is not None:
            c.observe_time("train.step_us", wall_s)
        if data_wait_s is not None:
            c.observe_time("train.data_wait_us", data_wait_s)
        if compute_s is not None:
            c.observe_time("train.compute_us", compute_s)
        if dispatch_s is not None:
            c.observe_time("train.dispatch_us", dispatch_s)
        if collective_s is not None:
            c.observe_time("train.collective_us", collective_s)
        if loss is not None and math.isfinite(loss):
            c.observe("train.loss", float(loss))
        if not ok:
            c.incr("train.steps_skipped")
        if traces is not None:
            if traces > self._last_own:
                c.incr("train.steps_compiling")
            self._last_own = traces
        else:
            g = c.get("train.traces")
            if g > self._last_global:
                c.incr("train.steps_compiling")
            self._last_global = g

    def record_checkpoint(self, seconds):
        self._c.observe_time("train.checkpoint_us", seconds)
