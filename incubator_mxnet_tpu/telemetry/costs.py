"""Per-executable FLOPs/HBM cost attribution (ISSUE 5 tentpole
part 2).

Telemetry so far says how long things took; nothing says what the
hardware was ASKED to do.  XLA exposes exactly that per executable —
`cost_analysis()` (flops, bytes accessed) and `memory_analysis()`
(argument/output/temp/alias bytes) — and the repo already touches the
surface per-op (ndarray.py:77) but never aggregates it.  This registry
is the aggregation point: every jitted executable the framework builds
(the aot_cache entries, the fused imperative train step in
gluon/block.py + optimizer.py, ShardedTrainer/ResilientTrainer steps,
the serving bucket executables) registers one row per input signature,
and every call bumps the row's invocation count — so a blackbox dump
or a `/metrics` scrape can say "this run spent N invocations × M
GFLOPs on `resilient.gstep`, and the serving buckets held K bytes of
HBM".

Two registration paths:

- `note_executable(...)` — the aot_cache path: a `Lowered` and/or
  `Compiled` is already in hand, analysis is extracted eagerly (no
  extra work was done to get it).
- `metered_jit(fn, ...)` — the plain-jit path (ShardedTrainer /
  ResilientTrainer steps, aot_jit's no-cache-dir fallback).  New
  signatures are detected by a trace-time hook (a jit cache hit never
  runs the python body — the `train.traces` pattern), which captures
  the tracer avals and files a PENDING row; `table()`/`totals()`
  resolve pending rows by lowering against the stored avals — off the
  hot path, and (because jit shares its trace cache with `.lower()`)
  usually without re-tracing.  The steady-state call pays two int
  compares and one locked counter bump, never a pytree flatten.

Both guards: `cost_analysis()`/`memory_analysis()` returning None or
raising (the axon plugin, ndarray.py:77) degrades to a row with the
walls and invocation counts but zeroed cost fields — never a crash.
The per-call hot path is gated on `flightrec.enabled()`:
MXNET_BLACKBOX=0 makes `MeteredJit.__call__` a bool read + the inner
jit call.
"""
from __future__ import annotations

import threading
import time
import weakref

__all__ = ["note_executable", "note_collective", "invoke", "table",
           "totals", "snapshot", "reset", "metered_jit", "MeteredJit",
           "footprint_bytes", "suggest_bucket_mb"]

_LOCK = threading.Lock()
_ROWS = {}                      # key -> dict row
_NEXT = [1]


def _cost_dict(obj):
    """`obj.cost_analysis()` as a plain dict — tolerant of None, a
    per-device list, a missing method, or a raising backend."""
    fn = getattr(obj, "cost_analysis", None)
    if fn is None:
        return {}
    try:
        c = fn()
    except Exception:               # noqa: BLE001 — axon returns None /
        return {}                   # raises; attribution degrades
    if isinstance(c, (list, tuple)):
        c = c[0] if c else None
    return dict(c) if c else {}


def _mem_dict(compiled):
    """`compiled.memory_analysis()` fields as a plain dict (same
    tolerance as `_cost_dict`)."""
    fn = getattr(compiled, "memory_analysis", None)
    if fn is None:
        return {}
    try:
        m = fn()
    except Exception:               # noqa: BLE001
        return {}
    if m is None:
        return {}
    out = {}
    for field, key in (("argument_size_in_bytes", "argument_bytes"),
                       ("output_size_in_bytes", "output_bytes"),
                       ("temp_size_in_bytes", "temp_bytes"),
                       ("alias_size_in_bytes", "donated_bytes"),
                       ("generated_code_size_in_bytes", "code_bytes")):
        v = getattr(m, field, None)
        if v is not None:
            try:
                out[key] = int(v)
            except (TypeError, ValueError):
                pass
    return out


def _apply_analysis(row, cost, mem):
    c = _cost_dict(cost) if cost is not None else {}
    row["flops"] = float(c.get("flops", 0.0) or 0.0)
    row["bytes_accessed"] = float(c.get("bytes accessed", 0.0) or 0.0)
    row["analyzed"] = bool(c)
    if mem is not None:
        row.update(_mem_dict(mem))


def note_executable(kind, label, lowered=None, compiled=None,
                    compile_s=None, loaded=False, nsig=None):
    """Register one executable's cost row (eager path — analysis
    objects are in hand).  Prefers `compiled` for cost/memory analysis,
    falls back to `lowered` for cost (a deserialized executable may not
    re-expose cost_analysis).  Returns the row key for `invoke()`."""
    row = {"kind": str(kind), "label": str(label),
           "flops": 0.0, "bytes_accessed": 0.0,
           "compile_wall_s": float(compile_s) if compile_s else 0.0,
           "loaded": bool(loaded), "invocations": 0,
           "analyzed": False, "pending": None}
    c = _cost_dict(compiled)
    # prefer the compiled executable's analysis; a deserialized blob
    # may not re-expose it, so fall back to the lowering's (one
    # cost_analysis pass either way)
    if c:
        row["flops"] = float(c.get("flops", 0.0) or 0.0)
        row["bytes_accessed"] = float(c.get("bytes accessed", 0.0)
                                      or 0.0)
        row["analyzed"] = True
        row.update(_mem_dict(compiled))
    else:
        _apply_analysis(row, lowered, compiled)
    if nsig:
        row["sig"] = str(nsig)
    with _LOCK:
        key = _NEXT[0]
        _NEXT[0] += 1
        _ROWS[key] = row
    return key


def note_collective(label, op, wire_bytes, n_shards, dtype="float32"):
    """Register one bucket-collective's cost row (ISSUE 10 satellite):
    the ZeRO-2/3 reduce-scatter / all-gather buckets are not separate
    executables (they live inside the fused train step), so XLA's
    per-executable analysis cannot attribute their bytes-on-wire per
    bucket.  This row carries the bucket's wire bytes explicitly
    (``bytes_accessed`` = bytes each shard contributes to the ring),
    kind="collective", so teletop and the bench JSON can rank buckets
    the same way they rank executables.  ``invoke(key)`` per step keeps
    cumulative wire totals honest.  Returns the row key."""
    row = {"kind": "collective", "label": str(label),
           "flops": 0.0, "bytes_accessed": float(wire_bytes),
           "compile_wall_s": 0.0, "loaded": False, "invocations": 0,
           "analyzed": True, "pending": None,
           "sig": "%s[%d shards, %s]" % (op, int(n_shards), dtype)}
    with _LOCK:
        key = _NEXT[0]
        _NEXT[0] += 1
        _ROWS[key] = row
    return key


_HEURISTIC_WARNED = set()


def suggest_bucket_mb(param_bytes, n_shards, label_prefix=None,
                      default_mb=4.0, deciding=False):
    """Bucket-size cap steering (ISSUE 10 tentpole b): pick the
    MXNET_ZERO_BUCKET_MB default from measured per-executable bytes.

    When a train-step row for ``label_prefix`` already exists with a
    resolved bytes-accessed figure (a previous build of this trainer —
    e.g. the elastic rebuild path, where the registry has watched the
    step run), the cap targets ~1/32 of the executable's measured
    per-step traffic: enough buckets to interleave with backward,
    each well under the backend's large-collective cliff.  Without a
    row, the same 1/32 rule applies to the param bytes themselves.
    Clamped to [1, 16] MB; an explicit MXNET_ZERO_BUCKET_MB (> 0)
    always wins at the call site.

    ISSUE 18 deprecation shim: the compile autotuner
    (compile/autotune.py) is the default steering now, and this
    one-shot heuristic survives as its COLD-HISTORY fallback.
    ``deciding=True`` is the autotuner saying "no measured evidence
    existed — this heuristic's answer is the deciding input": that
    warns once per label (so tuned-vs-heuristic provenance is visible
    in the blackbox via the `autotune/heuristic_fallback` ring event)
    without penalizing advisory callers."""
    if deciding:
        key = str(label_prefix or "<unlabeled>")
        if key not in _HEURISTIC_WARNED:
            _HEURISTIC_WARNED.add(key)
            from . import flightrec as _bb
            _bb.record("autotune", "heuristic_fallback", label=key)
            import warnings
            warnings.warn(
                "costs.suggest_bucket_mb is the DECIDING input for "
                "executable %r: the autotune history holds no measured "
                "probe/cost rows for it yet — the one-shot heuristic "
                "steers this build; run with MXNET_HISTORY_DIR set so "
                "the next run tunes from measurements" % key)
    basis = float(param_bytes)
    if label_prefix:
        bracket = label_prefix + "["
        with _LOCK:
            rows = [dict(r) for r in _ROWS.values()]
        for r in rows:
            label = str(r.get("label", ""))
            if (label == label_prefix or label.startswith(bracket)) \
                    and r.get("bytes_accessed", 0) > 0 \
                    and r.get("pending") is None:
                basis = max(basis, float(r["bytes_accessed"]))
                break
    if basis <= 0:
        return float(default_mb)
    return float(min(16.0, max(1.0, basis / 32.0 / 1e6)))


def _note_pending(kind, label, resolver, compile_s=None):
    """Register a row whose analysis is resolved lazily by `resolver()`
    (returns a Lowered, or None) at table/totals time."""
    row = {"kind": str(kind), "label": str(label),
           "flops": 0.0, "bytes_accessed": 0.0,
           "compile_wall_s": float(compile_s) if compile_s else 0.0,
           "loaded": False, "invocations": 0,
           "analyzed": False, "pending": resolver}
    with _LOCK:
        key = _NEXT[0]
        _NEXT[0] += 1
        _ROWS[key] = row
    return key


def invoke(key, n=1):
    """Bump a row's cumulative invocation count (one lock; the per-step
    cost of attribution)."""
    with _LOCK:
        row = _ROWS.get(key)
        if row is not None:
            row["invocations"] += int(n)


def set_compile_wall(key, seconds):
    with _LOCK:
        row = _ROWS.get(key)
        if row is not None:
            row["compile_wall_s"] = float(seconds)


def _resolve(row):
    # pending swap under the lock: two concurrent table() callers (the
    # exporter worker and a crash dump) must not run one resolver twice
    with _LOCK:
        resolver, row["pending"] = row["pending"], None
    if resolver is None:
        return
    try:
        lowered = resolver()
    except Exception:               # noqa: BLE001 — resolution is
        lowered = None              # best-effort forensics
    if lowered is not None:
        _apply_analysis(row, lowered, None)


def table():
    """The cost table: one dict per registered executable, pending
    analyses resolved, sorted by cumulative FLOPs (flops × calls)
    descending."""
    with _LOCK:
        items = list(_ROWS.items())
    out = []
    for key, row in items:
        if row.get("pending") is not None:
            _resolve(row)
        r = {k: v for k, v in row.items() if k != "pending"}
        r["key"] = key
        r["cum_flops"] = r["flops"] * max(1, r["invocations"])
        r["cum_bytes"] = r["bytes_accessed"] * max(1, r["invocations"])
        out.append(r)
    out.sort(key=lambda r: r["cum_flops"], reverse=True)
    return out


def totals():
    """Aggregates for embedding in one JSON line (bench.py): executable
    and invocation counts, total/cumulative flops + bytes accessed, and
    the HBM peak watermark (flightrec's `hbm_sample` high-water)."""
    rows = table()
    from . import flightrec as _bb
    peaks = _bb.hbm_peaks()
    return {"executables": len(rows),
            "invocations": sum(r["invocations"] for r in rows),
            "flops": sum(r["flops"] for r in rows),
            "bytes_accessed": sum(r["bytes_accessed"] for r in rows),
            "cum_flops": sum(r["cum_flops"] for r in rows),
            "cum_bytes": sum(r["cum_bytes"] for r in rows),
            "compile_wall_s": round(sum(r["compile_wall_s"]
                                        for r in rows), 3),
            "hbm_peak_bytes": max(peaks.values()) if peaks else 0}


def footprint_bytes(label_prefix, kind=None):
    """MEASURED per-device HBM footprint of one executable family
    (ISSUE 8 admission control): the max over registered rows of one
    label family (optionally filtered by `kind`) of argument + output
    + temp bytes from XLA's memory_analysis.  Buckets of one serving
    model share parameters, so the max row — the largest bucket — IS
    the family's working set.  Rows are labeled `<family>[<idx>]`
    (aot_cache appends the signature ordinal), so the match is exact
    up to the '[' delimiter — plain startswith would let model
    'ranker' read model 'ranker2's footprint.  Returns 0 when no
    matching row carries memory fields (plain-jit rows resolve
    cost_analysis only; admission then falls back to projection)."""
    best = 0
    bracket = label_prefix + "["
    for r in table():
        if kind is not None and r.get("kind") != kind:
            continue
        label = str(r.get("label", ""))
        if label != label_prefix and not label.startswith(bracket):
            continue
        b = (r.get("argument_bytes", 0) + r.get("output_bytes", 0)
             + r.get("temp_bytes", 0))
        best = max(best, int(b))
    return best


def drop_rows(label_prefix, kind=None):
    """Remove the registered rows of one label family (the
    `footprint_bytes` matching rule: exact, or `<prefix>[...]`).  The
    ModelRegistry drops a model's rows on unregister so a later
    re-registration under the same name cannot read the previous
    incarnation's footprint; stale `invoke()`s against dropped keys
    are no-ops.  Returns the number of rows removed."""
    bracket = label_prefix + "["
    with _LOCK:
        gone = [k for k, r in _ROWS.items()
                if (kind is None or r.get("kind") == kind)
                and (str(r.get("label", "")) == label_prefix
                     or str(r.get("label", "")).startswith(bracket))]
        for k in gone:
            del _ROWS[k]
    return len(gone)


def snapshot():
    """{"rows": table(), "totals": totals()} — the dump/export block."""
    return {"rows": table(), "totals": totals()}


def reset():
    with _LOCK:
        _ROWS.clear()


_DONATION_WARNED = set()


def _audit_donation(label, donate_argnums, expect_donated):
    """Donation audit (ISSUE 10 satellite): a trainer step that fails
    to donate its state doubles the persistent HBM bill and breaks the
    in-place-update contract silently.  ``expect_donated`` names the
    argnums the CALLER says hold donatable state; any of them missing
    from ``donate_argnums`` warns ONCE per executable label (the label
    is the thing an operator can grep the cost table / blackbox for)."""
    if not expect_donated:
        return
    missing = sorted(set(int(i) for i in expect_donated)
                     - set(int(i) for i in donate_argnums))
    if not missing or label in _DONATION_WARNED:
        return
    _DONATION_WARNED.add(label)
    import warnings
    warnings.warn(
        "executable %r: argument(s) %s hold donatable state but are "
        "not donated (donate_argnums=%s) — the update will copy "
        "instead of aliasing, doubling this state's memory footprint"
        % (label, missing, tuple(donate_argnums)))


class MeteredJit:
    """`jax.jit` + cost-row registration + invocation counting for the
    plain-jit executables (no aot_cache involved).

    Hot-path contract (the check_overhead.py gate): NO per-call
    signature computation.  New input signatures are detected by a
    trace-time side effect inside the wrapped function (the
    `train.traces` pattern — a jit cache hit never runs the python
    body): the tracer avals are captured THERE, at trace cost, and the
    steady-state call pays one bool read, two int compares and one
    locked counter bump.  Recorder off: one bool read, then the inner
    jit."""

    def __init__(self, fn, donate_argnums=(), kind="jit", label=None,
                 expect_donated=None):
        import jax
        self._kind = kind
        self._label = label or getattr(fn, "__name__", "fn")
        _audit_donation(self._label, donate_argnums, expect_donated)
        self._keys = []             # registry row key per traced sig
        self._pending = []          # avals captured at trace time
        # suppresses the hook during lazy cost resolution (its lower()
        # may re-trace).  THREAD-local: a resolver running on the
        # exporter thread must not swallow a genuinely new signature
        # the training thread traces concurrently
        self._tls = threading.local()

        def _traced(*a):
            # trace-time only: a jit cache hit never runs this
            if not getattr(self._tls, "resolving", False):
                self._pending.append(jax.tree_util.tree_map(
                    lambda t: jax.ShapeDtypeStruct(t.shape, t.dtype),
                    a))
            return fn(*a)

        self._jit = jax.jit(_traced, donate_argnums=donate_argnums)

    def _register_pending(self, wall_s):
        """Turn trace-time aval captures into pending cost rows (the
        lowering/analysis happens at table()/dump time — jit shares
        its trace cache with .lower(), so resolution usually re-traces
        nothing).  `wall_s` (this call's wall, which included the
        trace+compile) is the compile-wall proxy."""
        jref = weakref.ref(self._jit)
        me = weakref.ref(self)
        while self._pending:
            avals = self._pending.pop(0)

            def resolver(avals=avals):
                j, s = jref(), me()
                if j is None:
                    return None
                if s is not None:
                    s._tls.resolving = True
                try:
                    return j.lower(*avals)
                finally:
                    if s is not None:
                        s._tls.resolving = False

            key = _note_pending(
                self._kind, "%s[%d]" % (self._label, len(self._keys)),
                resolver, compile_s=wall_s)
            self._keys.append(key)

    def __call__(self, *args):
        from . import flightrec as _bb
        if not _bb.enabled():
            return self._jit(*args)
        t0 = time.perf_counter()
        out = self._jit(*args)
        if self._pending:
            # this call traced a new signature: register it, with the
            # call's wall (≈ trace + compile + one execution) as the
            # honest compile-wall proxy
            self._register_pending(time.perf_counter() - t0)
        if self._keys:
            # cache-hit calls attribute to the newest row — knowing the
            # exact signature would cost a per-call pytree flatten,
            # which is precisely what the overhead gate forbids; totals
            # stay exact, per-row splits are approximate under
            # alternating shapes
            invoke(self._keys[-1])
        return out

    def lower(self, *args, **kw):       # introspection passthrough
        return self._jit.lower(*args, **kw)


def metered_jit(fn, donate_argnums=(), kind="jit", label=None,
                expect_donated=None):
    """`jax.jit(fn, donate_argnums=...)` with a cost-registry row per
    input signature and cumulative invocation counts.
    ``expect_donated`` arms the donation audit: argnums named there but
    absent from ``donate_argnums`` warn once with the executable
    label."""
    return MeteredJit(fn, donate_argnums=donate_argnums, kind=kind,
                      label=label, expect_donated=expect_donated)
